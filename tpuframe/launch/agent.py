"""Per-host launch agent: ``python -m tpuframe.launch.agent``.

The remote half of :class:`tpuframe.launch.RemoteDistributor` — the piece
the reference outsources to Spark executors / Ray actors (worker placement,
`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:360-367`,
`/root/reference/05_ray/01_fashion_mnist_pytorch_ray.ipynb:cell-5`).  One
agent runs per host and executes the shipped train fn as that host's rank.

Protocol (transport-agnostic: anything that can exec a command and pipe
stdio works — ssh, kubectl exec, docker exec, or a bare subprocess):

- **stdin**: one JSON header line ``{"payload_bytes": N, "env": {...}}``
  followed by exactly ``N`` bytes of cloudpickled ``(fn, args, kwargs)``.
- **stdout**: the fn's own stdout passes through untouched; the agent's
  last line is ``TPUFRAME_RESULT <base64(cloudpickle(outcome))>`` where
  ``outcome`` is ``{"ok": True, "value": ...}`` or
  ``{"ok": False, "error": exc}``.
- **stderr**: passes through (the driver keeps a per-rank tail).
- **exit code**: 0 on success, nonzero on failure — the result frame still
  carries the typed exception when it was picklable, so restart policies
  can dispatch on the type.

The env contract (``RANK``/``WORLD_SIZE``/``MASTER_ADDR``/…) arrives in
the header and is applied to ``os.environ`` *before* the payload is
unpickled; the header's ``PYTHONPATH`` additionally lands on ``sys.path``
so by-reference functions resolve.  Vars that must exist before
interpreter start (e.g. an image sitecustomize that pins a TPU plugin off
an env trigger) belong in the *transport command* (the ``connect`` hook),
not the header — by header time the interpreter is already up.
"""

from __future__ import annotations

import base64
import json
import os
import pickle
import sys
import threading

RESULT_SENTINEL = "TPUFRAME_RESULT "

#: Exit code of the stdin-EOF watchdog (driver/transport gone).
ORPHANED_EXIT = 17


def _arm_orphan_watchdog() -> None:
    """Self-terminate when the driver disappears.

    The driver holds our stdin open for the whole run.  Killing the local
    transport client (ssh) does NOT signal a non-pty remote command — an
    orphaned agent would keep training and hold the host's chips.  EOF on
    stdin is the one signal every stdio transport delivers on disconnect,
    so a blocked read doubles as a zero-cost death watch.
    """

    def watch() -> None:
        try:
            # raw-fd read, NOT sys.stdin.buffer: a daemon thread blocked
            # inside the buffered reader holds its lock and aborts
            # interpreter shutdown ("could not acquire lock ... at
            # interpreter shutdown")
            fd = sys.stdin.fileno()
            while os.read(fd, 4096):
                pass  # stray bytes after the payload: ignore, keep watching
        except Exception:
            pass
        os._exit(ORPHANED_EXIT)

    threading.Thread(target=watch, daemon=True, name="orphan-watchdog").start()


def _emit(outcome: dict) -> None:
    import cloudpickle

    try:
        blob = cloudpickle.dumps(outcome)
    except Exception as e:  # unpicklable return value
        blob = pickle.dumps(
            {"ok": False, "error": RuntimeError(f"result not picklable: {e}")}
        )
    # leading newline guards against the fn leaving a partial stdout line
    sys.stdout.write("\n" + RESULT_SENTINEL + base64.b64encode(blob).decode() + "\n")
    sys.stdout.flush()


def main() -> None:
    header = json.loads(sys.stdin.buffer.readline())
    env = dict(header.get("env", {}))
    os.environ.update(env)
    if env.get("PYTHONPATH"):
        for p in reversed(env["PYTHONPATH"].split(os.pathsep)):
            if p and p not in sys.path:
                sys.path.insert(0, p)

    n = int(header["payload_bytes"])
    blob = sys.stdin.buffer.read(n)
    if len(blob) != n:
        _emit(
            {
                "ok": False,
                "error": RuntimeError(
                    f"truncated payload: got {len(blob)}/{n} bytes"
                ),
            }
        )
        raise SystemExit(1)
    _arm_orphan_watchdog()

    # preemption watcher: a SIGTERM to this host's agent (spot reclaim,
    # maintenance drain, `kubectl delete pod` grace period) sets the
    # cross-thread flag the Trainer converts into a last-chance
    # checkpoint + Preempted exit.  TPUFRAME_PREEMPT_SIGNALS=0 opts out.
    if os.environ.get("TPUFRAME_PREEMPT_SIGNALS", "1") != "0":
        from tpuframe.fault import preempt

        preempt.install()

    if env.get("TPUFRAME_HB_PORT"):
        from tpuframe.core.native import maybe_start_beacon

        maybe_start_beacon()

    if env.get("TPUFRAME_SIMULATE_DEVICES"):
        # virtual CPU mesh for pod-topology tests; must beat any real
        # backend init AND undo an image sitecustomize's platform pin,
        # which simulate_cpu_devices handles (env + live jax config)
        from tpuframe.core.runtime import simulate_cpu_devices

        simulate_cpu_devices(int(env["TPUFRAME_SIMULATE_DEVICES"]))

    import cloudpickle

    fn, args, kwargs = cloudpickle.loads(blob)
    try:
        value = fn(*args, **kwargs)
    except BaseException as e:  # recorded in the frame, then re-raised
        try:
            cloudpickle.dumps(e)
            _emit({"ok": False, "error": e})
        except Exception:
            _emit({"ok": False, "error": RuntimeError(repr(e))})
        # distinguishable exit code (143): the driver's restart policy
        # can classify a preempted host without unpickling the frame
        from tpuframe.fault.preempt import reraise_for_exit

        reraise_for_exit(e)
    _emit({"ok": True, "value": value})


if __name__ == "__main__":
    main()
