"""Elastic recovery: checkpoint-resume restart loop.

The reference has no fault tolerance (SURVEY.md §5: "No elastic logic";
Ray merely *surfaces* failures via ``result.error``).  tpuframe's model:
training state lives in a :class:`tpuframe.ckpt.Checkpointer` with
auto-resume (``maybe_restore``), so recovery = rerun the train fn and let it
pick up the latest checkpoint.  :func:`run_with_restarts` drives that loop
with bounded retries and failure classification.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable

logger = logging.getLogger(__name__)

#: Exception types that are never worth retrying (bugs, not infra).
_FATAL = (KeyboardInterrupt, SystemExit, TypeError, ValueError, AttributeError)


def run_with_restarts(
    fn: Callable[[], Any],
    *,
    max_restarts: int = 2,
    backoff_s: float = 1.0,
    retryable: Callable[[BaseException], bool] | None = None,
    on_restart: Callable[[int, BaseException], None] | None = None,
) -> Any:
    """Run ``fn`` until success or retry budget exhaustion.

    ``fn`` must be resumable — i.e. it restores from its checkpointer on
    entry (the Trainer's ``maybe_restore`` does this) so a restart continues
    rather than recomputes.  ``retryable`` classifies failures (default:
    anything except obvious code bugs); ``on_restart(attempt, error)`` is the
    observability hook (log, page, mark the run).
    """

    def default_retryable(e: BaseException) -> bool:
        return not isinstance(e, _FATAL)

    retryable = retryable or default_retryable
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as e:
            if attempt >= max_restarts or not retryable(e):
                raise
            attempt += 1
            logger.warning(
                "train fn failed (%s); restart %d/%d after %.1fs",
                repr(e), attempt, max_restarts, backoff_s,
            )
            if on_restart is not None:
                on_restart(attempt, e)
            time.sleep(backoff_s)
