"""Elastic recovery: checkpoint-resume restart loops, now
**topology-shifting**.

Two layers:

- :func:`run_with_restarts` — the established equal-capacity entry
  point, a thin front for :mod:`tpuframe.fault.supervisor`
  (failure-classified budgets, jittered exponential backoff, pre-resume
  quarantine of torn checkpoints).
- :func:`run_elastic` — shrink-to-survivors supervision: before every
  attempt the supervisor probes surviving capacity; when the world
  shrank, this layer rebuilds the runtime mesh from the survivors,
  rebinds the ``ParallelPlan`` (``ParallelPlan.rebind``), and hands the
  train fn an :class:`ElasticContext` whose plan restores checkpoints
  **with reshard** (the topology manifest every committed step carries —
  ``tpuframe.ckpt``).  The run gives up only when survivors fall below
  ``min_world_size``.  :func:`rederive_batch_split` keeps the *global*
  batch constant across the resize so the data-order contract (the
  consumer-true loader position inside checkpoints) survives the shrink.

tpuframe's recovery model is unchanged: training state lives in a
:class:`tpuframe.ckpt.Checkpointer` with auto-resume (``maybe_restore``),
so recovery = rerun the train fn and let it pick up the newest committed
checkpoint — at whatever world size is still alive.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

from tpuframe.fault.supervisor import (
    FATAL_TYPES as _FATAL,  # noqa: F401  (compat re-export)
    FailureClass,
    RestartPolicy,
    Supervisor,
    classify_failure,
)
from tpuframe.track.telemetry import get_telemetry


def run_with_restarts(
    fn: Callable[[], Any],
    *,
    max_restarts: int = 2,
    backoff_s: float = 1.0,
    retryable: Callable[[BaseException], bool] | None = None,
    on_restart: Callable[[int, BaseException], None] | None = None,
    max_preemptions: int | None = None,
    backoff_max_s: float = 60.0,
    checkpoint_dir: str | None = None,
) -> Any:
    """Run ``fn`` until success or retry budget exhaustion.

    ``fn`` must be resumable — i.e. it restores from its checkpointer on
    entry (the Trainer's ``maybe_restore`` does this) so a restart continues
    rather than recomputes.  Failures are classified (preemption / retryable
    infra / fatal code bug — ``fault.supervisor.classify_failure``);
    ``retryable`` overrides the infra-vs-fatal split for non-preemption
    failures.  Retry delays follow full-jitter exponential backoff with
    ``backoff_s`` as the base and ``backoff_max_s`` the cap; preemption
    restarts are immediate and draw on their own ``max_preemptions``
    budget.  ``on_restart(attempt, error)`` is the observability hook
    (log, page, mark the run); ``checkpoint_dir`` additionally enables
    pre-resume validation (torn checkpoint steps are quarantined before
    every attempt).
    """
    classifier = None
    if retryable is not None:
        def classifier(e: BaseException) -> FailureClass:
            cls = classify_failure(e)
            if cls is FailureClass.PREEMPTION:
                return cls
            return (FailureClass.RETRYABLE if retryable(e)
                    else FailureClass.FATAL)

    policy = RestartPolicy(
        max_restarts=max_restarts,
        backoff_base_s=backoff_s,
        backoff_max_s=backoff_max_s,
    )
    if max_preemptions is not None:
        policy.max_preemptions = max_preemptions
    return Supervisor(
        policy,
        checkpoint_dir=checkpoint_dir,
        classifier=classifier,
        on_restart=on_restart,
    ).run(fn)


# -- topology-shifting supervision (shrink to survivors) ----------------------


@dataclasses.dataclass(frozen=True)
class ElasticContext:
    """What one supervised attempt needs to know about its world.

    ``plan`` is the :class:`~tpuframe.parallel.ParallelPlan` to train
    under **this attempt** — the original plan at full capacity, the
    rebound plan over the survivor mesh after a shrink.  Build the
    Trainer/TrainState from it and checkpoints restore-with-reshard
    automatically (the template's shardings are the reshard target).
    """

    attempt: int
    world_size: int
    initial_world_size: int
    plan: Any
    #: True when this attempt runs on a different world than the last one
    resized: bool

    @property
    def mesh(self):
        return self.plan.mesh


def simulated_survivor_probe(initial_world: int) -> Callable[[], int]:
    """Capacity probe for CPU chaos runs: the original world minus the
    ranks :class:`tpuframe.fault.LoseRank` injectors have killed (one
    simulated rank == one device).  Production supplies a real probe —
    k8s endpoints, TPU pod metadata, an orchestrator's member list."""
    from tpuframe.fault import chaos

    def probe() -> int:
        lost = sum(1 for r in chaos.lost_ranks() if 0 <= r < initial_world)
        return initial_world - lost

    return probe


def rederive_batch_split(
    global_batch: int,
    *,
    dp_size: int,
    grad_accum: int = 1,
    process_count: int = 1,
) -> dict:
    """Re-derive the per-process batch / grad-accum split for a new
    ``dp_size`` while holding the **global** batch fixed.

    The global batch is the data-order contract: checkpoints record the
    loader position in units of global batches, and the LR schedule is
    calibrated to it — so a world resize must change the *split*, never
    the product.  Keeps ``grad_accum`` when the microbatch still divides
    over the new shards; otherwise picks the nearest divisor of
    ``global_batch`` that does (one ``fault/batch_resplit`` event marks
    the change).  Raises when no split exists (``global_batch`` not a
    multiple of ``dp_size``).
    """
    if global_batch < 1 or dp_size < 1 or grad_accum < 1 or process_count < 1:
        raise ValueError("all batch-split inputs must be >= 1")
    if global_batch % process_count:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"{process_count} surviving process(es)"
        )
    candidates = sorted(
        (a for a in range(1, global_batch + 1) if global_batch % a == 0),
        key=lambda a: (abs(a - grad_accum), a),
    )
    for ga in candidates:
        if (global_batch // ga) % dp_size == 0:
            if ga != grad_accum:
                get_telemetry().event(
                    "fault/batch_resplit",
                    global_batch=global_batch,
                    dp_size=dp_size,
                    from_grad_accum=grad_accum,
                    to_grad_accum=ga,
                )
            return {
                "global_batch": global_batch,
                "local_batch": global_batch // process_count,
                "grad_accum": ga,
                "micro_batch": global_batch // ga // dp_size,
            }
    raise ValueError(
        f"no grad-accum split preserves global batch {global_batch} over "
        f"{dp_size} data-parallel shards — the global batch must be a "
        "multiple of the surviving dp size (shrink further or change "
        "the schedule deliberately)"
    )


def _survivor_context(
    base_plan: Any,
    base_devices: Sequence[Any],
    world: int,
    attempt: int,
    *,
    elastic_axis: str,
) -> ElasticContext:
    """Rebuild mesh + plan for ``world`` survivors of ``base_devices``.

    Survivor selection: the base mesh's device order minus chaos-lost
    ranks, truncated to ``world`` — a real multi-host deployment replaces
    this whole function via ``train_fn`` constructing its own runtime,
    but the contract (same axis layout, ``elastic_axis`` absorbs the
    change) is the one ``MeshSpec.shrink_to`` enforces either way."""
    from tpuframe.core.runtime import MeshSpec
    from tpuframe.fault import chaos

    world0 = len(base_devices)
    if world == world0:
        return ElasticContext(
            attempt=attempt, world_size=world, initial_world_size=world0,
            plan=base_plan, resized=False,
        )
    if world > world0:
        # the reshard-restore itself grows as readily as it shrinks, but
        # survivor selection is bounded by the base mesh's device list —
        # reporting a bigger world than the plan knows would silently
        # build a smaller mesh than fault/world_resized announced
        raise ValueError(
            f"capacity probe reports {world} device(s) but the base plan "
            f"only spans {world0}: growing beyond the original mesh needs "
            "a new base ParallelPlan over the larger device set (restart "
            "run_elastic with it; the checkpoint manifest reshards up at "
            "restore just the same)"
        )
    lost = chaos.lost_ranks()
    survivors = [d for i, d in enumerate(base_devices) if i not in lost]
    if len(survivors) < world:  # custom probe, no chaos registry
        survivors = list(base_devices)
    survivors = survivors[:world]
    spec = MeshSpec.from_mesh(base_plan.mesh).shrink_to(
        world, elastic_axis=elastic_axis
    )
    mesh = spec.build(survivors)
    return ElasticContext(
        attempt=attempt, world_size=world, initial_world_size=world0,
        plan=base_plan.rebind(mesh), resized=True,
    )


def run_elastic(
    train_fn: Callable[[ElasticContext], Any],
    *,
    plan: Any,
    policy: RestartPolicy | None = None,
    checkpoint_dir: str | None = None,
    capacity_probe: Callable[[], int] | None = None,
    min_world_size: int = 1,
    elastic_axis: str | None = None,
    **kwargs: Any,
) -> Any:
    """Supervise ``train_fn`` with **shrink-to-survivors** recovery.

    Each attempt: the supervisor probes surviving capacity
    (``capacity_probe``; default: the chaos lost-rank registry under the
    plan's original world — CPU simulation), rebuilds the mesh from the
    survivors when the world changed (``elastic_axis`` — default the
    ``data`` axis — absorbs the size change; TP/PP axes keep their
    layout or the rebuild refuses), rebinds ``plan``, and calls
    ``train_fn(ctx)``.  The fn builds its Trainer/TrainState from
    ``ctx.plan``; auto-resume then restores the last committed
    checkpoint **with reshard** (manifest-vs-target mismatch =>
    gather-or-slice at load, one ``fault/reshard`` event).  Below
    ``min_world_size`` survivors the supervisor gives up
    (:class:`~tpuframe.fault.WorldTooSmall`).

    All other knobs (``policy``, ``checkpoint_dir`` pre-resume
    quarantine, ``classifier``, ``on_restart``, ``sleep``) pass through
    to :class:`~tpuframe.fault.Supervisor`.
    """
    from tpuframe.core.runtime import DATA_AXIS

    base_devices = list(plan.mesh.devices.flat)
    if capacity_probe is None:
        capacity_probe = simulated_survivor_probe(len(base_devices))
    axis = elastic_axis or DATA_AXIS
    attempts = {"n": 0}

    def attempt(world: int) -> Any:
        attempts["n"] += 1
        # a (re)started attempt runs on a (re)built world: re-arm the
        # fleet-gather ladder a dead peer may have degraded to sticky
        # local-only — the peer that wedged it is no longer in this mesh
        from tpuframe.track.analyze import reset_fleet_degraded

        reset_fleet_degraded()
        ctx = _survivor_context(
            plan, base_devices, int(world), attempts["n"], elastic_axis=axis
        )
        return train_fn(ctx)

    return Supervisor(
        policy,
        checkpoint_dir=checkpoint_dir,
        capacity_probe=capacity_probe,
        min_world_size=min_world_size,
        **kwargs,
    ).run(attempt)
