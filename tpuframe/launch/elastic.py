"""Elastic recovery: checkpoint-resume restart loop.

Thin compatibility front for :mod:`tpuframe.fault.supervisor` — the
original 58-line constant-backoff loop grew into a real subsystem
(failure-classified budgets, exponential backoff with full jitter,
pre-resume quarantine of torn checkpoints) and lives there now.  This
entry point keeps the established signature: ``backoff_s`` is the *base*
delay of the jittered exponential schedule, and ``retryable`` still
overrides failure classification.

tpuframe's recovery model is unchanged: training state lives in a
:class:`tpuframe.ckpt.Checkpointer` with auto-resume (``maybe_restore``),
so recovery = rerun the train fn and let it pick up the newest committed
checkpoint.
"""

from __future__ import annotations

from typing import Any, Callable

from tpuframe.fault.supervisor import (
    FATAL_TYPES as _FATAL,  # noqa: F401  (compat re-export)
    FailureClass,
    RestartPolicy,
    Supervisor,
    classify_failure,
)


def run_with_restarts(
    fn: Callable[[], Any],
    *,
    max_restarts: int = 2,
    backoff_s: float = 1.0,
    retryable: Callable[[BaseException], bool] | None = None,
    on_restart: Callable[[int, BaseException], None] | None = None,
    max_preemptions: int | None = None,
    backoff_max_s: float = 60.0,
    checkpoint_dir: str | None = None,
) -> Any:
    """Run ``fn`` until success or retry budget exhaustion.

    ``fn`` must be resumable — i.e. it restores from its checkpointer on
    entry (the Trainer's ``maybe_restore`` does this) so a restart continues
    rather than recomputes.  Failures are classified (preemption / retryable
    infra / fatal code bug — ``fault.supervisor.classify_failure``);
    ``retryable`` overrides the infra-vs-fatal split for non-preemption
    failures.  Retry delays follow full-jitter exponential backoff with
    ``backoff_s`` as the base and ``backoff_max_s`` the cap; preemption
    restarts are immediate and draw on their own ``max_preemptions``
    budget.  ``on_restart(attempt, error)`` is the observability hook
    (log, page, mark the run); ``checkpoint_dir`` additionally enables
    pre-resume validation (torn checkpoint steps are quarantined before
    every attempt).
    """
    classifier = None
    if retryable is not None:
        def classifier(e: BaseException) -> FailureClass:
            cls = classify_failure(e)
            if cls is FailureClass.PREEMPTION:
                return cls
            return (FailureClass.RETRYABLE if retryable(e)
                    else FailureClass.FATAL)

    policy = RestartPolicy(
        max_restarts=max_restarts,
        backoff_base_s=backoff_s,
        backoff_max_s=backoff_max_s,
    )
    if max_preemptions is not None:
        policy.max_preemptions = max_preemptions
    return Supervisor(
        policy,
        checkpoint_dir=checkpoint_dir,
        classifier=classifier,
        on_restart=on_restart,
    ).run(fn)
