"""Process/runtime initialization and device-mesh construction.

TPU-native replacement for the reference's process-group bootstrap:

- ``dist.init_process_group("nccl")``
  (`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:269`)
  becomes :func:`initialize` — ``jax.distributed.initialize`` for multi-host
  rendezvous over DCN, with collectives compiled into XLA programs over ICI.
- The torchrun env contract (``RANK``/``LOCAL_RANK``/``WORLD_SIZE`` read at
  `01_basic_torch_distributor.py:271-272`,
  `/root/reference/02_deepspeed/01_cifar_deepspeed_resnet.py:213-216`) maps to
  the coordinator env contract honoured here (``TPUFRAME_COORDINATOR`` /
  ``MASTER_ADDR:MASTER_PORT``, ``WORLD_SIZE`` = host processes, ``RANK``).
- The cluster-topology probe (`/root/reference/setup/00_setup.py:105-113`, a
  Spark map job counting GPUs) becomes plain ``jax.device_count()`` /
  ``jax.local_device_count()`` — the TPU runtime already knows its topology.

Parallelism is expressed on a named :class:`jax.sharding.Mesh`; axis names are
the framework-wide vocabulary used by every PartitionSpec in tpuframe.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

# Framework-wide mesh-axis vocabulary.  Order below is the physical layout
# order (outermost -> innermost): axes that carry the most traffic (model/TP)
# sit innermost so their collectives ride nearest-neighbour ICI links.
PIPELINE_AXIS = "pipe"
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
SEQUENCE_AXIS = "seq"
EXPERT_AXIS = "expert"
MODEL_AXIS = "model"

AXIS_ORDER = (PIPELINE_AXIS, DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS, EXPERT_AXIS, MODEL_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; ``-1`` on at most one axis means "all remaining".

    >>> MeshSpec(data=-1).build()          # pure data parallel
    >>> MeshSpec(data=-1, model=2).build() # DP x TP
    >>> MeshSpec(data=2, fsdp=2, model=2)  # DP x ZeRO-3 x TP on 8 chips
    """

    pipe: int = 1
    data: int = -1
    fsdp: int = 1
    seq: int = 1
    expert: int = 1
    model: int = 1

    def sizes(self) -> dict[str, int]:
        return {
            PIPELINE_AXIS: self.pipe,
            DATA_AXIS: self.data,
            FSDP_AXIS: self.fsdp,
            SEQUENCE_AXIS: self.seq,
            EXPERT_AXIS: self.expert,
            MODEL_AXIS: self.model,
        }

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Concrete axis sizes for ``n_devices``, filling one ``-1`` axis."""
        sizes = self.sizes()
        bad = {n: s for n, s in sizes.items() if s != -1 and s < 1}
        if bad:
            raise ValueError(f"mesh axis sizes must be -1 or >= 1, got {bad}")
        wildcard = [name for name, size in sizes.items() if size == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wildcard}")
        fixed = int(np.prod([s for s in sizes.values() if s != -1]))
        if wildcard:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices but {n_devices} are visible"
            )
        return sizes

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        """Construct a named Mesh over ``devices`` (default: all devices).

        Axis types are ``Auto`` (GSPMD propagation): tpuframe's ParallelPlan
        constrains inputs/outputs and lets the partitioner place every
        intermediate — jax 0.9's ``make_mesh`` default of ``Explicit`` would
        instead demand a sharding proof per op.
        """
        devices = list(devices) if devices is not None else jax.devices()
        sizes = self.resolve(len(devices))
        shape = tuple(sizes[name] for name in AXIS_ORDER)
        try:
            kw = {"axis_types": (jax.sharding.AxisType.Auto,) * len(AXIS_ORDER)}
        except AttributeError:  # older jax: no AxisType — Auto is the only mode
            kw = {}
        if devices == jax.devices() and hasattr(jax, "make_mesh"):
            # jax.make_mesh picks an ICI-friendly physical ordering.
            return jax.make_mesh(shape, AXIS_ORDER, **kw)
        grid = np.asarray(devices).reshape(shape)
        return Mesh(grid, AXIS_ORDER, **kw)

    @classmethod
    def from_config(cls, cfg: Mapping[str, int]) -> "MeshSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; known: {sorted(known)}")
        return cls(**{k: int(v) for k, v in cfg.items()})

    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "MeshSpec":
        """The concrete spec of an already-built mesh (every axis fixed,
        no wildcard) — the starting point for re-deriving a spec over a
        different world size (:meth:`shrink_to`)."""
        return cls.from_config(
            {name: int(size) for name, size in mesh.shape.items()}
        )

    def shrink_to(self, n_devices: int, *, elastic_axis: str = DATA_AXIS) -> "MeshSpec":
        """The equivalent spec for a smaller/larger world: ``elastic_axis``
        (default ``data``) absorbs the size change, every other axis keeps
        its layout.  Raises when the fixed axes no longer fit — losing a
        host out of a TP/PP group cannot be absorbed by data parallelism,
        and silently reshaping model parallelism would change the program.
        """
        sizes = dict(self.sizes())
        wildcard = [n for n, s in sizes.items() if s == -1 and n != elastic_axis]
        if wildcard:
            raise ValueError(
                f"shrink_to needs a fully-resolved spec (use "
                f"MeshSpec.from_mesh on the built mesh); axis {wildcard} "
                "is still a wildcard"
            )
        sizes[elastic_axis] = -1
        fixed = int(np.prod([s for n, s in sizes.items() if n != elastic_axis]))
        if n_devices < 1 or n_devices % fixed:
            raise ValueError(
                f"cannot rebuild mesh for {n_devices} device(s): the fixed "
                f"axes {({n: s for n, s in sizes.items() if n != elastic_axis and s > 1})} "
                f"need a multiple of {fixed} — shrink in units of whole "
                f"{elastic_axis}-groups or lower min_world_size no further"
            )
        return MeshSpec.from_config(sizes)


@dataclasses.dataclass
class Runtime:
    """Everything a train function needs to know about where it is running."""

    mesh: Mesh
    spec: MeshSpec
    process_index: int
    process_count: int
    platform: str

    @property
    def is_main(self) -> bool:
        return self.process_index == 0

    @property
    def device_count(self) -> int:
        return self.mesh.devices.size

    @property
    def local_device_count(self) -> int:
        return jax.local_device_count()

    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding on this runtime's mesh, e.g. ``rt.sharding("data")``."""
        return NamedSharding(self.mesh, P(*spec))

    def data_sharding(self) -> NamedSharding:
        """Batch-dimension sharding over every data-ish axis (data+fsdp)."""
        return NamedSharding(self.mesh, P((DATA_AXIS, FSDP_AXIS)))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


_CURRENT: Runtime | None = None


def initialize(
    mesh: MeshSpec | Mapping[str, int] | None = None,
    *,
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    platform: str | None = None,
    debug: bool | None = None,
) -> Runtime:
    """Initialize the distributed runtime and build the global mesh.

    Single-process (one host, N local chips) needs no arguments.  Multi-host
    needs a coordinator (≈ ``MASTER_ADDR:MASTER_PORT`` rendezvous in the
    reference's torchrun contract); values fall back to env vars
    ``TPUFRAME_COORDINATOR`` (or ``MASTER_ADDR``+``MASTER_PORT``),
    ``WORLD_SIZE``/``TPUFRAME_NUM_PROCESSES``, ``RANK``/``TPUFRAME_PROCESS_ID``.

    ``debug=True`` (or env ``TPUFRAME_DEBUG=1``) is the XLA counterpart of
    the reference's CUDA debug env block (`setup/00_setup.py:66-67,117-123`
    — ``CUDA_LAUNCH_BLOCKING``/``TORCH_DISTRIBUTED_DEBUG``): enables
    ``jax_debug_nans`` (first NaN raises at the op that produced it,
    de-optimizing like launch-blocking does) and ``jax_disable_most_optimizations``
    for deterministic, debuggable compiles.  Leave off for performance runs.
    """
    global _CURRENT

    # TPUFRAME_COMMS_ASYNC: merge the latency-hiding-scheduler /
    # async-collective-fusion flags into XLA_FLAGS FIRST — XLA reads
    # them at backend init, and everything below (distributed init,
    # mesh build) can trigger that.  The resolver is platform-gated
    # without importing a backend (asking jax would initialize it), and
    # returns the empty set on CPU where the flags would abort the
    # compiler; restart-only semantics, like every comms knob.
    _apply_comms_async_flags()

    if debug is None:
        debug = os.environ.get("TPUFRAME_DEBUG", "").strip().lower() not in (
            "", "0", "false", "no", "off",
        )
    if debug:
        global _DEBUG_FLAGS_SET
        jax.config.update("jax_debug_nans", True)
        jax.config.update("jax_disable_most_optimizations", True)
        _DEBUG_FLAGS_SET = True

    # persistent compilation cache: on by default (opt out with
    # TPUFRAME_COMPILE_CACHE=0) so every process that initializes a
    # runtime — driver, launch worker, supervised restart — compiles
    # against the same host-shared cache.  Enabled before any mesh/jit
    # work so even the first compile of this process is cacheable.
    from tpuframe.compile import cache as _compile_cache

    _compile_cache.enable_from_env()

    coordinator_address = coordinator_address or _env_coordinator()
    if num_processes is None:
        num_processes = _env_int("TPUFRAME_NUM_PROCESSES", "WORLD_SIZE")
    if process_id is None:
        process_id = _env_int("TPUFRAME_PROCESS_ID", "RANK")

    multi_host = (num_processes or 1) > 1
    if multi_host or (coordinator_address and num_processes is not None):
        # A half-specified multi-host config must fail loudly, not degrade to
        # N independent rank-0 processes all claiming main-process duties.
        if not coordinator_address or num_processes is None or process_id is None:
            raise ValueError(
                "multi-host init requires coordinator_address, num_processes and "
                f"process_id (got coordinator={coordinator_address!r}, "
                f"num_processes={num_processes!r}, process_id={process_id!r}); "
                "set TPUFRAME_COORDINATOR/MASTER_ADDR, WORLD_SIZE and RANK"
            )
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    if isinstance(mesh, Mapping):
        mesh = MeshSpec.from_config(mesh)
    spec = mesh or MeshSpec()
    built = spec.build()
    _CURRENT = Runtime(
        mesh=built,
        spec=spec,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        platform=platform or jax.default_backend(),
    )
    logger.info(
        "tpuframe runtime: %d device(s) on %s, mesh %s, process %d/%d",
        _CURRENT.device_count,
        _CURRENT.platform,
        dict(zip(built.axis_names, built.devices.shape)),
        _CURRENT.process_index,
        _CURRENT.process_count,
    )
    return _CURRENT


def current_runtime(auto_init: bool = True) -> Runtime:
    """The active Runtime; lazily initializes a default one if allowed."""
    global _CURRENT
    if _CURRENT is None:
        if not auto_init:
            raise RuntimeError("tpuframe runtime not initialized; call core.initialize()")
        initialize()
    return _CURRENT


_DEBUG_FLAGS_SET = False


def reset_runtime() -> None:
    """Drop the cached Runtime (tests / re-init with a different mesh).

    Clears the debug-mode jax flags only when ``initialize(debug=True)``
    set them — flags the user enabled directly are left alone."""
    global _CURRENT, _DEBUG_FLAGS_SET
    _CURRENT = None
    if _DEBUG_FLAGS_SET:
        jax.config.update("jax_debug_nans", False)
        jax.config.update("jax_disable_most_optimizations", False)
        _DEBUG_FLAGS_SET = False


def shard_map(f, **kwargs):
    """``jax.shard_map`` across the supported jax range: the public API
    (jax >= 0.6, ``check_vma=`` kwarg) when present, else the experimental
    one (same semantics, the kwarg was named ``check_rep``).  Call sites
    pass ``f`` positionally and everything else by keyword."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn(f, **kwargs)
    from jax.experimental.shard_map import shard_map as exp_shard_map

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return exp_shard_map(f, **kwargs)


def named_axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside a shard_map body —
    ``jax.lax.axis_size`` where it exists (jax >= 0.6), else the older
    axis-env frame (which on the 0.4 line already resolves to the int)."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        import jax.core as jax_core

        frame = jax_core.axis_frame(axis_name)
        return int(getattr(frame, "size", frame))


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_main_process() -> bool:
    """Rank-0 discipline gate, used by track/ and ckpt/ (the reference checks
    ``global_rank == 0`` before every MLflow/checkpoint call, e.g.
    `/root/reference/01_torch_distributor/01_basic_torch_distributor.py:236-237`)."""
    return jax.process_index() == 0


def _apply_comms_async_flags() -> None:
    """Merge the ``TPUFRAME_COMMS_ASYNC`` flag set into ``XLA_FLAGS``
    (idempotent: flags already present are not duplicated).  No-op when
    the knob is off or the platform resolves no flags."""
    from tpuframe.parallel.comms_env import comms_async_flags

    wanted = comms_async_flags()
    if not wanted:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    missing = [f for f in wanted if f.split("=")[0] not in flags]
    if missing:
        os.environ["XLA_FLAGS"] = " ".join([flags] + missing).strip()


def simulate_cpu_devices(n: int = 8) -> None:
    """Force ``n`` virtual CPU devices (multi-chip simulation).

    Must run before JAX initializes its backends — typically at the top of a
    test conftest or as env config of a spawned worker.  This is the TPU-world
    answer to "test multi-node without a cluster" (SURVEY.md §4).  Overrides
    any pre-existing device-count flag or platform selection (including a
    sitecustomize that pinned a TPU plugin platform).
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "--xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = f"{flags} {flag}".strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    # jax may already be imported (it is, by this module); the env var alone is
    # then too late for jax.config's import-time default.
    jax.config.update("jax_platforms", "cpu")


def _env_coordinator() -> str | None:
    addr = os.environ.get("TPUFRAME_COORDINATOR")
    if addr:
        return addr
    host = os.environ.get("MASTER_ADDR")
    if host:
        port = os.environ.get("MASTER_PORT", "29500")
        return f"{host}:{port}"
    return None


def _env_int(*names: str) -> int | None:
    for name in names:
        value = os.environ.get(name)
        if value is not None:
            return int(value)
    return None
