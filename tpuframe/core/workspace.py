"""Workspace: the idempotent storage-layout bootstrap.

The reference bootstraps its storage locations with ``CREATE CATALOG /
SCHEMA / VOLUME IF NOT EXISTS`` against Unity Catalog
(`/root/reference/setup/00_setup.py:27-54`: one volume per dataset —
cifar, tiny_imagenet, imagenet_1k, ms_coco) and exports credentials for
worker re-auth (`setup/00_setup.py:86-92`).  The TPU-world equivalent is
a filesystem contract: one workspace root (local disk, NFS, or a mounted
bucket) with a fixed layout every subsystem agrees on, created
idempotently, plus an env channel that ships tracking credentials to
worker processes.

>>> ws = Workspace("/mnt/experiments/run42")
>>> ws.dataset_dir("cifar10")        # ≈ the cifar UC volume
>>> ws.shards_dir("tiny_imagenet")   # TFS shard root ("remote")
>>> ws.checkpoints, ws.mlruns        # orbax root, tracking store
>>> ws.local_scratch()               # per-host cache (≈ /local_disk0)
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Mapping

#: layout version written to the root marker; bump on breaking changes
LAYOUT_VERSION = 1

#: Input-pipeline / kernel-dispatch / debug knobs that must reach every
#: worker — the fifth spine knob list, aggregated by
#: ``launch.remote.all_env_vars()`` next to OBSERVABILITY/COMPILE/HEALTH/
#: SERVE.  Declared here (stdlib-only module) so the aggregate resolves
#: on a wedged-backend doctor run; documented in PERF.md.  A knob read
#: anywhere in tpuframe that appears in no ``*_ENV_VARS`` list is a
#: ``tpuframe.lint`` finding (KN001) — that is what keeps this list and
#: its consumers honest.
PERF_ENV_VARS = (
    "TPUFRAME_NATIVE_JPEG",
    "TPUFRAME_JPEG_THREADS",
    "TPUFRAME_DISABLE_PALLAS",
    "TPUFRAME_PALLAS_INTERPRET",
    "TPUFRAME_DEBUG",
    "TPUFRAME_CKPT_DIR",
    "TPUFRAME_LOADER_WORKERS",
    "TPUFRAME_LOADER_RING_BUFFERS",
    "TPUFRAME_LOADER_TRANSFER_DTYPE",
    "TPUFRAME_PREFETCH_DEPTH",
    "TPUFRAME_GRAD_ACCUM",
    "TPUFRAME_CKPT_INTERVAL_BATCHES",
)

#: value domains for the knobs above (KN007).  ``apply`` semantics per
#: AUTOTUNE.md: the loader/prefetch/grad-accum knobs are env-defaults
#: resolved when DataLoader/Trainer objects are built -> "restart"
#: (a supervised restart — or a fresh probe run — picks them up);
#: TPUFRAME_CKPT_INTERVAL_BATCHES is re-read by the running Trainer's
#: step loop via ``Trainer.apply_tuned`` -> "live".
PERF_ENV_DOMAINS = {
    "TPUFRAME_NATIVE_JPEG": {"type": "bool", "apply": "restart"},
    "TPUFRAME_JPEG_THREADS": {
        "type": "int", "range": (1, None), "apply": "restart"},
    "TPUFRAME_DISABLE_PALLAS": {"type": "bool", "apply": "restart"},
    "TPUFRAME_PALLAS_INTERPRET": {"type": "bool", "apply": "restart"},
    "TPUFRAME_DEBUG": {"type": "bool", "apply": "restart"},
    "TPUFRAME_CKPT_DIR": {"type": "path", "apply": "restart"},
    "TPUFRAME_LOADER_WORKERS": {
        "type": "int", "range": (0, 64), "apply": "restart"},
    "TPUFRAME_LOADER_RING_BUFFERS": {
        "type": "int", "range": (2, 64), "apply": "restart"},
    "TPUFRAME_LOADER_TRANSFER_DTYPE": {
        "type": "enum", "choices": ("uint8", "float32"), "apply": "restart"},
    "TPUFRAME_PREFETCH_DEPTH": {
        "type": "int", "range": (1, 16), "apply": "restart"},
    "TPUFRAME_GRAD_ACCUM": {
        "type": "int", "range": (1, 256), "apply": "restart"},
    "TPUFRAME_CKPT_INTERVAL_BATCHES": {
        "type": "int", "range": (1, None), "apply": "live"},
}


@dataclasses.dataclass(frozen=True)
class Workspace:
    """Canonical directory layout under one root, created on first access.

    Everything is idempotent — calling any accessor twice, or from many
    processes at once, is safe (``os.makedirs(exist_ok=True)`` semantics,
    like the reference's ``IF NOT EXISTS`` SQL).
    """

    root: str

    def __post_init__(self):
        object.__setattr__(self, "root", os.path.abspath(os.fspath(self.root)))
        self._ensure(self.root)
        marker = os.path.join(self.root, ".tpuframe-workspace")
        if not os.path.exists(marker):
            tmp = f"{marker}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                f.write(f"version: {LAYOUT_VERSION}\n")
            os.replace(tmp, marker)  # atomic vs concurrent bootstrappers

    @staticmethod
    def _ensure(path: str) -> str:
        os.makedirs(path, exist_ok=True)
        return path

    # -- shared (workspace-root) locations ---------------------------------
    @property
    def checkpoints(self) -> str:
        """Orbax checkpoint root (pass to ckpt.Checkpointer)."""
        return self._ensure(os.path.join(self.root, "checkpoints"))

    @property
    def mlruns(self) -> str:
        """File-store tracking URI (pass to MLflowLogger/set_experiment)."""
        return self._ensure(os.path.join(self.root, "mlruns"))

    def dataset_dir(self, name: str) -> str:
        """Raw-dataset cache, one dir per dataset (≈ the UC volumes,
        `setup/00_setup.py:38-53`)."""
        return self._ensure(os.path.join(self.root, "datasets", name))

    def shards_dir(self, name: str) -> str:
        """TFS shard root for ``name`` — the StreamingDataset 'remote'."""
        return self._ensure(os.path.join(self.root, "shards", name))

    def run_dir(self, run_name: str) -> str:
        """Per-run scratch for launcher APIs (Ray RunConfig.storage_path
        parity, `05_ray/01_fashion_mnist_pytorch_ray.ipynb:cell-7`)."""
        return self._ensure(os.path.join(self.root, "runs", run_name))

    # -- per-host locations -------------------------------------------------
    def local_scratch(self, subdir: str = "") -> str:
        """Fast host-local cache (≈ ``/local_disk0/mds``,
        `03a_…_mds.py:382-390`): stays on this machine even when the
        workspace root is shared storage.  Keyed by the env process rank
        (no jax dependency — usable before backend init)."""
        base = os.environ.get("TPUFRAME_LOCAL_SCRATCH") or os.path.join(
            tempfile.gettempdir(), "tpuframe_scratch"
        )
        rank = os.environ.get("TPUFRAME_PROCESS_ID") or os.environ.get("RANK", "0")
        return self._ensure(os.path.join(base, f"host{rank}", subdir))


def export_worker_env(
    credentials: Mapping[str, str], overwrite: bool = True
) -> None:
    """Export credentials into this process's env so spawned workers
    inherit them — the reference's ``DATABRICKS_HOST/TOKEN`` export for
    child re-auth (`setup/00_setup.py:86-92`).  Typical keys:
    ``MLFLOW_TRACKING_TOKEN``, ``MLFLOW_TRACKING_USERNAME/PASSWORD``,
    ``TPUFRAME_CP_TOKEN``.  Values never transit the pickled payload —
    env only, like the reference."""
    for key, value in credentials.items():
        if overwrite or key not in os.environ:
            os.environ[key] = str(value)
