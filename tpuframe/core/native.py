"""Native (C++) extensions: lazy g++ build + ctypes bindings.

Three components (SURVEY.md §2.3 — the native layers the reference
consumes from its dependency stack):

- :class:`ZstdCodec` — batch shard decompression on a GIL-free thread pool
  (``tpuframe/_native/codec.cpp``), the mosaicml-streaming-native-codec
  equivalent feeding the TFS streaming reader.
- :class:`JpegDecoder` — batch JPEG decode via libjpeg(-turbo) on the
  same thread-pool shape (``tpuframe/_native/jpegdec.cpp``).  Pillow's
  decoders hold the GIL, capping thread-worker decode at ~1 core; this
  path scales across cores toward the chip's ~2.2k img/s ingest
  (SURVEY §7 "input pipeline feeding HBM", PERF.md sizing).
- :class:`ControlPlane` — TCP rendezvous + barrier/broadcast/allgather of
  host-side byte payloads (``tpuframe/_native/controlplane.cpp``), the
  c10d/torchrun control surface (run-id broadcast, pre-jax rendezvous).
  Works BEFORE `jax.distributed.initialize` — it is how hosts can agree on
  a coordinator in the first place.

Sources ship in-repo and compile lazily with g++ into
``tpuframe/_native/build/`` keyed by a source hash; environments without a
toolchain get ``native_available() == False`` and pure-Python fallbacks
(the `zstandard` module; single-process no-op control plane).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Sequence

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "_native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "build")
_LOCK = threading.Lock()
_LIBS: dict[str, ctypes.CDLL | None] = {}

#: hash-keyed .so builds kept per library (newest first).  More than one:
#: two long-lived processes on different source versions of a shared
#: checkout would otherwise delete each other's current build on every
#: compile and ping-pong full g++ rebuilds forever (ADVICE r05 #4).
_KEEP_BUILDS = max(1, int(os.environ.get("TPUFRAME_NATIVE_KEEP_BUILDS", "3")))


def _prune_stale_builds(build_dir: str, name: str, current_so: str,
                        keep: int = _KEEP_BUILDS) -> list[str]:
    """Delete this library's hash-keyed builds beyond the ``keep`` newest
    (the just-written ``current_so`` always survives).  Returns the
    basenames removed.  Safe on Linux even if another process still has a
    victim dlopened; a not-yet-dlopened process rebuilds from its own
    source and retries."""
    prefix, removed = f"lib{name}.", []
    try:
        entries = os.listdir(build_dir)
    except OSError:
        return removed
    candidates = []
    for base in entries:
        if not (base.startswith(prefix) and base.endswith(".so")):
            continue
        path = os.path.join(build_dir, base)
        if base == os.path.basename(current_so):
            continue
        try:
            candidates.append((os.path.getmtime(path), base))
        except OSError:
            continue  # concurrently pruned by another process
    candidates.sort(reverse=True)
    for _, base in candidates[max(0, keep - 1):]:  # current counts toward keep
        try:
            os.remove(os.path.join(build_dir, base))
            removed.append(base)
        except OSError:
            pass
    return removed


def _build_and_load(name: str, source: str, extra_libs: Sequence[str]) -> ctypes.CDLL | None:
    """Compile ``source`` (if stale) and dlopen it; None if unavailable."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        lib = None
        try:
            src_path = os.path.join(_NATIVE_DIR, source)
            with open(src_path, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
            os.makedirs(_BUILD_DIR, exist_ok=True)
            so_path = os.path.join(_BUILD_DIR, f"lib{name}.{digest}.so")

            def build() -> None:
                tmp = f"{so_path}.tmp.{os.getpid()}"
                cmd = [
                    "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                    src_path, "-o", tmp, "-lpthread",
                ] + [f"-l{l}" for l in extra_libs]
                subprocess.run(
                    cmd, check=True, capture_output=True, timeout=120
                )
                os.replace(tmp, so_path)  # atomic vs. concurrent builders
                _prune_stale_builds(_BUILD_DIR, name, so_path)

            if not os.path.exists(so_path):
                build()
            try:
                lib = ctypes.CDLL(so_path)
            except OSError:
                # a concurrent newer-source process's cleanup may have
                # unlinked our digest between the exists-check and dlopen;
                # rebuild from OUR source and retry once
                build()
                lib = ctypes.CDLL(so_path)
        except Exception:
            lib = None
        _LIBS[name] = lib
        return lib


def _codec_lib():
    lib = _build_and_load("tfscodec", "codec.cpp", ["zstd"])
    if lib is not None and not getattr(lib, "_tf_sigs", False):
        lib.tfs_compress_bound.restype = ctypes.c_size_t
        lib.tfs_compress_bound.argtypes = [ctypes.c_size_t]
        lib.tfs_frame_content_size.restype = ctypes.c_uint64
        lib.tfs_frame_content_size.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.tfs_compress.restype = ctypes.c_int
        lib.tfs_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t), ctypes.c_int,
        ]
        lib.tfs_batch_decompress.restype = ctypes.c_int
        lib._tf_sigs = True
    return lib


def native_available() -> bool:
    """True when the C++ codec built (toolchain + libzstd present)."""
    return _codec_lib() is not None


def _jpeg_lib():
    lib = _build_and_load("tfjpeg", "jpegdec.cpp", ["jpeg"])
    if lib is not None and not getattr(lib, "_tf_sigs", False):
        pp = ctypes.POINTER(ctypes.c_char_p)
        lib.tfj_dims.restype = ctypes.c_int
        lib.tfj_dims.argtypes = [
            pp, ctypes.POINTER(ctypes.c_size_t), ctypes.c_int,
            ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ]
        lib.tfj_decode_batch.restype = ctypes.c_int
        lib.tfj_decode_batch.argtypes = [
            pp, ctypes.POINTER(ctypes.c_size_t), pp,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int,
        ]
        lib._tf_sigs = True
    return lib


def jpeg_native_available() -> bool:
    """True when the C++ JPEG decoder built (toolchain + libjpeg)."""
    return _jpeg_lib() is not None


#: decompression-bomb fallback when PIL isn't importable: PIL's own
#: default MAX_IMAGE_PIXELS (0.25 GiB of 32-bit pixels)
_DEFAULT_MAX_PIXELS = 178956970


def _pil_max_pixels() -> int:
    try:
        from PIL import Image

        # a user's Image.MAX_IMAGE_PIXELS = None disables PIL's guard;
        # mirror that as "no budget"
        return Image.MAX_IMAGE_PIXELS or (1 << 62)
    except ImportError:
        return _DEFAULT_MAX_PIXELS


class JpegDecoder:
    """Batch JPEG decode backed by libjpeg(-turbo) on a C++ thread pool.

    Returns HWC uint8 arrays — RGB for color images, HW for grayscale
    (matching PIL's ``np.asarray(Image.open(...))`` shapes so the two
    decode paths are drop-in interchangeable).  Exotic color spaces
    (CMYK/YCCK) fail the item; callers fall back to PIL for those.

    ``max_pixels`` (default: PIL's ``Image.MAX_IMAGE_PIXELS``) bounds
    header-declared output size *before* any allocation: a
    few-hundred-byte JPEG claiming 65500x65500 would otherwise force a
    ~12.8 GB allocation per item (the decompression-bomb guard PIL
    enforces and the native fast path must not bypass, ADVICE r05 #3).
    Oversized items raise ValueError — callers fall back to PIL, whose
    own bomb limit then decides.
    """

    def __init__(self, n_threads: int | None = None,
                 max_pixels: int | None = None):
        self._lib = _jpeg_lib()
        if self._lib is None:
            raise RuntimeError("native jpeg decoder unavailable (no g++/libjpeg)")
        self.n_threads = n_threads or min(8, os.cpu_count() or 1)
        self.max_pixels = _pil_max_pixels() if max_pixels is None else int(max_pixels)

    def decode_batch(self, blobs: Sequence[bytes],
                     min_hw: tuple | None = None) -> list:
        """Decode many JPEGs in one GIL-free C call.

        ``min_hw=(h, w)`` enables fused decode-at-scale: each image is
        decoded at the smallest DCT scale M/8 whose output still covers
        (h, w) — most of a downstream ``Resize`` happens inside the IDCT
        for ~free, at a fraction of a full decode's cost.  The output is
        the scaled size (>= min_hw per dimension, never upscaled); an
        exact-size finisher resize, if still needed, is the caller's.
        """
        import numpy as np

        n = len(blobs)
        if n == 0:
            return []
        min_h, min_w = (int(min_hw[0]), int(min_hw[1])) if min_hw else (0, 0)
        src_arr = (ctypes.c_char_p * n)(*blobs)
        src_p = ctypes.cast(src_arr, ctypes.POINTER(ctypes.c_char_p))
        sizes = (ctypes.c_size_t * n)(*[len(b) for b in blobs])
        dims = (ctypes.c_int32 * (3 * n))()
        rc = self._lib.tfj_dims(src_p, sizes, n, min_h, min_w, dims)
        if rc != 0:
            raise ValueError(f"invalid JPEG header at item {rc - 1}")
        # Decompression-bomb guard BEFORE any allocation: budget the
        # header-DECLARED dims (PIL's Image.open semantics), not the
        # scaled output — fused decode-at-scale shrinks the buffer up to
        # 64x but the entropy-decode cost still tracks the declared size.
        if self.max_pixels:
            decl = dims
            if min_h or min_w:  # dims above are at the covering M/8 scale
                decl = (ctypes.c_int32 * (3 * n))()
                self._lib.tfj_dims(src_p, sizes, n, 0, 0, decl)
            for i in range(n):
                h, w = int(decl[3 * i]), int(decl[3 * i + 1])
                if h * w > self.max_pixels:
                    raise ValueError(
                        f"image {i}: header declares {h}x{w} = {h * w} "
                        f"pixels, over the {self.max_pixels}-pixel budget "
                        "(decompression-bomb guard)"
                    )
        outs = []
        for i in range(n):
            h, w, c = dims[3 * i], dims[3 * i + 1], dims[3 * i + 2]
            shape = (h, w, 3) if c == 3 else (h, w)
            outs.append(np.empty(shape, np.uint8))
        dst_arr = (ctypes.c_void_p * n)(*[out.ctypes.data for out in outs])
        rc = self._lib.tfj_decode_batch(
            src_p, sizes,
            ctypes.cast(dst_arr, ctypes.POINTER(ctypes.c_char_p)),
            dims, n, min_h, min_w, self.n_threads,
        )
        if rc != 0:
            raise ValueError(f"JPEG decode failed at item {rc - 1}")
        return outs

    def decode(self, blob: bytes, min_hw: tuple | None = None):
        return self.decode_batch([blob], min_hw=min_hw)[0]


class ZstdCodec:
    """Batch zstd codec backed by the C++ thread pool.

    ``decompress_batch`` releases the GIL for the whole batch — shard
    blocks decode in parallel while Python goes on prefetching.
    """

    def __init__(self, n_threads: int | None = None):
        self._lib = _codec_lib()
        if self._lib is None:
            raise RuntimeError("native codec unavailable (no g++/libzstd)")
        self.n_threads = n_threads or min(8, os.cpu_count() or 1)

    def compress(self, data: bytes, level: int = 3) -> bytes:
        lib = self._lib
        cap = lib.tfs_compress_bound(len(data))
        out = ctypes.create_string_buffer(cap)
        out_size = ctypes.c_size_t()
        rc = lib.tfs_compress(data, len(data), out, cap,
                              ctypes.byref(out_size), level)
        if rc != 0:
            raise RuntimeError("zstd compress failed")
        return out.raw[: out_size.value]

    def decompress(self, data: bytes, max_output_size: int | None = None) -> bytes:
        return self.decompress_batch([data], [max_output_size] if max_output_size else None)[0]

    def decompress_batch(
        self, blobs: Sequence[bytes], raw_sizes: Sequence[int] | None = None
    ) -> list[bytes]:
        """Decompress many frames at once (one C call, GIL released)."""
        lib = self._lib
        n = len(blobs)
        if n == 0:
            return []
        caps = []
        unknown = (1 << 64) - 1  # codec.cpp's unknown/error sentinel
        for i, blob in enumerate(blobs):
            if raw_sizes is not None and raw_sizes[i]:
                caps.append(int(raw_sizes[i]))
            else:
                size = lib.tfs_frame_content_size(blob, len(blob))
                if size == unknown:
                    raise ValueError(f"frame {i}: unknown content size")
                caps.append(int(size))
        src_arr = (ctypes.c_char_p * n)(*blobs)
        src_sizes = (ctypes.c_size_t * n)(*[len(b) for b in blobs])
        outs = [ctypes.create_string_buffer(max(1, c)) for c in caps]
        dst_arr = (ctypes.c_void_p * n)(*[ctypes.addressof(o) for o in outs])
        dst_caps = (ctypes.c_size_t * n)(*caps)
        dst_sizes = (ctypes.c_size_t * n)()
        rc = lib.tfs_batch_decompress(
            ctypes.cast(src_arr, ctypes.POINTER(ctypes.c_char_p)),
            src_sizes,
            ctypes.cast(dst_arr, ctypes.POINTER(ctypes.c_char_p)),
            dst_caps, dst_sizes, n, self.n_threads,
        )
        if rc != 0:
            raise RuntimeError(f"zstd decompress failed on frame {rc - 1}")
        return [outs[i].raw[: dst_sizes[i]] for i in range(n)]


class ControlPlane:
    """Host barrier/broadcast/allgather over the rank-0 hub.

    >>> cp = ControlPlane(rank=r, world=n, address="10.0.0.1", port=29400)
    >>> cp.barrier()
    >>> run_id = cp.broadcast_str(run_id if r == 0 else None)
    >>> all_hosts = cp.allgather_bytes(socket.gethostname().encode())
    """

    MAX_PAYLOAD = 1 << 20  # 1 MiB of control data per op

    def __init__(
        self,
        rank: int | None = None,
        world: int | None = None,
        address: str | None = None,
        port: int | None = None,
        timeout_ms: int = 60_000,
        token: str | None = None,
    ):
        rank = int(os.environ.get("RANK", 0)) if rank is None else rank
        world = int(os.environ.get("WORLD_SIZE", 1)) if world is None else world
        if address is None:
            address = os.environ.get("MASTER_ADDR", "127.0.0.1")
        if port is None:
            port = int(os.environ.get("TPUFRAME_CP_PORT", "29401"))
        if token is None:
            token = os.environ.get("TPUFRAME_CP_TOKEN", "")
        # shared-token handshake: strangers that don't know the token can't
        # claim a rank slot (ADVICE r01); empty token -> 0, c10d-style trust
        token_u64 = _token_u64(token)
        self.rank, self.world = rank, world
        self._h = None
        self._lib = None
        if world > 1:
            lib = _build_and_load("tfcp", "controlplane.cpp", [])
            if lib is None:
                raise RuntimeError("control plane needs g++ (no toolchain found)")
            if not getattr(lib, "_tf_sigs", False):
                lib.tfcp_hub_create.restype = ctypes.c_void_p
                lib.tfcp_hub_create.argtypes = [
                    ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_uint64]
                lib.tfcp_spoke_create.restype = ctypes.c_void_p
                lib.tfcp_spoke_create.argtypes = [
                    ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_int, ctypes.c_uint64]
                lib.tfcp_barrier.argtypes = [ctypes.c_void_p]
                lib.tfcp_broadcast.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
                lib.tfcp_allgather.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                    ctypes.c_char_p, ctypes.c_uint64,
                    ctypes.POINTER(ctypes.c_uint64)]
                lib.tfcp_destroy.argtypes = [ctypes.c_void_p]
                lib._tf_sigs = True
            self._lib = lib
            if rank == 0:
                bind = os.environ.get("TPUFRAME_CP_BIND", "")
                self._h = lib.tfcp_hub_create(
                    bind.encode(), port, world, timeout_ms, token_u64
                )
            else:
                self._h = lib.tfcp_spoke_create(
                    address.encode(), port, rank, world, timeout_ms, token_u64
                )
            if not self._h:
                raise TimeoutError(
                    f"control-plane rendezvous failed (rank {rank}/{world} "
                    f"@ {address}:{port})"
                )

    def barrier(self) -> None:
        if self.world == 1:
            return
        if self._lib.tfcp_barrier(self._h) != 0:
            raise RuntimeError("control-plane barrier failed")

    def broadcast_bytes(self, payload: bytes | None) -> bytes:
        if self.world == 1:
            return payload or b""
        if payload is not None and len(payload) > self.MAX_PAYLOAD:
            # fail loudly on every rank path that can know (ADVICE r01:
            # an oversized rank-0 payload used to raise mid-protocol and
            # leave spokes blocked; the SO_RCVTIMEO backstop now also
            # bounds any peer left waiting)
            raise ValueError(
                f"control-plane payload {len(payload)} bytes exceeds "
                f"MAX_PAYLOAD={self.MAX_PAYLOAD}"
            )
        buf = ctypes.create_string_buffer(self.MAX_PAYLOAD)
        size = ctypes.c_uint64(0)
        if self.rank == 0:
            payload = payload or b""
            buf.raw = payload + b"\0" * (self.MAX_PAYLOAD - len(payload))
            size.value = len(payload)
        rc = self._lib.tfcp_broadcast(self._h, buf, ctypes.byref(size), self.MAX_PAYLOAD)
        if rc != 0:
            raise RuntimeError(f"control-plane broadcast failed ({rc})")
        return payload if self.rank == 0 else buf.raw[: size.value]

    def broadcast_str(self, value: str | None) -> str:
        return self.broadcast_bytes(value.encode() if value else None).decode()

    def allgather_bytes(self, payload: bytes) -> list[bytes]:
        if self.world == 1:
            return [payload]
        # per-rank bound only: payload sizes may differ across ranks, so a
        # total-size guess here would raise on some ranks and not others.
        # The hub enforces the true total against MAX_PAYLOAD (rc=-2).
        if len(payload) > self.MAX_PAYLOAD:
            raise ValueError(
                f"allgather payload {len(payload)} bytes exceeds "
                f"MAX_PAYLOAD={self.MAX_PAYLOAD}"
            )
        out = ctypes.create_string_buffer(self.MAX_PAYLOAD)
        sizes = (ctypes.c_uint64 * self.world)()
        rc = self._lib.tfcp_allgather(
            self._h, payload, len(payload), out, self.MAX_PAYLOAD, sizes
        )
        if rc != 0:
            raise RuntimeError(f"control-plane allgather failed ({rc})")
        parts, off = [], 0
        for i in range(self.world):
            parts.append(out.raw[off : off + sizes[i]])
            off += sizes[i]
        return parts

    def close(self) -> None:
        if self._h:
            self._lib.tfcp_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _hb_lib():
    """The heartbeat entry points live in the control-plane library."""
    lib = _build_and_load("tfcp", "controlplane.cpp", [])
    if lib is not None and not getattr(lib, "_hb_sigs", False):
        lib.tfhb_monitor_create.restype = ctypes.c_void_p
        lib.tfhb_monitor_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64]
        lib.tfhb_last_seen_ms.restype = ctypes.c_int64
        lib.tfhb_last_seen_ms.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tfhb_monitor_destroy.argtypes = [ctypes.c_void_p]
        lib.tfhb_beacon_create.restype = ctypes.c_void_p
        lib.tfhb_beacon_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int]
        lib.tfhb_beacon_destroy.argtypes = [ctypes.c_void_p]
        lib._hb_sigs = True
    return lib


def _token_u64(token: str) -> int:
    return (
        int.from_bytes(hashlib.sha256(token.encode()).digest()[:8], "little")
        if token
        else 0
    )


class HeartbeatMonitor:
    """Driver-side liveness tracker (SURVEY §5 missing-host heartbeat).

    Workers run a :class:`HeartbeatBeacon`; the monitor answers "how long
    since rank k last beat?".  Detects worker/host/network death even when
    the launcher's local transport process (e.g. an ssh client) is still
    alive.  A wedged-but-alive main thread is NOT detected — the beacon
    ticks from a background thread; that case stays with the run deadline.
    """

    def __init__(self, port: int, world: int, *, token: str = "",
                 bind: str = ""):
        lib = _hb_lib()
        if lib is None:
            raise RuntimeError("heartbeat needs g++ (no toolchain found)")
        self._lib = lib
        self.world = world
        self._h = lib.tfhb_monitor_create(
            bind.encode(), port, world, _token_u64(token)
        )
        if not self._h:
            raise OSError(f"heartbeat monitor failed to bind port {port}")

    def ms_since(self, rank: int) -> int:
        """Milliseconds since ``rank``'s last beat; -1 if never seen."""
        return int(self._lib.tfhb_last_seen_ms(self._h, rank))

    def stale_ranks(self, timeout_s: float, *, include_unseen: bool = False
                    ) -> list[int]:
        """Ranks whose last beat is older than ``timeout_s`` (unseen ranks
        only when ``include_unseen`` — startup takes a while)."""
        out = []
        for r in range(self.world):
            ms = self.ms_since(r)
            if ms < 0:
                if include_unseen:
                    out.append(r)
            elif ms > timeout_s * 1000:
                out.append(r)
        return out

    def close(self) -> None:
        if self._h:
            self._lib.tfhb_monitor_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class HeartbeatBeacon:
    """Worker-side beat: a background thread ticking one byte per interval
    at the monitor, reconnecting forever on failure.  Start it early (the
    launcher's worker shims do) and forget it."""

    def __init__(self, address: str, port: int, rank: int, *,
                 token: str = "", interval_ms: int = 1000):
        lib = _hb_lib()
        if lib is None:
            raise RuntimeError("heartbeat needs g++ (no toolchain found)")
        self._lib = lib
        self._h = lib.tfhb_beacon_create(
            address.encode(), port, rank, _token_u64(token), interval_ms
        )

    def close(self) -> None:
        if self._h:
            self._lib.tfhb_beacon_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def maybe_start_beacon() -> HeartbeatBeacon | None:
    """Start a beacon from the launcher env contract, if one is requested
    (``TPUFRAME_HB_PORT`` set).  Called by the worker/agent shims before
    the user fn runs; returns None when heartbeating is off."""
    port = os.environ.get("TPUFRAME_HB_PORT")
    if not port:
        return None
    try:
        return HeartbeatBeacon(
            os.environ.get("TPUFRAME_HB_ADDR")
            or os.environ.get("MASTER_ADDR", "127.0.0.1"),
            int(port),
            int(os.environ.get("RANK", "0")),
            token=os.environ.get("TPUFRAME_CP_TOKEN", ""),
        )
    except Exception:
        return None  # liveness is best-effort; never block training on it


_CONTROL_PLANE: ControlPlane | None = None


def control_plane() -> ControlPlane:
    """Process-wide ControlPlane built from the torchrun-style env contract
    (RANK/WORLD_SIZE/MASTER_ADDR + TPUFRAME_CP_PORT/TOKEN, injected by the
    Distributor).  Created on first use; all ranks must make the same
    sequence of collective calls on it."""
    global _CONTROL_PLANE
    if _CONTROL_PLANE is None:
        _CONTROL_PLANE = ControlPlane()
    return _CONTROL_PLANE
