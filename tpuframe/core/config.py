"""Layered configuration tree with ``"auto"`` deferred resolution.

The reference stacks four config layers (SURVEY.md §5 "Config / flag system"):
a user YAML file (`/root/reference/UPDATE_local_config.yaml:1-8`), globals
exported by `%run` of `/root/reference/setup/00_setup.py:15-23`, per-example
literal dicts, and env vars re-exported into child processes
(`/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:184-189`).
DeepSpeed configs additionally use the string ``"auto"`` for values resolved at
engine-init time (`/root/reference/02_deepspeed/deepspeed_config.py:16`).

tpuframe collapses all of that into one structure: :class:`Config` — a nested
attribute-access mapping with deep merge, YAML round-trip, environment-variable
overlay, and explicit ``"auto"`` resolution hooks.  No Spark, no ``%run``
globals: everything is an explicit object.
"""

from __future__ import annotations

import copy
import json
import os
import re
from typing import Any, Callable, Iterator, Mapping

import yaml

#: Sentinel value meaning "resolve me later from runtime context".
AUTO = "auto"

_ENV_SEP = "__"  # TPUFRAME_TRAIN__BATCH_SIZE=128 -> train.batch_size = 128


def _wrap(value: Any) -> Any:
    """Recursively convert plain mappings into Config nodes."""
    if isinstance(value, Config):
        return value
    if isinstance(value, Mapping):
        return Config({k: _wrap(v) for k, v in value.items()})
    if isinstance(value, (list, tuple)):
        return type(value)(_wrap(v) for v in value)
    return value


class Config(dict):
    """Nested dict with attribute access, deep merge and dotted-path access.

    >>> cfg = Config({"train": {"batch_size": 128}})
    >>> cfg.train.batch_size
    128
    >>> cfg.get_path("train.batch_size")
    128
    """

    def __init__(self, data: Mapping[str, Any] | None = None, **kwargs: Any):
        super().__init__()
        merged = dict(data or {})
        merged.update(kwargs)
        for key, value in merged.items():
            self[key] = value

    # -- attribute access -------------------------------------------------
    def __getattr__(self, key: str) -> Any:
        try:
            return self[key]
        except KeyError:
            raise AttributeError(
                f"Config has no key {key!r}; available: {sorted(self.keys())}"
            ) from None

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def __delattr__(self, key: str) -> None:
        try:
            del self[key]
        except KeyError:
            raise AttributeError(key) from None

    def __setitem__(self, key: str, value: Any) -> None:
        super().__setitem__(key, _wrap(value))

    # -- construction -----------------------------------------------------
    @classmethod
    def from_yaml(cls, path: str | os.PathLike) -> "Config":
        """Load a YAML file into a Config (empty file -> empty Config)."""
        with open(path) as f:
            data = yaml.safe_load(f)
        if data is None:
            data = {}
        if not isinstance(data, Mapping):
            raise TypeError(f"top level of {path} must be a mapping, got {type(data)}")
        return cls(data)

    @classmethod
    def from_json(cls, text: str) -> "Config":
        return cls(json.loads(text))

    # -- merge / overlay --------------------------------------------------
    def merged(self, *overlays: Mapping[str, Any]) -> "Config":
        """Return a new Config: self deep-merged with overlays (later wins)."""
        out = copy.deepcopy(self)
        for overlay in overlays:
            _deep_merge(out, overlay)
        return out

    def overlay_env(self, prefix: str = "TPUFRAME_") -> "Config":
        """Overlay env vars: ``TPUFRAME_TRAIN__BATCH_SIZE=128`` -> train.batch_size.

        Values are parsed with ``yaml.safe_load`` so numbers/bools/null come
        through typed.  Mirrors the reference's env-var config channel into
        child processes (SURVEY.md §5), but typed and scoped by prefix.
        """
        overlay: dict[str, Any] = {}
        for name, raw in os.environ.items():
            if not name.startswith(prefix):
                continue
            dotted = name[len(prefix):].lower().replace(_ENV_SEP, ".")
            try:
                value = yaml.safe_load(raw)
            except yaml.YAMLError:
                value = raw
            _set_dotted(overlay, dotted, value)
        return self.merged(overlay)

    # -- dotted path access ----------------------------------------------
    def get_path(self, dotted: str, default: Any = None) -> Any:
        node: Any = self
        for part in dotted.split("."):
            if isinstance(node, Mapping) and part in node:
                node = node[part]
            elif (
                isinstance(node, (list, tuple))
                and part.isdigit()
                and int(part) < len(node)
            ):
                node = node[int(part)]
            else:
                return default
        return node

    def set_path(self, dotted: str, value: Any) -> None:
        parts = dotted.split(".")
        node: Any = self
        for part in parts[:-1]:
            if isinstance(node, list):
                node = node[int(part)]
                continue
            nxt = node.get(part)
            if not isinstance(nxt, (Config, list)):
                nxt = Config()
                node[part] = nxt
            node = node[part]
        if isinstance(node, list):
            node[int(parts[-1])] = _wrap(value)
        else:
            node[parts[-1]] = value

    def flat(self) -> dict[str, Any]:
        """Flatten into ``{"a.b.c": value}`` (for logging params, MLflow-style)."""
        out: dict[str, Any] = {}
        for dotted, value in _walk(self):
            out[dotted] = value
        return out

    # -- auto resolution --------------------------------------------------
    def auto_paths(self) -> list[str]:
        """Dotted paths whose value is the ``"auto"`` sentinel."""
        return [dotted for dotted, value in _walk(self) if value == AUTO]

    def resolve_auto(
        self,
        resolvers: Mapping[str, Callable[["Config"], Any]],
        strict: bool = True,
    ) -> "Config":
        """Return a new Config with every ``"auto"`` leaf replaced.

        ``resolvers`` maps dotted paths (exact or ``fnmatch``-style ``*``
        patterns) to callables receiving the full config.  With ``strict``,
        unresolved ``"auto"`` leaves raise — configs never reach the train
        step half-resolved (unlike the reference, where "auto" only means
        something if DeepSpeed is actually engaged, which it never is:
        `/root/reference/02_deepspeed/01_cifar_deepspeed_resnet.py:108`).
        """
        from fnmatch import fnmatchcase

        out = copy.deepcopy(self)
        unresolved = []
        for dotted in out.auto_paths():
            resolver = resolvers.get(dotted)
            if resolver is None:
                for pattern, candidate in resolvers.items():
                    if fnmatchcase(dotted, pattern):
                        resolver = candidate
                        break
            if resolver is None:
                unresolved.append(dotted)
                continue
            out.set_path(dotted, resolver(out))
        if unresolved and strict:
            raise ValueError(f"unresolved 'auto' config values at: {unresolved}")
        return out

    # -- serialization ----------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        return _unwrap(self)

    def to_yaml(self, path: str | os.PathLike | None = None) -> str:
        text = yaml.safe_dump(self.to_dict(), sort_keys=True)
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def __deepcopy__(self, memo: dict) -> "Config":
        return Config({k: copy.deepcopy(v, memo) for k, v in self.items()})


def _deep_merge(dst: Config, src: Mapping[str, Any]) -> None:
    for key, value in src.items():
        if (
            key in dst
            and isinstance(dst[key], Mapping)
            and isinstance(value, Mapping)
        ):
            _deep_merge(dst[key], value)
        else:
            dst[key] = value


def _set_dotted(tree: dict, dotted: str, value: Any) -> None:
    parts = dotted.split(".")
    node = tree
    for i, part in enumerate(parts[:-1]):
        node = node.setdefault(part, {})
        if not isinstance(node, dict):
            raise ValueError(
                f"config path conflict at {'.'.join(parts[: i + 1])!r}: "
                f"cannot set {dotted!r} because a scalar already lives there"
            )
    node[parts[-1]] = value


def _walk(node: Any, prefix: str = "") -> Iterator[tuple[str, Any]]:
    if isinstance(node, Mapping):
        items: Iterator[tuple[Any, Any]] = iter(node.items())
    elif isinstance(node, (list, tuple)):
        items = iter(enumerate(node))
    else:
        yield prefix.rstrip("."), node
        return
    for key, value in items:
        dotted = f"{prefix}{key}"
        if isinstance(value, (Mapping, list, tuple)):
            yield from _walk(value, f"{dotted}.")
        else:
            yield dotted, value


def _unwrap(value: Any) -> Any:
    if isinstance(value, Mapping):
        return {k: _unwrap(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_unwrap(v) for v in value]
    return value


def load_config(
    path: str | os.PathLike | None = None,
    overrides: Mapping[str, Any] | None = None,
    env_prefix: str = "TPUFRAME_",
) -> Config:
    """Standard layering: defaults file -> overrides dict -> environment.

    The reference's layering, minus Spark (`setup/00_setup.py:15-23` reads
    `local_config.yaml` then exports globals; examples then override inline).
    """
    cfg = Config.from_yaml(path) if path is not None else Config()
    if overrides:
        cfg = cfg.merged(overrides)
    if env_prefix:
        cfg = cfg.overlay_env(env_prefix)
    return cfg
