"""Core runtime: configuration, process/runtime init, device meshes, control plane.

Exports resolve lazily (PEP 562): ``core.workspace`` (per-host layout,
the ``PERF_ENV_VARS`` knob list) must be importable without dragging in
``core.runtime``'s jax import — ``launch.remote.all_env_vars()`` and the
doctor read the knob registry from wedged-backend (or jax-less)
processes.  ``from tpuframe.core import X`` works exactly as before.
Note ``core.config`` imports pyyaml, so even the config surface resolves
lazily here.
"""

# tpuframe-lint: stdlib-only

import importlib

# name -> submodule it lives in (all under tpuframe.core)
_EXPORTS = {
    "AUTO": "config",
    "Config": "config",
    "load_config": "config",
    "Workspace": "workspace",
    "export_worker_env": "workspace",
    "DATA_AXIS": "runtime",
    "EXPERT_AXIS": "runtime",
    "FSDP_AXIS": "runtime",
    "MODEL_AXIS": "runtime",
    "PIPELINE_AXIS": "runtime",
    "SEQUENCE_AXIS": "runtime",
    "MeshSpec": "runtime",
    "Runtime": "runtime",
    "current_runtime": "runtime",
    "initialize": "runtime",
    "is_main_process": "runtime",
    "process_count": "runtime",
    "process_index": "runtime",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        mod = importlib.import_module(f"tpuframe.core.{_EXPORTS[name]}")
        return getattr(mod, name)
    raise AttributeError(f"module 'tpuframe.core' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list(_EXPORTS)))
