"""Core runtime: configuration, process/runtime init, device meshes, control plane."""

from tpuframe.core.config import AUTO, Config, load_config
from tpuframe.core.workspace import Workspace, export_worker_env
from tpuframe.core.runtime import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPELINE_AXIS,
    SEQUENCE_AXIS,
    MeshSpec,
    Runtime,
    current_runtime,
    initialize,
    is_main_process,
    process_count,
    process_index,
)

__all__ = [
    "Workspace",
    "export_worker_env",
    "AUTO",
    "Config",
    "load_config",
    "DATA_AXIS",
    "FSDP_AXIS",
    "MODEL_AXIS",
    "PIPELINE_AXIS",
    "SEQUENCE_AXIS",
    "EXPERT_AXIS",
    "MeshSpec",
    "Runtime",
    "current_runtime",
    "initialize",
    "is_main_process",
    "process_count",
    "process_index",
]
