"""``python -m tpuframe`` -> the environment doctor (tpuframe.doctor)."""

from tpuframe.doctor import main

if __name__ == "__main__":
    raise SystemExit(main())
