"""Small CNNs for the MNIST-class examples.

Architecture parity with the reference's MNIST ``Net``
(`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:75-92`):
two 5x5 VALID convs (10, 20 channels) each followed by 2x2 max-pool, Dropout2d
on the second conv, 320->50->10 MLP with dropout, log-softmax output (the
reference trains with ``F.nll_loss`` on log-probs).  Inputs are NHWC
(N, 28, 28, 1).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistNet(nn.Module):
    """LeNet-style MNIST classifier returning log-probabilities."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = nn.Conv(10, (5, 5), padding="VALID", dtype=self.dtype, name="conv1")(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype, name="conv2")(x)
        # Dropout2d drops whole feature maps: broadcast over spatial dims.
        x = nn.Dropout(
            rate=0.5,
            broadcast_dims=(1, 2),
            deterministic=not train,
            name="conv2_drop",
        )(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))  # (N, 320) for 28x28 inputs
        x = nn.relu(nn.Dense(50, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(rate=0.5, deterministic=not train, name="fc_drop")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc2")(x)
        return nn.log_softmax(x.astype(jnp.float32))
