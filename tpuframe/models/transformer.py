"""Decoder-only transformer LM with sequence-parallel ring attention.

The reference repo is vision-only (SURVEY.md §5: no attention models
anywhere), but long-context training is first-class in tpuframe: this
family is the workload that exercises the ``seq`` mesh axis.  Design:

- NHWC-free (B, L, D) layout; bf16-ready via ``dtype``.
- Attention dispatch: ``attn_impl="auto"`` uses exact ring attention
  (`tpuframe.ops.ring_attention`) whenever the current mesh shards the
  sequence axis — K/V rotate the ICI ring, scores never materialize
  globally; unsharded sequences of ``_BLOCKWISE_AUTO_LEN`` (4k) tokens
  or more take the flash-style linear-memory blockwise path; short
  unsharded sequences use plain XLA attention.
- Tensor-parallel ready: :func:`transformer_tp_rules` gives the
  ParallelPlan rules that split QKV/MLP projections over ``model``
  (Megatron-style column->row pairing; XLA inserts the all-reduces).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpuframe.core.runtime import (
    DATA_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    SEQUENCE_AXIS,
    current_runtime,
)
from tpuframe.ops.ring_attention import attention_reference, ring_attention_local
from tpuframe.ops.layer_norm import FusedLayerNorm
from tpuframe.ops.ulysses import ulysses_attention_local
from tpuframe.core.runtime import shard_map

#: attn_impl="auto" switches full -> blockwise at this unsharded sequence
#: length: 4k tokens is a 64 MB f32 score matrix PER (batch, head) — the
#: materialization, not the FLOPs, starts to dominate HBM there.
_BLOCKWISE_AUTO_LEN = 4096


def transformer_tp_rules():
    """ParallelPlan TP rules: column-parallel QKV/fc1, row-parallel out/fc2
    (≈ Megatron sharding, expressed declaratively)."""
    return (
        (r"(query|key|value)/kernel", P(None, MODEL_AXIS)),
        (r"attn_out/kernel", P(MODEL_AXIS, None)),
        (r"mlp_in/kernel", P(None, MODEL_AXIS)),
        (r"mlp_out/kernel", P(MODEL_AXIS, None)),
        (r"embed/embedding", P(None, MODEL_AXIS)),
        (r"lm_head/kernel", P(None, MODEL_AXIS)),
    )


def _mesh_or_none():
    try:
        return current_runtime(auto_init=False).mesh
    except RuntimeError:
        return None


class SelfAttention(nn.Module):
    """Causal multi-head self-attention with ring/full dispatch."""

    num_heads: int
    head_dim: int
    causal: bool = True
    #: "auto" picks ring attention when the mesh shards the sequence axis
    #: (no head-count constraint), blockwise for unsharded sequences of
    #: _BLOCKWISE_AUTO_LEN+ tokens, full otherwise; "ulysses" opts into
    #: the all-to-all form
    #: (tpuframe.ops.ulysses — one re-shard instead of N-1 ppermute hops,
    #: needs num_heads divisible by the seq-axis size); "blockwise" is the
    #: single-shard flash-style O(L*block) path
    #: (tpuframe.ops.blockwise_attention) for long context on one chip.
    attn_impl: str = "auto"  # "auto" | "full" | "ring" | "ulysses" | "blockwise"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        features = self.num_heads * self.head_dim
        dense = lambda name: nn.Dense(  # noqa: E731
            features, use_bias=False, dtype=self.dtype, name=name
        )
        b, l, _ = x.shape
        heads = (b, l, self.num_heads, self.head_dim)
        q = dense("query")(x).reshape(heads)
        k = dense("key")(x).reshape(heads)
        v = dense("value")(x).reshape(heads)

        impl = self.attn_impl
        mesh = _mesh_or_none()
        if self.is_initializing():
            # init traces with a sample batch that need not divide the mesh;
            # attention has no params, so the full path initializes
            # identically to ring.
            impl = "full"
        elif impl == "auto":
            seq_sharded = mesh is not None and mesh.shape.get(SEQUENCE_AXIS, 1) > 1
            if seq_sharded:
                impl = "ring"
            else:
                # measured first: the kernel ledger's priced verdict for
                # this seq-length shape class (bench_attention persists
                # them); the static memory-hazard heuristic is only the
                # fallback when nothing has been measured here
                from tpuframe.ops.ledger import attention_choice

                impl = attention_choice(l)
                if impl is None:
                    # long unsharded context: the (B,H,L,L) score matrix
                    # is the memory hazard; take the flash-style
                    # linear-memory path
                    impl = (
                        "blockwise" if l >= _BLOCKWISE_AUTO_LEN else "full"
                    )
        if impl in ("ring", "ulysses"):
            if mesh is None:
                raise ValueError(
                    f"attn_impl={impl!r} needs an initialized runtime mesh"
                )
            if impl == "ulysses":
                # the all-to-all owns the head dim during attention, so no
                # head_axis sharding here (TP composes via the projections)
                local_fn = ulysses_attention_local
                head_axis = None
            else:
                local_fn = ring_attention_local
                head_axis = MODEL_AXIS if (
                    mesh.shape.get(MODEL_AXIS, 1) > 1
                    and self.num_heads % mesh.shape[MODEL_AXIS] == 0
                ) else None
            spec = P((DATA_AXIS, FSDP_AXIS), SEQUENCE_AXIS, head_axis, None)
            out = shard_map(
                lambda q, k, v: local_fn(q, k, v, causal=self.causal),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                check_vma=False,
            )(q, k, v)
        elif impl == "blockwise":
            from tpuframe.ops.blockwise_attention import blockwise_attention

            out = blockwise_attention(q, k, v, causal=self.causal)
        elif impl == "full":
            out = attention_reference(q, k, v, causal=self.causal)
        else:
            raise ValueError(
                f"unknown attn_impl {impl!r}; known: auto, full, ring, "
                "ulysses, blockwise"
            )
        out = out.reshape(b, l, features)
        return nn.Dense(
            x.shape[-1], use_bias=False, dtype=self.dtype, name="attn_out"
        )(out)


class Block(nn.Module):
    """Pre-norm transformer block: LN -> attn -> +res, LN -> MLP -> +res.

    ``moe_experts > 0`` replaces the dense MLP with a top-k gated
    MoE (GShard pattern): expert weights shard over the ``expert`` mesh
    axis via ``moe_rules`` and the router's load-balancing loss rides the
    ``aux_loss`` collection into the train objective.
    """

    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    causal: bool = True
    attn_impl: str = "auto"
    dtype: Any = jnp.float32
    #: False when the block runs inside an existing shard_map (GPipe):
    #: the fused LN must not open a nested shard_map there.
    ln_use_mesh: bool = True
    moe_experts: int = 0
    moe_top_k: int = 2

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        d = x.shape[-1]
        ln = lambda name: FusedLayerNorm(  # noqa: E731
            dtype=self.dtype, use_mesh=self.ln_use_mesh, name=name
        )
        y = ln("ln1")(x)
        y = SelfAttention(
            self.num_heads, self.head_dim, causal=self.causal,
            attn_impl=self.attn_impl, dtype=self.dtype, name="attn",
        )(y, train=train)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = x + y
        y = ln("ln2")(x)
        if self.moe_experts:
            from tpuframe.models.moe import MoEMLP

            y = MoEMLP(
                num_experts=self.moe_experts, top_k=self.moe_top_k,
                mlp_ratio=self.mlp_ratio, dtype=self.dtype, name="moe",
            )(y, train=train)
        else:
            y = nn.Dense(
                d * self.mlp_ratio, dtype=self.dtype, name="mlp_in"
            )(y)
            y = nn.gelu(y)
            y = nn.Dense(d, dtype=self.dtype, name="mlp_out")(y)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        return x + y


#: Block with backward-pass rematerialization (jax.checkpoint); the static
#: index pins ``train`` (arg 2: module, x, train) — single definition so
#: callers can't drift from Block.__call__'s positional signature.
RematBlock = nn.remat(Block, static_argnums=(2,))


class TransformerLM(nn.Module):
    """Decoder-only LM: (B, L) int tokens -> (B, L, vocab) logits.

    ``remat=True`` rematerializes each block in the backward pass
    (``jax.checkpoint`` via ``nn.remat``): activation memory drops from
    O(layers) to O(1) blocks at ~1/3 extra FLOPs — the standard trade
    for long-context or memory-bound configs.  Numerics are identical.
    """

    vocab_size: int
    num_layers: int = 4
    num_heads: int = 8
    head_dim: int = 32
    max_len: int = 2048
    mlp_ratio: int = 4
    dropout: float = 0.0
    attn_impl: str = "auto"
    dtype: Any = jnp.float32
    remat: bool = False
    #: >0 swaps every block's dense MLP for a top-k gated MoE (GShard);
    #: compose with ParallelPlan(rules=moe_rules()) for expert parallelism
    moe_experts: int = 0
    moe_top_k: int = 2

    @nn.compact
    def __call__(self, tokens: jax.Array, train: bool = False) -> jax.Array:
        d_model = self.num_heads * self.head_dim
        x = nn.Embed(self.vocab_size, d_model, dtype=self.dtype, name="embed")(tokens)
        pos = nn.Embed(self.max_len, d_model, dtype=self.dtype, name="pos_embed")(
            jnp.arange(tokens.shape[1])[None, :]
        )
        x = x + pos
        block_cls = RematBlock if self.remat else Block
        for i in range(self.num_layers):
            x = block_cls(
                self.num_heads, self.head_dim, mlp_ratio=self.mlp_ratio,
                dropout=self.dropout, causal=True, attn_impl=self.attn_impl,
                dtype=self.dtype, moe_experts=self.moe_experts,
                moe_top_k=self.moe_top_k, name=f"block{i}",
            )(x, train)
        x = FusedLayerNorm(dtype=self.dtype, name="ln_f")(x)
        logits = nn.Dense(
            self.vocab_size, use_bias=False, dtype=self.dtype, name="lm_head"
        )(x)
        return logits.astype(jnp.float32)
