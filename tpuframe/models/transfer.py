"""Frozen-backbone transfer-learning wrappers.

Capability parity with the reference's inline wrappers — pretrained
ResNet18/50 with every backbone param frozen and a fresh
``Dropout(0.5) + Linear`` head sized to the dataset
(`/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:141-159`,
`/root/reference/02_deepspeed/03_1k_imagenet_deepspeed_resnet.py:121-139`).

TPU-first differences: freezing is not a mutable ``requires_grad`` flag on the
module (modules are pure functions here); it is an *optimizer partition* —
:func:`backbone_frozen_labels` labels the param pytree and
``optax.multi_transform`` routes backbone leaves to ``set_to_zero`` while the
head trains.  That keeps the whole model one XLA program (backbone still runs
on the MXU in bf16) with zero optimizer state for frozen leaves — the same
memory win ``requires_grad=False`` buys in torch.

Pretrained weights are imported from torch checkpoints via
``tpuframe.models.interop.import_torch_resnet`` (no torchvision download
needed at train time).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class TransferClassifier(nn.Module):
    """Backbone (headless) + Dropout(0.5) + Dense head.

    ``backbone`` must be a module returning (N, C) features — e.g.
    ``ResNet50(num_classes=0)``.  Params land under ``backbone/`` and
    ``head/`` so freezing partitions are trivial to express.
    """

    backbone: nn.Module
    num_classes: int
    dropout_rate: float = 0.5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        # A dataclass-field submodule is named after the field: params land
        # under params['backbone'] (and head under params['head']).
        feats = self.backbone(x, train=train)
        y = nn.Dropout(rate=self.dropout_rate, deterministic=not train, name="head_drop")(
            feats
        )
        y = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(y)
        return y.astype(jnp.float32)


def backbone_frozen_labels(params: Any, frozen_keys: tuple = ("backbone",)) -> Any:
    """Label a TransferClassifier param tree: 'frozen' backbone, 'trainable' head.

    Use with ``optax.multi_transform({'trainable': tx, 'frozen':
    optax.set_to_zero()}, labels)`` — the JAX equivalent of the reference's
    ``param.requires_grad = False`` loop
    (`02_cifar_torch_distributor_resnet.py:150-151`).
    """
    import jax

    return {
        key: jax.tree_util.tree_map(
            lambda _: "frozen" if key in frozen_keys else "trainable", sub
        )
        for key, sub in params.items()
    }
