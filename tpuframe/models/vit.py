"""Vision Transformer: the attention-based vision family.

The reference's vision zoo is ResNet-only (`/root/reference/setup/
resnet18.py`, torchvision ResNet50 wrappers — SURVEY.md §2.1 C6/C8); ViT
extends tpuframe's coverage to the other standard image backbone while
reusing the transformer machinery (``tpuframe.models.transformer.Block``
with ``causal=False``), so every sequence-parallel/TP capability the LM
family has — ring or Ulysses attention over the ``seq`` axis, Megatron
rules on the projections — applies to patch sequences unchanged.

TPU-first choices: patch embedding is a single strided conv (one MXU op,
no gather); learned position embeddings; mean-pool head by default
(``pool="mean"``) with the classic class-token variant available; all
compute respects the ``dtype`` knob like the other models.

Standard sizes: ViT-S/16 ≈ 22M params, ViT-B/16 ≈ 86M params.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P

from tpuframe.core.runtime import MODEL_AXIS
from tpuframe.models.transformer import Block, RematBlock, transformer_tp_rules
from tpuframe.ops.layer_norm import FusedLayerNorm


class ViT(nn.Module):
    """(B, H, W, C) images -> (B, num_classes) logits.

    Args:
      num_classes: classifier width; 0 = no head (feature extractor).
      patch_size: square patch edge; image H/W must divide evenly.
      hidden_dim / num_layers / num_heads: encoder shape
        (head_dim = hidden_dim // num_heads).
      pool: "mean" (default) or "cls" (prepends a class token; note the
        token makes the sequence length patches+1, which usually breaks
        the even seq-shard constraint for SP — mean-pool on a mesh).
      attn_impl: "auto" | "full" | "ring" | "ulysses" (bidirectional).
      dtype: activation/compute dtype (bf16 recommended on TPU).
      remat: rematerialize blocks in the backward pass (jax.checkpoint).
    """

    num_classes: int = 1000
    patch_size: int = 16
    hidden_dim: int = 384
    num_layers: int = 12
    num_heads: int = 6
    mlp_ratio: int = 4
    dropout: float = 0.0
    pool: str = "mean"
    attn_impl: str = "auto"
    dtype: Any = jnp.float32
    #: rematerialize blocks in the backward pass (jax.checkpoint): O(1)
    #: activation memory across depth for ~1/3 extra FLOPs
    remat: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        if self.hidden_dim % self.num_heads:
            raise ValueError(
                f"hidden_dim {self.hidden_dim} must divide into "
                f"{self.num_heads} heads"
            )
        if self.pool not in ("mean", "cls"):
            raise ValueError(f"unknown pool {self.pool!r}; 'mean' or 'cls'")
        p = self.patch_size
        b, h, w, _ = x.shape
        if h % p or w % p:
            raise ValueError(f"image {h}x{w} not divisible by patch size {p}")

        x = x.astype(self.dtype)
        # patchify = one strided conv straight onto the MXU
        x = nn.Conv(
            self.hidden_dim, (p, p), strides=(p, p), padding="VALID",
            dtype=self.dtype, name="patch_embed",
        )(x)
        x = x.reshape(b, -1, self.hidden_dim)  # (B, n_patches, D)
        n_tokens = x.shape[1]

        if self.pool == "cls":
            cls = self.param(
                "cls_token", nn.initializers.zeros, (1, 1, self.hidden_dim),
                jnp.float32,
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (b, 1, self.hidden_dim)).astype(self.dtype), x],
                axis=1,
            )
            n_tokens += 1
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, n_tokens, self.hidden_dim),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        if self.dropout:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)

        block_cls = RematBlock if self.remat else Block
        for i in range(self.num_layers):
            x = block_cls(
                self.num_heads,
                self.hidden_dim // self.num_heads,
                mlp_ratio=self.mlp_ratio,
                dropout=self.dropout,
                causal=False,  # bidirectional over patches
                attn_impl=self.attn_impl,
                dtype=self.dtype,
                name=f"block{i}",
            )(x, train)
        x = FusedLayerNorm(dtype=self.dtype, name="ln_f")(x)

        x = x[:, 0] if self.pool == "cls" else jnp.mean(x, axis=1)
        if self.num_classes:
            x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def vit_tp_rules():
    """ParallelPlan TP rules for ViT: the shared transformer Block rules
    (column-parallel QKV/mlp_in, row-parallel attn_out/mlp_out) plus the
    patch embedding's output channels and the classifier head on the
    model axis."""
    block_rules = tuple(
        r for r in transformer_tp_rules() if "embed" not in r[0] and "lm_head" not in r[0]
    )
    return block_rules + (
        (r"patch_embed/kernel", P(None, None, None, MODEL_AXIS)),
        (r"head/kernel", P(None, MODEL_AXIS)),
    )


#: Standard recipes (patch 16): S ≈ 22M, B ≈ 86M params.
ViT_S16 = functools.partial(ViT, hidden_dim=384, num_layers=12, num_heads=6)
ViT_B16 = functools.partial(ViT, hidden_dim=768, num_layers=12, num_heads=12)
