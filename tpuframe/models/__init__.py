"""Model zoo: ResNet family + small CNNs (flax.linen, NHWC, bf16-ready).

TPU-native re-expression of the reference's L2 model layer (SURVEY.md §1):
from-scratch ResNet18 (`/root/reference/setup/resnet18.py`), torchvision-style
ResNet18/34/50 with ImageNet stems, the MNIST `Net` CNN
(`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:75-92`),
and frozen-backbone transfer-learning wrappers
(`/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:141-159`).
"""

from tpuframe.models.cnn import MnistNet
from tpuframe.models.transformer import TransformerLM, transformer_tp_rules
from tpuframe.models.resnet import (
    BasicBlock,
    Bottleneck,
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
)
from tpuframe.models.norm import ReplicaGroupedBatchNorm
from tpuframe.models.transfer import TransferClassifier, backbone_frozen_labels
from tpuframe.models.vit import ViT, ViT_B16, ViT_S16, vit_tp_rules

__all__ = [
    "MnistNet",
    "TransformerLM",
    "transformer_tp_rules",
    "BasicBlock",
    "Bottleneck",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ReplicaGroupedBatchNorm",
    "ViT",
    "ViT_S16",
    "ViT_B16",
    "vit_tp_rules",
    "TransferClassifier",
    "backbone_frozen_labels",
]

from tpuframe.models.moe import MoEMLP, moe_rules  # noqa: E402
__all__ += ["MoEMLP", "moe_rules"]
