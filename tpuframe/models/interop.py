"""Torch checkpoint interop: torchvision-style ResNet state_dicts <-> flax params.

The reference's transfer-learning examples start from torchvision pretrained
weights (`models.resnet18(weights=ResNet18_Weights.DEFAULT)` at
`/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:146`).
This container has no egress, and a TPU framework shouldn't depend on
torchvision at train time anyway — instead, any torchvision-format ResNet
``state_dict`` (a file the user already has) can be converted into a tpuframe
ResNet variables tree.  The tpuframe ResNet keeps stable module names
(``conv1``, ``layer{i}_{j}``, ``downsample_*``, ``fc``) precisely so this
mapping is mechanical.

Layout conversions:
- Conv:   torch OIHW  -> flax HWIO
- Linear: torch (out, in) -> flax (in, out)
- BatchNorm: weight/bias -> scale/bias (params); running_mean/var -> mean/var
  (batch_stats collection)

:func:`export_torch_resnet` is the exact inverse — a tpuframe-trained
ResNet leaves as a torchvision-format state_dict, so users moving back to
the reference stack (or serving with torch) keep their weights.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import numpy as np


def import_torch_resnet(state_dict: Mapping[str, Any]) -> dict:
    """Convert a torchvision-format ResNet state_dict to tpuframe variables.

    Accepts tensors or numpy arrays as values (call ``.numpy()`` upstream or
    pass ``torch.load(..., map_location='cpu')`` output directly).  Returns
    ``{"params": ..., "batch_stats": ...}`` matching
    ``tpuframe.models.ResNet{18,34,50,101}``.
    """
    params: dict = {}
    batch_stats: dict = {}

    def to_np(v: Any) -> np.ndarray:
        if hasattr(v, "detach"):
            v = v.detach().cpu().numpy()
        return np.asarray(v)

    def put(tree: dict, path: list[str], leaf: np.ndarray) -> None:
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = leaf

    for key, value in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        value = to_np(value)
        parts = key.split(".")
        # torchvision names: conv1.weight, bn1.weight, layer1.0.conv2.weight,
        # layer1.0.downsample.{0,1}.weight, fc.{weight,bias}
        if parts[0].startswith("layer"):
            stage, block_idx = parts[0], parts[1]
            module = f"{stage}_{block_idx}"
            rest = parts[2:]
            if rest[0] == "downsample":
                sub = "downsample_conv" if rest[1] == "0" else "downsample_bn"
                rest = [sub] + rest[2:]
            path = [module] + rest
        else:
            path = parts

        *mods, attr = path
        leaf_name, is_stat, array = _convert_leaf(mods[-1], attr, value)
        if is_stat:
            put(batch_stats, mods + [leaf_name], array)
        else:
            put(params, mods + [leaf_name], array)

    return {"params": params, "batch_stats": batch_stats}


def export_torch_resnet(variables: Mapping[str, Any]) -> dict:
    """Convert tpuframe ResNet variables back to a torchvision-format
    state_dict (numpy values; wrap with ``torch.from_numpy`` to load into
    a torch module).  Exact inverse of :func:`import_torch_resnet`:
    ``export(import(sd)) == sd`` up to the dropped ``num_batches_tracked``
    counters, and round-tripping tpuframe variables is the identity.
    """
    params = variables.get("params", {})
    batch_stats = variables.get("batch_stats", {})
    out: dict[str, np.ndarray] = {}

    def torch_module_name(mod: str) -> str:
        # layer{i}_{j} -> layer{i}.{j}; downsample_{conv,bn} -> downsample.{0,1}
        m = re.fullmatch(r"(layer\d+)_(\d+)", mod)
        return f"{m.group(1)}.{m.group(2)}" if m else mod

    def walk(tree: Mapping[str, Any], prefix: list[str], stats: bool) -> None:
        for name, value in tree.items():
            if isinstance(value, Mapping):
                walk(value, prefix + [name], stats)
                continue
            arr = np.asarray(value)
            mods = [torch_module_name(m) for m in prefix]
            if mods and mods[-1] == "downsample_conv":
                mods[-1] = "downsample.0"
            elif mods and mods[-1] == "downsample_bn":
                mods[-1] = "downsample.1"
            module = ".".join(mods)
            is_bn = bool(re.search(r"bn|downsample\.1", module))
            if stats:
                attr = {"mean": "running_mean", "var": "running_var"}[name]
            elif is_bn:
                attr = {"scale": "weight", "bias": "bias"}[name]
            elif name == "kernel":
                attr = "weight"
                arr = arr.transpose(3, 2, 0, 1) if arr.ndim == 4 else arr.T
            else:
                attr = name
            out[f"{module}.{attr}"] = arr

    walk(params, [], stats=False)
    walk(batch_stats, [], stats=True)
    return out


def _convert_leaf(module: str, attr: str, value: np.ndarray):
    """Map one torch leaf to (flax_name, goes_to_batch_stats, converted array)."""
    is_bn = bool(re.search(r"bn|downsample_bn", module))
    if is_bn:
        mapping = {
            "weight": ("scale", False),
            "bias": ("bias", False),
            "running_mean": ("mean", True),
            "running_var": ("var", True),
        }
        name, is_stat = mapping[attr]
        return name, is_stat, value
    if value.ndim == 4:  # conv kernel OIHW -> HWIO
        return "kernel", False, value.transpose(2, 3, 1, 0)
    if value.ndim == 2:  # linear (out, in) -> (in, out)
        return "kernel", False, value.T
    return attr if attr != "weight" else "kernel", False, value
