"""ResNet family, written TPU-first in flax.linen.

Capability parity with the reference's models (not a port):

- from-scratch CIFAR ResNet18 — `/root/reference/setup/resnet18.py:29-67`
  (3x3 stride-1 stem + 3x3/s2 maxpool, 4 stages of BasicBlock, adaptive
  avgpool head) -> ``ResNet18(stem="cifar")``.
- torchvision-style ResNet18/50 used by the transfer-learning wrappers
  (`/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:146`,
  `/root/reference/02_deepspeed/03_1k_imagenet_deepspeed_resnet.py:121-139`)
  -> ``ResNet18()``/``ResNet50()`` with the classic 7x7/s2 ImageNet stem.

TPU-first choices:

- NHWC layout (XLA's preferred conv layout on TPU) and a ``dtype`` knob for
  bf16 activations feeding the MXU; params and BN statistics stay float32.
- No Python control flow on data: the whole forward is trace-once, so it
  compiles to a single XLA program.
- BatchNorm under ``jit`` + GSPMD sharding computes batch statistics over the
  *global* (all-chip) batch: cross-replica sync-BN is the default by
  construction, the opposite of torch DDP's per-replica BN.  Per-replica
  statistics are available by running the step under ``shard_map`` instead
  (see tpuframe.parallel).  SURVEY.md §7 "Hard parts" flags this choice.
- Module names are stable (``conv1``, ``layer{i}_{j}``, ``fc`` ...) so torch
  checkpoints can be imported by tpuframe.models.interop.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Type

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity/projection skip (reference Block,
    `/root/reference/setup/resnet18.py:3-28`)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    expansion: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), name="conv2")(y)
        y = self.norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), (self.strides, self.strides), name="downsample_conv"
            )(x)
            residual = self.norm(name="downsample_bn")(residual)
        return self.act(y + residual)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1(x4) bottleneck (torchvision ResNet50-style)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    expansion: int = 4

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        y = self.conv(self.filters, (1, 1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides), name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = self.conv(self.filters * self.expansion, (1, 1), name="conv3")(y)
        y = self.norm(name="bn3")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * self.expansion,
                (1, 1),
                (self.strides, self.strides),
                name="downsample_conv",
            )(x)
            residual = self.norm(name="downsample_bn")(residual)
        return self.act(y + residual)


class ResNet(nn.Module):
    """Generic 4-stage ResNet over NHWC inputs.

    Args:
      stage_sizes: blocks per stage, e.g. (2, 2, 2, 2) for ResNet18.
      block_cls: BasicBlock or Bottleneck.
      num_classes: classifier width; 0 means "no head" (feature extractor).
      stem: "imagenet" = 7x7/s2 conv + 3x3/s2 maxpool (torchvision);
            "cifar" = 3x3/s1 conv + 3x3/s2 maxpool (reference
            `setup/resnet18.py:35-39` keeps the maxpool even for CIFAR).
      dtype: activation/compute dtype (bf16 recommended on TPU); params and
             BN statistics are kept float32.
      bn_stats: "sync" (default) computes train-time BN moments over the
            global batch — the SPMD-natural choice (XLA all-reduces the
            moments over the data axes).  "local" reproduces torch DDP's
            per-replica BN (`01_basic_torch_distributor.py:289-291` uses
            plain DDP, not SyncBatchNorm) via ``bn_groups`` statistic
            groups; with groups == data shards the reductions stay
            shard-local (no cross-chip collective).  SURVEY.md §7 flags
            this convergence-relevant choice as necessarily explicit.
      bn_groups: statistic groups for ``bn_stats="local"`` (0 = treat as
            sync; the Trainer auto-fills it with the plan's data shard
            count).
      norm_dtype: BatchNorm OUTPUT dtype.  None (default) keeps f32
            outputs — numerically identical to torch's BN-in-f32 and the
            behavior of earlier rounds.  Setting ``norm_dtype=dtype``
            (bf16) keeps statistics/affine math in f32 inside flax's BN
            (``_compute_stats`` promotes) but emits bf16 activations, so
            the BN→relu→conv chain stops materializing f32 tensors — on
            an HBM-bound step that traffic is the headroom PERF.md
            identifies.  Convergence-relevant: measure before defaulting.
    """

    stage_sizes: Sequence[int]
    block_cls: Type[nn.Module]
    num_classes: int = 10
    num_filters: int = 64
    stem: str = "imagenet"
    dtype: jnp.dtype = jnp.float32
    act: Callable = nn.relu
    bn_stats: str = "sync"
    bn_groups: int = 0
    norm_dtype: jnp.dtype | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        def conv(features, kernel_size, strides=(1, 1), name=None):
            # torch-symmetric half padding (Conv2d's padding=k//2), NOT
            # "SAME": identical for stride 1, but SAME pads stride-2 convs
            # asymmetrically ((2,3) for the 7x7/s2 stem), which silently
            # shifts every window of an imported torchvision checkpoint.
            # Shapes match SAME for even inputs, so this costs nothing and
            # makes interop.import_torch_resnet numerically exact.
            return nn.Conv(
                features,
                kernel_size,
                strides,
                use_bias=False,
                dtype=self.dtype,
                padding=tuple((k // 2, k // 2) for k in kernel_size),
                kernel_init=nn.initializers.he_normal(),
                name=name,
            )
        # stats/affine math stays f32 either way (flax promotes inside);
        # norm_dtype only picks the OUTPUT dtype of the normalize
        bn_out_dtype = self.norm_dtype if self.norm_dtype is not None else jnp.float32
        if self.bn_stats == "local" and self.bn_groups > 1:
            from tpuframe.models.norm import ReplicaGroupedBatchNorm

            norm = functools.partial(
                ReplicaGroupedBatchNorm,
                use_running_average=not train,
                groups=self.bn_groups,
                momentum=0.9,
                epsilon=1e-5,
                # the bn_stats knob must toggle ONLY the statistics scope,
                # not activation dtype — that's norm_dtype's job
                dtype=bn_out_dtype,
            )
        elif self.bn_stats in ("sync", "local"):
            norm = functools.partial(
                nn.BatchNorm,
                use_running_average=not train,
                momentum=0.9,
                epsilon=1e-5,
                dtype=bn_out_dtype,
            )
        else:
            raise ValueError(
                f"unknown bn_stats {self.bn_stats!r}; expected 'sync' or 'local'"
            )

        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv1")(x)
        elif self.stem == "cifar":
            x = conv(self.num_filters, (3, 3), (1, 1), name="conv1")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = norm(name="bn1")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                    name=f"layer{i + 1}_{j}",
                )(x)
                x = x.astype(self.dtype)

        x = jnp.mean(x, axis=(1, 2))  # adaptive avg-pool to (N, C)
        if self.num_classes:
            x = nn.Dense(
                self.num_classes, dtype=self.dtype, name="fc"
            )(x)
        return x.astype(jnp.float32)

    @property
    def feature_width(self) -> int:
        return self.num_filters * 8 * self.block_cls.expansion


ResNet18 = functools.partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck)
ResNet101 = functools.partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=Bottleneck)
