"""Batch normalization with an explicit DP-statistics choice.

SURVEY.md §7 "Hard parts": *BatchNorm under DP — per-replica BN stats vs
cross-replica sync-BN changes convergence vs the torch reference; must be
an explicit option.*

Under ``jit`` + GSPMD sharding, a plain reduction over the batch axis IS
a global reduction — flax's ``nn.BatchNorm`` on a data-sharded batch is
cross-replica sync-BN by construction (XLA inserts the cross-chip
all-reduce of the moments).  torch DDP's default is the opposite: each
replica normalizes with its own local-batch statistics
(`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:289-291`
wraps in DDP without SyncBatchNorm, so BN stays per-replica).

:class:`ReplicaGroupedBatchNorm` reproduces the per-replica semantics in
SPMD form: the global batch is reshaped to ``(groups, B/groups, ...)``
and moments are taken per group.  When ``groups`` equals the number of
data shards and the batch axis is sharded over them, the reshape aligns
group boundaries with shard boundaries, so the moment reductions stay
shard-local and no cross-chip collective is emitted — per-replica BN is
simultaneously the torch-DDP-parity choice *and* the cheaper one on an
ICI mesh.

Running statistics: each group contributes its batch moments, and the
running buffers are updated with the group-mean — torch DDP would let
each replica's buffers drift independently and checkpoint rank 0's; a
single global array cannot drift per replica, so averaging the groups is
the SPMD-faithful equivalent.  Eval always normalizes with the shared
running buffers (identical everywhere, like the reference's rank-0
checkpoint reloaded on every worker).

Variable layout matches ``nn.BatchNorm`` (params ``scale``/``bias``,
batch_stats ``mean``/``var``) so checkpoints and the torch interop table
(`tpuframe/models/interop.py`) work unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class ReplicaGroupedBatchNorm(nn.Module):
    """BatchNorm computing train-time moments per batch group.

    ``groups=1`` is exactly global (sync) BN.  ``groups=N`` with the batch
    sharded N ways over the data axes gives torch-DDP per-replica
    semantics with shard-local reductions.

    Args:
      use_running_average: eval mode — normalize with running buffers.
      groups: number of statistic groups; global batch must divide evenly.
      momentum / epsilon: as ``nn.BatchNorm``.
      dtype: output dtype (moments and affine are always float32).
    """

    use_running_average: bool = False
    groups: int = 1
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        feat = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (feat,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (feat,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((feat,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((feat,), jnp.float32)
        )

        if self.use_running_average:
            y = (x.astype(jnp.float32) - ra_mean.value) * jax.lax.rsqrt(
                ra_var.value + self.epsilon
            )
            return (y * scale + bias).astype(self.dtype)

        g = self.groups
        b = x.shape[0]
        if g < 1 or b % g:
            raise ValueError(
                f"batch size {b} must divide evenly into {g} BN groups"
            )
        xg = x.reshape((g, b // g) + x.shape[1:]).astype(jnp.float32)
        axes = tuple(range(1, xg.ndim - 1))  # sub-batch + spatial dims
        mean_g = jnp.mean(xg, axes)  # (g, C)
        # E[x^2] - E[x]^2 ("fast variance"): one pass over the activations
        # instead of two — this is the HBM-bound part of the op.
        var_g = jnp.maximum(jnp.mean(xg * xg, axes) - mean_g**2, 0.0)

        bshape = (g,) + (1,) * len(axes) + (feat,)
        y = (xg - mean_g.reshape(bshape)) * jax.lax.rsqrt(
            var_g.reshape(bshape) + self.epsilon
        )
        y = (y * scale + bias).reshape(x.shape).astype(self.dtype)

        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * jnp.mean(mean_g, 0)
            ra_var.value = m * ra_var.value + (1 - m) * jnp.mean(var_g, 0)
        return y
