"""Mixture-of-Experts MLP with expert parallelism over the ``expert`` axis.

Absent from the vision-only reference (SURVEY.md §2.2 marks EP "No"), but
the ``expert`` mesh axis is first-class in tpuframe.  TPU-first design —
the GShard/Switch dense-dispatch formulation: routing becomes einsums
against one-hot dispatch/combine tensors (MXU work, static shapes), and
expert parallelism is *declared* by sharding the expert-stacked weights
``(E, ...)`` over the ``expert`` axis — GSPMD inserts the all-to-alls
that imperative MoE frameworks hand-write.

Components:
- :class:`MoEMLP` — drop-in replacement for a transformer block's MLP:
  top-k softmax gating, capacity-factor truncation, load-balancing aux
  loss (Switch-style) exposed via the ``"aux_loss"`` mutable collection.
- :func:`moe_rules` — ParallelPlan rules placing expert weights on the
  ``expert`` axis (compose with the TP/fsdp rules).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpuframe.core.runtime import EXPERT_AXIS
from tpuframe.ops.moe_gating import moe_dispatch_combine


def moe_rules():
    """ParallelPlan rules: expert-stacked weights shard over ``expert``."""
    return (
        (r"(^|/)(w_in|w_out)$", P(EXPERT_AXIS, None, None)),
    )


class MoEMLP(nn.Module):
    """Top-k gated mixture of expert MLPs (dense dispatch).

    Args:
      num_experts: E.
      mlp_ratio: hidden = d_model * mlp_ratio per expert.
      top_k: experts per token (1 = Switch, 2 = GShard default).
      capacity_factor: per-expert slots = ceil(top_k * N / E * factor);
        overflow tokens are dropped (their combine weight is zero), the
        standard Switch behavior.
      aux_loss_weight: weight of the load-balancing loss, stored in the
        ``aux_loss`` mutable collection for the train step to pick up.
    """

    num_experts: int = 8
    mlp_ratio: int = 4
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        *lead, d = x.shape
        n = 1
        for s in lead:
            n *= s
        tokens = x.reshape(n, d)
        e = self.num_experts
        k = min(self.top_k, e)
        capacity = max(1, int(-(-(k * n) // e) * self.capacity_factor))

        # --- routing ----------------------------------------------------
        logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="router"
        )(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # (N, E)

        # top-k expert choices per token
        gate_vals, gate_idx = jax.lax.top_k(probs, k)  # (N, k)
        # renormalize chosen gates to sum 1 (GShard convention)
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, -1, keepdims=True), 1e-9
        )

        # --- dispatch / expert MLPs / combine ----------------------------
        # tpuframe.ops.moe_gating owns the mechanics: the fused path
        # scatter-adds kept tokens straight into the (E, C, D) expert
        # buffers (no (kN, E, C) one-hot tensor), the dense-einsum
        # reference is the oracle, and the kernel ledger decides which
        # runs (TPUFRAME_KERNELS / a priced per-shape verdict).
        h = d * self.mlp_ratio
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(), (e, d, h), self.dtype
        )
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(), (e, h, d), self.dtype
        )
        out = moe_dispatch_combine(
            tokens, gate_vals, gate_idx, w_in, w_out,
            capacity=capacity, act=nn.gelu,
        )

        # --- load-balance aux loss (Switch eq. 4) ------------------------
        # fraction of tokens routed to each expert (by top-1 choice) x
        # mean router prob; scaled by E so balanced = 1.0
        top1 = jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32)
        aux = jnp.sum(
            jnp.mean(top1, axis=0) * jnp.mean(probs, axis=0)
        ) * e * self.aux_loss_weight
        self.sow("aux_loss", "moe", aux)

        return out.reshape(*lead, d).astype(x.dtype)
