"""LR schedules: the reference examples' schedulers, optax-native.

Parity targets:

- DeepSpeed ``WarmupLR`` — linear (or log) ramp ``warmup_min_lr`` →
  ``warmup_max_lr`` over ``warmup_num_steps``, then hold
  (`/root/reference/02_deepspeed/deepspeed_config.py:33-40`).
- DeepSpeed ``WarmupDecayLR`` — same warmup, then linear decay back to
  the ``warmup_min_lr`` floor at ``total_num_steps`` (the other
  scheduler the DeepSpeed docs pair with the base config).
- torch ``CosineAnnealingLR`` — the Accelerate example's scheduler
  (`/root/reference/04_accelerate/01_cifar_accelerate.ipynb:cell-16`).
- torch ``StepLR``-style staircase decay.
- ``warmup_cosine`` — warmup + cosine decay, the idiomatic TPU default.

Every schedule is an ``optax.Schedule`` (``step -> lr``) built from
``jnp`` ops, so it traces under ``jit`` and lives inside the compiled
train step — no host-side scheduler object to ``.step()`` (the torch
pattern) and nothing to checkpoint beyond ``state.step``.

``from_config`` accepts the DeepSpeed-shaped
``{"type": ..., "params": {...}}`` dict so configs written for the
reference's ``deepspeed_config.py`` carry their scheduler through
unchanged; ``"auto"``-style deferred values resolve against the
caller-supplied ``total_steps``.
"""

from __future__ import annotations

import math
from typing import Any, Mapping

import jax.numpy as jnp
import optax

__all__ = [
    "warmup_lr",
    "warmup_decay_lr",
    "cosine_annealing",
    "step_decay",
    "warmup_cosine",
    "from_config",
    "resolve_schedule",
]


def warmup_lr(
    max_lr: float,
    warmup_steps: int,
    *,
    min_lr: float = 0.0,
    warmup_type: str = "linear",
) -> optax.Schedule:
    """DeepSpeed ``WarmupLR``: ramp to ``max_lr`` then hold forever.

    ``warmup_type="log"`` uses DeepSpeed's logarithmic ramp
    ``log(step + 1) / log(warmup_steps)`` (denominator clamped to
    ``log 2``), clipped to 1.  Note DeepSpeed clamps ``warmup_num_steps``
    itself to >= 2 for *both* ramp types — :func:`from_config` applies
    that clamp; calling this directly keeps ``warmup_steps=0`` as the
    "no warmup, constant max_lr" convenience.
    """
    if warmup_steps < 0:
        raise ValueError(f"warmup_steps must be >= 0, got {warmup_steps}")
    if warmup_type not in ("linear", "log"):
        raise ValueError(f"warmup_type must be 'linear' or 'log', got {warmup_type!r}")
    if warmup_steps == 0:
        return lambda step: jnp.asarray(max_lr, jnp.float32)

    # DeepSpeed clamps warmup_num_steps to >= 2 so log(1) never divides.
    log_denom = math.log(max(2, warmup_steps))

    def schedule(step):
        s = jnp.asarray(step, jnp.float32)
        if warmup_type == "log":
            frac = jnp.log1p(s) / log_denom
        else:
            frac = s / warmup_steps
        frac = jnp.clip(frac, 0.0, 1.0)
        return min_lr + (max_lr - min_lr) * frac

    return schedule


def warmup_decay_lr(
    max_lr: float,
    warmup_steps: int,
    total_steps: int,
    *,
    min_lr: float = 0.0,
) -> optax.Schedule:
    """DeepSpeed ``WarmupDecayLR``: linear warmup, then linear decay back
    to the ``min_lr`` floor at ``total_steps`` (DeepSpeed holds the floor,
    not zero)."""
    if total_steps <= warmup_steps:
        raise ValueError(
            f"total_steps ({total_steps}) must exceed warmup_steps ({warmup_steps})"
        )
    ramp = warmup_lr(max_lr, warmup_steps, min_lr=min_lr)

    def schedule(step):
        s = jnp.asarray(step, jnp.float32)
        decay = (total_steps - s) / (total_steps - warmup_steps)
        decay = jnp.clip(decay, 0.0, 1.0)
        # DeepSpeed decays back to the min_lr floor, not to zero
        return jnp.where(
            s < warmup_steps, ramp(step), min_lr + (max_lr - min_lr) * decay
        )

    return schedule


def cosine_annealing(
    base_lr: float, t_max: int, *, eta_min: float = 0.0
) -> optax.Schedule:
    """torch ``CosineAnnealingLR``: half-cosine from ``base_lr`` to
    ``eta_min`` over ``t_max`` steps, holding ``eta_min`` after (torch
    would oscillate back up; training past ``T_max`` is out-of-contract
    there, so hold is the safer tail)."""
    if t_max <= 0:
        raise ValueError(f"t_max must be > 0, got {t_max}")

    def schedule(step):
        t = jnp.clip(jnp.asarray(step, jnp.float32), 0.0, t_max)
        return eta_min + 0.5 * (base_lr - eta_min) * (1.0 + jnp.cos(jnp.pi * t / t_max))

    return schedule


def step_decay(
    base_lr: float, step_size: int, *, gamma: float = 0.1
) -> optax.Schedule:
    """torch ``StepLR``: multiply by ``gamma`` every ``step_size`` steps."""
    return optax.exponential_decay(
        base_lr, transition_steps=step_size, decay_rate=gamma, staircase=True
    )


def warmup_cosine(
    max_lr: float,
    warmup_steps: int,
    total_steps: int,
    *,
    end_lr: float = 0.0,
    init_lr: float = 0.0,
) -> optax.Schedule:
    """Linear warmup into cosine decay — the TPU-idiomatic default."""
    return optax.warmup_cosine_decay_schedule(
        init_value=init_lr,
        peak_value=max_lr,
        warmup_steps=warmup_steps,
        decay_steps=total_steps,
        end_value=end_lr,
    )


def _resolve_auto(value: Any, name: str, fallback: int | None) -> int:
    """DeepSpeed-style ``"auto"`` resolution against a caller-known total."""
    if value in ("auto", None):
        if fallback is None:
            raise ValueError(
                f"scheduler param {name!r} is 'auto' but no total_steps was "
                "supplied to resolve it (pass total_steps=, or set the param "
                "explicitly)"
            )
        return int(fallback)
    return int(value)


def from_config(
    cfg: Mapping[str, Any], *, total_steps: int | None = None
) -> optax.Schedule:
    """Build a schedule from a DeepSpeed-shaped scheduler dict.

    Accepts either the full config (reads its ``"scheduler"`` key) or the
    scheduler block itself: ``{"type": "WarmupLR", "params": {...}}``
    (`deepspeed_config.py:33-40`).  ``total_num_steps: "auto"`` (and a
    missing ``total_num_steps`` on decaying types) resolves to
    ``total_steps``.
    """
    sched = cfg.get("scheduler", cfg)
    kind = str(sched.get("type", "")).strip()
    params = dict(sched.get("params", {}))
    k = kind.lower()

    if k in ("warmuplr", "warmup"):
        return warmup_lr(
            max_lr=float(params["warmup_max_lr"]),
            # DeepSpeed's WarmupLR clamps warmup_num_steps to >= 2 for both
            # ramp types; a config written for it must ramp identically here
            warmup_steps=max(2, int(params.get("warmup_num_steps", 0))),
            min_lr=float(params.get("warmup_min_lr", 0.0)),
            warmup_type=params.get("warmup_type", "linear"),
        )
    if k == "warmupdecaylr":
        return warmup_decay_lr(
            max_lr=float(params["warmup_max_lr"]),
            warmup_steps=max(2, int(params.get("warmup_num_steps", 0))),
            total_steps=_resolve_auto(
                params.get("total_num_steps", "auto"), "total_num_steps", total_steps
            ),
            min_lr=float(params.get("warmup_min_lr", 0.0)),
        )
    if k in ("warmupcosinelr", "warmup_cosine"):
        total = _resolve_auto(
            params.get("total_num_steps", "auto"), "total_num_steps", total_steps
        )
        peak = params.get("warmup_max_lr", params.get("max_lr"))
        if peak is None:
            raise ValueError(
                "WarmupCosineLR needs 'warmup_max_lr' (or 'max_lr') — a "
                "missing peak would silently train at lr 0"
            )
        return warmup_cosine(
            max_lr=float(peak),
            warmup_steps=int(params.get("warmup_num_steps", 0)),
            total_steps=total,
            end_lr=float(params.get("cos_min_ratio", 0.0)) * float(peak),
        )
    if k in ("cosineannealinglr", "cosine", "cosine_annealing"):
        return cosine_annealing(
            base_lr=float(params["base_lr"]),
            t_max=_resolve_auto(params.get("T_max", "auto"), "T_max", total_steps),
            eta_min=float(params.get("eta_min", 0.0)),
        )
    if k in ("steplr", "step", "step_decay"):
        return step_decay(
            base_lr=float(params["base_lr"]),
            step_size=int(params["step_size"]),
            gamma=float(params.get("gamma", 0.1)),
        )
    if k in ("constant", "constantlr"):
        lr = float(params.get("lr", params.get("base_lr", 0.0)))
        return lambda step: jnp.asarray(lr, jnp.float32)
    if not kind:
        # a dict with no "type" is almost always a forgotten
        # {"type": ..., "params": {...}} wrapper — silently training at a
        # constant 0.0 lr would be the worst possible outcome.
        raise ValueError(
            "scheduler dict has no 'type' key; expected the DeepSpeed shape "
            '{"type": "WarmupLR", "params": {...}} (or a config with a '
            '"scheduler" key)'
        )
    raise ValueError(
        f"unknown scheduler type {kind!r}; known: WarmupLR, WarmupDecayLR, "
        "WarmupCosineLR, CosineAnnealingLR, StepLR, constant"
    )


def resolve_schedule(
    spec: float | Mapping[str, Any] | optax.Schedule,
    *,
    total_steps: int | None = None,
):
    """Trainer-facing resolver: float → constant, dict → :func:`from_config`,
    callable → as-is."""
    if isinstance(spec, Mapping):
        return from_config(spec, total_steps=total_steps)
    if callable(spec):
        return spec
    return float(spec)
