"""Train state: one pytree holding everything a step updates.

Replaces the reference's scattered mutable objects — ``model`` +
``optimizer`` + implicit BN buffers inside torch modules
(`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:282-291`)
— with a single immutable :class:`TrainState` that jit can donate and a
ParallelPlan can shard leaf-by-leaf.  Checkpoints serialize exactly this
object (plus step metadata), which is what makes resume trivial.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax

from tpuframe.parallel.sharding import ParallelPlan


def _any_host_resident(tree: Any) -> bool:
    """True if any leaf's (traced or concrete) aval sits in host memory."""
    try:
        host_space = jax.memory.Space.Host
    except AttributeError:  # older jax: no memory-space API => never offloaded
        return False
    for leaf in jax.tree.leaves(tree):
        aval = getattr(leaf, "aval", None)
        if getattr(aval, "memory_space", None) == host_space:
            return True
    return False


class TrainState(flax.struct.PyTreeNode):
    """Params + optimizer state + mutable model collections + step counter.

    ``apply_fn``/``tx`` are static (not traced); everything else is data.
    ``batch_stats`` carries BatchNorm running statistics (flax's ``mutable``
    collection) — empty dict for stat-free models.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any
    rng: jax.Array
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    #: training-health sentinel state (``tpuframe.fault.health``): loss
    #: EWMA + bad-step bookkeeping, a plain-dict pytree of f32 scalars
    #: carried through the jitted step so spike detection is branch-free
    #: on device.  Deliberately NOT serialized into checkpoints
    #: (``ckpt._DATA_FIELDS``): a restore restarts the EWMA warmup on
    #: fresh ground, and pre-sentinel checkpoints stay restorable.
    health: Any = flax.struct.field(default_factory=dict)
    #: wire-compression error-feedback residuals
    #: (``tpuframe.parallel.compression.init_comms_state``): one
    #: full-size quantization residual per data-parallel shard, carried
    #: through the compressed train step (EF-SGD).  Empty dict when
    #: gradient compression (or error feedback) is off.  Unlike
    #: ``health``, this IS checkpointed when present — the residual is
    #: accumulated gradient mass, and dropping it on resume would lose
    #: exactly the updates EF was deferring; reshard-on-restore folds
    #: it onto a different world size (``ckpt.checkpoint``).
    comms: Any = flax.struct.field(default_factory=dict)

    def apply_gradients(self, grads: Any, **changes: Any) -> "TrainState":
        opt_state = self.opt_state
        if _any_host_resident(opt_state):
            # ZeRO-3 CPU offload (`deepspeed_config.py:87-105`): the state
            # lives in pinned host memory; stream it to HBM for the update.
            # The step wrapper (make_train_step) moves the new state back.
            opt_state = jax.tree.map(
                lambda x: jax.device_put(x, jax.memory.Space.Device), opt_state
            )
        updates, new_opt_state = self.tx.update(grads, opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            **changes,
        )

    def step_rng(self, name: str = "dropout") -> jax.Array:
        """Per-step, per-collection RNG derived from the state's base key.

        crc32, not ``hash()``: PYTHONHASHSEED randomizes ``hash`` per process,
        which would bake different fold-in constants into each host's compiled
        step and desynchronize nominally-replicated computation."""
        import zlib

        key = jax.random.fold_in(self.rng, self.step)
        return jax.random.fold_in(key, zlib.crc32(name.encode()) % (2**31))


def create_train_state(
    model: Any,
    rng: jax.Array,
    sample_input: jax.Array,
    tx: optax.GradientTransformation,
    plan: ParallelPlan | None = None,
    init_kwargs: dict | None = None,
) -> TrainState:
    """Initialize a TrainState, sharded per ``plan`` from the very first byte.

    With a plan, initialization runs under jit with ``out_shardings`` so
    ZeRO-3 params materialize *already sharded* — no single-device spike,
    the property DeepSpeed stage-3 buys with ``zero.Init()``.
    """
    init_kwargs = dict(init_kwargs or {})
    params_rng, dropout_rng, state_rng = jax.random.split(rng, 3)

    def init_fn():
        variables = model.init(
            {"params": params_rng, "dropout": dropout_rng},
            sample_input,
            **init_kwargs,
        )
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        return params, batch_stats, tx.init(params)

    from tpuframe.fault.health import init_health_state

    step = jnp.zeros((), jnp.int32)
    health = init_health_state()
    if plan is None:
        params, batch_stats, opt_state = init_fn()
    else:
        a_params, a_stats, a_opt = jax.eval_shape(init_fn)
        shardings = (
            plan.param_shardings(a_params),
            plan.param_shardings(a_stats),
            # memory kinds are illegal in jit out_shardings; offload moves
            # the state to pinned host right after init
            plan.state_shardings(a_opt, a_params, with_offload=False),
        )
        params, batch_stats, opt_state = jax.jit(init_fn, out_shardings=shardings)()
        offloaded = plan.state_shardings(a_opt, a_params)
        if offloaded != shardings[2]:
            opt_state = jax.device_put(opt_state, offloaded)
        # Scalars must be *committed replicated* on the same mesh as the
        # params: a checkpoint restore reproduces the template's placement,
        # and a single-device committed step next to mesh-wide params is a
        # jit device mismatch.
        step = jax.device_put(step, plan.replicated())
        state_rng = jax.device_put(state_rng, plan.replicated())
        health = jax.device_put(health, plan.replicated())

    return TrainState(
        step=step,
        params=params,
        opt_state=opt_state,
        batch_stats=batch_stats,
        rng=state_rng,
        apply_fn=model.apply,
        tx=tx,
        health=health,
    )


def param_count(state_or_params: Any) -> int:
    params = getattr(state_or_params, "params", state_or_params)
    return sum(int(x.size) for x in jax.tree.leaves(params))
