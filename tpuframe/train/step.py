"""Jitted train/eval step factories — the framework's hot loop.

The reference's per-batch body (H2D copy -> forward -> loss -> backward ->
allreduce -> optimizer.step, `/root/reference/01_torch_distributor/
01_basic_torch_distributor.py:224-230`) compiles here into ONE XLA program:
forward+backward+update fused, gradients all-reduced (or reduce-scattered
under ZeRO) by the partitioner over ICI, input batch donated, bf16 on the MXU.

Factories return plain jitted callables — the high-level Trainer wraps them,
but they are equally the "Accelerate-style" low-level API (SURVEY.md §7:
train/ exposes both levels).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import optax

from tpuframe.parallel.precision import Policy, full_precision
from tpuframe.parallel.sharding import ParallelPlan
from tpuframe.train.state import TrainState
from tpuframe.core.runtime import shard_map

#: loss_fn(logits, labels) -> per-example losses, pluggable.
LossFn = Callable[[jax.Array, jax.Array], jax.Array]


def cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    mesh=None,
    batch_axes: tuple | None = None,
) -> jax.Array:
    """Integer-label softmax cross entropy (≈ reference's ``nll_loss`` after
    log_softmax, `01_basic_torch_distributor.py:90-92,226`).  Supports soft
    labels (N, C) for CutMix/LabelSmoothing mixtures.

    (B,) integer labels route through the fused Pallas kernel on TPU
    (recompute backward, no HBM softmax materialization) — per batch
    shard under ``shard_map`` when ``mesh`` is given (the step factories
    pass it from their ``plan``), single-chip directly.  Higher-rank
    integer labels keep the optax path."""
    if labels.ndim == logits.ndim:
        return optax.softmax_cross_entropy(logits, labels)
    if labels.ndim == 1 and logits.ndim == 2:
        from tpuframe.ops import fused_cross_entropy

        return fused_cross_entropy(
            logits, labels, mesh=mesh, batch_axes=batch_axes
        )
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


@functools.lru_cache(maxsize=64)
def _supports_mutable(apply_fn) -> bool:
    """True when ``apply_fn`` takes flax's ``mutable=`` kwarg."""
    import inspect

    try:
        return "mutable" in inspect.signature(apply_fn).parameters
    except (TypeError, ValueError):  # builtins / C callables
        return False


def _forward(state: TrainState, params: Any, batch: Mapping[str, jax.Array],
             policy: Policy, train: bool, rng: jax.Array | None,
             loss_fn: LossFn):
    """Shared forward: handles batch_stats mutability, dropout rngs, and
    auxiliary losses (``aux_loss`` collection — MoE load balancing).

    Returns (losses, logits, new_stats, aux) where ``aux`` is the summed
    auxiliary loss (0.0 when the model sows none); train steps add it to
    the objective so e.g. MoE routers actually feel their balance loss."""
    variables = {"params": policy.cast_params_for_compute(params)}
    has_stats = bool(jax.tree.leaves(state.batch_stats))
    if has_stats:
        variables["batch_stats"] = state.batch_stats
    kwargs: dict[str, Any] = {"train": train}
    if train and rng is not None:
        kwargs["rngs"] = {"dropout": rng}
    # "input" is the generic key (token ids, features); "image" the vision
    # alias the reference examples use.  Int inputs pass cast_batch untouched.
    x = batch["input"] if "input" in batch else batch["image"]
    x = policy.cast_batch(x)
    aux = jnp.zeros((), jnp.float32)
    if train:
        if _supports_mutable(state.apply_fn):
            mutable = ["aux_loss"] + (["batch_stats"] if has_stats else [])
            logits, updates = state.apply_fn(variables, x, mutable=mutable, **kwargs)
        else:
            # non-flax apply_fn (e.g. PipelinedTransformerLM's duck-typed
            # adapter) takes no `mutable` kwarg
            logits = state.apply_fn(variables, x, **kwargs)
            updates = {}
        new_stats = updates.get("batch_stats", state.batch_stats)
        aux_leaves = jax.tree.leaves(updates.get("aux_loss", {}))
        if aux_leaves:
            aux = sum(jnp.sum(a) for a in aux_leaves)
    else:
        logits = state.apply_fn(variables, x, **kwargs)
        new_stats = state.batch_stats
    logits = policy.cast_outputs(logits)
    losses = loss_fn(logits, batch["label"])
    return losses, logits, new_stats, aux


def _train_metrics(loss, logits, labels) -> dict:
    """The summed train-metrics triple every train-step flavor reports
    (mean is taken by whoever logs).  One definition — grad-accum adds
    across microbatches, the compressed step psums across shards."""
    hard = jnp.argmax(labels, -1) if labels.ndim == logits.ndim else labels
    n = jnp.asarray(hard.size, jnp.float32)  # tokens for LM, images for vision
    return {
        "loss_sum": loss * n,
        "correct": jnp.sum(jnp.argmax(logits, -1) == hard).astype(jnp.float32),
        "count": n,
    }


def _apply_with_health(state: TrainState, grads: Any, new_stats: Any,
                       loss, metrics: dict, health, *,
                       apply_fn: Callable | None = None, grad_sq=None,
                       extra_state: dict | None = None):
    """The sentinel tail every train-step flavor shares
    (``tpuframe.fault.health``): one fused grad-norm/finiteness
    reduction + the EWMA spike test produce a scalar ``bad`` verdict,
    and a bad step applies NO update — ``jnp.where`` selects the old
    params/opt_state/batch_stats leaf-by-leaf, so the compiled program
    is branch-free and the batch/AOT signature is untouched.  A bad
    step's metrics contributions are zeroed (a NaN loss_sum would
    poison the whole window sum); the health flags ride the metrics
    pytree to the host, which reads them at its window cadence.

    ``apply_fn`` overrides the plain ``state.apply_gradients`` (the
    compressed ZeRO step applies a sharded update + all-gather);
    ``grad_sq`` supplies a pre-reduced global gradient square when the
    gradient tree is sharded across the mesh (the verdict must be
    identical on every shard); ``extra_state`` = ``{field: (old, new)}``
    adds more state fields to the bad-step rollback (the EF residual —
    a poisoned step's quantization error must not be committed).
    """
    from tpuframe.fault.health import health_verdict

    hstate = getattr(state, "health", None)
    if not hstate:
        raise ValueError(
            "health-checked step needs a TrainState with a health slot; "
            "create_train_state initializes one (or pass "
            "health=tpuframe.fault.health.init_health_state() to replace)"
        )
    bad, new_hstate, hmetrics = health_verdict(
        loss, grads, hstate, state.step, health, grad_sq=grad_sq
    )
    if apply_fn is None:
        applied = state.apply_gradients(grads, batch_stats=new_stats)
    else:
        applied = apply_fn(grads)

    def keep_old(old, new):
        return jax.tree.map(lambda o, n: jnp.where(bad, o, n), old, new)

    changes = {
        "params": keep_old(state.params, applied.params),
        "opt_state": keep_old(state.opt_state, applied.opt_state),
        "batch_stats": keep_old(state.batch_stats, applied.batch_stats),
        "health": new_hstate,
    }
    for field, (old, new) in (extra_state or {}).items():
        changes[field] = keep_old(old, new)
    new_state = applied.replace(**changes)
    metrics = {
        k: jnp.where(bad, jnp.zeros_like(v), v) for k, v in metrics.items()
    }
    metrics.update(hmetrics)
    return new_state, metrics


def _bind_loss(loss_fn: LossFn, plan: ParallelPlan | None) -> LossFn:
    """Give the default loss its mesh so the fused CE kernel can run
    per-shard on multi-chip meshes; custom losses pass through untouched."""
    if plan is not None and loss_fn is cross_entropy:
        return functools.partial(
            cross_entropy, mesh=plan.mesh, batch_axes=tuple(plan.data_axes)
        )
    return loss_fn


def _wrap_offload(jstep, plan: ParallelPlan | None):
    """Return the new opt state to pinned host after each step when the
    plan offloads it (jit outputs land on device; the put-back keeps the
    steady-state HBM footprint at params+grads, not params+grads+moments)."""
    if plan is None or not plan._offload_active():
        return jstep
    cache: dict[str, Any] = {}

    def step(state, batch):
        # Restore the *input* placement (pinned_host for offloaded leaves,
        # device for scalars like the adamw count): step N+1 then has the
        # exact sharding signature step N traced with — no recompile, and
        # the step counter stays deviceside where it gates control flow.
        if "sh" not in cache:
            cache["sh"] = jax.tree.map(lambda x: x.sharding, state.opt_state)
        new_state, metrics = jstep(state, batch)
        return (
            new_state.replace(
                opt_state=jax.device_put(new_state.opt_state, cache["sh"])
            ),
            metrics,
        )

    # the compile spine (tpuframe.compile) AOT-lowers through the inner
    # jitted program; the wrapper itself stays the call path (its
    # per-call put-back is host work an executable can't carry)
    step._inner_jit = jstep
    return step


def make_train_step(
    policy: Policy | None = None,
    loss_fn: LossFn = cross_entropy,
    donate: bool = True,
    plan: ParallelPlan | None = None,
    batch_transform: Callable[[dict], dict] | None = None,
    grad_compression: str | None = None,
    health=None,
    grad_clip: float | None = None,
) -> Callable[[TrainState, Mapping[str, jax.Array]], tuple[TrainState, dict]]:
    """Build the jitted train step: (state, batch) -> (state, metrics).

    Metrics are summed (loss_sum, correct, count) so they aggregate exactly
    across microbatches and hosts — the mean is taken by whoever logs.
    ``plan`` (optional) lets the default cross-entropy run its Pallas
    kernel per batch shard over the plan's mesh.  ``batch_transform``
    runs *inside* the jitted program (e.g. fused uint8 normalization:
    ship raw bytes over PCIe, normalize on-chip).

    ``grad_compression="int8"``/``"fp8"`` (or a
    :class:`~tpuframe.parallel.comms_env.CommsConfig`) swaps the
    implicit GSPMD gradient all-reduce for an explicit bucketed,
    error-feedback quantized mean (EQuARX-style, see
    :mod:`tpuframe.parallel.compression`) — ~4x fewer sync bytes where
    DCN bandwidth bounds DP scaling.  Composes with DP and ZeRO-1/2/3
    plans (plan-derived compressed reduce-scatter -> sharded update ->
    all-gather; stage 3 adds gather-on-use over the fsdp-resident
    params); TP/pipeline rules re-shard params inside the model and
    refuse — their shard_map cannot nest inside the compressed one.
    ``grad_clip`` applies a plan-global-norm clip inside the compressed
    step (the uncompressed path chains ``optax.clip_by_global_norm``
    into ``tx`` instead and refuses the kwarg).
    BatchNorm: use the models' PLAIN/sync BN — inside ``shard_map`` it
    sees only its shard, i.e. shard-local statistics (torch-DDP
    semantics) fall out for free; ``bn_stats="local"``/``bn_groups`` is
    the GSPMD-path emulation of the same thing and would degenerate to
    per-sample groups here.

    ``health`` (a :class:`tpuframe.fault.health.HealthPolicy`) arms the
    training-health sentinel: grad-norm/finiteness + EWMA loss-spike
    detection fused into the step, with bad steps applying no update
    (branch-free skip) — see :func:`_apply_with_health`.
    """
    policy = policy or full_precision()
    if grad_compression is not None:
        # the step body runs INSIDE shard_map there: the loss must stay
        # unbound (mesh=None) or the fused-CE kernel would open a second,
        # mismatched shard_map and crash
        return _make_compressed_train_step(
            policy, loss_fn, donate, plan, batch_transform, grad_compression,
            health, grad_clip=grad_clip,
        )
    if grad_clip is not None:
        raise ValueError(
            "grad_clip is a compressed-step parameter (the clip needs the "
            "plan-global synced norm); for the uncompressed step chain "
            "optax.clip_by_global_norm into tx instead"
        )
    loss_fn = _bind_loss(loss_fn, plan)

    def step(state: TrainState, batch: Mapping[str, jax.Array]):
        if batch_transform is not None:
            batch = batch_transform(dict(batch))
        rng = state.step_rng("dropout")

        def compute_loss(params):
            losses, logits, new_stats, aux = _forward(
                state, params, batch, policy, True, rng, loss_fn
            )
            data_loss = jnp.mean(losses)
            # aux (MoE load balance etc.) joins the objective; metrics
            # report the data loss so learning curves stay comparable
            return data_loss + aux, (data_loss, logits, new_stats)

        (_, (loss, logits, new_stats)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        metrics = _train_metrics(loss, logits, batch["label"])
        if health is None:
            new_state = state.apply_gradients(grads, batch_stats=new_stats)
            return new_state, metrics
        return _apply_with_health(state, grads, new_stats, loss, metrics, health)

    return _wrap_offload(jax.jit(step, donate_argnums=(0,) if donate else ()), plan)


class _CompressedStep:
    """Deferred-built compressed train step.

    The shard_map in/out specs depend on the *state's* tree structure
    (per-leaf update sharding, the EF residual layout), which a factory
    can't know — so the program is built on the first call (or AOT
    lower) from the state's shapes, then cached.  ``lower`` makes the
    object a first-class citizen of the compile spine
    (``precompile_call`` AOT-compiles it and dispatches straight to the
    executable — zero recompiles with compression on)."""

    def __init__(self, builder: Callable):
        self._builder = builder
        self._fn = None
        #: static per-step wire accounting (``comms/wire_plan``), set at
        #: build; the Trainer meters ``comms/bytes_on_wire`` from it
        self.wire = None

    def _ensure(self, state):
        if self._fn is None:
            self._fn, self.wire = self._builder(state)

    def __call__(self, state, batch):
        self._ensure(state)
        return self._fn(state, batch)

    def lower(self, state, batch):
        self._ensure(state)
        return self._fn.lower(state, batch)


def _make_compressed_train_step(
    policy: Policy,
    loss_fn: LossFn,
    donate: bool,
    plan: ParallelPlan | None,
    batch_transform: Callable[[dict], dict] | None,
    grad_compression,
    health=None,
    n_microbatches: int = 1,
    grad_clip: float | None = None,
):
    """shard_map train step with explicit bucketed, error-feedback
    compressed gradient sync (:mod:`tpuframe.parallel.compression`).

    Each data shard computes grads on its slice of the batch (grad-accum
    scans microbatches first and compresses ONCE per super-batch), the
    mean crosses the wire as int8/fp8 buckets with per-bucket scales,
    and:

    - stage 0: every shard applies the identical update to its
      replicated params;
    - stage 1/2: plan-sharded leaves take a compressed reduce-scatter,
      the optimizer updates only the owned slice against the plan's
      sharded state, and the f32 update is all-gathered back (the
      arXiv:2004.13336 pipeline, derived from
      ``ParallelPlan.update_shard_specs``);
    - stage 3: params additionally live fsdp-sharded BETWEEN steps
      (``plan.param_spec``): the step all-gathers them on entry
      (gather-on-use), runs the stage-1/2 sliced update against the
      full view, and re-slices the new params back to their storage
      shard on exit — the compressed wire is untouched, only the
      params' resting layout changes.

    ``grad_clip`` (a float) applies torch-style global-norm clipping to
    the *synced* gradient before the update, using the plan-global norm
    (sliced leaves psum across shards), so the scale is identical
    everywhere; the health sentinel still judges the RAW norm — a
    clipped-away spike is exactly what it must see.

    Metrics psum exactly (they're tiny).  Error feedback needs the
    ``TrainState.comms`` residual (``init_comms_state``); a state
    without one runs compressed-without-EF, loudly
    (``comms/ef_inactive``).

    **Overlapped flavor** (``plan.comms_groups`` > 1 or
    ``TPUFRAME_COMMS_GROUPS``): the sync fires as the layout's
    bucket-group schedule (reverse-backward order, one collective per
    group — see :func:`~tpuframe.parallel.compression.sync_gradients`),
    and the grad-accum path peels the last microbatch out of the scan
    so the groups overlap its open backward graph.  Pair with
    ``TPUFRAME_COMMS_ASYNC=1`` so XLA's latency-hiding scheduler
    actually moves the independent collectives into the compute gaps.
    Bit-exact against the single-shot step; the schedule rides
    ``comms/wire_plan`` as the ``overlap_groups``/``groups`` block.
    """
    from jax.sharding import PartitionSpec as P

    from tpuframe.parallel.compression import (
        CommsConfig,
        comms_template,
        grad_layout,
        resolve_fused,
        sync_gradients,
        wire_plan,
    )
    from tpuframe.parallel.sharding import path_str

    config = CommsConfig.from_env(grad_compression)
    assert config is not None  # caller checked grad_compression truthy
    if plan is None:
        raise ValueError("grad_compression needs a plan (its mesh and data axes)")
    # a pinned plan.comms_fused wins over the TPUFRAME_COMMS_FUSED env
    # (plan-first, like comms_groups); the resolved flag rides the plan
    # signature, so fused and staged programs get distinct AOT keys
    config = resolve_fused(plan, config)
    if plan.rules:
        raise ValueError(
            "grad_compression composes with DP and ZeRO-1/2/3 (the "
            "compressed step owns the whole gradient wire); TP/pipeline "
            "rules re-shard params inside the model and own their "
            "collectives — a second shard_map cannot nest inside the "
            f"compressed one (got rules={len(plan.rules)} on this plan)"
        )
    if plan.offload_optimizer:
        raise ValueError(
            "grad_compression does not compose with offload_optimizer: the "
            "compressed step's explicit collectives pin the optimizer "
            "state layout on device"
        )
    mesh = plan.mesh
    data_axes = tuple(a for a in plan.data_axes if mesh.shape[a] > 1) or tuple(
        plan.data_axes[:1]
    )

    def build(state: TrainState):
        from tpuframe.track.telemetry import get_telemetry

        layout = grad_layout(state.params, config, plan)
        expected = comms_template(state.params, config, plan)
        have = {
            path_str(p): tuple(leaf.shape)
            for p, leaf in jax.tree_util.tree_flatten_with_path(state.comms)[0]
        }
        ef = bool(expected) and bool(have)
        if ef and have != {k: tuple(v) for k, v in expected.items()}:
            raise ValueError(
                "TrainState.comms does not match this plan/config's EF "
                f"residual layout (have {have}, expected {expected}); "
                "re-initialize it with parallel.compression."
                "init_comms_state(params, plan, config)"
            )
        run_config = (
            config if ef or not config.error_feedback
            else dataclasses.replace(config, error_feedback=False)
        )
        wire = wire_plan(layout, run_config)
        tele = get_telemetry()
        if config.error_feedback and not ef:
            tele.event(
                "comms/ef_inactive",
                reason="TrainState.comms is empty — init_comms_state() "
                       "was never applied; running compressed without "
                       "error feedback",
            )
        tele.event(
            "comms/wire_plan",
            zero_stage=plan.zero_stage,
            error_feedback=ef,
            n_microbatches=n_microbatches,
            stochastic=run_config.stochastic_rounding,
            **wire,
        )
        sliced_dims = {path: dim for path, _, _, dim in layout.sliced}
        world = layout.world
        # ZeRO-3 gather-on-use: params REST fsdp-sharded (plan.param_spec)
        # and the step materializes the full view on entry / re-slices on
        # exit.  fsdp_dims maps each sharded leaf to its storage dim.
        fsdp_world = plan.axis_size(plan.fsdp_axis)
        fsdp_dims: dict[str, int] = {}
        if plan.zero_stage == 3 and fsdp_world > 1:
            for p, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]:
                spec = plan.param_spec(path_str(p), tuple(leaf.shape))
                for d, entry in enumerate(spec):
                    names = entry if isinstance(entry, tuple) else (entry,)
                    if plan.fsdp_axis in names:
                        fsdp_dims[path_str(p)] = d

        def gather_param(path, leaf):
            dim = fsdp_dims.get(path_str(path))
            if dim is None:
                return leaf
            return jax.lax.all_gather(leaf, plan.fsdp_axis, axis=dim, tiled=True)

        def scatter_param(path, leaf):
            dim = fsdp_dims.get(path_str(path))
            if dim is None:
                return leaf
            chunk = leaf.shape[dim] // fsdp_world
            i = jax.lax.axis_index(plan.fsdp_axis)
            return jax.lax.dynamic_slice_in_dim(leaf, i * chunk, chunk, axis=dim)

        def shard_step(state: TrainState, batch: Mapping[str, jax.Array]):
            if fsdp_dims:
                # gather-on-use: full params for forward/backward/update;
                # the steady-state HBM between steps holds only the shard
                state = state.replace(
                    params=jax.tree_util.tree_map_with_path(
                        gather_param, state.params
                    )
                )

            def _reslice(out):
                new_state, out_metrics = out
                if fsdp_dims:
                    new_state = new_state.replace(
                        params=jax.tree_util.tree_map_with_path(
                            scatter_param, new_state.params
                        )
                    )
                return new_state, out_metrics

            rng = state.step_rng("dropout")
            # decorrelate dropout across shards (params stay identical:
            # the synced gradient is what updates them)
            for ax in data_axes:
                rng = jax.random.fold_in(rng, jax.lax.axis_index(ax))

            if n_microbatches == 1:
                b = batch_transform(dict(batch)) if batch_transform else batch

                def compute_loss(params):
                    losses, logits, new_stats, aux = _forward(
                        state, params, b, policy, True, rng, loss_fn
                    )
                    return (
                        jnp.mean(losses) + aux,
                        (jnp.mean(losses), logits, new_stats),
                    )

                (_, (loss, logits, new_stats)), grads = jax.value_and_grad(
                    compute_loss, has_aux=True
                )(state.params)
                metrics = _train_metrics(loss, logits, b["label"])
            else:
                # grad-accum composition: scan the microbatches, average
                # the accumulated gradient, compress ONCE per super-batch
                zero_grads = jax.tree.map(jnp.zeros_like, state.params)

                def micro(carry, scanned):
                    mb, micro_idx = scanned
                    if batch_transform is not None:
                        mb = batch_transform(dict(mb))
                    grads_acc, stats, acc_metrics = carry
                    mb_rng = jax.random.fold_in(rng, micro_idx)

                    def compute_loss(params):
                        losses, logits, new_stats, aux = _forward(
                            state.replace(batch_stats=stats),
                            params, mb, policy, True, mb_rng, loss_fn,
                        )
                        data_loss = jnp.mean(losses)
                        return data_loss + aux, (data_loss, logits, new_stats)

                    (_, (mloss, logits, new_stats)), g = jax.value_and_grad(
                        compute_loss, has_aux=True
                    )(state.params)
                    acc_metrics = jax.tree.map(
                        jnp.add, acc_metrics,
                        _train_metrics(mloss, logits, mb["label"]),
                    )
                    return (
                        jax.tree.map(jnp.add, grads_acc, g),
                        new_stats,
                        acc_metrics,
                    ), None

                init_metrics = {
                    "loss_sum": jnp.zeros(()),
                    "correct": jnp.zeros(()),
                    "count": jnp.zeros(()),
                }
                carry0 = (zero_grads, state.batch_stats, init_metrics)
                if layout.n_groups > 1:
                    # microbatch interleave: peel the LAST microbatch out
                    # of the scan and inline its VJP, so the grouped sync
                    # below depends on the scan result plus an OPEN
                    # backward graph — group i's collective needs only
                    # its own leaves' final grads and can go on the wire
                    # while the peeled VJP is still producing the rest.
                    # Addition order is the scan's exactly
                    # (((g0+g1)+...)+g_{n-1}), so grads are bit-identical
                    # to the unpeeled scan.
                    head = jax.tree.map(lambda x: x[:-1], batch)
                    carry, _ = jax.lax.scan(
                        micro, carry0, (head, jnp.arange(n_microbatches - 1))
                    )
                    tail = jax.tree.map(lambda x: x[-1], batch)
                    (grads, new_stats, metrics), _ = micro(
                        carry, (tail, jnp.int32(n_microbatches - 1))
                    )
                else:
                    (grads, new_stats, metrics), _ = jax.lax.scan(
                        micro, carry0, (batch, jnp.arange(n_microbatches))
                    )
                grads = jax.tree.map(lambda g: g / n_microbatches, grads)
                loss = metrics["loss_sum"] / jnp.maximum(metrics["count"], 1.0)

            # -- the wire: bucketed compressed sync (+EF residual) --
            srng = None
            if run_config.stochastic_rounding:
                srng = state.step_rng("comms")
                for ax in data_axes:
                    srng = jax.random.fold_in(srng, jax.lax.axis_index(ax))
            synced, new_comms = sync_gradients(
                grads, state.comms, layout, run_config, srng
            )
            # BN moments were computed shard-locally (torch-DDP
            # semantics); average the *updated running stats* so the
            # replicated state is deterministic rather than whichever
            # shard's copy wins assembly
            new_stats = jax.tree.map(
                lambda s: jax.lax.pmean(s, data_axes)
                if jnp.issubdtype(s.dtype, jnp.floating)
                else s,
                new_stats,
            )
            metrics = jax.tree.map(
                lambda m: jax.lax.psum(m, data_axes), metrics
            )
            gloss = jax.lax.pmean(loss, data_axes)

            if not sliced_dims:
                # stage 0 (or a plan too small to slice): identical full
                # mean grads on every shard
                raw_sq = None
                if grad_clip is not None:
                    raw_sq = sum(
                        jnp.sum(jnp.square(leaf.astype(jnp.float32)))
                        for leaf in jax.tree.leaves(synced)
                    )
                    scale = jnp.minimum(
                        1.0, grad_clip / jnp.maximum(jnp.sqrt(raw_sq), 1e-12)
                    )
                    synced = jax.tree.map(lambda g: g * scale, synced)
                if health is None:
                    new_state = state.apply_gradients(
                        synced, batch_stats=new_stats
                    ).replace(comms=new_comms)
                    return _reslice((new_state, metrics))
                # the verdict must be identical on every shard (params
                # are replicated and updated in lockstep): judge the
                # GLOBAL mean loss — the grads are already synced
                return _reslice(_apply_with_health(
                    state, synced, new_stats, gloss, metrics, health,
                    grad_sq=raw_sq,
                    extra_state={"comms": (state.comms, new_comms)},
                ))

            # -- stage 1/2: sharded optimizer update over owned slices --
            idx = jnp.int32(0)
            for ax in layout.axes:
                idx = idx * jax.lax.psum(1, ax) + jax.lax.axis_index(ax)

            def slice_leaf(path, leaf):
                dim = sliced_dims.get(path_str(path))
                if dim is None:
                    return leaf
                chunk = leaf.shape[dim] // world
                return jax.lax.dynamic_slice_in_dim(
                    leaf, idx * chunk, chunk, axis=dim
                )

            def gather_leaf(path, leaf):
                dim = sliced_dims.get(path_str(path))
                if dim is None:
                    return leaf
                return jax.lax.all_gather(
                    leaf, layout.axes, axis=dim, tiled=True
                )

            def zero_apply(grads_mixed):
                # opt_state arrived SLICED (the step's in_specs shard it
                # per update_shard_specs); update the owned slices, then
                # all-gather the f32 *update* onto the replicated params
                params_view = jax.tree_util.tree_map_with_path(
                    slice_leaf, state.params
                )
                updates, new_opt = state.tx.update(
                    grads_mixed, state.opt_state, params_view
                )
                full_updates = jax.tree_util.tree_map_with_path(
                    gather_leaf, updates
                )
                new_params = optax.apply_updates(state.params, full_updates)
                return state.replace(
                    step=state.step + 1,
                    params=new_params,
                    opt_state=new_opt,
                    batch_stats=new_stats,
                )

            # global grad norm: slices psum across shards, full leaves
            # (identical everywhere) added once — same scalar on every
            # shard, so the health verdict can't split the fleet
            sliced_sq = sum(
                jnp.sum(jnp.square(leaf.astype(jnp.float32)))
                for p, leaf in jax.tree_util.tree_flatten_with_path(synced)[0]
                if path_str(p) in sliced_dims
            )
            full_sq = sum(
                jnp.sum(jnp.square(leaf.astype(jnp.float32)))
                for p, leaf in jax.tree_util.tree_flatten_with_path(synced)[0]
                if path_str(p) not in sliced_dims
            )
            grad_sq = jax.lax.psum(sliced_sq, layout.axes) + full_sq
            if grad_clip is not None:
                # plan-global norm → identical scale on every shard
                # (torch clip_grad_norm_ semantics, never shard-local);
                # grad_sq stays RAW for the health verdict below
                scale = jnp.minimum(
                    1.0, grad_clip / jnp.maximum(jnp.sqrt(grad_sq), 1e-12)
                )
                synced = jax.tree.map(lambda g: g * scale, synced)
            if health is None:
                return _reslice(
                    (zero_apply(synced).replace(comms=new_comms), metrics)
                )
            return _reslice(_apply_with_health(
                state, synced, new_stats, gloss, metrics, health,
                apply_fn=zero_apply, grad_sq=grad_sq,
                extra_state={"comms": (state.comms, new_comms)},
            ))

        # -- specs: state fields replicated except the plan-sharded
        # optimizer slices and the per-shard EF residuals --
        param_shapes = {
            path_str(p): tuple(leaf.shape)
            for p, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]
        }

        def opt_spec(path: str, shape: tuple):
            # longest param-path suffix match (mu/nu/EMA mirror params)
            parts = path.split("/")
            for start in range(len(parts)):
                suffix = "/".join(parts[start:])
                if suffix in param_shapes:
                    dim = sliced_dims.get(suffix)
                    if dim is not None and param_shapes[suffix] == tuple(shape):
                        entries = [None] * len(shape)
                        entries[dim] = layout.axes
                        return P(*entries)
                    return P()
            return P()

        def spec_assign(path, leaf):
            field = path_str(path[:1])
            rest = path_str(path[1:])
            if field == "comms":
                return P(layout.axes)
            if field == "params":
                dim = fsdp_dims.get(rest)
                if dim is not None:  # ZeRO-3 storage shard
                    entries = [None] * len(leaf.shape)
                    entries[dim] = plan.fsdp_axis
                    return P(*entries)
                return P()
            if field == "opt_state" and hasattr(leaf, "shape") and leaf.shape:
                return opt_spec(rest, tuple(leaf.shape))
            return P()

        state_specs = jax.tree_util.tree_map_with_path(spec_assign, state)
        batch_spec = P(data_axes)
        if n_microbatches > 1:
            batch_spec = P(None, *batch_spec)
        mapped = shard_map(
            shard_step,
            mesh=mesh,
            in_specs=(state_specs, batch_spec),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
        return (
            jax.jit(mapped, donate_argnums=(0,) if donate else ()),
            wire,
        )

    return _wrap_offload(_CompressedStep(build), plan)


def make_eval_step(
    policy: Policy | None = None,
    loss_fn: LossFn = cross_entropy,
    plan: ParallelPlan | None = None,
    batch_transform: Callable[[dict], dict] | None = None,
) -> Callable[[TrainState, Mapping[str, jax.Array]], dict]:
    """Jitted eval step: (state, batch) -> summed metrics.

    ``batch["weight"]`` (0/1 per example) masks wrap-around-padded duplicates
    the DataLoader adds to equalize per-host counts — eval never double-counts
    (the reference's rank-0-only eval sidesteps this by not distributing eval
    at all, `01_basic_torch_distributor.py:302-323`)."""
    policy = policy or full_precision()
    loss_fn = _bind_loss(loss_fn, plan)

    def step(state: TrainState, batch: Mapping[str, jax.Array]):
        if batch_transform is not None:
            batch = batch_transform(dict(batch))
        losses, logits, _, _ = _forward(
            state, state.params, batch, policy, False, None, loss_fn
        )
        labels = batch["label"]
        hard = jnp.argmax(labels, -1) if labels.ndim == logits.ndim else labels
        weight = batch.get("weight")
        if weight is None:
            weight = jnp.ones_like(losses)
        weight = weight.astype(jnp.float32)
        if weight.ndim < losses.ndim:  # per-example mask over per-token losses
            weight = weight.reshape(weight.shape + (1,) * (losses.ndim - weight.ndim))
        return {
            "loss_sum": jnp.sum(losses * weight),
            "correct": jnp.sum(
                (jnp.argmax(logits, -1) == hard).astype(jnp.float32) * weight
            ),
            "count": jnp.sum(weight),
        }

    return jax.jit(step)


def make_predict_fn(
    policy: Policy | None = None,
    input_transform: Callable[[jax.Array], jax.Array] | None = None,
) -> Callable[[TrainState, jax.Array], jax.Array]:
    """Jitted logits fn for inference (the reference's ``predict_image``
    path, `02_cifar_torch_distributor_resnet.py:370-387`).

    ``input_transform`` runs inside the jitted program — the Trainer wires
    its ``normalize=`` transform here so inference sees the same
    preprocessing as training."""
    policy = policy or full_precision()

    def predict(state: TrainState, x: jax.Array) -> jax.Array:
        if input_transform is not None:
            x = input_transform(x)
        variables = {"params": policy.cast_params_for_compute(state.params)}
        if jax.tree.leaves(state.batch_stats):
            variables["batch_stats"] = state.batch_stats
        logits = state.apply_fn(variables, policy.cast_batch(x), train=False)
        return policy.cast_outputs(logits)

    return jax.jit(predict)


def make_grad_accum_step(
    n_microbatches: int,
    policy: Policy | None = None,
    loss_fn: LossFn = cross_entropy,
    donate: bool = True,
    plan: ParallelPlan | None = None,
    batch_transform: Callable[[dict], dict] | None = None,
    health=None,
    grad_compression=None,
    grad_clip: float | None = None,
):
    """Gradient accumulation over leading-dim microbatches via ``lax.scan``.

    Batch arrays must be shaped (n_microbatches, micro_size, ...).  Grads are
    averaged across microbatches; BN stats roll forward through the scan.
    Replaces DeepSpeed's ``gradient_accumulation_steps: auto``
    (`/root/reference/02_deepspeed/deepspeed_config.py:17`).

    ``grad_compression`` composes: the scan accumulates the super-batch
    gradient first and the compressed sync runs ONCE per optimizer step
    (not per micro-step) — see :func:`_make_compressed_train_step`.
    """
    policy = policy or full_precision()
    if grad_compression is not None:
        # the step body runs inside shard_map there: the loss must stay
        # unbound (mesh=None), same as make_train_step's compressed path
        return _make_compressed_train_step(
            policy, loss_fn, donate, plan, batch_transform,
            grad_compression, health, n_microbatches, grad_clip=grad_clip,
        )
    if grad_clip is not None:
        raise ValueError(
            "grad_clip is a compressed-step parameter (the clip needs the "
            "plan-global synced norm); for the uncompressed step chain "
            "optax.clip_by_global_norm into tx instead"
        )
    loss_fn = _bind_loss(loss_fn, plan)

    def step(state: TrainState, batch: Mapping[str, jax.Array]):
        rng = state.step_rng("dropout")
        zero_grads = jax.tree.map(jnp.zeros_like, state.params)

        def micro(carry, scanned):
            mb, micro_idx = scanned
            # transform per microbatch: a whole-super-batch transform
            # before the scan would materialize the full float copy and
            # defeat grad-accum's memory purpose
            if batch_transform is not None:
                mb = batch_transform(dict(mb))
            grads_acc, stats, metrics = carry
            # distinct dropout mask per microbatch — matching what the same
            # samples would draw as separate steps
            mb_rng = jax.random.fold_in(rng, micro_idx)

            def compute_loss(params):
                losses, logits, new_stats, aux = _forward(
                    state.replace(batch_stats=stats),
                    params, mb, policy, True, mb_rng, loss_fn,
                )
                data_loss = jnp.mean(losses)
                return data_loss + aux, (data_loss, logits, new_stats)

            (_, (loss, logits, new_stats)), grads = jax.value_and_grad(
                compute_loss, has_aux=True
            )(state.params)
            metrics = jax.tree.map(
                jnp.add, metrics, _train_metrics(loss, logits, mb["label"])
            )
            grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
            return (grads_acc, new_stats, metrics), None

        init_metrics = {
            "loss_sum": jnp.zeros(()),
            "correct": jnp.zeros(()),
            "count": jnp.zeros(()),
        }
        (grads, new_stats, metrics), _ = jax.lax.scan(
            micro,
            (zero_grads, state.batch_stats, init_metrics),
            (batch, jnp.arange(n_microbatches)),
        )
        grads = jax.tree.map(lambda g: g / n_microbatches, grads)
        if health is None:
            new_state = state.apply_gradients(grads, batch_stats=new_stats)
            return new_state, metrics
        # the super-batch is the unit of update, so it is the unit of
        # health too: one NaN microbatch poisons the accumulated grads
        # (sum propagates it) and the whole step skips
        mean_loss = metrics["loss_sum"] / jnp.maximum(metrics["count"], 1.0)
        return _apply_with_health(
            state, grads, new_stats, mean_loss, metrics, health
        )

    return _wrap_offload(jax.jit(step, donate_argnums=(0,) if donate else ()), plan)


def merge_metrics(acc: dict | None, new: Mapping[str, jax.Array]) -> dict:
    """Host-side accumulation of summed metrics across steps."""
    new = {k: float(v) for k, v in new.items()}
    if acc is None:
        return new
    return {k: acc.get(k, 0.0) + v for k, v in new.items()}


def summarize_metrics(acc: Mapping[str, float], prefix: str = "") -> dict:
    """Summed metrics -> {loss, accuracy} means."""
    count = max(acc.get("count", 0.0), 1.0)
    out = {
        f"{prefix}loss": acc.get("loss_sum", 0.0) / count,
        f"{prefix}accuracy": acc.get("correct", 0.0) / count,
    }
    return out
