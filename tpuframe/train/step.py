"""Jitted train/eval step factories — the framework's hot loop.

The reference's per-batch body (H2D copy -> forward -> loss -> backward ->
allreduce -> optimizer.step, `/root/reference/01_torch_distributor/
01_basic_torch_distributor.py:224-230`) compiles here into ONE XLA program:
forward+backward+update fused, gradients all-reduced (or reduce-scattered
under ZeRO) by the partitioner over ICI, input batch donated, bf16 on the MXU.

Factories return plain jitted callables — the high-level Trainer wraps them,
but they are equally the "Accelerate-style" low-level API (SURVEY.md §7:
train/ exposes both levels).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import optax

from tpuframe.parallel.precision import Policy, full_precision
from tpuframe.parallel.sharding import ParallelPlan
from tpuframe.train.state import TrainState
from tpuframe.core.runtime import shard_map

#: loss_fn(logits, labels) -> per-example losses, pluggable.
LossFn = Callable[[jax.Array, jax.Array], jax.Array]


def cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    mesh=None,
    batch_axes: tuple | None = None,
) -> jax.Array:
    """Integer-label softmax cross entropy (≈ reference's ``nll_loss`` after
    log_softmax, `01_basic_torch_distributor.py:90-92,226`).  Supports soft
    labels (N, C) for CutMix/LabelSmoothing mixtures.

    (B,) integer labels route through the fused Pallas kernel on TPU
    (recompute backward, no HBM softmax materialization) — per batch
    shard under ``shard_map`` when ``mesh`` is given (the step factories
    pass it from their ``plan``), single-chip directly.  Higher-rank
    integer labels keep the optax path."""
    if labels.ndim == logits.ndim:
        return optax.softmax_cross_entropy(logits, labels)
    if labels.ndim == 1 and logits.ndim == 2:
        from tpuframe.ops import fused_cross_entropy

        return fused_cross_entropy(
            logits, labels, mesh=mesh, batch_axes=batch_axes
        )
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


@functools.lru_cache(maxsize=64)
def _supports_mutable(apply_fn) -> bool:
    """True when ``apply_fn`` takes flax's ``mutable=`` kwarg."""
    import inspect

    try:
        return "mutable" in inspect.signature(apply_fn).parameters
    except (TypeError, ValueError):  # builtins / C callables
        return False


def _forward(state: TrainState, params: Any, batch: Mapping[str, jax.Array],
             policy: Policy, train: bool, rng: jax.Array | None,
             loss_fn: LossFn):
    """Shared forward: handles batch_stats mutability, dropout rngs, and
    auxiliary losses (``aux_loss`` collection — MoE load balancing).

    Returns (losses, logits, new_stats, aux) where ``aux`` is the summed
    auxiliary loss (0.0 when the model sows none); train steps add it to
    the objective so e.g. MoE routers actually feel their balance loss."""
    variables = {"params": policy.cast_params_for_compute(params)}
    has_stats = bool(jax.tree.leaves(state.batch_stats))
    if has_stats:
        variables["batch_stats"] = state.batch_stats
    kwargs: dict[str, Any] = {"train": train}
    if train and rng is not None:
        kwargs["rngs"] = {"dropout": rng}
    # "input" is the generic key (token ids, features); "image" the vision
    # alias the reference examples use.  Int inputs pass cast_batch untouched.
    x = batch["input"] if "input" in batch else batch["image"]
    x = policy.cast_batch(x)
    aux = jnp.zeros((), jnp.float32)
    if train:
        if _supports_mutable(state.apply_fn):
            mutable = ["aux_loss"] + (["batch_stats"] if has_stats else [])
            logits, updates = state.apply_fn(variables, x, mutable=mutable, **kwargs)
        else:
            # non-flax apply_fn (e.g. PipelinedTransformerLM's duck-typed
            # adapter) takes no `mutable` kwarg
            logits = state.apply_fn(variables, x, **kwargs)
            updates = {}
        new_stats = updates.get("batch_stats", state.batch_stats)
        aux_leaves = jax.tree.leaves(updates.get("aux_loss", {}))
        if aux_leaves:
            aux = sum(jnp.sum(a) for a in aux_leaves)
    else:
        logits = state.apply_fn(variables, x, **kwargs)
        new_stats = state.batch_stats
    logits = policy.cast_outputs(logits)
    losses = loss_fn(logits, batch["label"])
    return losses, logits, new_stats, aux


def _train_metrics(loss, logits, labels) -> dict:
    """The summed train-metrics triple every train-step flavor reports
    (mean is taken by whoever logs).  One definition — grad-accum adds
    across microbatches, the compressed step psums across shards."""
    hard = jnp.argmax(labels, -1) if labels.ndim == logits.ndim else labels
    n = jnp.asarray(hard.size, jnp.float32)  # tokens for LM, images for vision
    return {
        "loss_sum": loss * n,
        "correct": jnp.sum(jnp.argmax(logits, -1) == hard).astype(jnp.float32),
        "count": n,
    }


def _apply_with_health(state: TrainState, grads: Any, new_stats: Any,
                       loss, metrics: dict, health):
    """The sentinel tail every train-step flavor shares
    (``tpuframe.fault.health``): one fused grad-norm/finiteness
    reduction + the EWMA spike test produce a scalar ``bad`` verdict,
    and a bad step applies NO update — ``jnp.where`` selects the old
    params/opt_state/batch_stats leaf-by-leaf, so the compiled program
    is branch-free and the batch/AOT signature is untouched.  A bad
    step's metrics contributions are zeroed (a NaN loss_sum would
    poison the whole window sum); the health flags ride the metrics
    pytree to the host, which reads them at its window cadence.
    """
    from tpuframe.fault.health import health_verdict

    hstate = getattr(state, "health", None)
    if not hstate:
        raise ValueError(
            "health-checked step needs a TrainState with a health slot; "
            "create_train_state initializes one (or pass "
            "health=tpuframe.fault.health.init_health_state() to replace)"
        )
    bad, new_hstate, hmetrics = health_verdict(
        loss, grads, hstate, state.step, health
    )
    applied = state.apply_gradients(grads, batch_stats=new_stats)

    def keep_old(old, new):
        return jax.tree.map(lambda o, n: jnp.where(bad, o, n), old, new)

    new_state = applied.replace(
        params=keep_old(state.params, applied.params),
        opt_state=keep_old(state.opt_state, applied.opt_state),
        batch_stats=keep_old(state.batch_stats, applied.batch_stats),
        health=new_hstate,
    )
    metrics = {
        k: jnp.where(bad, jnp.zeros_like(v), v) for k, v in metrics.items()
    }
    metrics.update(hmetrics)
    return new_state, metrics


def _bind_loss(loss_fn: LossFn, plan: ParallelPlan | None) -> LossFn:
    """Give the default loss its mesh so the fused CE kernel can run
    per-shard on multi-chip meshes; custom losses pass through untouched."""
    if plan is not None and loss_fn is cross_entropy:
        return functools.partial(
            cross_entropy, mesh=plan.mesh, batch_axes=tuple(plan.data_axes)
        )
    return loss_fn


def _wrap_offload(jstep, plan: ParallelPlan | None):
    """Return the new opt state to pinned host after each step when the
    plan offloads it (jit outputs land on device; the put-back keeps the
    steady-state HBM footprint at params+grads, not params+grads+moments)."""
    if plan is None or not plan._offload_active():
        return jstep
    cache: dict[str, Any] = {}

    def step(state, batch):
        # Restore the *input* placement (pinned_host for offloaded leaves,
        # device for scalars like the adamw count): step N+1 then has the
        # exact sharding signature step N traced with — no recompile, and
        # the step counter stays deviceside where it gates control flow.
        if "sh" not in cache:
            cache["sh"] = jax.tree.map(lambda x: x.sharding, state.opt_state)
        new_state, metrics = jstep(state, batch)
        return (
            new_state.replace(
                opt_state=jax.device_put(new_state.opt_state, cache["sh"])
            ),
            metrics,
        )

    # the compile spine (tpuframe.compile) AOT-lowers through the inner
    # jitted program; the wrapper itself stays the call path (its
    # per-call put-back is host work an executable can't carry)
    step._inner_jit = jstep
    return step


def make_train_step(
    policy: Policy | None = None,
    loss_fn: LossFn = cross_entropy,
    donate: bool = True,
    plan: ParallelPlan | None = None,
    batch_transform: Callable[[dict], dict] | None = None,
    grad_compression: str | None = None,
    health=None,
) -> Callable[[TrainState, Mapping[str, jax.Array]], tuple[TrainState, dict]]:
    """Build the jitted train step: (state, batch) -> (state, metrics).

    Metrics are summed (loss_sum, correct, count) so they aggregate exactly
    across microbatches and hosts — the mean is taken by whoever logs.
    ``plan`` (optional) lets the default cross-entropy run its Pallas
    kernel per batch shard over the plan's mesh.  ``batch_transform``
    runs *inside* the jitted program (e.g. fused uint8 normalization:
    ship raw bytes over PCIe, normalize on-chip).

    ``grad_compression="int8"`` swaps the implicit GSPMD gradient
    all-reduce for an explicit int8-quantized mean (EQuARX-style, see
    :mod:`tpuframe.parallel.compression`) — ~4x fewer sync bytes where
    DCN bandwidth bounds DP scaling.  Pure-DP plans only (ZeRO/TP
    re-shard gradients and own their collectives).  BatchNorm: use the
    models' PLAIN/sync BN — inside ``shard_map`` it sees only its shard,
    i.e. shard-local statistics (torch-DDP semantics) fall out for free;
    ``bn_stats="local"``/``bn_groups`` is the GSPMD-path emulation of
    the same thing and would degenerate to per-sample groups here.

    ``health`` (a :class:`tpuframe.fault.health.HealthPolicy`) arms the
    training-health sentinel: grad-norm/finiteness + EWMA loss-spike
    detection fused into the step, with bad steps applying no update
    (branch-free skip) — see :func:`_apply_with_health`.
    """
    policy = policy or full_precision()
    if grad_compression is not None:
        # the step body runs INSIDE shard_map there: the loss must stay
        # unbound (mesh=None) or the fused-CE kernel would open a second,
        # mismatched shard_map and crash
        return _make_compressed_train_step(
            policy, loss_fn, donate, plan, batch_transform, grad_compression,
            health,
        )
    loss_fn = _bind_loss(loss_fn, plan)

    def step(state: TrainState, batch: Mapping[str, jax.Array]):
        if batch_transform is not None:
            batch = batch_transform(dict(batch))
        rng = state.step_rng("dropout")

        def compute_loss(params):
            losses, logits, new_stats, aux = _forward(
                state, params, batch, policy, True, rng, loss_fn
            )
            data_loss = jnp.mean(losses)
            # aux (MoE load balance etc.) joins the objective; metrics
            # report the data loss so learning curves stay comparable
            return data_loss + aux, (data_loss, logits, new_stats)

        (_, (loss, logits, new_stats)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        metrics = _train_metrics(loss, logits, batch["label"])
        if health is None:
            new_state = state.apply_gradients(grads, batch_stats=new_stats)
            return new_state, metrics
        return _apply_with_health(state, grads, new_stats, loss, metrics, health)

    return _wrap_offload(jax.jit(step, donate_argnums=(0,) if donate else ()), plan)


def _make_compressed_train_step(
    policy: Policy,
    loss_fn: LossFn,
    donate: bool,
    plan: ParallelPlan | None,
    batch_transform: Callable[[dict], dict] | None,
    grad_compression: str,
    health=None,
):
    """shard_map train step with explicit quantized gradient sync.

    Each data shard computes grads on its slice of the batch, the mean
    crosses the wire as int8 (:func:`quantized_pmean`), and every shard
    applies the identical update to its replicated params.  Metrics psum
    exactly (they're tiny).
    """
    from jax.sharding import PartitionSpec as P

    from tpuframe.parallel.compression import quantized_pmean

    if grad_compression != "int8":
        raise ValueError(
            f"unknown grad_compression {grad_compression!r}; known: 'int8'"
        )
    if plan is None:
        raise ValueError("grad_compression needs a plan (its mesh and data axes)")
    if plan.zero_stage != 0 or plan.rules:
        raise ValueError(
            "grad_compression is pure-DP only: ZeRO/TP re-shard gradients "
            f"and own their collectives (got zero_stage={plan.zero_stage}, "
            f"rules={bool(plan.rules)})"
        )
    mesh = plan.mesh
    data_axes = tuple(a for a in plan.data_axes if mesh.shape[a] > 1) or tuple(
        plan.data_axes[:1]
    )

    def shard_step(state: TrainState, batch: Mapping[str, jax.Array]):
        if batch_transform is not None:
            batch = batch_transform(dict(batch))
        rng = state.step_rng("dropout")
        # decorrelate dropout across shards (params stay identical:
        # the synced gradient is what updates them)
        for ax in data_axes:
            rng = jax.random.fold_in(rng, jax.lax.axis_index(ax))

        def compute_loss(params):
            losses, logits, new_stats, aux = _forward(
                state, params, batch, policy, True, rng, loss_fn
            )
            return jnp.mean(losses) + aux, (jnp.mean(losses), logits, new_stats)

        (_, (loss, logits, new_stats)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        # equal shard batch sizes => mean of per-shard mean-grads is the
        # global mean; the wire format is int8
        grads = quantized_pmean(grads, data_axes)
        # BN moments were computed shard-locally (torch-DDP semantics);
        # average the *updated running stats* so the replicated state is
        # deterministic rather than whichever shard's copy wins assembly
        new_stats = jax.tree.map(
            lambda s: jax.lax.pmean(s, data_axes)
            if jnp.issubdtype(s.dtype, jnp.floating)
            else s,
            new_stats,
        )
        metrics = jax.tree.map(
            lambda m: jax.lax.psum(m, data_axes),
            _train_metrics(loss, logits, batch["label"]),
        )
        if health is None:
            new_state = state.apply_gradients(grads, batch_stats=new_stats)
            return new_state, metrics
        # the verdict must be identical on every shard (params are
        # replicated and updated in lockstep): judge the GLOBAL mean
        # loss, not this shard's — the grads are already synced
        return _apply_with_health(
            state, grads, new_stats, jax.lax.pmean(loss, data_axes),
            metrics, health,
        )

    batch_spec = P(data_axes)
    mapped = shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(P(), batch_spec),  # params/state replicated, batch split
        out_specs=(P(), P()),
        check_vma=False,
    )
    return _wrap_offload(
        jax.jit(mapped, donate_argnums=(0,) if donate else ()), plan
    )


def make_eval_step(
    policy: Policy | None = None,
    loss_fn: LossFn = cross_entropy,
    plan: ParallelPlan | None = None,
    batch_transform: Callable[[dict], dict] | None = None,
) -> Callable[[TrainState, Mapping[str, jax.Array]], dict]:
    """Jitted eval step: (state, batch) -> summed metrics.

    ``batch["weight"]`` (0/1 per example) masks wrap-around-padded duplicates
    the DataLoader adds to equalize per-host counts — eval never double-counts
    (the reference's rank-0-only eval sidesteps this by not distributing eval
    at all, `01_basic_torch_distributor.py:302-323`)."""
    policy = policy or full_precision()
    loss_fn = _bind_loss(loss_fn, plan)

    def step(state: TrainState, batch: Mapping[str, jax.Array]):
        if batch_transform is not None:
            batch = batch_transform(dict(batch))
        losses, logits, _, _ = _forward(
            state, state.params, batch, policy, False, None, loss_fn
        )
        labels = batch["label"]
        hard = jnp.argmax(labels, -1) if labels.ndim == logits.ndim else labels
        weight = batch.get("weight")
        if weight is None:
            weight = jnp.ones_like(losses)
        weight = weight.astype(jnp.float32)
        if weight.ndim < losses.ndim:  # per-example mask over per-token losses
            weight = weight.reshape(weight.shape + (1,) * (losses.ndim - weight.ndim))
        return {
            "loss_sum": jnp.sum(losses * weight),
            "correct": jnp.sum(
                (jnp.argmax(logits, -1) == hard).astype(jnp.float32) * weight
            ),
            "count": jnp.sum(weight),
        }

    return jax.jit(step)


def make_predict_fn(
    policy: Policy | None = None,
    input_transform: Callable[[jax.Array], jax.Array] | None = None,
) -> Callable[[TrainState, jax.Array], jax.Array]:
    """Jitted logits fn for inference (the reference's ``predict_image``
    path, `02_cifar_torch_distributor_resnet.py:370-387`).

    ``input_transform`` runs inside the jitted program — the Trainer wires
    its ``normalize=`` transform here so inference sees the same
    preprocessing as training."""
    policy = policy or full_precision()

    def predict(state: TrainState, x: jax.Array) -> jax.Array:
        if input_transform is not None:
            x = input_transform(x)
        variables = {"params": policy.cast_params_for_compute(state.params)}
        if jax.tree.leaves(state.batch_stats):
            variables["batch_stats"] = state.batch_stats
        logits = state.apply_fn(variables, policy.cast_batch(x), train=False)
        return policy.cast_outputs(logits)

    return jax.jit(predict)


def make_grad_accum_step(
    n_microbatches: int,
    policy: Policy | None = None,
    loss_fn: LossFn = cross_entropy,
    donate: bool = True,
    plan: ParallelPlan | None = None,
    batch_transform: Callable[[dict], dict] | None = None,
    health=None,
):
    """Gradient accumulation over leading-dim microbatches via ``lax.scan``.

    Batch arrays must be shaped (n_microbatches, micro_size, ...).  Grads are
    averaged across microbatches; BN stats roll forward through the scan.
    Replaces DeepSpeed's ``gradient_accumulation_steps: auto``
    (`/root/reference/02_deepspeed/deepspeed_config.py:17`).
    """
    policy = policy or full_precision()
    loss_fn = _bind_loss(loss_fn, plan)

    def step(state: TrainState, batch: Mapping[str, jax.Array]):
        rng = state.step_rng("dropout")
        zero_grads = jax.tree.map(jnp.zeros_like, state.params)

        def micro(carry, scanned):
            mb, micro_idx = scanned
            # transform per microbatch: a whole-super-batch transform
            # before the scan would materialize the full float copy and
            # defeat grad-accum's memory purpose
            if batch_transform is not None:
                mb = batch_transform(dict(mb))
            grads_acc, stats, metrics = carry
            # distinct dropout mask per microbatch — matching what the same
            # samples would draw as separate steps
            mb_rng = jax.random.fold_in(rng, micro_idx)

            def compute_loss(params):
                losses, logits, new_stats, aux = _forward(
                    state.replace(batch_stats=stats),
                    params, mb, policy, True, mb_rng, loss_fn,
                )
                data_loss = jnp.mean(losses)
                return data_loss + aux, (data_loss, logits, new_stats)

            (_, (loss, logits, new_stats)), grads = jax.value_and_grad(
                compute_loss, has_aux=True
            )(state.params)
            metrics = jax.tree.map(
                jnp.add, metrics, _train_metrics(loss, logits, mb["label"])
            )
            grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
            return (grads_acc, new_stats, metrics), None

        init_metrics = {
            "loss_sum": jnp.zeros(()),
            "correct": jnp.zeros(()),
            "count": jnp.zeros(()),
        }
        (grads, new_stats, metrics), _ = jax.lax.scan(
            micro,
            (zero_grads, state.batch_stats, init_metrics),
            (batch, jnp.arange(n_microbatches)),
        )
        grads = jax.tree.map(lambda g: g / n_microbatches, grads)
        if health is None:
            new_state = state.apply_gradients(grads, batch_stats=new_stats)
            return new_state, metrics
        # the super-batch is the unit of update, so it is the unit of
        # health too: one NaN microbatch poisons the accumulated grads
        # (sum propagates it) and the whole step skips
        mean_loss = metrics["loss_sum"] / jnp.maximum(metrics["count"], 1.0)
        return _apply_with_health(
            state, grads, new_stats, mean_loss, metrics, health
        )

    return _wrap_offload(jax.jit(step, donate_argnums=(0,) if donate else ()), plan)


def merge_metrics(acc: dict | None, new: Mapping[str, jax.Array]) -> dict:
    """Host-side accumulation of summed metrics across steps."""
    new = {k: float(v) for k, v in new.items()}
    if acc is None:
        return new
    return {k: acc.get(k, 0.0) + v for k, v in new.items()}


def summarize_metrics(acc: Mapping[str, float], prefix: str = "") -> dict:
    """Summed metrics -> {loss, accuracy} means."""
    count = max(acc.get("count", 0.0), 1.0)
    out = {
        f"{prefix}loss": acc.get("loss_sum", 0.0) / count,
        f"{prefix}accuracy": acc.get("correct", 0.0) / count,
    }
    return out
