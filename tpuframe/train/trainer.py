"""High-level Trainer: the Composer-shaped engine on a jitted TPU step.

Capability parity with the reference's four L4 engines (SURVEY.md §1):

- Composer ``Trainer(model, optimizers, loaders, max_duration, algorithms,
  loggers)`` + ``.fit()`` (`/root/reference/03_composer/
  01_cifar_composer_resnet.ipynb:cell-16`) — same constructor shape, same
  duration grammar, same algorithm/callback/logger registries.
- The DDP epoch loop with rank-0 eval/checkpoint discipline
  (`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:293-323`).
- Ray Train's per-epoch "report metrics + checkpoint bundle" contract via
  the ``report`` hook -> :class:`FitResult` (`/root/reference/05_ray/
  01_fashion_mnist_pytorch_ray.ipynb:cell-6,cell-8`).
- Early stopping / eval cadence from the DeepSpeed TinyImageNet example
  (`/root/reference/02_deepspeed/02_tiny_imagenet_deepspeed_resnet.py:219-297`).

TPU-first: the loop body is ONE donated jitted step on global arrays; host
work (algorithms, metric sums, logging) overlaps device compute through the
DevicePrefetcher pipeline.  Metrics cross host<->device once per logging
interval, not per batch.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpuframe.compile.cache import compile_label
from tpuframe.compile.precompile import (
    ShapeGuard,
    batch_signature,
    format_signature,
    loader_batch_template,
    precompile_step,
)
from tpuframe.core import runtime as rt
from tpuframe.data.loader import DataLoader, DevicePrefetcher
from tpuframe.fault import chaos
from tpuframe.fault import health as _health
from tpuframe.fault import preempt as _preempt
from tpuframe.fault.health import Divergence
from tpuframe.fault.preempt import Preempted
from tpuframe.track import memory as _memory
from tpuframe.track.analyze import StragglerMonitor
from tpuframe.track.telemetry import get_telemetry
from tpuframe.parallel.precision import Policy, align_model_dtype, get_policy
from tpuframe.parallel.sharding import ParallelPlan
from tpuframe.train.algorithms import Algorithm, apply_algorithms, resolve_algorithms
from tpuframe.train.callbacks import Callback
from tpuframe.train.duration import Duration
from tpuframe.train.schedules import resolve_schedule
from tpuframe.train.state import TrainState, create_train_state
from tpuframe.train.step import (
    cross_entropy,
    make_eval_step,
    make_grad_accum_step,
    make_predict_fn,
    make_train_step,
    merge_metrics,
    summarize_metrics,
)


class FitResult:
    """Ray-style structured result: metrics + checkpoint path + error
    (`05_ray/01_fashion_mnist_pytorch_ray.ipynb:cell-8`: ``result.metrics``,
    ``result.checkpoint``, ``result.error``)."""

    def __init__(self):
        self.metrics: dict[str, float] = {}
        self.history: list[dict[str, float]] = []
        self.checkpoint: str | None = None
        self.error: BaseException | None = None
        self.stopped_reason: str | None = None

    def __repr__(self):
        return (
            f"FitResult(metrics={self.metrics}, checkpoint={self.checkpoint!r}, "
            f"error={self.error!r}, stopped={self.stopped_reason!r})"
        )


class Trainer:
    """Train a flax model over a mesh with algorithms/callbacks/loggers.

    Args:
      model: flax module with ``__call__(x, train: bool)``.
      tx: optax transform (or use ``optimizer=`` name + ``lr=``; ``lr``
        also takes an optax schedule or a DeepSpeed-shaped scheduler dict,
        see ``tpuframe.train.schedules``).
      train_dataloader / eval_dataloader: tpuframe DataLoaders.
      max_duration: ``"2ep"`` / ``"500ba"`` / int epochs.
      algorithms: batch algorithms (LabelSmoothing, CutMix, ...).
      callbacks: event hooks (EarlyStopping, ProgressLogger, ...).
      loggers: objects with ``log_params(dict)`` / ``log_metrics(dict, step)``
        (tpuframe.track trackers fit; anything duck-typed works).  Rank-0
        discipline is enforced *here*, not by each logger.
      plan: ParallelPlan (default: pure DP over the current runtime mesh).
      precision: policy name or Policy ("bf16" recommended on TPU).  When
        given, it is the source of truth: the model is cloned so its
        compute dtype matches.  When omitted, the policy follows the
        model's own ``dtype`` knob (explicitly-bf16 models keep bf16
        compute with f32 master params).
      checkpointer: tpuframe.ckpt.Checkpointer (optional; saved per
        ``checkpoint_interval`` epochs + best tracking).
      ema_decay: maintain an exponential moving average of the params
        inside the optimizer state (fused into the train step,
        ZeRO-sharded, checkpointed for free); evaluate/predict/export
        then use the averaged weights.  Typical: 0.999.
      checkpoint_interval_batches: additionally save every N global
        batches *inside* an epoch, bundling the consumer-true loader
        position — a crash then auto-resumes with the very next batch
        (deterministic mid-epoch resume) instead of replaying the epoch.
      eval_interval: run eval every N epochs (0 = never).
      preemption: preemption handling (``tpuframe.fault.preempt``).
        None (default) uses the process-wide watcher when one is
        installed (launch workers install it during bootstrap); True
        installs the process-wide watcher at ``fit()``; False disables;
        a :class:`~tpuframe.fault.PreemptionWatcher` instance is
        installed at ``fit()`` and used directly.  On notice, the
        Trainer finishes the in-flight step, writes a last-chance
        synchronous snapshot (model/opt state + loader position, into
        the ``_intra`` sibling directory) and raises
        :class:`~tpuframe.fault.Preempted` — the supervisor restarts
        the run on a fresh machine from exactly that step.
      preempt_sync_steps: multi-host cadence (in steps) of the
        preemption agreement collective — every host must save the same
        step, so the flag check is an all-gather at a fixed step cadence
        (single-process checks locally every step; the collective only
        exists on pods).
      straggler_sync_steps / straggler_factor: live slow-rank detection
        (``tpuframe.track.analyze.StragglerMonitor``).  Every rank keeps
        a rolling step-time EWMA (``train/step_ewma_s`` gauge); every
        ``straggler_sync_steps`` steps the EWMAs cross ranks through a
        tiny all-gather (degraded to a self-baseline off-pod) and a rank
        exceeding the fleet median by ``straggler_factor`` emits a
        ``train/straggler`` event + the ``train/skew_ratio`` gauge.
        Defaults come from ``TPUFRAME_STRAGGLER_STEPS`` (0 disables;
        else 32) and ``TPUFRAME_STRAGGLER_FACTOR`` (2.0), which launch
        propagates to every worker.
      precompile: AOT warm-start (``tpuframe.compile``).  ``fit()``
        derives the train/eval step signatures from the loader specs and
        lowers+compiles them in a background thread *overlapped with the
        DataLoader / ring-buffer spin-up*, so first-batch latency is
        ``max(compile, loader warmup)`` instead of their sum; the hot
        loop then dispatches straight to the compiled executables (no
        per-first-step re-trace), and the armed shape guard turns any
        runtime signature miss into a loud ``compile/recompile`` event.
        Default None follows ``TPUFRAME_PRECOMPILE`` (on unless set
        falsy); False opts out.  :meth:`precompile` runs the same thing
        synchronously on demand.
      grad_compression: gradient wire format (``"int8"`` / ``"fp8"`` /
        a :class:`~tpuframe.parallel.comms_env.CommsConfig`).  The DP
        allreduce then moves as bucketed quantized payloads with
        per-bucket scales and EF-SGD error feedback (residual carried
        as a checkpointed ``TrainState.comms`` leaf — ~4x fewer sync
        bytes where DCN bandwidth bounds scaling; see
        ``tpuframe.parallel.compression`` and PERF.md round 10).
        Composes with ``grad_accum`` (compress once per super-batch)
        and ZeRO-1/2/3 plans (plan-derived compressed reduce-scatter →
        sharded update → all-gather; stage 3 adds gather-on-use over
        the fsdp-resident params) and with ``grad_clip`` (the clip
        moves inside the compressed step as a plan-global-norm scale);
        refuses TP/pipeline rules.  Default None
        follows ``TPUFRAME_COMMS_COMPRESSION`` (off unless set); the
        per-step wire bytes are metered as ``comms/bytes_on_wire``.
      health: training-health sentinel (``tpuframe.fault.health``).
        The jitted step computes global grad-norm + loss/grad
        finiteness (one fused reduction) and an EWMA loss-spike test on
        device; a bad step applies NO update (branch-free ``jnp.where``
        skip) and its verdict rides the step's metrics pytree — the
        Trainer reads it every ``window`` steps (one tiny device fetch,
        not per-step sync), emits ``health/bad_step`` + counters, and
        raises :class:`~tpuframe.fault.health.Divergence` when
        ``max_bad`` bad steps land inside a window — the supervisor's
        DIVERGENCE class then rolls back to the last *healthy*
        committed checkpoint and re-enters with the configured LR
        backoff / data-order skip.  Every save is stamped with the
        sentinel state (loss EWMA, grad norm, bad-step count) next to
        the topology manifest.  Default None follows ``TPUFRAME_HEALTH``
        (on unless set falsy); False disables; a
        :class:`~tpuframe.fault.health.HealthPolicy` sets thresholds.
    """

    def __init__(
        self,
        model: Any,
        tx: optax.GradientTransformation | None = None,
        train_dataloader: DataLoader | None = None,
        eval_dataloader: DataLoader | None = None,
        *,
        optimizer: str = "adam",
        lr: float | Mapping[str, Any] | optax.Schedule = 1e-3,
        max_duration: str | int = "1ep",
        algorithms: Sequence[Algorithm] = (),
        callbacks: Sequence[Callback] = (),
        loggers: Sequence[Any] = (),
        plan: ParallelPlan | None = None,
        precision: str | Policy | None = None,
        loss_fn: Callable = cross_entropy,
        seed: int = 0,
        num_classes: int | None = None,
        sample_input: np.ndarray | None = None,
        checkpointer: Any = None,
        checkpoint_interval: int = 1,
        checkpoint_interval_batches: int | None = None,
        eval_interval: int = 1,
        log_interval: int = 10,
        report: Callable[[dict, str | None], None] | None = None,
        grad_accum: int | None = None,
        grad_clip: float | None = None,
        grad_compression: str | None = None,
        normalize: tuple | None = None,
        ema_decay: float | None = None,
        preemption: Any = None,
        preempt_sync_steps: int = 16,
        straggler_sync_steps: int | None = None,
        straggler_factor: float | None = None,
        precompile: bool | None = None,
        health: Any = None,
    ):
        if precision is None:
            # follow the model: an explicitly-bf16 model keeps bf16 compute
            # (f32 masters); an f32 model gets the plain f32 policy
            self.policy = Policy(compute_dtype=getattr(model, "dtype", jnp.float32))
            self.model = model
        else:
            # an explicit policy is the source of truth: align the model to
            # it (an f32 model under a bf16 policy would silently up-cast
            # inside every layer and double the HBM traffic)
            self.policy = get_policy(precision)
            self.model = align_model_dtype(model, self.policy)
        self.train_dataloader = train_dataloader
        self.eval_dataloader = eval_dataloader
        self.max_duration = Duration.parse(max_duration)
        self.callbacks = list(callbacks)
        # env-armed sampled profiler capture: a launch that ships
        # TPUFRAME_PROFILE_* gets bounded device-time evidence with no
        # code change; an explicitly-passed ProfilerCallback keeps
        # authority over its own cadence
        if os.environ.get("TPUFRAME_PROFILE_STEPS", "").strip():
            from tpuframe.track.profiler import ProfilerCallback

            if not any(
                isinstance(cb, ProfilerCallback) for cb in self.callbacks
            ):
                env_profiler = ProfilerCallback.from_env()
                if env_profiler is not None:
                    self.callbacks.append(env_profiler)
        self.loggers = list(loggers)
        self.loss_fn = loss_fn
        self.seed = seed
        self.checkpointer = checkpointer
        self.checkpoint_interval = checkpoint_interval
        if checkpoint_interval_batches is None:
            # env-defaulted (tolerant): the cadence half of the autotune
            # config; also live-appliable later via apply_tuned() — the
            # step loop re-reads the attribute every batch
            env_ckpt = _health._env_int("TPUFRAME_CKPT_INTERVAL_BATCHES", 0)
            checkpoint_interval_batches = env_ckpt if env_ckpt > 0 else None
        self.checkpoint_interval_batches = checkpoint_interval_batches
        self.eval_interval = eval_interval
        self.log_interval = log_interval
        self.report = report
        if preempt_sync_steps < 1:
            raise ValueError(
                f"preempt_sync_steps must be >= 1, got {preempt_sync_steps}"
            )
        if (
            preemption is not None
            and not isinstance(preemption, bool)
            and not hasattr(preemption, "requested")
        ):
            raise ValueError(
                "preemption must be None (auto), True (install the "
                "process-wide watcher), False (disable), or a "
                f"PreemptionWatcher; got {type(preemption).__name__}"
            )
        self.preemption = preemption
        self.preempt_sync_steps = preempt_sync_steps
        # live slow-rank detection: persists across epochs (the EWMA and
        # the self-baseline window are run-scoped, not epoch-scoped)
        self._straggler = StragglerMonitor(
            sync_steps=straggler_sync_steps, factor=straggler_factor
        )
        # training-health sentinel: the per-window buffer of the step's
        # on-device bad-step flags (run-scoped like the straggler)
        self.health = _health.resolve_policy(health)
        self._health_flags: list = []
        self._comms_gauge_set = False
        self._pp_gauge_set = False

        if plan is None:
            plan = ParallelPlan(mesh=rt.current_runtime().mesh)
        self.plan = plan
        # per-replica BN ("local") needs to know the data shard count; the
        # model can't see the mesh, so fill it from the plan here
        if (
            getattr(self.model, "bn_stats", None) == "local"
            and not getattr(self.model, "bn_groups", 1)
            and hasattr(self.model, "clone")
        ):
            self.model = self.model.clone(bn_groups=plan.dp_size)

        # wire compression (tpuframe.parallel.compression): the explicit
        # param wins; with grad_compression=None the fleet knob
        # TPUFRAME_COMMS_COMPRESSION decides (off unless set).  Resolved
        # BEFORE the optimizer chain — where the clip lives depends on it.
        from tpuframe.parallel.compression import CommsConfig

        self.comms_config = CommsConfig.from_env(grad_compression)
        # DeepSpeed's gradient_clipping knob (`deepspeed_config.py:18`):
        # global-norm clip.  With a ZeRO-sharded compressed wire the
        # optimizer sees only each shard's update slice, so an optax
        # chain clip would use a shard-local (silently wrong) norm — the
        # clip moves INSIDE the compressed step instead, scaled by the
        # plan-global synced norm (see _make_compressed_train_step).
        self._step_grad_clip: float | None = None
        if tx is None:
            tx = _make_optimizer(optimizer, self._resolve_lr(lr))
            if grad_clip:
                if self.comms_config is not None and plan.zero_stage >= 1:
                    self._step_grad_clip = float(grad_clip)
                else:
                    tx = optax.chain(
                        optax.clip_by_global_norm(float(grad_clip)), tx
                    )
        elif grad_clip:
            raise ValueError(
                "grad_clip only applies when the Trainer builds the optimizer "
                "(tx=None); chain optax.clip_by_global_norm into your tx instead"
            )
        self.ema_decay = ema_decay
        if ema_decay is not None:
            # outermost wrapper: the averaged weights live in opt_state
            # (ZeRO-sharded + checkpointed for free); evaluate/predict/
            # export then use them via _serving_state()
            from tpuframe.train.ema import with_ema

            tx = with_ema(tx, float(ema_decay))
        self.tx = tx

        if num_classes is None:
            num_classes = getattr(
                getattr(train_dataloader, "dataset", None), "num_classes", None
            )
        self.num_classes = num_classes
        self.algorithms = (
            resolve_algorithms(algorithms, num_classes) if algorithms else []
        )
        if sample_input is None and train_dataloader is not None:
            img, _ = train_dataloader.dataset[0]
            sample_input = np.asarray(img)[None]
        self.sample_input = sample_input

        if precompile is None:
            from tpuframe.compile.cache import _FALSY

            v = os.environ.get("TPUFRAME_PRECOMPILE", "").strip().lower()
            precompile = not v or v not in _FALSY
        self.precompile_enabled = bool(precompile)
        # AOT executables keyed by (step kind, batch signature); the
        # shape guard is armed by precompile with the expected set
        self._compiled: dict[tuple, Any] = {}
        self._shape_guard = ShapeGuard()
        self._precompile_thread: threading.Thread | None = None
        self._precompile_report: dict | None = None

        # live loop state
        self.state: TrainState | None = None
        self.epoch = 0
        self.batches_seen = 0
        self.samples_seen = 0
        self._stop_reason: str | None = None
        # mid-epoch resume: loader position restored from a checkpoint,
        # applied at the next epoch start (after its set_epoch rewind)
        self._pending_loader_state: dict | None = None
        self._train_prefetcher: DevicePrefetcher | None = None
        self._intra_ck: Any = None  # lazy sibling checkpointer (snapshots)

        if grad_accum is None:
            # env default (tolerant, restart-apply — the accum factor is
            # baked into the compiled step below)
            grad_accum = max(1, _health._env_int("TPUFRAME_GRAD_ACCUM", 1))
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = grad_accum
        # ``normalize=(mean, std[, scale])``: images cross host->HBM raw
        # (uint8 = 4x less PCIe traffic than f32) and are normalized
        # *inside* the jitted step by the fused Pallas kernel — the
        # reference's host-side ToTensor+Normalize
        # (`utils/hf_dataset_utilities.py:70-80`) with the same
        # convention: inputs in 0-255 (uint8 or float — algorithms like
        # MixUp emit 0-255 floats), mean/std in [0, 1] units.  Pass an
        # explicit third element to override the 1/255 scale.
        self.normalize = normalize
        # The ONE place the normalize tuple is interpreted — training,
        # eval, and the serving-artifact export all read these, so the
        # preprocessing convention cannot skew between them.
        if normalize is not None:
            _mean, _std, *_rest = normalize
            self._norm_args = (_mean, _std, _rest[0] if _rest else 1.0 / 255.0)
        else:
            self._norm_args = None

        def image_transform(img, mesh):
            from tpuframe.ops import normalize_images

            mean, std, scale = self._norm_args
            return normalize_images(
                img, mean, std, scale=scale,
                out_dtype=self.policy.compute_dtype, mesh=mesh,
                batch_axes=tuple(self.plan.data_axes),
            )

        train_transform = eval_transform = None
        if normalize is not None:
            # the mesh-sharded kernel matches the plain (B, ...) layout;
            # grad-accum train batches are (n_micro, micro, ...) and are
            # normalized per microbatch inside the scan (mesh=None there —
            # XLA shards + fuses the jnp path natively).  Eval batches are
            # never microbatched, so eval always keeps the kernel path.
            def train_transform(batch: dict) -> dict:
                mesh = self.plan.mesh if self.grad_accum == 1 else None
                batch["image"] = image_transform(batch["image"], mesh)
                return batch

            def eval_transform(batch: dict) -> dict:
                batch["image"] = image_transform(batch["image"], self.plan.mesh)
                return batch

        if grad_accum > 1:
            # DeepSpeed's gradient_accumulation_steps
            # (`deepspeed_config.py:17`): host batches are reshaped to
            # (n_micro, micro, ...) in _device_batches.  Compression
            # composes: the scan accumulates the super-batch gradient
            # and the compressed sync runs once per optimizer step.
            self._train_step = make_grad_accum_step(
                grad_accum, self.policy, loss_fn, plan=self.plan,
                batch_transform=train_transform,
                health=self.health,
                grad_compression=self.comms_config,
                grad_clip=self._step_grad_clip,
            )
        else:
            self._train_step = make_train_step(
                self.policy, loss_fn, plan=self.plan,
                batch_transform=train_transform,
                grad_compression=self.comms_config,
                health=self.health,
                grad_clip=self._step_grad_clip,
            )
        self._eval_step = make_eval_step(
            self.policy, loss_fn, plan=self.plan, batch_transform=eval_transform
        )
        self._predict = make_predict_fn(
            self.policy,
            input_transform=(
                (lambda x: image_transform(x, self.plan.mesh))
                if normalize is not None
                else None
            ),
        )

    # -- wiring ------------------------------------------------------------
    def _resolve_lr(self, lr):
        """Accept a float, an optax schedule, or a DeepSpeed-shaped
        scheduler dict (``{"type": "WarmupLR", "params": {...}}`` or a full
        config carrying a ``"scheduler"`` key — `deepspeed_config.py:33-40`);
        ``total_num_steps: "auto"`` resolves against max_duration and the
        train dataloader.

        A divergence-recovery directive (``fault.health``: the
        supervisor escalates one per rollback) scales the resolved
        schedule by its compounded LR backoff — the perturbation that
        keeps a deterministic replay from re-hitting the same spike.
        Wrapping the *schedule* (not the optimizer chain) keeps the
        opt_state structure identical, so the rolled-back checkpoint
        restores cleanly."""
        schedule = resolve_schedule(
            lr,
            total_steps=_planned_total_steps(self.max_duration, self.train_dataloader),
        )
        scale = _health.recovery_directive().lr_scale
        if scale == 1.0:
            return schedule
        get_telemetry().event("health/lr_backoff", lr_scale=round(scale, 6))
        if callable(schedule):
            return lambda step: schedule(step) * scale
        return schedule * scale

    @property
    def is_main(self) -> bool:
        return rt.is_main_process()

    def request_stop(self, reason: str) -> None:
        """Callbacks call this to end fit() after the current epoch."""
        self._stop_reason = reason

    def _intra_checkpointer(self, create: bool = False):
        """Sibling checkpointer for mid-epoch snapshots, ``max_to_keep=1``.

        A SEPARATE directory keeps snapshots out of the main
        checkpointer's retention (frequent snapshots would evict real
        epoch-end checkpoints mid-epoch) and out of its step namespace
        (an epoch-end save landing on a snapshot's optimizer step would
        otherwise collide).  Only the most recent snapshot matters for
        crash-resume, so one is kept.
        """
        if self._intra_ck is None and self.checkpointer is not None:
            from tpuframe.ckpt import Checkpointer
            from tpuframe.ckpt.meta import latest_step

            intra_dir = str(self.checkpointer.directory) + "_intra"
            # Construct when the feature is on, OR when a previous run
            # (that had it on) left a snapshot behind — auto-resume must
            # see that snapshot even if this run disabled the feature,
            # else a restart silently replays from an older epoch-end
            # checkpoint.  The path probe avoids creating the directory
            # just to look.  ``create`` forces construction (the
            # preemption last-chance save needs a snapshot home even
            # with interval snapshots off).
            if (
                create
                or self.checkpoint_interval_batches
                or latest_step(intra_dir) is not None
            ):
                self._intra_ck = Checkpointer(intra_dir, max_to_keep=1)
        return self._intra_ck

    def _emit(self, hook: str, *args) -> None:
        for cb in self.callbacks:
            getattr(cb, hook)(self, *args)

    def _meter_comms(self, tele) -> None:
        """Per-step bytes-on-wire accounting: the compressed step's wire
        plan is static per signature, so the meter is one host add per
        step (no device sync).  f32 runs meter nothing."""
        wire = getattr(self._train_step, "wire", None)
        if not wire or not wire.get("bytes_per_step"):
            return
        if not self._comms_gauge_set:
            tele.registry.gauge("comms/bytes_per_step").set(
                wire["bytes_per_step"]
            )
            # the declared collective schedule: >1 means the sync fires
            # as that many bucket groups in reverse-backward order (bytes
            # are invariant under grouping; exposed-comms is what moves)
            tele.registry.gauge("comms/overlap_groups").set(
                wire.get("overlap_groups") or 1
            )
            self._comms_gauge_set = True
        tele.registry.counter("comms/bytes_on_wire").inc(
            wire["bytes_per_step"]
        )
        if wire.get("fused"):
            # steps whose sync rode the in-collective (fused ring)
            # transport — bytes are invariant under fusion, so this
            # counter is how dashboards tell the transports apart
            tele.registry.counter("comms/fused_steps").inc()

    def _meter_pp(self, tele) -> None:
        """Pipeline-plan accounting, same shape as the comms meter: the
        schedule is static per plan signature, so the first step emits
        one ``pp/schedule`` event + sets the gauges, and every pipelined
        step is one host counter add.  Non-pipeline plans meter nothing."""
        stages = self.plan.axis_size("pipe")
        if stages <= 1:
            return
        sched = self.plan.comms_schedule()
        if not self._pp_gauge_set:
            tele.event(
                "pp/schedule",
                schedule=sched["pp_schedule"],
                pinned=sched["pp_pinned"],
                stages=stages,
                microbatches=self.plan.pp_microbatches,
                signature=self.plan.signature(),
            )
            tele.registry.gauge("pp/stages").set(stages)
            tele.registry.gauge("pp/microbatches").set(
                self.plan.pp_microbatches or 0
            )
            self._pp_gauge_set = True
        tele.registry.counter("pp/steps").inc()

    # -- preemption ----------------------------------------------------------
    def _preempt_watcher(self):
        if self.preemption is False:
            return None
        if self.preemption is None:
            return _preempt.active_watcher()
        return self.preemption

    def _maybe_preempt_exit(self) -> None:
        """Step-boundary preemption exit (``tpuframe.fault.preempt``).

        Single-process: the local flag is checked every step.  Multi-host:
        hosts learn of the notice at different times, but all must save
        the SAME step — so the flag crosses hosts through a tiny
        all-gather at a fixed step cadence (``preempt_sync_steps``),
        entered by every host at the same step boundary (the loop is
        synchronous).  On agreement: one synchronous snapshot (state +
        consumer-true loader position, into the ``_intra`` sibling dir,
        so auto-resume continues from this very step), then
        :class:`Preempted` propagates out with the checkpoint path.
        """
        watcher = self._preempt_watcher()
        multi_host = rt.process_count() > 1
        if watcher is None and not multi_host:
            return
        local = watcher is not None and watcher.requested
        if multi_host:
            if self.batches_seen % self.preempt_sync_steps:
                return
            flagged = _preempt.agree(local)
        else:
            flagged = local
        if not flagged:
            return
        reason = (watcher.reason if watcher is not None and watcher.reason
                  else "peer-host")
        tele = get_telemetry()
        path = None
        if self.checkpointer is not None:
            intra = self._intra_checkpointer(create=True)
            cur_step = int(jax.device_get(self.state.step))
            if intra.latest_step() == cur_step:
                # an interval snapshot already captured this exact step
                path = str(intra.directory) + f"/{cur_step}"
            else:
                meta = {
                    "epoch": self.epoch,
                    "batches_seen": self.batches_seen,
                    "samples_seen": self.samples_seen,
                    "preempted": True,
                    "global_batch": self.train_dataloader.global_batch_size,
                }
                if (
                    self._train_prefetcher is not None
                    and hasattr(self.train_dataloader, "state_dict")
                ):
                    meta["loader_state"] = self._train_prefetcher.state_dict()
                elif self._train_prefetcher is not None:
                    # mid-epoch with an untrackable loader: the snapshot
                    # still beats losing the step, but resume replays
                    # this epoch from its first batch.  Warn (raising
                    # here would forfeit the last-chance save entirely —
                    # unlike opt-in interval snapshots, which reject
                    # untrackable loaders up front).
                    import warnings

                    warnings.warn(
                        "preemption snapshot taken mid-epoch but the "
                        f"train_dataloader ({type(self.train_dataloader).__name__}) "
                        "has no state_dict(): resume will replay this "
                        "epoch's already-trained batches",
                        stacklevel=2,
                    )
                    meta["loader_state_missing"] = True
                with tele.span(
                    "fault/preempt_checkpoint", step=self.batches_seen
                ), tele.guard("ckpt/save"):
                    path = intra.save(self.state, meta=meta, plan=self.plan,
                                      health=self._health_stamp())
                    intra.wait()  # synchronous: the machine is going away
        # no counter here: fault/preempt_notices counted at the watcher,
        # fault/preemptions at the supervisor's restart — incrementing a
        # third time per event would double-read on dashboards
        tele.event(
            "fault/preempted",
            reason=reason,
            batch=self.batches_seen,
            checkpoint=path,
        )
        self._stop_reason = f"preempted: {reason}"
        if watcher is not None:
            # the notice is fully acted on (checkpoint written): consume
            # the flag HERE, on the watcher that was actually checked —
            # an in-process supervised restart of a Trainer holding an
            # explicit watcher must not re-preempt at its first boundary
            # (a real preemption replaces the process; clearing is moot)
            watcher.clear()
        raise Preempted(reason, step=self.batches_seen, checkpoint=path)

    # -- training health -----------------------------------------------------
    def _health_step(self, metrics: Mapping[str, Any]) -> None:
        """Buffer the step's on-device bad flag; check per window.

        The buffer holds the scalar flag arrays un-fetched (a list
        append — zero dispatch, zero sync on the hot path); the only
        host sync is the once-per-window fused fetch in
        :meth:`_health_check`, so the sentinel costs the hot loop
        nothing between checks."""
        if self.health is None:
            return
        stats = metrics.get("health_stats")
        if stats is None:
            return
        self._health_flags.append(stats)
        if len(self._health_flags) >= self.health.window:
            self._health_check()

    def _health_check(self) -> None:
        """Materialize the window's verdict: gauges + ``health/bad_step``
        events, and the escalation — ``max_bad`` bad steps inside the
        window raises :class:`Divergence` for the supervisor's rollback
        ladder."""
        import math

        if self.health is None or not self._health_flags:
            return
        stats = jax.device_get(self._health_flags)
        n_bad = int(round(sum(float(s[0]) for s in stats)))
        window_steps = len(stats)
        self._health_flags = []
        tele = get_telemetry()
        hs = {
            k: float(v) for k, v in jax.device_get(self.state.health).items()
        }
        for key, name in (("loss_ewma", "health/loss_ewma"),
                          ("grad_norm", "health/grad_norm")):
            if math.isfinite(hs.get(key, float("nan"))):
                tele.registry.gauge(name).set(hs[key])
        if not n_bad:
            return
        tele.registry.counter("health/bad_steps").inc(n_bad)
        tele.event(
            "health/bad_step",
            batch=self.batches_seen,
            bad_in_window=n_bad,
            window_steps=window_steps,
            bad_steps_total=int(hs.get("bad_steps", 0.0)),
            loss_ewma=hs["loss_ewma"] if math.isfinite(hs["loss_ewma"]) else None,
            grad_norm=hs["grad_norm"] if math.isfinite(hs["grad_norm"]) else None,
        )
        if n_bad >= self.health.max_bad:
            tele.registry.counter("health/divergences").inc()
            tele.event(
                "health/divergence",
                batch=self.batches_seen,
                bad_in_window=n_bad,
                window_steps=window_steps,
                max_bad=self.health.max_bad,
            )
            raise Divergence(
                f"{n_bad} bad step(s) inside a {window_steps}-step health "
                f"window (max_bad={self.health.max_bad}) at batch "
                f"{self.batches_seen}: skip-step is no longer converging",
                step=self.batches_seen,
                bad_in_window=n_bad,
                window=window_steps,
                loss_ewma=hs.get("loss_ewma"),
                policy=self.health,
            )

    def _health_stamp(self) -> dict | None:
        """The health record stamped into every save's meta JSON (next
        to the topology manifest): loss EWMA, grad norm, bad-step count,
        and the ``healthy`` verdict rollback selects on."""
        if self.health is None or self.state is None:
            return None
        hs = jax.device_get(self.state.health)
        if not hs:
            return None
        return _health.health_stamp(
            hs, int(jax.device_get(self.state.step)), self.health
        )

    def _log_metrics(self, metrics: Mapping[str, float], step: int) -> None:
        if not self.is_main:
            return
        for lg in self.loggers:
            lg.log_metrics(dict(metrics), step=step)

    def _log_params(self, params: Mapping[str, Any]) -> None:
        if not self.is_main:
            return
        for lg in self.loggers:
            if hasattr(lg, "log_params"):
                lg.log_params(dict(params))

    # -- state -------------------------------------------------------------
    def init_state(self) -> TrainState:
        if self.state is None:
            if self.sample_input is None:
                raise ValueError("need sample_input or a train_dataloader to init")
            self.state = create_train_state(
                self.model,
                jax.random.PRNGKey(self.seed),
                self.sample_input,
                self.tx,
                plan=self.plan,
                init_kwargs={"train": False},
            )
            if self.comms_config is not None:
                # EF residuals for the compressed wire (zeros; a restore
                # overwrites them — the residual is checkpoint state)
                from tpuframe.parallel.compression import init_comms_state

                self.state = self.state.replace(
                    comms=init_comms_state(
                        self.state.params, self.plan, self.comms_config
                    )
                )
        return self.state

    # -- compile warm-start ------------------------------------------------
    def precompile(self, wait: bool = True) -> dict | None:
        """AOT-compile the train/eval steps from the loader specs
        (``tpuframe.compile``): derive each step's full batch signature
        up front (ragged-tail padding and the grad-accum reshape
        included), ``lower().compile()`` it under ``compile/lower`` /
        ``compile/backend_compile`` spans, arm the shape guard with the
        expected set, and stash the executables for direct dispatch.

        ``fit()`` auto-invokes this with ``wait=False`` so the compile
        overlaps DataLoader/ring-buffer spin-up; the first step joins.
        Idempotent; returns the precompile report (signatures + walls).
        """
        if self._precompile_thread is None:
            self.init_state()  # model init on the caller's thread
            t = threading.Thread(
                target=self._precompile_run,
                name="tpuframe-precompile",
                daemon=True,
            )
            self._precompile_thread = t
            t.start()
        if wait:
            self._precompile_thread.join()
        return self._precompile_report

    def _precompile_run(self) -> None:
        """Background body: a failed precompile must degrade to today's
        lazy-compile behavior, never take the fit down."""
        tele = get_telemetry()
        # precompiles are keyed on the plan: after an elastic shrink the
        # same batch signature lowers a DIFFERENT program (survivor mesh,
        # rebound shardings), and the label must attribute those compiles
        # to the rebound plan rather than look like cache misses of the
        # old one
        plan_sig = self.plan.signature()
        report: dict[str, Any] = {
            "steps": [], "wall_s": 0.0, "plan_signature": plan_sig,
        }
        t0 = time.perf_counter()
        targets = [("train", self._train_step, True)]
        if self.eval_dataloader is not None:
            targets.append(("eval", self._eval_step, False))
        for kind, fn, train in targets:
            entry: dict[str, Any] = {"kind": kind}
            try:
                template = loader_batch_template(self, train=train)
                if template is None:
                    entry["skipped"] = "no derivable loader signature"
                    report["steps"].append(entry)
                    continue
                sig = batch_signature(template)
                entry["signature"] = format_signature(sig)
                t1 = time.perf_counter()
                compiled = precompile_step(
                    fn, self.state, template,
                    label=f"precompile/{kind}@{plan_sig}",
                )
                entry["wall_s"] = round(time.perf_counter() - t1, 6)
                # arm the guard even when direct dispatch isn't possible
                # (offload wrapper): the signature is still the contract,
                # and the persistent cache is warm for the jit path
                self._shape_guard.expect(kind, sig)
                if compiled is not None:
                    self._compiled[(kind, sig)] = compiled
                entry["dispatchable"] = compiled is not None
            except Exception as e:
                # an OOM during AOT compile gets the forensics event
                # (estimate vs compiled vs live + fit suggestion); the
                # precompile itself still degrades to lazy-compile
                _memory.maybe_oom_event(e, where="precompile")
                entry["error"] = f"{type(e).__name__}: {e}"[:300]
                tele.event(
                    "compile/precompile_error", step_kind=kind,
                    error=entry["error"],
                )
            report["steps"].append(entry)
        report["wall_s"] = round(time.perf_counter() - t0, 6)
        self._precompile_report = report
        tele.event("compile/precompile", **{
            "wall_s": report["wall_s"],
            "compiled": sum(
                1 for s in report["steps"] if s.get("signature")
            ),
            "dispatchable": sum(
                1 for s in report["steps"] if s.get("dispatchable")
            ),
        })

    def _step_call(self, kind: str, fn, state, batch):
        """One step through the compile spine: join an in-flight
        precompile (first step = ``max(compile, loader warmup)``),
        dispatch straight to the AOT executable on a signature match,
        else fall back to the jitted fn with the shape guard shouting
        about unexpected signatures and the compile label attributing
        whatever backend compile follows."""
        tele = get_telemetry()
        t = self._precompile_thread
        if t is not None and t.is_alive():
            with tele.span("compile/wait"):
                t.join()
        sig = batch_signature(batch)
        compiled = self._compiled.get((kind, sig))
        if compiled is not None:
            try:
                return compiled(state, batch)
            except Exception as e:
                # sharding/layout drift: drop the executable, shout once,
                # let the jit path (below) own the call
                self._compiled.pop((kind, sig), None)
                tele.event(
                    "compile/aot_fallback",
                    step_kind=kind,
                    signature=format_signature(sig),
                    error=f"{type(e).__name__}: {e}"[:300],
                )
                # the train executable donates state: an error raised
                # AFTER execution launched (OOM, runtime fault) has
                # already invalidated those buffers, and "retrying" on
                # deleted arrays would mask the real failure — only
                # pre-execution rejections (aval/sharding mismatch,
                # buffers intact) may fall through to the jit path
                if any(
                    getattr(x, "is_deleted", lambda: False)()
                    for x in jax.tree.leaves(state)
                    if isinstance(x, jax.Array)
                ):
                    raise
        else:
            self._shape_guard.check(kind, sig)
        with compile_label(f"{kind} {format_signature(sig)}"):
            return fn(state, batch)

    # -- data --------------------------------------------------------------
    def _device_batches(self, loader: DataLoader, train: bool):
        """Host pipeline: algorithms -> dict batches -> prefetched global arrays."""
        algs = self.algorithms if train else []
        accum = self.grad_accum if train else 1
        run_key = (self.seed * 1_000_003 + self.epoch) * 2 + int(train)

        fallback_pos = iter(range(1, 1 << 62))

        def batch_rng() -> np.random.Generator:
            """Augmentation rng keyed by (run, absolute batch position) —
            stateless, so a mid-epoch resume applies the SAME augmentation
            draws to batch k as the uninterrupted run would (a single
            sequential rng would hand the skipped batches' draws to the
            resumed ones).  Duck-typed iterables without a position
            counter fall back to a local sequence (distinct draws per
            batch; mid-epoch resume isn't supported for those anyway)."""
            pos = getattr(loader, "_batches_yielded", None)
            if pos is None:
                pos = next(fallback_pos)
            return np.random.default_rng(run_key * 1_000_003 + pos)

        def split_micro(x: np.ndarray) -> np.ndarray:
            if x.shape[0] % accum:
                raise ValueError(
                    f"batch size {x.shape[0]} not divisible by "
                    f"grad_accum={accum}"
                )
            micro = x.shape[0] // accum
            # x holds this process's rows; the dp check is on the *global*
            # microbatch assembled across processes.
            global_micro = micro * loader.process_count
            if global_micro % self.plan.dp_size:
                raise ValueError(
                    f"global microbatch size {global_micro} (global batch "
                    f"{x.shape[0] * loader.process_count} / grad_accum="
                    f"{accum}) not divisible by the mesh's "
                    f"{self.plan.dp_size} data-parallel shards"
                )
            return x.reshape((accum, micro) + x.shape[1:])

        def host_iter():
            # consumption index of this epoch's first yielded batch —
            # the prefetcher runs this generator ahead of training, but
            # batch i of the epoch is consumed at step base+i, so chaos
            # scheduled by step fires on exactly the batch that step eats
            base = self.batches_seen
            for pos, batch in enumerate(loader):
                images, labels = np.asarray(batch[0]), np.asarray(batch[1])
                if algs:
                    images, labels = apply_algorithms(
                        algs, images, labels, batch_rng()
                    )
                # chaos site: poison the HOST batch in place (NaNAt /
                # SpikeAt) exactly where a corrupt record or a broken
                # augmentation would land — upstream of the device copy,
                # so the jitted step's sentinel sees it like the real thing
                if train:
                    chaos.maybe_fire("batch", step=base + pos, images=images)
                out = {"image": images, "label": labels}
                if len(batch) > 2:
                    out["weight"] = np.asarray(batch[2], np.float32)
                if accum > 1:
                    out = {k: split_micro(v) for k, v in out.items()}
                yield out

        # consumer-true resume position for mid-epoch checkpoints (the
        # loader's own counter runs `depth` batches ahead).  Duck-typed
        # train iterables without state_dict() are fine — they just can't
        # be position-tracked, so mid-epoch checkpointing must be off.
        trackable = hasattr(loader, "state_dict")
        if (
            train
            and self.checkpointer is not None
            and self.checkpoint_interval_batches
            and not trackable
        ):
            raise ValueError(
                "checkpoint_interval_batches (mid-epoch snapshots) requires "
                "a train_dataloader with state_dict()/load_state_dict() "
                f"(got {type(loader).__name__}); use tpuframe.data.DataLoader "
                "or disable checkpoint_interval_batches"
            )
        pf = DevicePrefetcher(
            host_iter(),
            # env-defaulted pipeline depth (tolerant read): how many
            # batches the H2D copy runs ahead of the consuming step
            depth=max(1, _health._env_int("TPUFRAME_PREFETCH_DEPTH", 2)),
            sharding=self.plan.batch_sharding(leading_microbatch=accum > 1),
            track_loader=loader if train and trackable else None,
            # ring-buffer recycling: host_iter yields exactly one dict per
            # loader batch (grad-accum reshapes within a batch), so the
            # prefetcher's release-after-H2D stays FIFO-aligned with the
            # loader's lease order
            recycler=loader if hasattr(loader, "release_oldest") else None,
        )
        if train:
            self._train_prefetcher = pf
        yield from pf

    # -- autotune ----------------------------------------------------------
    def _autotune_identity(self) -> tuple[str, str, str]:
        """The persistence key the autotune store uses for this run:
        (host, topology, plan signature) — same-host ranks and a
        supervised restart of the same program share it; a different
        world shape or plan misses and tunes fresh."""
        from tpuframe.autotune.config import default_host

        topology = f"{rt.process_count()}x{rt.current_runtime().device_count}"
        return default_host(), topology, self.plan.signature()

    def apply_tuned(self, env: Mapping[str, str]) -> dict:
        """Apply a tuned config's env to this process: every knob is
        written to ``os.environ`` (so per-use readers and anything
        constructed later — eval loaders, a supervisor's next attempt —
        see it), and the domain registry's ``apply`` field classifies
        each into ``applied`` (live effect now; the mid-epoch snapshot
        cadence is additionally pushed onto the running loop) vs
        ``restart_only`` (takes effect at the next construction).
        Returns ``{"applied": {...}, "restart_only": {...}}``.
        """
        from tpuframe.autotune.config import all_env_domains

        domains = all_env_domains()
        applied: dict[str, str] = {}
        restart_only: dict[str, str] = {}
        for knob, value in env.items():
            d = domains.get(knob)
            if d is None:
                continue  # not in the legal registry: never apply
            os.environ[knob] = str(value)
            if d.get("apply") == "live":
                applied[knob] = str(value)
            else:
                restart_only[knob] = str(value)
        if "TPUFRAME_CKPT_INTERVAL_BATCHES" in applied:
            # the one live knob the Trainer itself re-reads per step
            iv = _health._env_int("TPUFRAME_CKPT_INTERVAL_BATCHES", 0)
            self.checkpoint_interval_batches = iv if iv > 0 else None
        if applied or restart_only:
            get_telemetry().event(
                "autotune/apply", applied=len(applied),
                restart_only=len(restart_only), side="train",
            )
        return {"applied": applied, "restart_only": restart_only}

    def apply_persisted_tuning(self) -> dict:
        """Load the persisted winning config for this run's identity and
        :meth:`apply_tuned` it.  Called from :meth:`fit` when
        ``TPUFRAME_AUTOTUNE`` is truthy — the supervised-restart half of
        the loop: the restarting attempt (and every same-host rank)
        starts tuned without re-probing.  No config is a no-op."""
        from tpuframe.autotune.config import load_tuned

        host, topology, signature = self._autotune_identity()
        cfg = load_tuned(host, topology, signature)
        if cfg is None:
            return {}
        return self.apply_tuned(cfg.env)

    # -- the loop ----------------------------------------------------------
    def fit(self) -> FitResult:
        """Run to max_duration; returns the Ray-style FitResult."""
        from tpuframe.autotune.config import autotune_enabled

        if autotune_enabled():
            self.apply_persisted_tuning()
        result = FitResult()
        state = self.init_state()
        if self.preemption is True:
            # enable: ensure the process-wide watcher exists and use it
            self.preemption = _preempt.install()
        elif self.preemption is not None and self.preemption is not False:
            # an explicitly-passed watcher: make sure its signal handlers
            # / poll thread are live for the duration of the fit
            self.preemption.install()
        if self.checkpointer is not None:
            # auto-resume from whichever is newer: the last epoch-end
            # checkpoint or a mid-epoch snapshot (crash inside an epoch)
            source = self.checkpointer
            intra = self._intra_checkpointer()
            if intra is not None:
                main_step = self.checkpointer.latest_step()
                intra_step = intra.latest_step()
                if intra_step is not None and (
                    main_step is None or intra_step > main_step
                ):
                    source = intra
            state, restored_meta = source.maybe_restore(state, plan=self.plan)
            self.state = state
            if restored_meta:
                self.epoch = int(restored_meta.get("epoch", 0))
                self.batches_seen = int(restored_meta.get("batches_seen", 0))
                self.samples_seen = int(restored_meta.get("samples_seen", 0))
                # a mid-epoch snapshot carries the loader position;
                # applied after _run_epoch's set_epoch rewind
                self._pending_loader_state = restored_meta.get("loader_state")
                # the data-order contract across an elastic resize: the
                # loader position above counts GLOBAL batches, so the
                # global batch must survive the shrink unchanged — a
                # resized world re-splits it (per-process batch x
                # processes x grad-accum), never changes the product.
                # Misconfiguration is FATAL (ValueError): retrying would
                # replay/skip samples on every attempt.
                saved_gb = restored_meta.get("global_batch")
                cur_gb = getattr(self.train_dataloader, "global_batch_size", None)
                if saved_gb and cur_gb and int(saved_gb) != int(cur_gb):
                    raise ValueError(
                        f"restored checkpoint was trained at global batch "
                        f"{saved_gb} but this loader produces {cur_gb}: a "
                        "world resize must preserve the global batch to "
                        "keep the checkpointed loader position meaningful "
                        "— re-derive the per-process split with "
                        "tpuframe.launch.rederive_batch_split(global_batch="
                        f"{saved_gb}, dp_size={self.plan.dp_size})"
                    )
        # memory-forensics context: register the plan + the live state's
        # shape/dtype trees (the walker only reads attrs — nothing
        # materializes) so an OOM anywhere in this fit can attribute
        # bytes and suggest the nearest-fitting plan without recompiling
        try:
            batch_template = loader_batch_template(self, train=True)
        except Exception:
            batch_template = None
        _memory.set_context(
            plan=self.plan,
            model_template=self.state.params,
            batch_spec=batch_template,
            opt_template=self.state.opt_state,
            comms_template=self.state.comms,
            microbatches=self.grad_accum,
        )
        # divergence-recovery data-order skip: after a rollback the
        # supervisor may direct this attempt to re-enter PAST the poison
        # window instead of deterministically replaying into it.
        # Applied on top of whatever loader position the restore carried
        # — INCLUDING a restore-less fresh start (every step quarantined,
        # or no checkpointer at all: the perturbation half of recovery
        # must not depend on there being something to roll back to).
        # One-shot: consumed here so a later unrelated restart in the
        # same run doesn't re-skip healthy batches.
        skip = (
            _health.consume_skip_batches()
            if self.health is not None
            and hasattr(self.train_dataloader, "load_state_dict")
            else 0
        )
        if skip:
            ls = self._pending_loader_state
            if ls is None:
                ls = self.train_dataloader.state_dict()
                ls["epoch"] = self.epoch
                ls["batches_yielded"] = 0
            ls = dict(ls)
            try:
                epoch_len = len(self.train_dataloader)
            except TypeError:
                epoch_len = int(ls["batches_yielded"]) + skip
            ls["batches_yielded"] = min(
                int(ls["batches_yielded"]) + skip, epoch_len
            )
            self._pending_loader_state = ls
            get_telemetry().event(
                "health/skip_batches",
                skip=skip,
                batches_yielded=ls["batches_yielded"],
                epoch=int(ls.get("epoch", self.epoch)),
            )

        if self.precompile_enabled:
            # background AOT warm-start, overlapped with the epoch's
            # loader/ring-buffer spin-up; the first _step_call joins.
            # Started AFTER restore so the lowered programs see the
            # restored state's exact shardings.
            self.precompile(wait=False)
        self._log_params(
            {
                "max_duration": str(self.max_duration),
                "optimizer": type(self.tx).__name__,
                "precision": str(self.policy.compute_dtype.__name__)
                if hasattr(self.policy.compute_dtype, "__name__")
                else str(self.policy.compute_dtype),
                "devices": rt.current_runtime().device_count,
                "zero_stage": self.plan.zero_stage,
                "algorithms": ",".join(type(a).__name__ for a in self.algorithms),
            }
        )
        self._emit("on_fit_start")
        try:
            while not self._done() and self._stop_reason is None:
                with get_telemetry().span("train/epoch", epoch=self.epoch):
                    epoch_metrics = self._run_epoch()
                eval_metrics: dict[str, float] = {}
                if (
                    self.eval_dataloader is not None
                    and self.eval_interval
                    and (self.epoch + 1) % self.eval_interval == 0
                ):
                    eval_metrics = self.evaluate()
                    self._emit("on_eval_end", self.epoch, eval_metrics)
                epoch_summary = {**epoch_metrics, **eval_metrics}
                result.history.append(epoch_summary)
                result.metrics = epoch_summary
                self._log_metrics(epoch_summary, step=self.epoch)
                self._emit("on_epoch_end", self.epoch, epoch_summary)

                ckpt_path = None
                # Every process participates: orbax sharded saves are
                # collective (rank-0-only discipline applies to *logging*,
                # not checkpoint writes).
                if self.checkpointer is not None and (
                    (self.epoch + 1) % self.checkpoint_interval == 0
                ):
                    ckpt_path = self.checkpointer.save(
                        self.state,
                        metrics=epoch_summary,
                        meta={
                            "epoch": self.epoch + 1,
                            "batches_seen": self.batches_seen,
                            "samples_seen": self.samples_seen,
                            "global_batch": self.train_dataloader.global_batch_size,
                        },
                        plan=self.plan,
                        health=self._health_stamp(),
                    )
                    result.checkpoint = str(ckpt_path)
                    # An epoch-end save supersedes any mid-epoch snapshot
                    # at an earlier-or-equal optimizer step: drop it so it
                    # neither lingers on disk nor wins a later auto-resume
                    # it no longer should.
                    intra = self._intra_checkpointer()
                    if intra is not None:
                        saved = self.checkpointer.latest_step()
                        stale = intra.latest_step()
                        if (
                            saved is not None
                            and stale is not None
                            and stale <= saved
                        ):
                            intra.delete(stale)
                if self.report is not None:
                    self.report(epoch_summary, result.checkpoint)
                self.epoch += 1
        except BaseException as e:  # Ray-style: surface, don't swallow rank-0 state
            result.error = e
            raise
        finally:
            result.stopped_reason = self._stop_reason
            self._emit("on_fit_end")
            for lg in self.loggers:
                # finish(error=) lets status-aware loggers record FAILED for a
                # crashed fit instead of a blanket flush-as-success.
                if hasattr(lg, "finish"):
                    lg.finish(error=result.error)
                elif hasattr(lg, "flush"):
                    lg.flush()
        return result

    def _done(self) -> bool:
        return self.max_duration.reached(
            epoch=self.epoch, batch=self.batches_seen, samples=self.samples_seen
        )

    def _run_epoch(self) -> dict[str, float]:
        self._emit("on_epoch_start", self.epoch)
        self.train_dataloader.set_epoch(self.epoch)
        if self._pending_loader_state is not None:
            # resume mid-epoch: skip the already-trained batches of this
            # epoch (this epoch's summary then covers only the remainder)
            if not hasattr(self.train_dataloader, "load_state_dict"):
                # a leftover snapshot from a previous run can reach here
                # even with checkpoint_interval_batches off; silently
                # dropping the position would replay trained batches
                raise ValueError(
                    "resuming a mid-epoch snapshot requires a "
                    "train_dataloader with load_state_dict() (got "
                    f"{type(self.train_dataloader).__name__}); restore "
                    "with a tpuframe.data.DataLoader or delete the "
                    "*_intra snapshot directory"
                )
            self.train_dataloader.load_state_dict(self._pending_loader_state)
            self._pending_loader_state = None
        acc = None
        window = None  # device-side metric pytree, materialized per interval
        t0 = time.perf_counter()
        # DeepSpeed-style wall-clock breakdown (`deepspeed_config.py:47-48`):
        # where host time goes per epoch — now measured by telemetry spans
        # at the SAME points the old perf_counter pairs sat, so the epoch
        # summary keys keep their values while per-step distributions
        # (span/train/* histograms) and the watchdog's live position come
        # free.  Inner per-batch spans use emit=False: one JSONL event per
        # *step* (train/step), not three.
        tele = get_telemetry()
        data_wait = dispatch = host_block = 0.0
        # producer-side costs (assembly in the loader, H2D in the
        # prefetcher thread) accrue in their span histograms; the delta
        # over this epoch lands in the summary next to data_wait_s —
        # together they attribute an input stall to production vs
        # transfer vs consumption.
        _h_assemble = tele.registry.histogram("span/data/assemble")
        _h_h2d = tele.registry.histogram("span/data/h2d")
        assemble0, h2d0 = _h_assemble.total, _h_h2d.total
        _epoch_end = object()

        def drain(window):
            """Materialize the device-side window (the only host sync)."""
            nonlocal host_block
            with tele.span("train/host_block", emit=False) as sp:
                out = {
                    k: float(v) for k, v in window.items()
                    if k != "health_stats"
                }
                # the sentinel's packed vector splits into its named
                # scalar sums (one device leaf on the hot path, five
                # host columns in the summary)
                if "health_stats" in window:
                    out.update(
                        _health.unpack_health_stats(window["health_stats"])
                    )
            host_block += sp.elapsed
            return out

        batches = iter(self._device_batches(self.train_dataloader, train=True))
        # straggler boundary: the gap back to the previous epoch (eval,
        # epoch-end checkpoint) must not read as one slow step
        self._straggler.mark()
        while True:
            # chaos site: a scheduled loader fault raises here, exactly
            # where a real worker-pool / shard-fetch failure surfaces
            chaos.maybe_fire("loader", step=self.batches_seen)
            with tele.span("train/data_wait", emit=False) as sp:
                batch = next(batches, _epoch_end)
            if batch is _epoch_end:
                break  # the exhausted final pull never counted toward data_wait
            wait_s = sp.elapsed
            data_wait += wait_s
            if self._done() or self._stop_reason is not None:
                break
            self._emit("on_step_start")
            try:
                chaos.maybe_fire("step", step=self.batches_seen)
                # the guard turns a wedged dispatch (first-step compile,
                # stuck collective) into an attributed watchdog report
                # instead of a silent hang; unmonitored unless a watchdog
                # is configured.  data_wait_s rides as a span attr so the
                # fleet analyzer can classify this step input-bound
                # without a second JSONL line.
                with tele.span("train/step", batch=self.batches_seen,
                               data_wait_s=round(wait_s, 6)) as sp, \
                        tele.guard("train/step"):
                    self.state, metrics = self._step_call(
                        "train", self._train_step, self.state, batch
                    )
            except Exception as e:
                # OOM forensics: a RESOURCE_EXHAUSTED here (the chaos
                # OomAt fires inside this block too) becomes one
                # memory/oom event with the attribution table + fit
                # suggestion; everything re-raises untouched
                _memory.maybe_oom_event(e, where="step",
                                        step=self.batches_seen)
                raise
            dispatch += sp.elapsed
            self.batches_seen += 1
            self.samples_seen += self.train_dataloader.global_batch_size
            self._meter_comms(tele)
            self._meter_pp(tele)
            # boundary-to-boundary step time: charges whatever actually
            # slowed this rank (wait, dispatch, snapshot, callback)
            self._straggler.observe()
            # health sentinel: accumulate the step's bad-flag on device
            # (async, like the metrics window) and check once per window
            # — may raise Divergence, BEFORE this step's interval
            # snapshot would write yet another doomed checkpoint
            self._health_step(metrics)
            if (
                self.checkpointer is not None
                and self.checkpoint_interval_batches
                and self.batches_seen % self.checkpoint_interval_batches == 0
            ):
                try:
                    epoch_len = len(self.train_dataloader) or 1
                except TypeError:  # duck-typed iterable without __len__
                    epoch_len = 1 << 62
                snap = self._train_prefetcher.state_dict()
                # the epoch-final batch is followed immediately by the
                # epoch-end save — a snapshot there would be a throwaway
                # full serialization of the same state.  The WITHIN-epoch
                # position decides (cumulative batches_seen desyncs from
                # epoch boundaries after any mid-epoch stop).
                if snap["batches_yielded"] < epoch_len:
                    # mid-epoch snapshot (sibling checkpointer): model/opt
                    # state + the consumer-true loader position, so a
                    # crash resumes with the very next batch (no replayed
                    # or skipped samples)
                    self._intra_checkpointer().save(
                        self.state,
                        meta={
                            "epoch": self.epoch,
                            "batches_seen": self.batches_seen,
                            "samples_seen": self.samples_seen,
                            "loader_state": snap,
                            "global_batch": self.train_dataloader.global_batch_size,
                        },
                        plan=self.plan,
                        health=self._health_stamp(),
                    )
            # step boundary = the preemption exit point: the step is the
            # atomic unit of progress, so a SIGTERM/maintenance notice is
            # acted on here — last-chance checkpoint, then Preempted out
            self._maybe_preempt_exit()
            # Accumulate on device (async) — floating every step would
            # block the host on each step's completion and serialize the
            # pipeline.
            window = (
                metrics
                if window is None
                else jax.tree.map(jnp.add, window, metrics)
            )
            self._emit("on_step_end")
            if self.log_interval and self.batches_seen % self.log_interval == 0:
                w = drain(window)
                acc = merge_metrics(acc, w)
                self._emit("on_batch_end", w)
                self._log_metrics(
                    summarize_metrics(w, prefix="train_batch_"),
                    step=self.batches_seen,
                )
                window = None
        if window is not None:
            w = drain(window)
            acc = merge_metrics(acc, w)
            self._emit("on_batch_end", w)
        # flush the partial health window: max_bad bad steps are max_bad
        # bad steps whether or not the window filled before epoch end
        self._health_check()
        elapsed = time.perf_counter() - t0
        summary = summarize_metrics(acc or {}, prefix="train_")
        if acc:
            # ``count`` comes from the jitted step over *global* arrays, so
            # it is already the global sample count — no process factor
            # (multiplying by process_count over-reported N x on pods).
            summary["train_samples_per_sec"] = acc.get("count", 0.0) / max(elapsed, 1e-9)
        if self.health is not None and acc:
            summary["health_bad_steps"] = acc.get("health_bad", 0.0)
            # mean over FINITE steps only: grad_norm_sum zeroes the
            # non-finite ones, so they must leave the denominator too
            finite_steps = (
                acc.get("health_steps", 0.0)
                - acc.get("health_nonfinite", 0.0)
            )
            if finite_steps > 0:
                summary["grad_norm"] = (
                    acc.get("grad_norm_sum", 0.0) / finite_steps
                )
        summary["epoch_time_s"] = elapsed
        summary["data_wait_s"] = data_wait
        summary["dispatch_s"] = dispatch
        summary["host_block_s"] = host_block
        summary["assemble_s"] = _h_assemble.total - assemble0
        summary["h2d_s"] = _h_h2d.total - h2d0
        return summary

    def evaluate(self) -> dict[str, float]:
        """Global, mask-correct eval over the eval dataloader."""
        if self.eval_dataloader is None:
            raise ValueError("no eval_dataloader")
        if getattr(self.eval_dataloader, "drop_last", False) and not getattr(
            self, "_warned_eval_drop", False
        ):
            # eval counts silently lose the ragged tail with drop_last=True;
            # the mask contract (DataLoader(drop_last=False) third element)
            # exists precisely so eval never miscounts
            import warnings

            warnings.warn(
                "eval_dataloader has drop_last=True: the final ragged batch "
                "is skipped and eval metrics undercount; use "
                "drop_last=False (yields a validity mask) for exact eval",
                stacklevel=2,
            )
            self._warned_eval_drop = True
        state = self._serving_state()
        self.eval_dataloader.set_epoch(0)
        acc = None
        with get_telemetry().span("train/eval", epoch=self.epoch):
            for batch in self._device_batches(self.eval_dataloader, train=False):
                metrics = self._step_call("eval", self._eval_step, state, batch)
                acc = merge_metrics(acc, metrics)
        return summarize_metrics(acc or {}, prefix="eval_")

    def _serving_state(self) -> TrainState:
        """The state evaluate/predict/export should read weights from:
        the live params, or the EMA average when ``ema_decay`` is on
        (the whole point of maintaining the average)."""
        state = self.init_state()
        if self.ema_decay is None:
            return state
        from tpuframe.train.ema import ema_params

        return state.replace(params=ema_params(state))

    def predict(self, images: np.ndarray) -> np.ndarray:
        """Logits for a (N, H, W, C) image batch (the reference's
        single-image demo path adds the batch dim itself)."""
        state = self._serving_state()
        return np.asarray(self._predict(state, np.asarray(images)))

    def export(
        self,
        path: str,
        sample_input: np.ndarray | None = None,
        batch_polymorphic: bool = True,
        platforms: tuple[str, ...] | None = None,
    ) -> str:
        """Freeze the trained model into a portable serving artifact.

        Bundles the current params/batch_stats AND the trainer's
        ``normalize=`` preprocessing into one StableHLO blob via
        :func:`tpuframe.serve.export_model` — callers of the artifact
        send the same raw batches training consumed.  Portability over
        performance, deliberately: params are gathered to host numpy
        (the artifact must not remember the training mesh's device
        count) and the normalize runs the plain-jnp reference path (the
        compiled Pallas kernel would pin the artifact to TPU).
        ``sample_input`` defaults to the trainer's own init sample;
        ``platforms=("cpu", "tpu")`` lowers for both targets.
        """
        from tpuframe.serve import export_model

        state = self._serving_state()
        variables = {"params": state.params}
        if jax.tree.leaves(state.batch_stats):
            variables["batch_stats"] = state.batch_stats
        # host-gathered constants: a multi-chip trainer's params are
        # sharded Arrays, and closing over those would bake the training
        # mesh's device count into the artifact.  Across processes a
        # plain device_get cannot read non-addressable shards, so gather
        # collectively first.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            variables = multihost_utils.process_allgather(variables)
        variables = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), variables
        )
        if sample_input is None:
            if self.sample_input is None:
                raise ValueError("pass sample_input= (none known to the trainer)")
            sample_input = self.sample_input
        preprocess = None
        if self._norm_args is not None:
            from tpuframe.ops.normalize import normalize_images_reference

            mean, std, scale = self._norm_args
            out_dtype = self.policy.compute_dtype

            def preprocess(x):
                return normalize_images_reference(
                    x, mean, std, scale, out_dtype
                )

        return export_model(
            self.model,
            variables,
            sample_input,
            path,
            preprocess=preprocess,
            batch_polymorphic=batch_polymorphic,
            platforms=platforms,
        )


def _planned_total_steps(duration, dataloader) -> int | None:
    """Best-effort optimizer-step count for schedule resolution (the
    DeepSpeed ``total_num_steps: "auto"`` pattern,
    `deepspeed_config.py:16` style deferred values)."""
    if duration.unit == "ba":
        return duration.value
    if dataloader is None:
        return None
    if duration.unit == "ep":
        try:
            return duration.value * len(dataloader)
        except TypeError:
            return None
    # "sp": samples -> batches at the loader's global batch size.  The loop
    # stops when samples_seen >= value, i.e. after ceil(value/gbs) steps.
    gbs = getattr(dataloader, "global_batch_size", None)
    return max(-(-duration.value // gbs), 1) if gbs else None


def _make_optimizer(name: str, lr: float | optax.Schedule) -> optax.GradientTransformation:
    """Named optimizers matching the reference examples' choices (Adam
    everywhere except MNIST's momentum SGD, `01_basic_torch_distributor.py:283`,
    and DeepSpeed's AdamW+warmup config, `deepspeed_config.py:28-40`)."""
    table = {
        "adam": optax.adam,
        "adamw": optax.adamw,
        "sgd": lambda lr: optax.sgd(lr, momentum=0.9),
        "lamb": optax.lamb,
        "lion": optax.lion,
        "adafactor": optax.adafactor,
    }
    try:
        return table[name.lower()](lr)
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; known: {sorted(table)}") from None
