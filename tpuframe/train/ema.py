"""Exponential moving average of parameters (Composer/timm's EMA).

TPU-first shape: the EMA is not a separate host-side copy to synchronize
(the torch pattern) — it lives INSIDE the optimizer state as one more
param-shaped pytree, updated in the same fused XLA step as the optimizer
itself.  Because ``ParallelPlan.state_shardings`` shards param-shaped
state leaves by suffix match, the EMA is automatically ZeRO-sharded over
the fsdp axis with zero extra plumbing, and checkpoints carry it for
free (it is just opt_state).

Usage::

    tx = with_ema(optax.adamw(3e-4), decay=0.999)   # outermost wrapper
    ...
    eval_params = ema_params(state)                 # the averaged weights

or ``Trainer(ema_decay=0.999)``, which evaluates/predicts/exports with
the averaged weights automatically.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import optax

__all__ = ["EmaState", "with_ema", "ema_params"]


class EmaState(NamedTuple):
    inner: Any
    ema: Any


def with_ema(
    tx: optax.GradientTransformation, decay: float = 0.999
) -> optax.GradientTransformation:
    """Wrap ``tx`` so its state also tracks ``ema = d*ema + (1-d)*params``.

    Must be the OUTERMOST wrapper (``ema_params`` looks for :class:`EmaState`
    at the top of the optimizer state).  The average starts at the initial
    params (no zero-init bias, so no debiasing step is needed), and each
    ``update`` folds the POST-update params in — the average always lags
    the live weights by the usual EMA horizon ``1/(1-decay)`` steps.
    """
    if not 0.0 < decay < 1.0:
        raise ValueError(f"decay must be in (0, 1), got {decay}")

    def init(params):
        return EmaState(tx.init(params), params)

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("with_ema requires params= in update()")
        new_updates, inner = tx.update(updates, state.inner, params)
        new_params = optax.apply_updates(params, new_updates)
        ema = jax.tree.map(
            lambda e, p: decay * e + (1.0 - decay) * p, state.ema, new_params
        )
        return new_updates, EmaState(inner, ema)

    return optax.GradientTransformation(init, update)


def ema_params(state_or_opt_state: Any) -> Any:
    """The averaged params from a TrainState (or its opt_state).

    Raises ``ValueError`` when the optimizer was not wrapped with
    :func:`with_ema` — silently returning live params would make an
    "EMA eval" a lie.
    """
    opt_state = getattr(state_or_opt_state, "opt_state", state_or_opt_state)
    if isinstance(opt_state, EmaState):
        return opt_state.ema
    raise ValueError(
        "optimizer state carries no EMA — wrap the optimizer with "
        "with_ema(tx) (outermost) or pass Trainer(ema_decay=...)"
    )
