"""Trainer callbacks: the event hooks Composer's engine drives
(`/root/reference/03_composer/01_cifar_composer_resnet.ipynb:cell-16` —
algorithms/loggers are event callbacks under the hood) plus the early-stopping
behaviour the DeepSpeed TinyImageNet example hand-rolls
(`/root/reference/02_deepspeed/02_tiny_imagenet_deepspeed_resnet.py:219-220,289-297`).
"""

from __future__ import annotations

import math
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from tpuframe.train.trainer import Trainer


class Callback:
    """Override any subset; every hook receives the live Trainer."""

    def on_fit_start(self, trainer: "Trainer") -> None: ...
    def on_epoch_start(self, trainer: "Trainer", epoch: int) -> None: ...
    def on_step_start(self, trainer: "Trainer") -> None: ...
    def on_step_end(self, trainer: "Trainer") -> None: ...
    def on_batch_end(self, trainer: "Trainer", metrics: dict) -> None: ...
    def on_epoch_end(self, trainer: "Trainer", epoch: int, metrics: dict) -> None: ...
    def on_eval_end(self, trainer: "Trainer", epoch: int, metrics: dict) -> None: ...
    def on_fit_end(self, trainer: "Trainer") -> None: ...


class EarlyStopping(Callback):
    """Stop when a monitored eval metric stops improving (patience epochs).

    Mirrors the reference's hand-rolled loop: track best val loss, increment a
    counter, break at patience (`02_tiny_imagenet_deepspeed_resnet.py:289-297`).
    """

    def __init__(
        self, monitor: str = "eval_loss", patience: int = 3, mode: str = "min",
        min_delta: float = 0.0,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.min_delta = min_delta
        self.best = math.inf if mode == "min" else -math.inf
        self.stale = 0

    def _improved(self, value: float) -> bool:
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_eval_end(self, trainer: "Trainer", epoch: int, metrics: dict) -> None:
        value = metrics.get(self.monitor)
        if value is None:
            return
        if self._improved(value):
            self.best = value
            self.stale = 0
        else:
            self.stale += 1
            if self.stale >= self.patience:
                trainer.request_stop(
                    f"early stop: {self.monitor} stale for {self.stale} epochs "
                    f"(best {self.best:.5g})"
                )


class ProgressLogger(Callback):
    """Stdout progress every N batches (the reference prints every 10,
    `/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:229-230`).
    Rank-0 only."""

    def __init__(self, every_n_batches: int = 10):
        self.every = every_n_batches

    def on_batch_end(self, trainer: "Trainer", metrics: dict) -> None:
        if not trainer.is_main:
            return
        if trainer.batches_seen % self.every == 0:
            loss = metrics.get("loss_sum", 0.0) / max(metrics.get("count", 1.0), 1.0)
            print(
                f"[tpuframe] epoch {trainer.epoch} batch {trainer.batches_seen} "
                f"loss {loss:.4f}"
            )
