"""Composer-style time strings: ``"2ep"``, ``"500ba"``, ``"1000sp"``.

The reference passes ``max_duration="2ep"`` to Composer's Trainer
(`/root/reference/03_composer/01_cifar_composer_resnet.ipynb:cell-16`).
tpuframe keeps the same grammar, reduced to the units that make sense here:
epochs (ep), batches/steps (ba), samples (sp).
"""

from __future__ import annotations

import dataclasses
import re

_PATTERN = re.compile(r"^\s*(\d+)\s*(ep|ba|sp)\s*$")


@dataclasses.dataclass(frozen=True)
class Duration:
    value: int
    unit: str  # "ep" | "ba" | "sp"

    @classmethod
    def parse(cls, spec: "str | int | Duration") -> "Duration":
        if isinstance(spec, Duration):
            return spec
        if isinstance(spec, int):
            return cls(spec, "ep")
        m = _PATTERN.match(str(spec))
        if not m:
            raise ValueError(
                f"bad duration {spec!r}; expected '<N>ep' | '<N>ba' | '<N>sp' "
                "(e.g. '2ep', '500ba') or an int epoch count"
            )
        return cls(int(m.group(1)), m.group(2))

    def reached(self, *, epoch: int, batch: int, samples: int) -> bool:
        current = {"ep": epoch, "ba": batch, "sp": samples}[self.unit]
        return current >= self.value

    def __str__(self) -> str:
        return f"{self.value}{self.unit}"
