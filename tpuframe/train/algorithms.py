"""Trainer algorithms: batch/label transforms applied at defined events.

Capability parity with the Composer example's algorithm list
(`/root/reference/03_composer/01_cifar_composer_resnet.ipynb:cell-16`:
``algorithms=[LabelSmoothing(0.1), CutMix(1.0), ChannelsLast()]``), designed
TPU-first: algorithms are *pure functions on host batches* (numpy, before
device_put) so the jitted train step never changes shape or retraces — the
device program is identical with or without any algorithm stack.

Label-space algorithms (LabelSmoothing, CutMix, MixUp) emit soft labels
(N, C); the step's ``cross_entropy`` handles both hard and soft labels.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np


@dataclasses.dataclass
class Algorithm:
    """Base: transform (images, labels) before the device step."""

    def needs_num_classes(self) -> bool:
        return False

    def apply(
        self, images: np.ndarray, labels: np.ndarray, rng: np.random.Generator
    ) -> tuple[np.ndarray, np.ndarray]:
        return images, labels


def _one_hot(labels: np.ndarray, num_classes: int) -> np.ndarray:
    if labels.ndim == 2:
        return labels
    out = np.zeros((labels.shape[0], num_classes), np.float32)
    out[np.arange(labels.shape[0]), labels] = 1.0
    return out


@dataclasses.dataclass
class LabelSmoothing(Algorithm):
    """Uniform label smoothing (Composer ``LabelSmoothing(smoothing=0.1)``)."""

    smoothing: float = 0.1
    num_classes: int | None = None

    def needs_num_classes(self) -> bool:
        return True

    def apply(self, images, labels, rng):
        y = _one_hot(labels, self.num_classes)
        y = y * (1.0 - self.smoothing) + self.smoothing / y.shape[1]
        return images, y.astype(np.float32)


@dataclasses.dataclass
class CutMix(Algorithm):
    """CutMix: paste a random crop from a shuffled partner image; labels mix
    by pasted area (Composer ``CutMix(alpha=1.0)``)."""

    alpha: float = 1.0
    num_classes: int | None = None

    def needs_num_classes(self) -> bool:
        return True

    def apply(self, images, labels, rng):
        n, h, w = images.shape[:3]
        lam = float(rng.beta(self.alpha, self.alpha))
        perm = rng.permutation(n)
        cut = np.sqrt(1.0 - lam)
        ch, cw = int(h * cut), int(w * cut)
        cy, cx = int(rng.integers(h)), int(rng.integers(w))
        y0, y1 = np.clip([cy - ch // 2, cy + ch // 2], 0, h)
        x0, x1 = np.clip([cx - cw // 2, cx + cw // 2], 0, w)
        mixed = images.copy()
        mixed[:, y0:y1, x0:x1] = images[perm, y0:y1, x0:x1]
        area = (y1 - y0) * (x1 - x0) / (h * w)
        y = _one_hot(labels, self.num_classes)
        y = (1.0 - area) * y + area * y[perm]
        return mixed, y.astype(np.float32)


@dataclasses.dataclass
class MixUp(Algorithm):
    """Convex image/label mixing with a shuffled partner (mixup paper)."""

    alpha: float = 0.2
    num_classes: int | None = None

    def needs_num_classes(self) -> bool:
        return True

    def apply(self, images, labels, rng):
        lam = float(rng.beta(self.alpha, self.alpha))
        perm = rng.permutation(images.shape[0])
        imgs = images.astype(np.float32)
        mixed = lam * imgs + (1.0 - lam) * imgs[perm]
        y = _one_hot(labels, self.num_classes)
        y = lam * y + (1.0 - lam) * y[perm]
        return mixed.astype(images.dtype if images.dtype == np.float32 else np.float32), y.astype(np.float32)


@dataclasses.dataclass
class ChannelsLast(Algorithm):
    """No-op on TPU: tpuframe is NHWC end-to-end already (the memory-format
    win Composer's ChannelsLast buys on CUDA is the default here)."""


def resolve_algorithms(
    algorithms: Sequence[Algorithm], num_classes: int
) -> list[Algorithm]:
    """Fill in num_classes on algorithms that need it but weren't told."""
    out = []
    for alg in algorithms:
        if alg.needs_num_classes() and getattr(alg, "num_classes", None) is None:
            alg = dataclasses.replace(alg, num_classes=num_classes)
        out.append(alg)
    return out


def apply_algorithms(
    algorithms: Sequence[Algorithm],
    images: np.ndarray,
    labels: np.ndarray,
    rng: np.random.Generator,
) -> tuple[np.ndarray, np.ndarray]:
    for alg in algorithms:
        images, labels = alg.apply(images, labels, rng)
    return images, labels
