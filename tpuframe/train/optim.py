"""Optimizer construction from DeepSpeed-shaped config dicts.

Completes the config-consumption path: `ZeroConfig.from_dict` reads the
``zero_optimization`` block, ``schedules.from_config`` the ``scheduler``
block, and this module the rest of the reference's base config
(`/root/reference/02_deepspeed/deepspeed_config.py:14-40`):

- ``optimizer.type`` / ``optimizer.params`` (AdamW betas/eps/lr, SGD
  momentum, ...),
- ``scheduler`` — resolved into the learning rate,
- ``gradient_clipping`` — global-norm clip chained before the update
  (`deepspeed_config.py:18``, ``shared_parameters["gradient_clipping"]``).

So the dict a DeepSpeed user already has becomes one optax transform:

    tx = optimizer_from_config(deepspeed_base, total_steps=...)
"""

from __future__ import annotations

from typing import Any, Mapping

import optax

from tpuframe.train.schedules import from_config as schedule_from_config

__all__ = ["optimizer_from_config"]


def _adamw(lr, p):
    b1, b2 = p.get("betas", (0.9, 0.999))
    return optax.adamw(
        lr, b1=float(b1), b2=float(b2), eps=float(p.get("eps", 1e-8)),
        weight_decay=float(p.get("weight_decay", 1e-2)),
    )


def _adam(lr, p):
    b1, b2 = p.get("betas", (0.9, 0.999))
    return optax.adam(lr, b1=float(b1), b2=float(b2), eps=float(p.get("eps", 1e-8)))


def _lion(lr, p):
    b1, b2 = p.get("betas", (0.9, 0.99))
    # default weight_decay matches bare optax.lion (1e-3), so the
    # config path and Trainer(optimizer="lion") train identically
    return optax.lion(
        lr, b1=float(b1), b2=float(b2),
        weight_decay=float(p.get("weight_decay", 1e-3)),
    )


def _adafactor(lr, p):
    # the LLM-scale memory-lean choice: factored second moments mean the
    # ZeRO-sharded optimizer state is O(rows+cols) per matrix, not O(n).
    # min_dim_size_to_factor guards small tensors (mirrors optax default).
    return optax.adafactor(
        lr,
        min_dim_size_to_factor=int(p.get("min_dim_size_to_factor", 128)),
        decay_rate=float(p.get("decay_rate", 0.8)),
        weight_decay_rate=(
            float(p["weight_decay"]) if "weight_decay" in p else None
        ),
    )


#: single source of truth for supported types (error messages derive from it)
_OPTIMIZERS = {
    "adamw": _adamw,
    "adam": _adam,
    "sgd": lambda lr, p: optax.sgd(lr, momentum=float(p.get("momentum", 0.0))),
    "lamb": lambda lr, p: optax.lamb(
        lr, weight_decay=float(p.get("weight_decay", 0.0))
    ),
    # not DeepSpeed types, but keep parity with Trainer's optimizer= names
    "lion": _lion,
    "adafactor": _adafactor,
}


def optimizer_from_config(
    cfg: Mapping[str, Any], *, total_steps: int | None = None
) -> optax.GradientTransformation:
    """One optax transform from a DeepSpeed-shaped config.

    Reads ``optimizer``, ``scheduler`` (optional — its schedule replaces
    the optimizer's static lr), and ``gradient_clipping`` (optional,
    global-norm).  ``lr: "auto"`` with no scheduler is an error rather
    than a silent default.
    """
    opt_block = cfg.get("optimizer", {})
    kind = opt_block.get("type", "AdamW")
    try:
        # type before lr: "unknown optimizer" is the more useful error
        build = _OPTIMIZERS[kind.lower()]
    except KeyError:
        raise ValueError(
            f"unknown optimizer type {kind!r}; known: {sorted(_OPTIMIZERS)}"
        ) from None
    params = dict(opt_block.get("params", {}))

    if "scheduler" in cfg:
        lr = schedule_from_config(cfg, total_steps=total_steps)
    else:
        lr = params.get("lr")
        if lr in (None, "auto"):
            raise ValueError(
                "config has no scheduler and optimizer.params.lr is "
                f"{lr!r}; set an explicit lr or add a scheduler block"
            )
        lr = float(lr)

    tx = build(lr, params)
    clip = cfg.get("gradient_clipping")
    if clip not in (None, "auto", 0, 0.0):
        tx = optax.chain(optax.clip_by_global_norm(float(clip)), tx)
    return tx
