"""Training engine: jitted steps, TrainState, high-level Trainer, algorithms.

TPU-native re-expression of the reference's L4 layer (SURVEY.md §1): the
Composer Trainer shape, the DDP epoch loop, Accelerate's low-level step feel,
and Ray Train's structured results, all on one donated jitted XLA step.
"""

from tpuframe.train.algorithms import (
    Algorithm,
    ChannelsLast,
    CutMix,
    LabelSmoothing,
    MixUp,
    apply_algorithms,
    resolve_algorithms,
)
from tpuframe.train.callbacks import Callback, EarlyStopping, ProgressLogger
from tpuframe.train.duration import Duration
from tpuframe.train.schedules import (
    cosine_annealing,
    step_decay,
    warmup_cosine,
    warmup_decay_lr,
    warmup_lr,
)
from tpuframe.train.optim import optimizer_from_config
from tpuframe.train.schedules import from_config as schedule_from_config
from tpuframe.train.ema import EmaState, ema_params, with_ema
from tpuframe.train.state import TrainState, create_train_state, param_count
from tpuframe.train.step import (
    cross_entropy,
    make_eval_step,
    make_grad_accum_step,
    make_predict_fn,
    make_train_step,
    merge_metrics,
    summarize_metrics,
)
from tpuframe.train.trainer import FitResult, Trainer

__all__ = [
    "Algorithm",
    "ChannelsLast",
    "EmaState",
    "ema_params",
    "with_ema",
    "CutMix",
    "LabelSmoothing",
    "MixUp",
    "apply_algorithms",
    "resolve_algorithms",
    "Callback",
    "EarlyStopping",
    "ProgressLogger",
    "Duration",
    "warmup_lr",
    "warmup_decay_lr",
    "warmup_cosine",
    "cosine_annealing",
    "step_decay",
    "schedule_from_config",
    "optimizer_from_config",
    "TrainState",
    "create_train_state",
    "param_count",
    "cross_entropy",
    "make_eval_step",
    "make_grad_accum_step",
    "make_predict_fn",
    "make_train_step",
    "merge_metrics",
    "summarize_metrics",
    "FitResult",
    "Trainer",
]
