"""Training engine: jitted steps, TrainState, high-level Trainer, algorithms.

TPU-native re-expression of the reference's L4 layer (SURVEY.md §1): the
Composer Trainer shape, the DDP epoch loop, Accelerate's low-level step feel,
and Ray Train's structured results, all on one donated jitted XLA step.
"""

from tpuframe.train.algorithms import (
    Algorithm,
    ChannelsLast,
    CutMix,
    LabelSmoothing,
    MixUp,
    apply_algorithms,
    resolve_algorithms,
)
from tpuframe.train.callbacks import Callback, EarlyStopping, ProgressLogger
from tpuframe.train.duration import Duration
from tpuframe.train.state import TrainState, create_train_state, param_count
from tpuframe.train.step import (
    cross_entropy,
    make_eval_step,
    make_grad_accum_step,
    make_predict_fn,
    make_train_step,
    merge_metrics,
    summarize_metrics,
)
from tpuframe.train.trainer import FitResult, Trainer

__all__ = [
    "Algorithm",
    "ChannelsLast",
    "CutMix",
    "LabelSmoothing",
    "MixUp",
    "apply_algorithms",
    "resolve_algorithms",
    "Callback",
    "EarlyStopping",
    "ProgressLogger",
    "Duration",
    "TrainState",
    "create_train_state",
    "param_count",
    "cross_entropy",
    "make_eval_step",
    "make_grad_accum_step",
    "make_predict_fn",
    "make_train_step",
    "merge_metrics",
    "summarize_metrics",
    "FitResult",
    "Trainer",
]
