// Batch JPEG decoder for the image input pipeline.
//
// Role (SURVEY.md §2.3 / §7): "input pipeline feeding HBM at ImageNet
// rate" — the v5e chip consumes ~2.2k 224px images/sec (PERF.md) and the
// host must decode that fast.  Pillow's decoders hold the GIL, so python
// thread workers cannot scale JPEG decode across cores; these entry
// points run libjpeg(-turbo) with the GIL released (ctypes calls drop
// it) and fan a batch across a thread pool, same shape as the zstd batch
// codec (codec.cpp).
//
// Decode policy: grayscale JPEGs decode to 1 channel, everything else to
// RGB (libjpeg converts YCbCr; exotic spaces like CMYK fail the item and
// the python wrapper falls back to PIL for it).
//
// Build: g++ -O2 -shared -fPIC jpegdec.cpp -o libtfjpeg.so -ljpeg -lpthread
// (tpuframe.core.native compiles this lazily and caches the .so).

#include <cstddef>  // jpeglib.h uses size_t/FILE without including them
#include <cstdio>

// jpeglib.h first: it pulls jconfig.h, whose D_ARITH_CODING_SUPPORTED
// gates whether jerror.h's enum even contains JWRN_ARITH_BAD_CODE
#include <jpeglib.h>

#include <jerror.h>

#include <atomic>
#include <csetjmp>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct ErrJmp {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void err_exit(j_common_ptr cinfo) {
  ErrJmp* e = reinterpret_cast<ErrJmp*>(cinfo->err);
  longjmp(e->jb, 1);
}

void silent_emit(j_common_ptr cinfo, int msg_level) {
  // Keep quiet but keep COUNTING — and count only CORRUPTION-class
  // warnings as failures: truncation (premature EOF / hit marker /
  // resync) and corrupt entropy-coded data (bad Huffman/arithmetic
  // codes — libjpeg "recovers" from those by emitting garbage pixels
  // with rc=0, so they must fail the item to reach the PIL fallback,
  // ADVICE r05 #2).  Benign warnings (extraneous bytes, spec quirks
  // common in scraped data) must not fail the item: that would silently
  // decode twice (full native scan, then the PIL fallback), inverting
  // the fast path's advantage.
  if (msg_level < 0) {
    int code = cinfo->err->msg_code;
    if (code == JWRN_JPEG_EOF || code == JWRN_HIT_MARKER ||
        code == JWRN_MUST_RESYNC || code == JWRN_HUFF_BAD_CODE
#ifdef D_ARITH_CODING_SUPPORTED
        // the enum member only exists when jconfig.h enables arithmetic
        // decoding — an unguarded use would break the build (and thus
        // the whole native fast path) on arith-less libjpeg builds
        || code == JWRN_ARITH_BAD_CODE
#endif
    )
      cinfo->err->num_warnings++;
  }
}
void silent_output(j_common_ptr) {}

// Pick the smallest DCT scale M/8 (M in 1..8) whose output still covers
// (min_h, min_w).  min_h/min_w <= 0 means full size (M = 8).  libjpeg
// applies ceil(dim * M / 8).
int pick_scale(uint32_t h, uint32_t w, int32_t min_h, int32_t min_w) {
  if (min_h <= 0 || min_w <= 0) return 8;
  for (int m = 1; m < 8; ++m) {
    uint64_t sh = ((uint64_t)h * m + 7) / 8;
    uint64_t sw = ((uint64_t)w * m + 7) / 8;
    if (sh >= (uint64_t)min_h && sw >= (uint64_t)min_w) return m;
  }
  return 8;
}

// Parse one header; fills h, w, out_channels (post-policy: 1 or 3) at
// the chosen M/8 DCT scale covering (min_h, min_w).  Returns 0 on
// success.
int parse_header(const uint8_t* src, size_t size, int32_t min_h,
                 int32_t min_w, int32_t* h, int32_t* w, int32_t* c) {
  jpeg_decompress_struct cinfo;
  ErrJmp err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = err_exit;
  err.mgr.emit_message = silent_emit;
  err.mgr.output_message = silent_output;
  if (setjmp(err.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(src), size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.scale_num =
      (unsigned)pick_scale(cinfo.image_height, cinfo.image_width, min_h, min_w);
  cinfo.scale_denom = 8;
  jpeg_calc_output_dimensions(&cinfo);
  *h = (int32_t)cinfo.output_height;
  *w = (int32_t)cinfo.output_width;
  *c = (cinfo.jpeg_color_space == JCS_GRAYSCALE) ? 1 : 3;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decode one image into dst (capacity dims h*w*c from tfj_dims), at the
// same M/8 scale tfj_dims chose for (min_h, min_w).  Returns 0 on
// success.
int decode_one(const uint8_t* src, size_t size, uint8_t* dst, int32_t min_h,
               int32_t min_w, int32_t exp_h, int32_t exp_w, int32_t exp_c) {
  jpeg_decompress_struct cinfo;
  ErrJmp err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = err_exit;
  err.mgr.emit_message = silent_emit;
  err.mgr.output_message = silent_output;
  if (setjmp(err.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(src), size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space =
      (cinfo.jpeg_color_space == JCS_GRAYSCALE) ? JCS_GRAYSCALE : JCS_RGB;
  cinfo.scale_num =
      (unsigned)pick_scale(cinfo.image_height, cinfo.image_width, min_h, min_w);
  cinfo.scale_denom = 8;
  jpeg_start_decompress(&cinfo);
  // the caller allocated from tfj_dims; a mismatch (corrupt/substituted
  // bytes) must never overflow the buffer
  if ((int32_t)cinfo.output_height != exp_h ||
      (int32_t)cinfo.output_width != exp_w ||
      (int32_t)cinfo.output_components != exp_c) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  const size_t stride = (size_t)exp_w * (size_t)exp_c;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = dst + (size_t)cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  // libjpeg treats truncated streams as WARNINGS and silently pads the
  // image with dummy data; strict mode (PIL parity: truncated images
  // raise) fails the item when silent_emit counted a truncation-class
  // warning
  const long warnings = cinfo.err->num_warnings;
  jpeg_destroy_decompress(&cinfo);
  return warnings > 0 ? -1 : 0;
}

}  // namespace

extern "C" {

// Header pass: dims[i*3 + 0/1/2] = height, width, channels (1 or 3) at
// the M/8 DCT scale covering (min_h, min_w); min_h/min_w <= 0 = full
// size.  Returns 0 on success; otherwise (1 + index) of the first bad
// item.
int tfj_dims(const uint8_t** srcs, const size_t* sizes, int n,
             int32_t min_h, int32_t min_w, int32_t* dims) {
  for (int i = 0; i < n; ++i) {
    if (parse_header(srcs[i], sizes[i], min_h, min_w, &dims[i * 3],
                     &dims[i * 3 + 1], &dims[i * 3 + 2]) != 0)
      return 1 + i;
  }
  return 0;
}

// Decode n images on a thread pool into caller-allocated buffers sized
// from tfj_dims (same min_h/min_w!).  Returns 0 on success; otherwise
// (1 + index) of the first failing item (remaining items may be
// skipped).
int tfj_decode_batch(const uint8_t** srcs, const size_t* sizes,
                     uint8_t** dsts, const int32_t* dims, int n,
                     int32_t min_h, int32_t min_w, int n_threads) {
  if (n <= 0) return 0;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n) n_threads = n;

  std::atomic<int> next(0);
  std::atomic<int> failed(0);

  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n || failed.load() != 0) return;
      if (decode_one(srcs[i], sizes[i], dsts[i], min_h, min_w, dims[i * 3],
                     dims[i * 3 + 1], dims[i * 3 + 2]) != 0) {
        int expect = 0;
        failed.compare_exchange_strong(expect, 1 + i);
        return;
      }
    }
  };

  if (n_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failed.load();
}

}  // extern "C"
