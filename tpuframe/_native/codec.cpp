// Batch zstd codec for the TFS streaming shard format.
//
// Role (SURVEY.md §2.3): the reference's streaming path leans on
// mosaicml-streaming's native zstd decode ("compression='zstd'",
// /root/reference/01_torch_distributor/03a_tiny_imagenet_torch_distributor_resnet_mds.py:195).
// tpuframe's equivalent decodes whole shard blocks in parallel worker
// threads with the GIL released (ctypes calls drop it), keeping the host
// input pipeline ahead of HBM ingest at ImageNet rates.
//
// Build: g++ -O2 -shared -fPIC codec.cpp -o libtfscodec.so -lzstd -lpthread
// (tpuframe.core.native compiles this lazily and caches the .so).

#include <zstd.h>

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <thread>
#include <vector>

extern "C" {

// Upper bound for compress output.
size_t tfs_compress_bound(size_t n) { return ZSTD_compressBound(n); }

// Decompressed size recorded in a zstd frame header; 0 if unknown/error.
// UINT64_MAX = unknown/error sentinel; 0 is a valid (empty) content size.
uint64_t tfs_frame_content_size(const uint8_t* src, size_t src_size) {
  unsigned long long r = ZSTD_getFrameContentSize(src, src_size);
  if (r == ZSTD_CONTENTSIZE_UNKNOWN || r == ZSTD_CONTENTSIZE_ERROR)
    return UINT64_MAX;
  return (uint64_t)r;
}

// One-shot compress. Returns 0 on success.
int tfs_compress(const uint8_t* src, size_t src_size, uint8_t* dst,
                 size_t dst_cap, size_t* out_size, int level) {
  size_t r = ZSTD_compress(dst, dst_cap, src, src_size, level);
  if (ZSTD_isError(r)) return -1;
  *out_size = r;
  return 0;
}

// Decompress n independent buffers on a thread pool.
// Returns 0 on success; otherwise (1 + index) of the first failing buffer.
int tfs_batch_decompress(const uint8_t** srcs, const size_t* src_sizes,
                         uint8_t** dsts, const size_t* dst_caps,
                         size_t* dst_sizes, int n, int n_threads) {
  if (n <= 0) return 0;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n) n_threads = n;

  std::atomic<int> next(0);
  std::atomic<int> failed(0);  // 0 = ok, else 1 + index

  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n || failed.load() != 0) return;
      size_t r = ZSTD_decompress(dsts[i], dst_caps[i], srcs[i], src_sizes[i]);
      if (ZSTD_isError(r)) {
        int expect = 0;
        failed.compare_exchange_strong(expect, 1 + i);
        return;
      }
      dst_sizes[i] = r;
    }
  };

  if (n_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failed.load();
}

}  // extern "C"
