// Host control plane: TCP rendezvous + barrier/broadcast/allgather.
//
// Role (SURVEY.md §2.3): the NCCL/c10d control surface the reference leans
// on for *small host-side values* — torchrun's MASTER_ADDR rendezvous,
// `torch.distributed.barrier`/`broadcast`/`gather` of run ids and metric
// scalars (/root/reference/04_accelerate/01_cifar_accelerate.ipynb:cell-18).
// Device-data collectives are XLA's job (compiled over ICI); this plane
// carries the control values that must flow BEFORE or OUTSIDE compiled
// programs (choosing ports, spreading run ids, host health beacons).
//
// Topology: rank 0 is the hub (listens), ranks 1..n-1 connect.  All ops are
// hub-mediated; payloads are length-prefixed (u64 LE).  Every op carries an
// op tag so mismatched call sequences fail loudly instead of deadlocking.
//
// Build: g++ -O2 -shared -fPIC controlplane.cpp -o libtfcp.so -lpthread

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint8_t OP_BARRIER = 1;
constexpr uint8_t OP_BROADCAST = 2;
constexpr uint8_t OP_ALLGATHER = 3;

// Hard ceiling on any frame: control payloads are capped at 1 MiB on the
// Python side; the allgather blob concatenates one payload per rank.  An
// attacker-supplied length beyond this is rejected before malloc.
constexpr uint64_t MAX_FRAME = 1ull << 30;

struct Plane {
  int world = 1;
  int rank = 0;
  int listen_fd = -1;
  std::vector<int> peers;  // hub: fd per rank (index 0 unused); spoke: [fd]
};

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = (char*)buf;
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool send_frame(int fd, uint8_t op, const uint8_t* buf, uint64_t n) {
  if (!send_all(fd, &op, 1)) return false;
  uint64_t len = n;  // LE assumed (x86/arm little-endian)
  if (!send_all(fd, &len, 8)) return false;
  return n == 0 || send_all(fd, buf, n);
}

// Receives into a malloc'd buffer (caller frees); checks the op tag and
// rejects frames beyond MAX_FRAME (no attacker-sized mallocs).
bool recv_frame(int fd, uint8_t expect_op, uint8_t** buf, uint64_t* n) {
  uint8_t op;
  if (!recv_all(fd, &op, 1) || op != expect_op) return false;
  uint64_t len;
  if (!recv_all(fd, &len, 8)) return false;
  if (len > MAX_FRAME) return false;
  uint8_t* p = (uint8_t*)malloc(len ? len : 1);
  if (!p) return false;
  if (len && !recv_all(fd, p, len)) {
    free(p);
    return false;
  }
  *buf = p;
  *n = len;
  return true;
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// Post-rendezvous receive timeout: a crashed peer makes ops fail instead
// of blocking forever (the pre-fix behavior left spokes hung in recv).
void set_rcvtimeo(int fd, int timeout_ms) {
  if (timeout_ms <= 0) return;
  timeval tv{};
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
}

// Collective ops wait much longer than the rendezvous: ranks legitimately
// reach a barrier minutes apart (one rank checkpointing, say) and must not
// be failed by the rendezvous-scale timeout.
constexpr int OP_TIMEOUT_FACTOR = 10;

int64_t now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

// Hostname-or-dotted-quad resolver: inet_addr alone rejects DNS names,
// and launcher host lists are usually names (ssh targets, pod names).
bool resolve_ipv4(const char* host, in_addr* out) {
  in_addr_t a = inet_addr(host);
  if (a != INADDR_NONE) {
    out->s_addr = a;
    return true;
  }
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) return false;
  *out = ((sockaddr_in*)res->ai_addr)->sin_addr;
  freeaddrinfo(res);
  return true;
}

}  // namespace

extern "C" {

// Hub (rank 0): bind, accept world-1 connections.  Each spoke introduces
// itself with (rank u32, token u64); a token mismatch (stray/hostile
// connection) drops that connection and keeps accepting — rendezvous only
// fails when the timeout expires without all genuine spokes arriving.
// Returns handle or nullptr.
void* tfcp_hub_create(const char* bind_addr, int port, int world,
                      int timeout_ms, uint64_t token) {
  Plane* pl = new Plane;
  pl->world = world;
  pl->rank = 0;
  pl->peers.assign(world, -1);
  if (world == 1) return pl;

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) goto fail;
  {
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    addr.sin_addr.s_addr =
        bind_addr && *bind_addr ? inet_addr(bind_addr) : INADDR_ANY;
    if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) goto fail;
    if (listen(fd, world) != 0) goto fail;
    pl->listen_fd = fd;
    int joined = 0;
    // absolute rendezvous deadline: stray connections (port scanners,
    // health checks) must not re-arm the timeout
    const int64_t deadline = now_ms() + timeout_ms;
    while (joined < world - 1) {
      int64_t remaining = deadline - now_ms();
      if (remaining <= 0) goto fail;
      pollfd pfd{fd, POLLIN, 0};
      if (poll(&pfd, 1, (int)remaining) <= 0) goto fail;
      int cfd = accept(fd, nullptr, nullptr);
      if (cfd < 0) goto fail;
      set_nodelay(cfd);
      // short handshake window so a silent stray can't stall acceptance
      int hs = timeout_ms < 5000 ? timeout_ms : 5000;
      set_rcvtimeo(cfd, hs);
      uint32_t peer_rank;
      uint64_t peer_token;
      if (!recv_all(cfd, &peer_rank, 4) || !recv_all(cfd, &peer_token, 8) ||
          peer_token != token || peer_rank == 0 || (int)peer_rank >= world ||
          pl->peers[peer_rank] != -1) {
        close(cfd);  // stray or duplicate: reject, keep listening
        continue;
      }
      set_rcvtimeo(cfd, timeout_ms * OP_TIMEOUT_FACTOR);
      pl->peers[peer_rank] = cfd;
      ++joined;
    }
  }
  return pl;
fail:
  if (fd >= 0) close(fd);
  for (int p : pl->peers)
    if (p >= 0) close(p);
  delete pl;
  return nullptr;
}

// Spoke (rank > 0): connect to the hub, retrying until timeout.
void* tfcp_spoke_create(const char* hub_addr, int port, int rank, int world,
                        int timeout_ms, uint64_t token) {
  Plane* pl = new Plane;
  pl->world = world;
  pl->rank = rank;
  int waited = 0;
  for (;;) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) break;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (!resolve_ipv4(hub_addr, &addr.sin_addr)) {
      close(fd);
      break;
    }
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0) {
      set_nodelay(fd);
      set_rcvtimeo(fd, timeout_ms * OP_TIMEOUT_FACTOR);
      uint32_t r = (uint32_t)rank;
      if (send_all(fd, &r, 4) && send_all(fd, &token, 8)) {
        pl->peers.push_back(fd);
        return pl;
      }
      close(fd);
      break;
    }
    close(fd);
    if (waited >= timeout_ms) break;
    usleep(100 * 1000);  // 100ms between connect retries
    waited += 100;
  }
  delete pl;
  return nullptr;
}

// Barrier: spokes send an empty BARRIER frame; the hub replies once all
// have arrived.  Returns 0 on success.
int tfcp_barrier(void* h) {
  Plane* pl = (Plane*)h;
  if (pl->world == 1) return 0;
  if (pl->rank == 0) {
    for (int i = 1; i < pl->world; ++i) {
      uint8_t* b;
      uint64_t n;
      if (!recv_frame(pl->peers[i], OP_BARRIER, &b, &n)) return -1;
      free(b);
    }
    for (int i = 1; i < pl->world; ++i)
      if (!send_frame(pl->peers[i], OP_BARRIER, nullptr, 0)) return -1;
    return 0;
  }
  if (!send_frame(pl->peers[0], OP_BARRIER, nullptr, 0)) return -1;
  uint8_t* b;
  uint64_t n;
  if (!recv_frame(pl->peers[0], OP_BARRIER, &b, &n)) return -1;
  free(b);
  return 0;
}

// Broadcast from rank 0.  On rank 0, (buf, *size) is the payload; elsewhere
// buf is an output buffer of capacity cap and *size receives the length.
// Returns 0 on success, -2 if the receiver's buffer is too small.
int tfcp_broadcast(void* h, uint8_t* buf, uint64_t* size, uint64_t cap) {
  Plane* pl = (Plane*)h;
  if (pl->world == 1) return 0;
  if (pl->rank == 0) {
    for (int i = 1; i < pl->world; ++i)
      if (!send_frame(pl->peers[i], OP_BROADCAST, buf, *size)) return -1;
    return 0;
  }
  uint8_t* b;
  uint64_t n;
  if (!recv_frame(pl->peers[0], OP_BROADCAST, &b, &n)) return -1;
  if (n > cap) {
    free(b);
    return -2;
  }
  memcpy(buf, b, n);
  *size = n;
  free(b);
  return 0;
}

// Allgather of variable-size payloads.  Everyone sends (in, in_size); the
// hub concatenates in rank order and broadcasts sizes[world] + the blob.
// out must have capacity out_cap; sizes_out must hold world entries.
// Returns 0 on success, -2 if out_cap is too small.
int tfcp_allgather(void* h, const uint8_t* in, uint64_t in_size, uint8_t* out,
                   uint64_t out_cap, uint64_t* sizes_out) {
  Plane* pl = (Plane*)h;
  if (pl->world == 1) {
    if (in_size > out_cap) return -2;
    memcpy(out, in, in_size);
    sizes_out[0] = in_size;
    return 0;
  }
  if (pl->rank == 0) {
    std::vector<uint8_t*> bufs(pl->world, nullptr);
    std::vector<uint64_t> sizes(pl->world, 0);
    bufs[0] = (uint8_t*)in;
    sizes[0] = in_size;
    uint64_t total = in_size;
    for (int i = 1; i < pl->world; ++i) {
      if (!recv_frame(pl->peers[i], OP_ALLGATHER, &bufs[i], &sizes[i])) {
        for (int j = 1; j < i; ++j) free(bufs[j]);
        return -1;
      }
      total += sizes[i];
    }
    int rc = 0;
    if (total > out_cap) rc = -2;
    if (rc == 0) {
      uint64_t off = 0;
      for (int i = 0; i < pl->world; ++i) {
        memcpy(out + off, bufs[i], sizes[i]);
        off += sizes[i];
        sizes_out[i] = sizes[i];
      }
      // header frame: sizes vector; payload frame: concatenated blob
      for (int i = 1; i < pl->world; ++i) {
        if (!send_frame(pl->peers[i], OP_ALLGATHER,
                        (const uint8_t*)sizes_out, 8ull * pl->world) ||
            !send_frame(pl->peers[i], OP_ALLGATHER, out, total)) {
          rc = -1;
          break;
        }
      }
    }
    for (int j = 1; j < pl->world; ++j) free(bufs[j]);
    return rc;
  }
  if (!send_frame(pl->peers[0], OP_ALLGATHER, in, in_size)) return -1;
  uint8_t *sz_buf, *blob;
  uint64_t sz_len, blob_len;
  if (!recv_frame(pl->peers[0], OP_ALLGATHER, &sz_buf, &sz_len)) return -1;
  if (sz_len != 8ull * pl->world) {
    free(sz_buf);
    return -1;
  }
  if (!recv_frame(pl->peers[0], OP_ALLGATHER, &blob, &blob_len)) {
    free(sz_buf);
    return -1;
  }
  int rc = 0;
  if (blob_len > out_cap) {
    rc = -2;
  } else {
    memcpy(out, blob, blob_len);
    memcpy(sizes_out, sz_buf, sz_len);
  }
  free(sz_buf);
  free(blob);
  return rc;
}

void tfcp_destroy(void* h) {
  Plane* pl = (Plane*)h;
  if (!pl) return;
  for (int fd : pl->peers)
    if (fd >= 0) close(fd);
  if (pl->listen_fd >= 0) close(pl->listen_fd);
  delete pl;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Heartbeat: driver-side monitor + worker-side beacon (SURVEY §5: failure
// detection via missing-host heartbeat).  Deliberately a SEPARATE channel
// from the collective plane above: collectives are synchronous and
// sequence-checked, liveness must be asynchronous.  The beacon is a
// background thread ticking one byte per interval; the monitor records a
// monotonic last-seen per rank.  What this detects: worker process death,
// host death, network partition — including the cases where the launcher's
// local transport client (e.g. an ssh process) is still alive and so
// process-poll alone says nothing.  What it cannot detect: a wedged main
// thread (the beacon thread keeps ticking); that stays the run deadline's
// job.
// ---------------------------------------------------------------------------

namespace {

struct HbMonitor {
  int world = 0;
  uint64_t token = 0;
  int listen_fd = -1;
  std::unique_ptr<std::atomic<int64_t>[]> last_seen;  // now_ms, or -1 never
  std::atomic<bool> stop{false};
  std::thread acceptor;
  std::mutex mu;  // guards conns/readers
  std::vector<int> conns;  // slot == -1: retired, reusable
  std::vector<std::pair<std::thread, size_t>> readers;  // (thread, conn slot)
};

void hb_reader(HbMonitor* m, int fd, int rank, size_t conn_idx) {
  // 1-second receive slices so stop is honored promptly
  set_rcvtimeo(fd, 1000);
  while (!m->stop.load()) {
    uint8_t byte;
    ssize_t r = ::recv(fd, &byte, 1, 0);
    if (r == 1) {
      m->last_seen[rank].store(now_ms());
    } else if (r == 0) {
      break;  // beacon closed (worker exited or reconnecting)
    } else if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
      break;
    }
  }
  // close under the mutex AND retire the conns slot: reconnecting
  // beacons must not leak one fd per flap, and destroy()'s shutdown
  // pass must never touch a number the process has since reused.
  std::lock_guard<std::mutex> lock(m->mu);
  close(fd);
  m->conns[conn_idx] = -1;
}

void hb_acceptor(HbMonitor* m) {
  while (!m->stop.load()) {
    pollfd pfd{m->listen_fd, POLLIN, 0};
    int pr = poll(&pfd, 1, 200);
    if (pr <= 0) continue;
    int cfd = accept(m->listen_fd, nullptr, nullptr);
    if (cfd < 0) continue;
    set_nodelay(cfd);
    set_rcvtimeo(cfd, 2000);  // short handshake window for strays
    uint32_t rank;
    uint64_t token;
    if (!recv_all(cfd, &rank, 4) || !recv_all(cfd, &token, 8) ||
        token != m->token || (int)rank >= m->world) {
      close(cfd);
      continue;
    }
    m->last_seen[rank].store(now_ms());
    std::lock_guard<std::mutex> lock(m->mu);
    if (m->stop.load()) {  // destroy raced the accept
      close(cfd);
      return;
    }
    // reap finished readers + reuse their retired slot: a flapping
    // beacon reconnecting for days must not grow threads/slots unboundedly
    for (auto it = m->readers.begin(); it != m->readers.end();) {
      if (m->conns[it->second] == -1) {
        if (it->first.joinable()) it->first.join();  // already exited
        it = m->readers.erase(it);
      } else {
        ++it;
      }
    }
    size_t slot = m->conns.size();
    for (size_t i = 0; i < m->conns.size(); ++i)
      if (m->conns[i] == -1) {
        slot = i;
        break;
      }
    if (slot == m->conns.size())
      m->conns.push_back(cfd);
    else
      m->conns[slot] = cfd;
    m->readers.emplace_back(std::thread(hb_reader, m, cfd, (int)rank, slot),
                            slot);
  }
}

struct HbBeacon {
  std::atomic<bool> stop{false};
  std::thread t;
};

void hb_beat(HbBeacon* b, std::string addr, int port, int rank, uint64_t token,
             int interval_ms) {
  auto nap = [&](int ms) {  // sleep in slices so destroy() is prompt
    for (int done = 0; done < ms && !b->stop.load(); done += 50)
      usleep(50 * 1000);
  };
  while (!b->stop.load()) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) {
      nap(500);
      continue;
    }
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    if (!resolve_ipv4(addr.c_str(), &sa.sin_addr)) {
      close(fd);
      nap(2000);  // DNS may come up later; keep trying
      continue;
    }
    timeval tv{2, 0};
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (connect(fd, (sockaddr*)&sa, sizeof(sa)) != 0) {
      close(fd);
      nap(500);  // monitor not up yet / transient: retry forever
      continue;
    }
    set_nodelay(fd);
    uint32_t r = (uint32_t)rank;
    if (!send_all(fd, &r, 4) || !send_all(fd, &token, 8)) {
      close(fd);
      nap(500);
      continue;
    }
    while (!b->stop.load()) {
      uint8_t byte = 1;
      if (!send_all(fd, &byte, 1)) break;  // reconnect path
      nap(interval_ms);
    }
    close(fd);
  }
}

}  // namespace

extern "C" {

// Monitor (driver side): listens for beacon connections, tracks last-seen
// per rank.  Returns handle or nullptr.
void* tfhb_monitor_create(const char* bind_addr, int port, int world,
                          uint64_t token) {
  HbMonitor* m = new HbMonitor;
  m->world = world;
  m->token = token;
  m->last_seen.reset(new std::atomic<int64_t>[world]);
  for (int i = 0; i < world; ++i) m->last_seen[i].store(-1);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    delete m;
    return nullptr;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  addr.sin_addr.s_addr =
      bind_addr && *bind_addr ? inet_addr(bind_addr) : INADDR_ANY;
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, world > 0 ? world : 1) != 0) {
    close(fd);
    delete m;
    return nullptr;
  }
  m->listen_fd = fd;
  m->acceptor = std::thread(hb_acceptor, m);
  return m;
}

// Milliseconds since rank's last beacon; -1 if it never connected.
int64_t tfhb_last_seen_ms(void* h, int rank) {
  HbMonitor* m = (HbMonitor*)h;
  if (rank < 0 || rank >= m->world) return -1;
  int64_t t = m->last_seen[rank].load();
  if (t < 0) return -1;
  int64_t d = now_ms() - t;
  return d < 0 ? 0 : d;
}

void tfhb_monitor_destroy(void* h) {
  HbMonitor* m = (HbMonitor*)h;
  if (!m) return;
  m->stop.store(true);
  if (m->acceptor.joinable()) m->acceptor.join();
  {
    std::lock_guard<std::mutex> lock(m->mu);
    for (int fd : m->conns)
      if (fd >= 0) shutdown(fd, SHUT_RDWR);  // -1 = reader already retired it
  }
  for (auto& r : m->readers)
    if (r.first.joinable()) r.first.join();
  // every reader closed+retired its own slot on exit; nothing left to close
  if (m->listen_fd >= 0) close(m->listen_fd);
  delete m;
}

// Beacon (worker side): background thread, connects (retrying forever —
// the monitor may start later or restart) and ticks every interval_ms.
void* tfhb_beacon_create(const char* addr, int port, int rank, uint64_t token,
                         int interval_ms) {
  HbBeacon* b = new HbBeacon;
  b->t = std::thread(hb_beat, b, std::string(addr ? addr : "127.0.0.1"), port,
                     rank, token, interval_ms > 0 ? interval_ms : 1000);
  return b;
}

void tfhb_beacon_destroy(void* h) {
  HbBeacon* b = (HbBeacon*)h;
  if (!b) return;
  b->stop.store(true);
  if (b->t.joinable()) b->t.join();
  delete b;
}

}  // extern "C"
