"""Autotune configuration: the knob registry slice it owns, the legal
search space, and the persisted winning-config store.

The store lives next to the compile cache (same ``TPUFRAME_LOCAL_SCRATCH``
root) and is keyed ``(host, topology, plan.signature())`` — the same
identity the compile spine uses to tell "same program, rebound" from
"different program".  A supervised restart on the same host, and every
other rank on that host, loads the persisted config and starts tuned
instead of re-probing; a different topology or plan signature misses the
key and tunes fresh.  Writes are atomic (tmp + ``os.replace``) and reads
are tolerant (corrupt/partial JSON loads as "no config"), like every
other scratch artifact in the tree.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import tempfile
import time
from typing import Any

__all__ = [
    "AUTOTUNE_ENV_VARS",
    "AUTOTUNE_ENV_DOMAINS",
    "TunedConfig",
    "all_env_domains",
    "autotune_dir",
    "autotune_enabled",
    "clamp",
    "config_key",
    "default_host",
    "list_tuned",
    "load_tuned",
    "save_tuned",
]

#: every env knob the autotune spine reads — THE list, aggregated by
#: ``launch.remote.all_env_vars()`` and printed by the doctor's
#: ``autotune`` section.  Add new knobs here, not in the consumers.
AUTOTUNE_ENV_VARS = (
    "TPUFRAME_AUTOTUNE",
    "TPUFRAME_AUTOTUNE_DIR",
    "TPUFRAME_AUTOTUNE_PROBE_STEPS",
    "TPUFRAME_AUTOTUNE_WARMUP_STEPS",
    "TPUFRAME_AUTOTUNE_GUARD",
    "TPUFRAME_AUTOTUNE_ROUNDS",
)

#: value domains for the knobs above (KN007).  The probe-shape knobs are
#: re-read per ``tune_training`` call -> "live"; the master switch and
#: the store location are consulted where components are built ->
#: "restart".
AUTOTUNE_ENV_DOMAINS = {
    "TPUFRAME_AUTOTUNE": {"type": "bool", "apply": "restart"},
    "TPUFRAME_AUTOTUNE_DIR": {"type": "path", "apply": "restart"},
    "TPUFRAME_AUTOTUNE_PROBE_STEPS": {
        "type": "int", "range": (2, 10000), "apply": "live"},
    "TPUFRAME_AUTOTUNE_WARMUP_STEPS": {
        "type": "int", "range": (0, 1000), "apply": "live"},
    "TPUFRAME_AUTOTUNE_GUARD": {
        "type": "float", "range": (0.5, 1.0), "apply": "live"},
    "TPUFRAME_AUTOTUNE_ROUNDS": {
        "type": "int", "range": (1, 64), "apply": "live"},
}

_FALSY = ("", "0", "false", "no", "off", "disabled")


def autotune_enabled() -> bool:
    """The master switch: ``TPUFRAME_AUTOTUNE`` truthy."""
    return os.environ.get("TPUFRAME_AUTOTUNE", "").strip().lower() not in _FALSY


def autotune_dir() -> str:
    """Where winning configs persist: ``TPUFRAME_AUTOTUNE_DIR``, else an
    ``autotune/`` sibling of the compile cache under the host-shared
    scratch root (every rank on a host shares one store, which is the
    point — same-host ranks start tuned)."""
    v = os.environ.get("TPUFRAME_AUTOTUNE_DIR", "").strip()
    if v:
        return v
    base = os.environ.get("TPUFRAME_LOCAL_SCRATCH") or os.path.join(
        tempfile.gettempdir(), "tpuframe_scratch"
    )
    return os.path.join(base, "autotune")


def default_host() -> str:
    return socket.gethostname()


def config_key(host: str, topology: str, signature: str) -> str:
    """Filename-stable digest of the persistence identity."""
    blob = json.dumps([host, topology, signature]).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass
class TunedConfig:
    """One winning configuration: the env overrides that beat the
    baseline, plus enough provenance to audit how they won.

    ``env`` maps knob name -> string value (env-var encoding: this is
    exactly what a supervised restart exports).  ``probes`` records each
    A/B probe's (knob, value, p50, committed) so the doctor can show the
    decision trail.
    """

    host: str
    topology: str
    signature: str
    env: dict[str, str]
    source: str = "train"  # "train" | "serve"
    baseline_p50_s: float | None = None
    tuned_p50_s: float | None = None
    probes: list[dict] = dataclasses.field(default_factory=list)
    created_unix: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TunedConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    @property
    def convergence_ratio(self) -> float | None:
        """tuned p50 / baseline p50 (< 1.0 means the loop won)."""
        if self.baseline_p50_s and self.tuned_p50_s:
            return self.tuned_p50_s / self.baseline_p50_s
        return None


def _path_for(host: str, topology: str, signature: str,
              store_dir: str | None = None) -> str:
    d = store_dir or autotune_dir()
    return os.path.join(d, config_key(host, topology, signature) + ".json")


def save_tuned(cfg: TunedConfig, store_dir: str | None = None) -> str:
    """Atomically persist ``cfg``; returns the path.  A store that can't
    be written degrades to un-tuned restarts, never takes training down."""
    path = _path_for(cfg.host, cfg.topology, cfg.signature, store_dir)
    if not cfg.created_unix:
        cfg.created_unix = time.time()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(cfg.to_dict(), f, indent=2, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        return path
    return path


def load_tuned(host: str, topology: str, signature: str,
               store_dir: str | None = None) -> TunedConfig | None:
    """The persisted config for this identity, or None (missing store,
    corrupt JSON, wrong shape — all read as "tune fresh")."""
    path = _path_for(host, topology, signature, store_dir)
    try:
        with open(path) as f:
            d = json.load(f)
        cfg = TunedConfig.from_dict(d)
    except (OSError, ValueError, TypeError):
        return None
    if (cfg.host, cfg.topology, cfg.signature) != (host, topology, signature):
        return None  # hash collision or hand-edited file: don't trust it
    return cfg


def list_tuned(store_dir: str | None = None) -> list[TunedConfig]:
    """Every readable persisted config in the store (doctor/CLI view)."""
    d = store_dir or autotune_dir()
    out: list[TunedConfig] = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                out.append(TunedConfig.from_dict(json.load(f)))
        except (OSError, ValueError, TypeError):
            continue
    return out


def all_env_domains() -> dict[str, dict]:
    """Every spine's knob value-domains, aggregated — the runtime mirror
    of ``launch.remote.all_env_vars()`` and the autotuner's legal search
    space.  Same stdlib-only import set, same reason: this must resolve
    on a wedged-backend process (the doctor prints it)."""
    from tpuframe.compile.cache import COMPILE_ENV_DOMAINS
    from tpuframe.core.workspace import PERF_ENV_DOMAINS
    from tpuframe.fault.health import HEALTH_ENV_DOMAINS
    from tpuframe.ops.ledger import KERNEL_ENV_DOMAINS
    from tpuframe.parallel.comms_env import COMMS_ENV_DOMAINS
    from tpuframe.serve.admission import SERVE_ENV_DOMAINS
    from tpuframe.track.telemetry import OBSERVABILITY_ENV_DOMAINS

    out: dict[str, dict] = {}
    for d in (OBSERVABILITY_ENV_DOMAINS, COMPILE_ENV_DOMAINS,
              HEALTH_ENV_DOMAINS, SERVE_ENV_DOMAINS, PERF_ENV_DOMAINS,
              COMMS_ENV_DOMAINS, AUTOTUNE_ENV_DOMAINS, KERNEL_ENV_DOMAINS):
        out.update(d)
    return out


def clamp(knob: str, value: Any,
          domains: dict[str, dict] | None = None) -> str | None:
    """``value`` coerced into ``knob``'s legal domain as an env string,
    or None when the knob has no domain / the value can't be made legal.
    This is the single gate between a diagnosis and the environment: a
    move the registry doesn't sanction never reaches a probe."""
    d = (domains if domains is not None else all_env_domains()).get(knob)
    if d is None:
        return None
    t = d.get("type")
    try:
        if t == "int" or t == "float":
            num = int(value) if t == "int" else float(value)
            lo, hi = d.get("range", (None, None))
            if lo is not None and num < lo:
                num = int(lo) if t == "int" else float(lo)
            if hi is not None and num > hi:
                num = int(hi) if t == "int" else float(hi)
            return str(num)
        if t == "bool":
            if isinstance(value, str):
                return "0" if value.strip().lower() in _FALSY else "1"
            return "1" if value else "0"
        if t == "enum":
            s = str(value)
            return s if s in tuple(d.get("choices", ())) else None
        if t in ("str", "path"):
            return str(value)
    except (TypeError, ValueError):
        return None
    return None
