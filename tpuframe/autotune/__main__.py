"""CLI: ``python -m tpuframe.autotune [--json] [--signature SIG]``.

Lists the persisted winning configs in the store (all of them, or the
one matching ``--host/--topology/--signature``) — the same view the
doctor's ``autotune`` section prints.  Read-only: tuning itself runs
where the workload lives (``tune_training`` needs a run_fn; see
AUTOTUNE.md for the probe one-liner).
"""

# tpuframe-lint: stdlib-only

import argparse
import json

from tpuframe.autotune.config import (
    autotune_dir,
    default_host,
    list_tuned,
    load_tuned,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuframe.autotune",
        description="inspect the persisted autotune winning configs",
    )
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--dir", default=None, help="store dir (default: "
                    "TPUFRAME_AUTOTUNE_DIR or the scratch sibling of the "
                    "compile cache)")
    ap.add_argument("--host", default=None)
    ap.add_argument("--topology", default=None)
    ap.add_argument("--signature", default=None,
                    help="plan signature to look up (with --host/--topology "
                         "defaults: this host, topology '1')")
    args = ap.parse_args(argv)

    if args.signature is not None:
        cfg = load_tuned(args.host or default_host(),
                         args.topology or "1", args.signature, args.dir)
        if cfg is None:
            print("no persisted config for that (host, topology, signature)")
            return 1
        print(json.dumps(cfg.to_dict(), indent=2, sort_keys=True))
        return 0

    configs = list_tuned(args.dir)
    if args.as_json:
        print(json.dumps({"dir": args.dir or autotune_dir(),
                          "configs": [c.to_dict() for c in configs]},
                         indent=2, sort_keys=True))
        return 0
    print(f"store: {args.dir or autotune_dir()}")
    for c in configs:
        ratio = c.convergence_ratio
        print(f"  {c.source} host={c.host} topology={c.topology} "
              f"signature={c.signature or '-'} knobs={len(c.env)}"
              + (f" p50 x{ratio:.2f}" if ratio else ""))
    print(f"{len(configs)} config(s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
