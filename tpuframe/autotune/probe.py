"""The measured-probe harness: short timeboxed A/B runs, bench-style.

Methodology is lifted from the bench harness (``benchmarks/bench_*``):
per-step walls with the warmup prefix discarded, medians (robust to the
one GC pause), and the fleet analyzer's exit-3 regression-gate stance —
a probe can observe whatever it likes, but it can only *commit* a config
whose median beats the baseline by the guard margin.  A slower probe is
recorded (the decision trail persists with the config) and rolled back.

The probe's contract with its caller is one function:
``run_fn(env: dict[str, str]) -> list[float]`` — run a short workload
with ``env`` overlaid on the environment and return per-step wall
seconds.  The overlay/restore is handled HERE (``_env_overlay``), so a
run_fn that crashes can never leak probe env into the real run.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Callable, Iterator

__all__ = ["ProbeResult", "measure", "run_probe"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "").strip() or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "").strip() or default)
    except ValueError:
        return default


def probe_steps() -> int:
    """Steps per probe run (``TPUFRAME_AUTOTUNE_PROBE_STEPS``, default 8)."""
    return max(2, _env_int("TPUFRAME_AUTOTUNE_PROBE_STEPS", 8))


def warmup_steps() -> int:
    """Warmup prefix discarded from every probe
    (``TPUFRAME_AUTOTUNE_WARMUP_STEPS``, default 2)."""
    return max(0, _env_int("TPUFRAME_AUTOTUNE_WARMUP_STEPS", 2))


def guard_ratio() -> float:
    """Commit threshold (``TPUFRAME_AUTOTUNE_GUARD``, default 0.97): a
    probe commits only when ``median <= baseline * guard`` — capped at
    1.0 so no configuration can ever commit slower than its baseline."""
    return min(1.0, max(0.5, _env_float("TPUFRAME_AUTOTUNE_GUARD", 0.97)))


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


@dataclasses.dataclass
class ProbeResult:
    """One A/B probe's verdict (persists in ``TunedConfig.probes``)."""

    env: dict[str, str]
    p50_s: float
    baseline_p50_s: float
    committed: bool
    reason: str
    steps: int

    @property
    def ratio(self) -> float:
        return (self.p50_s / self.baseline_p50_s
                if self.baseline_p50_s > 0 else float("inf"))

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ratio"] = round(self.ratio, 4)
        return d


@contextlib.contextmanager
def _env_overlay(env: dict[str, str]) -> Iterator[None]:
    """Apply ``env`` to ``os.environ`` for the probe's duration and
    restore EXACTLY the prior state afterwards, crash or not."""
    saved = {k: os.environ.get(k) for k in env}
    try:
        os.environ.update(env)
        yield
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


def measure(run_fn: Callable[[dict], list[float]],
            env: dict[str, str] | None = None, *,
            warmup: int | None = None) -> float:
    """Warmup-discarded median step wall of one run under ``env``."""
    env = dict(env or {})
    w = warmup_steps() if warmup is None else warmup
    with _env_overlay(env):
        walls = list(run_fn(env))
    if not walls:
        raise ValueError("run_fn returned no step walls")
    kept = walls[w:] if len(walls) > w else walls[-1:]
    return _median(kept)


def run_probe(run_fn: Callable[[dict], list[float]],
              env: dict[str, str], baseline_p50_s: float, *,
              guard: float | None = None,
              warmup: int | None = None) -> ProbeResult:
    """One A/B probe of ``env`` against ``baseline_p50_s``.

    Never raises out of a failing candidate: a run_fn that dies under
    the probe env yields an uncommitted result (reason carries the
    error) — a config that cannot even run must never commit.
    """
    g = guard_ratio() if guard is None else min(1.0, guard)
    try:
        p50 = measure(run_fn, env, warmup=warmup)
    except Exception as e:  # the probe boundary: contain, report, roll back
        return ProbeResult(
            env=dict(env), p50_s=float("inf"),
            baseline_p50_s=baseline_p50_s, committed=False,
            reason=f"probe run failed: {type(e).__name__}: {e}",
            steps=0,
        )
    committed = p50 <= baseline_p50_s * g
    reason = (
        f"p50 {p50:.4f}s vs baseline {baseline_p50_s:.4f}s "
        f"(guard x{g:.2f}): " + ("committed" if committed else "rolled back")
    )
    return ProbeResult(
        env=dict(env), p50_s=p50, baseline_p50_s=baseline_p50_s,
        committed=committed, reason=reason, steps=probe_steps(),
    )
