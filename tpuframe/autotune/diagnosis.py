"""Diagnosis: turn the analyzer's skew report into ordered knob moves.

The decision table (AUTOTUNE.md mirrors it) reads the same signals a
human reads off ``python -m tpuframe.track.analyze``:

- **input-bound** (``lost_by_bound.input`` dominates, or — single-rank
  runs, where cross-rank skew is zero by construction — the per-step
  ``bound`` votes / ``data_wait_total_s`` fraction say the step waits on
  the host pipeline): more loader workers, deeper prefetch, more ring
  buffers, uint8 transfer.
- **checkpoint-bound** (``lost_by_bound.checkpoint`` dominates): stretch
  the mid-epoch snapshot cadence.
- **comms-bound** (the ``comms`` block shows allreduce wall a large
  fraction of step wall at mode "none"): int8 wire compression, then
  bucket sizing.
- **memory-bound** (the ``memory`` block carries an OOM, or the live
  HBM watermark sits above ~92% of the device limit): raise the ZeRO
  stage, split grad-accum microbatches, offload the optimizer — the
  ``memory/oom`` event's ``suggest_fit`` rung seeds the values when one
  exists.  Checked FIRST: a plan that doesn't fit can't be tuned
  faster.
- **compile** (cold-compile wall dominates total): make sure the AOT
  precompiler and the persistent compile cache are on.

Every proposed value passes through :func:`tpuframe.autotune.config.clamp`
against the lint-enforced ``*_ENV_DOMAINS`` registry — a move outside a
knob's legal domain is dropped here, before it can reach a probe.  The
diagnosis only *proposes*; the probe harness decides (a committed move
must beat its baseline, so a wrong diagnosis costs probe time, never a
slower run).
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import dataclasses

from tpuframe.autotune.config import all_env_domains, clamp
from tpuframe.ops.ledger import normalize_top_ops

__all__ = ["Diagnosis", "KnobMove", "diagnose"]

#: below this fraction of total step wall, a bottleneck class is noise
_SIGNIFICANT = 0.10

#: HBM watermark / device limit above which the fit is one fragmentation
#: spike away from RESOURCE_EXHAUSTED — memory-bound even without an OOM
_MEM_PRESSURE = 0.92

#: base-op name tokens that identify the compressed wire's staged
#: encode/decode math in a ``device_time.top_ops`` row — the
#: scale/round/clip/dequant chain XLA emits around a staged collective
#: (convert + round-nearest + clamp/floor on the bucket arrays)
_WIRE_MATH_OPS = ("convert", "round", "clamp", "floor", "clip", "quant")


@dataclasses.dataclass(frozen=True)
class KnobMove:
    """One candidate env write: knob -> value, with the symptom that
    motivated it (the doctor prints these as the decision trail)."""

    knob: str
    value: str
    reason: str


@dataclasses.dataclass
class Diagnosis:
    """What the report says is slow, and the ordered probe candidates."""

    bound: str  # "input" | "checkpoint" | "comms" | "memory" | "compute" | "none"
    detail: dict
    moves: list[KnobMove]


def _bound_votes(report: dict) -> dict[str, int]:
    """Per-step bound classification tally — the single-rank-safe signal
    (``lost_by_bound`` only accumulates on straggling steps, which need
    cross-rank skew to exist)."""
    votes: dict[str, int] = {}
    for row in report.get("per_step") or []:
        b = row.get("bound")
        if b:
            votes[b] = votes.get(b, 0) + 1
    return votes


def _data_wait_fraction(report: dict) -> float:
    """Fleet data-wait seconds over fleet step seconds — how much of the
    run the devices spent waiting on the host pipeline."""
    wait = sum(r.get("data_wait_total_s") or 0.0
               for r in report.get("per_rank") or [])
    st = report.get("step_time") or {}
    total = (st.get("mean") or 0.0) * (st.get("count") or 0)
    n_ranks = max(1, report.get("ranks") or 1)
    return wait / (total * n_ranks) if total > 0 else 0.0


def _classify(report: dict) -> tuple[str, dict]:
    st = report.get("step_time") or {}
    total_step_s = (st.get("mean") or 0.0) * (st.get("count") or 0)
    lost = dict(report.get("lost_by_bound") or {})
    votes = _bound_votes(report)
    wait_frac = _data_wait_fraction(report)
    detail = {
        "lost_by_bound": lost,
        "bound_votes": votes,
        "data_wait_fraction": round(wait_frac, 4),
    }

    # memory first: an OOM (or a watermark one fragmentation spike from
    # the limit) trumps every speed signal — a plan that doesn't fit
    # can't be tuned faster
    mem = report.get("memory") or None
    if mem:
        util = mem.get("hbm_peak_util") or 0.0
        detail["memory"] = {
            "ooms": mem.get("ooms") or 0,
            "hbm_peak_util": round(util, 4),
        }
        if (mem.get("ooms") or 0) > 0 or util >= _MEM_PRESSURE:
            return "memory", detail

    # multi-rank: straggler-attributed lost seconds name the bound
    if total_step_s > 0 and lost:
        top = max(lost, key=lambda k: lost[k])
        if lost[top] / total_step_s >= _SIGNIFICANT:
            return top, detail

    # comms: allreduce wall as a fraction of step wall.  The report's
    # allreduce_s is a percentile block (standalone/bench collectives
    # only) — p50 x count approximates the total collective wall.
    comms = report.get("comms") or None
    if comms and total_step_s > 0:
        ar = comms.get("allreduce_s") or 0.0
        if isinstance(ar, dict):
            ar = (ar.get("p50") or 0.0) * (ar.get("count") or 0)
        frac = float(ar) / total_step_s
        detail["comms_fraction"] = round(frac, 4)
        if frac >= _SIGNIFICANT:
            return "comms", detail

    # device-level comms: the parsed profiler capture's exposed-comms
    # fraction of the device step — the DIRECT measurement (the
    # allreduce heuristic above only sees standalone/bench collectives;
    # a fused step's collective is invisible to it but not to the trace)
    dt = report.get("device_time") or None
    if dt and (dt.get("device_step_s") or 0) > 0:
        frac = (
            (dt.get("exposed_comms_per_step_s") or 0.0)
            / dt["device_step_s"]
        )
        detail["exposed_comms_fraction"] = round(frac, 4)
        if frac >= _SIGNIFICANT:
            return "comms", detail

    # single-rank fallback: the device waiting on the host IS input-bound
    # even though no step ever "straggles"
    if wait_frac >= _SIGNIFICANT:
        return "input", detail
    steps = sum(votes.values())
    if steps:
        top = max(votes, key=lambda k: votes[k])
        if top != "compute" and votes[top] / steps >= 0.5:
            return top, detail
    return ("compute", detail) if steps else ("none", detail)


def diagnose(report: dict, *, gauges: dict | None = None) -> Diagnosis:
    """Ordered, domain-clamped knob moves for ``report``'s bottleneck.

    ``gauges`` (optional) is a snapshot of live registry gauges (name ->
    value) — currently consulted for the loader's ring-alloc pressure
    (``data/ring_allocs`` growing means the pool is undersized).
    """
    domains = all_env_domains()
    bound, detail = _classify(report)
    moves: list[KnobMove] = []

    def move(knob: str, value, reason: str) -> None:
        v = clamp(knob, value, domains)
        if v is not None:
            moves.append(KnobMove(knob=knob, value=v, reason=reason))

    if bound == "input":
        why = (f"input-bound: data_wait {detail['data_wait_fraction']:.0%} "
               "of step wall")
        move("TPUFRAME_LOADER_WORKERS", 2, why)
        move("TPUFRAME_LOADER_WORKERS", 4, why)
        move("TPUFRAME_PREFETCH_DEPTH", 4, why)
        move("TPUFRAME_LOADER_TRANSFER_DTYPE", "uint8",
             "input-bound: uint8 transfer is 4x less host->device bytes")
        move("TPUFRAME_LOADER_RING_BUFFERS", 8,
             "input-bound: deeper assembly ring")
        if gauges and (gauges.get("data/ring_allocs") or 0) > 0:
            move("TPUFRAME_LOADER_RING_BUFFERS", 16,
                 "ring pool undersized: data/ring_allocs still growing")
    elif bound == "checkpoint":
        lost = detail["lost_by_bound"].get("checkpoint", 0.0)
        move("TPUFRAME_CKPT_INTERVAL_BATCHES", 200,
             f"checkpoint-bound: {lost:.2f}s lost to snapshot stalls — "
             "stretch the mid-epoch cadence")
    elif bound == "comms":
        comms = report.get("comms") or {}
        dt = report.get("device_time") or {}
        exposed = dt.get("exposed_comms_per_step_s")
        why_bucket = "comms-bound: larger buckets amortize per-collective latency"
        if exposed:
            # the measured number the bucket probe must shrink: exposed
            # wall, not bytes — overlap is the win on real topology
            why_bucket = (
                f"comms-bound: {exposed * 1e3:.2f}ms/step of collective "
                "wall exposed (not hidden behind compute) — probe bucket "
                "sizing against overlap"
            )
        if (comms.get("mode") or "none") in ("none", ""):
            move("TPUFRAME_COMMS_COMPRESSION", "int8",
                 "comms-bound at f32 wire: int8 is ~4x fewer sync bytes")
        if exposed:
            # the overlap probe: gated on the MEASURED exposed wall (a
            # parsed capture), because group scheduling only pays when
            # collective seconds are provably NOT hidden behind compute
            # — bytes-on-wire is invariant under grouping, so the probe
            # must judge itself on exposed ms/step, nothing else
            move("TPUFRAME_COMMS_GROUPS", 4,
                 f"comms-bound: {exposed * 1e3:.2f}ms/step exposed — "
                 "fire the sync as 4 bucket groups in reverse-backward "
                 "order so the wire hides behind the remaining backward")
        move("TPUFRAME_COMMS_BUCKET_MB", 8.0, why_bucket)
        move("TPUFRAME_GRAD_ACCUM", 2,
             "comms-bound: accumulate micro-batches, sync once per "
             "super-batch")
    elif bound == "memory":
        mem = report.get("memory") or {}
        oom = mem.get("last_oom") or {}
        sug = oom.get("suggestion") or {}
        util = (detail.get("memory") or {}).get("hbm_peak_util") or 0.0
        why = (
            f"memory-bound: {mem.get('ooms') or 0} OOM event(s)"
            if (mem.get("ooms") or 0) > 0
            else f"memory-bound: HBM watermark at {util:.0%} of the limit"
        )
        # the estimator's nearest-fitting rung seeds the values when the
        # OOM event carried one; the escalation-ladder defaults
        # otherwise.  Every move still passes clamp + the
        # never-commit-slower probe — a bad suggestion costs probe time,
        # never a slower (or still-OOMing) run.
        move("TPUFRAME_ZERO_STAGE", sug.get("zero_stage", 3),
             why + " — shard optimizer/params over the data-parallel "
             "world (restart)")
        move("TPUFRAME_GRAD_ACCUM", sug.get("microbatches", 2),
             why + " — smaller microbatch slices shrink live activations")
        if sug.get("offload_optimizer") or not sug:
            move("TPUFRAME_OFFLOAD_OPTIMIZER", True,
                 why + " — optimizer state to pinned host memory")
    elif bound == "compute":
        # compute-bound is the healthy baseline; moves exist only when a
        # parsed capture NAMES where the compute goes — the top-op table
        # is the fusion target list (ROADMAP item 3(b)), and this branch
        # is its first consumer.  Every move still has to win the
        # never-commit-slower probe, so a wrong attribution costs probe
        # time, never a slower run.
        top = (report.get("device_time") or {}).get("top_ops")
        if top:
            # the ledger's name map turns raw profiler names into
            # actionable tpuframe ops: a detail row says
            # "cross_entropy", not "log_softmax_fusion" — an operator
            # (and the kernel plane) can act on the former
            top = normalize_top_ops(top[:5])
            detail["top_ops"] = top
            comms = report.get("comms") or {}
            wire_on = (comms.get("mode") or "none") not in ("none", "")
            wire_math = [
                op for op in top[:5]
                if any(tok in (op.get("raw") or op.get("name") or "").lower()
                       for tok in _WIRE_MATH_OPS)
            ]
            if wire_math and wire_on:
                names = ",".join(op.get("name") or "?" for op in wire_math[:3])
                pct = sum(op.get("pct") or 0.0 for op in wire_math)
                move("TPUFRAME_COMMS_FUSED", True,
                     f"compute-bound on staged wire math ({names}: "
                     f"{pct:.1f}% of device time with compression on) — "
                     "fuse encode/decode into the collective hops and let "
                     "the quant_wire kernels do each stage in one VMEM "
                     "pass")
            fusable = [
                op for op in top[:5]
                if op.get("class") == "compute"
                and (op.get("pct") or 0.0) >= 100.0 * _SIGNIFICANT
            ]
            if fusable:
                names = ",".join(op.get("name") or "?" for op in fusable[:3])
                move("TPUFRAME_DISABLE_PALLAS", False,
                     f"compute-bound on fusable ops ({names}) — make sure "
                     "the Pallas kernel paths (layer_norm, cross_entropy, "
                     "adamw, quant_wire) are engaged, not the staged jnp "
                     "references")
            # rows the name map pins to dispatchable tpuframe ops are
            # the kernel ledger's A/B territory: auto dispatch prices
            # each kernel (and its tile grid) per shape class
            priced = [op for op in top[:5] if op.get("op")]
            if priced:
                names = ",".join(op["op"] for op in priced[:3])
                move("TPUFRAME_KERNELS", "auto",
                     f"compute-bound on dispatchable ops ({names}) — let "
                     "the kernel ledger A/B-price each kernel and its "
                     "tile knobs for this shape class "
                     "(benchmarks/bench_kernels.py persists verdicts)")

    # compile block rides along regardless of bound: a cold compile that
    # dominates the window says the cache/precompiler are off
    compile_block = report.get("compile") or {}
    ttfs = report.get("time_to_first_step") or {}
    if (compile_block.get("wall_s") or 0.0) > 0 and (
        ttfs.get("s") or 0.0
    ) > 0 and compile_block["wall_s"] >= 0.5 * ttfs["s"]:
        move("TPUFRAME_PRECOMPILE", True,
             "compile wall dominates time-to-first-step: keep AOT "
             "precompile on")

    return Diagnosis(bound=bound, detail=detail, moves=moves)
