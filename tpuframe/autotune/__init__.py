"""Self-tuning loop: the analyzer's diagnosis drives the knobs.

The stack measures everything — bound classification per step, compile
walls, bytes-on-wire, serve latency percentiles — and this package
closes the loop: :func:`diagnose` turns a skew report into ordered knob
moves, :func:`tune_training` A/B-probes them with the bench harness's
warmup-discarded-median methodology under a never-commit-slower guard,
and the winning config persists per ``(host, topology,
plan.signature())`` next to the compile cache so supervised restarts
and same-host ranks start tuned.  :func:`derive_serve_knobs` runs the
same idea on the serve side from the observed request-size distribution
against the SLO.  AUTOTUNE.md is the runbook.

Exports are lazy (PEP 562): the knob list / domains / persistence store
stay importable while the jax backend is wedged — ``all_env_vars()``
and the doctor depend on that.
"""

# tpuframe-lint: stdlib-only

_LAZY = {
    "AUTOTUNE_ENV_VARS": "tpuframe.autotune.config",
    "AUTOTUNE_ENV_DOMAINS": "tpuframe.autotune.config",
    "Diagnosis": "tpuframe.autotune.diagnosis",
    "KnobMove": "tpuframe.autotune.diagnosis",
    "ProbeResult": "tpuframe.autotune.probe",
    "TunedConfig": "tpuframe.autotune.config",
    "all_env_domains": "tpuframe.autotune.config",
    "autotune_dir": "tpuframe.autotune.config",
    "autotune_enabled": "tpuframe.autotune.config",
    "derive_serve_knobs": "tpuframe.autotune.tuner",
    "diagnose": "tpuframe.autotune.diagnosis",
    "list_tuned": "tpuframe.autotune.config",
    "load_tuned": "tpuframe.autotune.config",
    "run_probe": "tpuframe.autotune.probe",
    "save_tuned": "tpuframe.autotune.config",
    "tune_training": "tpuframe.autotune.tuner",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module 'tpuframe.autotune' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))
