"""The tuning loops: greedy train-side probe search and the serve-side
distribution-derived knobs.

Train side (:func:`tune_training`): diagnose -> probe candidates in
order -> each committed winner becomes the new baseline (and its env
sticks for the remaining probes, so moves compose) -> persist the
accumulated winning config per ``(host, topology, signature)``.  The
whole loop is bounded by ``TPUFRAME_AUTOTUNE_ROUNDS`` probes; with the
guard capped at 1.0 the tuned config is monotonically no-slower than
the starting point by construction.

Serve side (:func:`derive_serve_knobs`): no probes — the bucket-shape
set and ``batch_wait_ms`` fall out of the *observed* request-size
distribution against the SLO (percentile sizes rounded up the
power-of-two ladder; wait budgeted as a fixed fraction of the SLO).
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import os
from typing import Callable, Iterable

from tpuframe.autotune import probe as _probe
from tpuframe.autotune.config import (
    TunedConfig,
    default_host,
    save_tuned,
)
from tpuframe.autotune.diagnosis import Diagnosis, diagnose

__all__ = ["derive_serve_knobs", "tune_training"]


def _rounds() -> int:
    try:
        v = int(os.environ.get("TPUFRAME_AUTOTUNE_ROUNDS", "").strip() or 6)
    except ValueError:
        v = 6
    return max(1, v)


def tune_training(
    run_fn: Callable[[dict], list[float]],
    report: dict | None = None, *,
    host: str | None = None,
    topology: str = "1",
    signature: str = "",
    gauges: dict | None = None,
    moves: Iterable | None = None,
    save: bool = True,
    store_dir: str | None = None,
) -> TunedConfig:
    """Probe the diagnosis-ordered knob moves and persist the winner.

    ``run_fn(env) -> [per-step wall seconds]`` is the probe workload —
    typically a handful of real training steps on the real loader (the
    bench harness and the acceptance test build it from a Trainer
    factory).  ``report`` is ``track.analyze.skew_report`` output for
    the mis-behaving run; without one, the candidate list must come in
    via ``moves``.
    """
    from tpuframe.track.telemetry import get_telemetry

    tel = get_telemetry()
    host = host or default_host()
    diag: Diagnosis | None = None
    if moves is None:
        diag = diagnose(report or {}, gauges=gauges)
        moves = diag.moves
    moves = list(moves)

    baseline_p50 = _probe.measure(run_fn, {})
    cfg = TunedConfig(
        host=host, topology=topology, signature=signature,
        env={}, source="train", baseline_p50_s=baseline_p50,
        tuned_p50_s=baseline_p50,
    )
    tel.event("autotune/start", bound=diag.bound if diag else "manual",
              baseline_p50_s=round(baseline_p50, 6), candidates=len(moves))

    for mv in moves[: _rounds()]:
        candidate = dict(cfg.env)
        candidate[mv.knob] = mv.value
        if candidate == cfg.env:
            continue  # committed earlier round already covers this value
        result = _probe.run_probe(run_fn, candidate, cfg.tuned_p50_s)
        record = result.to_dict()
        record["knob"], record["reason_for_move"] = mv.knob, mv.reason
        cfg.probes.append(record)
        tel.event(
            "autotune/probe", knob=mv.knob, value=mv.value,
            p50_s=round(result.p50_s, 6),
            baseline_p50_s=round(result.baseline_p50_s, 6),
            committed=result.committed,
        )
        if result.committed:
            cfg.env = candidate
            cfg.tuned_p50_s = result.p50_s

    if save:
        save_tuned(cfg, store_dir)
    tel.event(
        "autotune/tuned", knobs=len(cfg.env),
        baseline_p50_s=round(cfg.baseline_p50_s or 0.0, 6),
        tuned_p50_s=round(cfg.tuned_p50_s or 0.0, 6),
        convergence_ratio=round(cfg.convergence_ratio or 1.0, 4),
        signature=signature,
    )
    return cfg


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _percentile(sorted_xs: list, q: float) -> float:
    if not sorted_xs:
        return 0.0
    idx = min(len(sorted_xs) - 1, int(q * (len(sorted_xs) - 1) + 0.5))
    return sorted_xs[idx]


def derive_serve_knobs(sizes: Iterable[int], *, slo_ms: float,
                       max_bucket: int | None = None) -> dict[str, str]:
    """Serve knobs derived from the observed request-size distribution.

    Buckets: the p50/p95/max request sizes, each rounded up the
    power-of-two ladder and deduped — small frequent requests get a snug
    bucket (less padding waste), the tail still fits without a shape
    miss.  ``batch_wait_ms``: 5% of the SLO, clamped to [0.5, 20] ms —
    enough hold-open to fill a bucket at high rates without spending the
    latency budget on waiting.  Returns env-encoded knobs (the same
    shape :class:`TunedConfig.env` persists); empty observation returns
    just the wait default.
    """
    out: dict[str, str] = {
        "TPUFRAME_SERVE_BATCH_WAIT_MS":
            str(round(min(20.0, max(0.5, slo_ms * 0.05)), 3)),
    }
    xs = sorted(int(s) for s in sizes if int(s) > 0)
    if not xs:
        return out
    marks = {_pow2_at_least(int(_percentile(xs, q))) for q in (0.5, 0.95)}
    marks.add(_pow2_at_least(xs[-1]))
    if max_bucket is not None:
        marks = {min(m, int(max_bucket)) for m in marks}
    out["TPUFRAME_SERVE_BUCKETS"] = ",".join(str(b) for b in sorted(marks))
    return out
