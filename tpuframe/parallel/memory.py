"""Plan-level HBM capacity estimator: bytes from the ParallelPlan, no jax.

Answers "will this composed plan fit this mesh?" *before* anything
compiles: ``plan_memory(plan, model_template)`` walks a shape/dtype
template pytree (live arrays, ``ShapeDtypeStruct``s, or plain
``(shape, dtype)`` pairs — anything with ``.shape``/``.dtype`` works)
and prices each leaf under the plan's own sharding semantics — TP rules
via ``plan._rule_spec``, the ZeRO fsdp layering (``min_shard_elems``
gate, largest-divisible-dim placement, mirroring ``_maybe_fsdp``),
batch sharding over the data axes — producing a per-device byte budget
for params / grads / opt state / error-feedback residuals / batch /
activations, keyed by ``plan.signature()`` like every other
precompile-derivable artifact.

``suggest_fit`` is the forensics half: given a budget (or just "too
big"), it walks the escalation ladder — raise ``zero_stage``, split
into more grad-accum microbatches, offload the optimizer — re-pricing
each rung with the same math, and returns the first rung that fits.
The ``memory/oom`` event attaches its output so a crash arrives with
the remedy, not just the traceback.

Known-crude corner (stated in ``assumptions``): activations are
``activation_factor x`` the f32 bytes of one microbatch slice — a
transformer with remat will differ; the compiled-truth path
(``track.memory.record_executable_memory``) is the precise number once
an executable exists.  The agreement tests pin the estimator within
tolerance of ``memory_analysis()`` on state-dominated models, which is
the regime where capacity planning happens.

Stdlib-only: the doctor must price plans against a wedged backend, and
``track.memory`` (a knob module reachable from ``all_env_vars()``)
imports this.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import math
from typing import Any, Iterator, Mapping, Sequence

__all__ = [
    "DTYPE_BYTES",
    "PLAN_MEMORY_VERSION",
    "plan_memory",
    "suggest_fit",
]

#: schema version of the ``plan_memory`` record (rides into the
#: ``memory/oom`` event and the doctor's memory section).
PLAN_MEMORY_VERSION = "1.0"

#: fallback bytes-per-element by dtype *name* — used only when a leaf's
#: dtype has no ``.itemsize`` (e.g. a plain string in a ``(shape,
#: dtype)`` pair).  Unknown names price as 4 (f32): overestimating a
#: quantized leaf is the safe failure for a capacity check.
DTYPE_BYTES = {
    "bool": 1, "int8": 1, "uint8": 1, "float8_e4m3fn": 1, "float8_e5m2": 1,
    "int16": 2, "uint16": 2, "float16": 2, "bfloat16": 2,
    "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8, "complex64": 8,
    "complex128": 16,
}

_MB = 1024.0 * 1024.0

#: optimizer slot count when no ``opt_template`` is given: param-shaped
#: f32-class buffers per param leaf (adam keeps mu+nu; sgd a trace).
_OPT_SLOTS = {
    "adam": 2, "adamw": 2, "lamb": 2, "lion": 1,
    "sgd": 1, "momentum": 1, "adafactor": 1, "none": 0,
}


# -- template walking ---------------------------------------------------------

def _leaf_shape_dtype(x: Any) -> tuple[tuple[int, ...], Any] | None:
    """(shape, dtype) if ``x`` is a priceable leaf, else None."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return tuple(int(d) for d in x.shape), x.dtype
    if (
        isinstance(x, tuple)
        and not hasattr(x, "_fields")
        and len(x) == 2
        and isinstance(x[0], (tuple, list))
        and all(isinstance(d, int) for d in x[0])
        and isinstance(x[1], str)
    ):
        return tuple(x[0]), x[1]
    return None


def _walk(tree: Any, prefix: tuple[str, ...] = ()) -> Iterator[
    tuple[str, tuple[int, ...], Any]
]:
    """Yield ``(path, shape, dtype)`` per leaf; paths render ``a/b/c``
    like ``sharding.path_str`` so TP rules and param-suffix matching see
    the same strings the live tree would produce."""
    if tree is None:
        return
    leaf = _leaf_shape_dtype(tree)
    if leaf is not None:
        yield "/".join(prefix), leaf[0], leaf[1]
        return
    if isinstance(tree, Mapping):
        for k in tree:
            yield from _walk(tree[k], prefix + (str(k),))
    elif hasattr(tree, "_fields"):  # optax states are NamedTuples
        for name in tree._fields:
            yield from _walk(getattr(tree, name), prefix + (str(name),))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _walk(v, prefix + (str(i),))
    # other scalars (ints, floats, strings) carry no buffer


def _dtype_bytes(dtype: Any) -> int:
    itemsize = getattr(dtype, "itemsize", None)
    if itemsize:
        return int(itemsize)
    name = str(getattr(dtype, "name", dtype)).lower()
    return DTYPE_BYTES.get(name, 4)


# -- sharding math ------------------------------------------------------------

def _spec_entries(spec: Any) -> tuple:
    """A PartitionSpec (or any sequence of axis entries) as a tuple."""
    return tuple(spec) if spec is not None else ()


def _with_fsdp(plan: Any, shape: Sequence[int], entries: tuple) -> tuple:
    """Layer the plan's fsdp axis onto ``entries`` — same decision
    procedure as ``ParallelPlan._maybe_fsdp`` (size/min_shard_elems
    gates, no duplicate axis, largest divisible untaken dim), kept in
    plain tuples so a hypothetical ZeRO stage can be priced without
    constructing PartitionSpecs.  ``test_memory`` pins this against the
    plan's own ``param_spec`` output so the two can't drift."""
    size = plan.axis_size(plan.fsdp_axis)
    if size <= 1 or math.prod(shape) < plan.min_shard_elems:
        return entries
    named = {
        a for e in entries if e is not None
        for a in (e if isinstance(e, tuple) else (e,))
    }
    if plan.fsdp_axis in named:
        return entries
    ent = list(entries) + [None] * (len(shape) - len(entries))
    taken = {i for i, e in enumerate(ent) if e is not None}
    best = None
    for dim, s in enumerate(shape):
        if dim in taken or s % size or s < size:
            continue
        if best is None or s > shape[best]:
            best = dim
    if best is None:
        return entries
    ent[best] = plan.fsdp_axis
    return tuple(ent)


def _param_entries(plan: Any, path: str, shape: Sequence[int],
                   zero_stage: int) -> tuple:
    """``ParallelPlan.param_spec`` under a hypothetical ZeRO stage."""
    entries = _spec_entries(plan._rule_spec(path))
    if zero_stage == 3:
        entries = _with_fsdp(plan, shape, entries)
    return entries


def _state_entries(plan: Any, path: str, shape: Sequence[int],
                   zero_stage: int) -> tuple:
    """``ParallelPlan._state_spec`` under a hypothetical ZeRO stage."""
    entries = _spec_entries(plan._rule_spec(path))
    if len(entries) > len(shape):
        entries = ()
    if zero_stage >= 1:
        entries = _with_fsdp(plan, shape, entries)
    return entries


def _local_elems(plan: Any, shape: Sequence[int], entries: tuple) -> int:
    """Per-device element count after sharding ``shape`` by ``entries``."""
    elems = 1
    for i, size in enumerate(shape):
        e = entries[i] if i < len(entries) else None
        div = 1
        if e is not None:
            for a in (e if isinstance(e, tuple) else (e,)):
                div *= plan.axis_size(a)
        elems *= -(-size // div)  # ceil: ragged shards pay the pad
    return max(elems, 1) if shape else 1


# -- the estimator ------------------------------------------------------------

def plan_memory(
    plan: Any,
    model_template: Any,
    batch_spec: Any = None,
    *,
    opt_template: Any = None,
    comms_template: Any = None,
    optimizer: str = "adam",
    microbatches: int | None = None,
    activation_factor: float = 2.0,
    top_leaves: int = 8,
    zero_stage: int | None = None,
    offload_optimizer: bool | None = None,
) -> dict:
    """Per-device memory budget for ``plan`` — stdlib math, no compile.

    Args:
      plan: a composed ``ParallelPlan`` (only its sharding-decision
        surface is used, so any object with the same methods works).
      model_template: param pytree of shape/dtype carriers.
      batch_spec: batch pytree of shape/dtype carriers (one step's
        global batch; the leading dim shards over the data axes).
      opt_template: optimizer-state pytree (e.g. from ``eval_shape``);
        when omitted, ``optimizer`` prices param-shaped slots instead.
      comms_template: error-feedback residual pytree (``TrainState
        .comms``); omitted = no EF term.
      microbatches: grad-accum split (None = plan.pp_microbatches or 1).
        Activations scale with one microbatch slice; the super-batch
        stays argument-resident.
      zero_stage / offload_optimizer: hypothetical overrides used by
        ``suggest_fit`` — default to the plan's own values.

    Returns a dict keyed by ``plan.signature()`` with ``per_device_mb``
    component breakdown, a ``top_leaves`` attribution table, and the
    ``assumptions`` that produced it.
    """
    stage = plan.zero_stage if zero_stage is None else int(zero_stage)
    offload = (
        bool(plan.offload_optimizer) if offload_optimizer is None
        else bool(offload_optimizer)
    )
    micro = int(microbatches or getattr(plan, "pp_microbatches", None) or 1)

    leaves: list[tuple[str, float]] = []  # (component:path, bytes)
    param_paths: list[str] = []
    params_b = grads_b = 0.0
    for path, shape, dtype in _walk(model_template):
        param_paths.append(path)
        bpe = _dtype_bytes(dtype)
        local = _local_elems(plan, shape, _param_entries(plan, path, shape, stage))
        params_b += local * bpe
        # grads are param-shaped and param-sharded (stage-3 partitions
        # them; stage 1/2's transient reduce-scatter shards are priced
        # as full grads — the conservative side)
        grads_b += local * bpe
        leaves.append((f"params:{path}", local * bpe))

    opt_b = 0.0
    if opt_template is not None:
        param_set = set(param_paths)
        for path, shape, dtype in _walk(opt_template):
            # longest param-path suffix identifies param-mirroring slots
            parts = path.split("/")
            match = next(
                ("/".join(parts[s:]) for s in range(len(parts))
                 if "/".join(parts[s:]) in param_set), path,
            )
            local = _local_elems(
                plan, shape, _state_entries(plan, match, shape, stage)
            )
            opt_b += local * _dtype_bytes(dtype)
            leaves.append((f"opt_state:{match}", local * _dtype_bytes(dtype)))
    else:
        slots = _OPT_SLOTS.get(optimizer.lower(), 2)
        for path, shape, dtype in _walk(model_template):
            local = _local_elems(
                plan, shape, _state_entries(plan, path, shape, stage)
            )
            opt_b += local * _dtype_bytes(dtype) * slots
            if slots:
                leaves.append(
                    (f"opt_state:{path}", local * _dtype_bytes(dtype) * slots)
                )

    ef_b = 0.0
    for path, shape, dtype in _walk(comms_template):
        local = _local_elems(plan, shape, _state_entries(plan, path, shape, stage))
        ef_b += local * _dtype_bytes(dtype)
        leaves.append((f"ef_residual:{path}", local * _dtype_bytes(dtype)))

    batch_b = 0.0
    batch_elems_local = 0
    batch_entries = _spec_entries(plan.batch_spec())
    for path, shape, dtype in _walk(batch_spec):
        local = _local_elems(plan, shape, batch_entries)
        batch_b += local * _dtype_bytes(dtype)
        batch_elems_local += local
        leaves.append((f"batch:{path}", local * _dtype_bytes(dtype)))

    # crude-by-design: activation_factor x one f32 microbatch slice
    act_b = activation_factor * batch_elems_local * 4.0 / max(micro, 1)

    hbm_b = params_b + grads_b + ef_b + batch_b + act_b
    host_b = 0.0
    if offload:
        host_b = opt_b
    else:
        hbm_b += opt_b

    leaves.sort(key=lambda kv: -kv[1])
    top = [
        {
            "component": name.split(":", 1)[0],
            "path": name.split(":", 1)[1],
            "mb": round(b / _MB, 3),
        }
        for name, b in leaves[: max(int(top_leaves), 0)]
    ]

    world = int(getattr(getattr(plan.mesh, "devices", None), "size", 0) or 0)
    round_mb = lambda b: round(b / _MB, 3)  # noqa: E731
    return {
        "schema_version": PLAN_MEMORY_VERSION,
        "plan_signature": plan.signature(),
        "topology": {
            "world": world,
            "dp": int(plan.dp_size),
            "tp": int(plan.axis_size("model")),
            "pp": int(plan.axis_size("pipe")),
            "sp": int(plan.axis_size("seq")),
            "zero_stage": stage,
            "microbatches": micro,
            "offload_optimizer": offload,
        },
        "per_device_mb": {
            "params": round_mb(params_b),
            "grads": round_mb(grads_b),
            "opt_state": round_mb(opt_b),
            "ef_residual": round_mb(ef_b),
            "batch": round_mb(batch_b),
            "activations": round_mb(act_b),
            "total": round_mb(hbm_b),
            "host_total": round_mb(host_b),
        },
        "top_leaves": top,
        "assumptions": {
            "optimizer": optimizer if opt_template is None else "template",
            "activation_factor": activation_factor,
            "grads": "param-sharded, param dtype",
            "activations": "factor x f32 bytes of one microbatch slice",
        },
    }


# -- fit suggestion -----------------------------------------------------------

def suggest_fit(
    plan: Any,
    model_template: Any,
    batch_spec: Any = None,
    *,
    budget_mb: float | None = None,
    opt_template: Any = None,
    comms_template: Any = None,
    optimizer: str = "adam",
    microbatches: int | None = None,
    activation_factor: float = 2.0,
) -> dict:
    """Walk the escalation ladder until the estimate fits.

    Rungs, cumulative and cheap-first (each is a restartable knob move,
    no mesh rebuild): raise ``zero_stage`` to 1 then 3, split grad-accum
    into 2x/4x microbatches, finally offload the optimizer to host.
    "Fits" means total <= 0.9 x ``budget_mb`` (headroom for allocator
    fragmentation); with no budget a rung counts as a fix when it cuts
    >= 20% off the base estimate.  Returns the base estimate, every rung
    priced, and ``suggestion`` = the first fitting rung (None when even
    the top rung doesn't fit — the caller should shrink the model or
    grow the mesh).
    """
    base_micro = int(microbatches or getattr(plan, "pp_microbatches", None) or 1)
    kw = dict(
        opt_template=opt_template, comms_template=comms_template,
        optimizer=optimizer, activation_factor=activation_factor,
        top_leaves=0,
    )
    base = plan_memory(
        plan, model_template, batch_spec, microbatches=base_micro, **kw
    )
    base_total = base["per_device_mb"]["total"]

    def fits(total_mb: float) -> bool:
        if budget_mb:
            return total_mb <= 0.9 * budget_mb
        return total_mb <= 0.8 * base_total

    stage0 = int(plan.zero_stage)
    rungs: list[dict] = []
    for s in (1, 3):
        if s > stage0:
            rungs.append({"zero_stage": s})
    top_stage = max(stage0, 3)
    for mult in (2, 4):
        rungs.append({"zero_stage": top_stage, "microbatches": base_micro * mult})
    rungs.append({
        "zero_stage": top_stage, "microbatches": base_micro * 4,
        "offload_optimizer": True,
    })

    candidates = []
    suggestion = None
    for rung in rungs:
        est = plan_memory(
            plan, model_template, batch_spec,
            zero_stage=rung.get("zero_stage", stage0),
            microbatches=rung.get("microbatches", base_micro),
            offload_optimizer=rung.get("offload_optimizer"),
            **kw,
        )
        total = est["per_device_mb"]["total"]
        cand = dict(rung, total_mb=total, fits=fits(total))
        candidates.append(cand)
        if suggestion is None and cand["fits"]:
            suggestion = dict(cand, estimate=est)

    return {
        "schema_version": PLAN_MEMORY_VERSION,
        "plan_signature": plan.signature(),
        "budget_mb": budget_mb,
        "base_total_mb": base_total,
        "base_fits": fits(base_total) if budget_mb else False,
        "candidates": candidates,
        "suggestion": suggestion,
    }
