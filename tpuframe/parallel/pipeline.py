"""Pipeline parallelism: SPMD GPipe over the ``pipe`` mesh axis.

Absent from the reference (SURVEY.md §2.2 marks PP "No"), but a
first-class tpuframe axis.  TPU-native design — no per-stage processes,
no send/recv graphs: every device runs the SAME program under
``shard_map``; stage identity is ``lax.axis_index('pipe')``, stage
weights are the slice of a layer-stacked parameter pytree sharded over
``pipe``, and activations hop stage->stage with ``lax.ppermute``
(nearest-neighbour ICI transfers).  The schedule is GPipe: M microbatches
fill the S-deep pipeline over M+S-1 ticks; reverse-mode AD through the
``lax.scan`` of ticks gives the backward pipeline automatically.

Bubble fraction is (S-1)/(M+S-1) — choose ``n_microbatches >> stages``.

Schedule choice (why GPipe + ``remat_stages`` rather than 1F1B): in this
SPMD formulation the backward pipeline comes from reverse-mode through
the tick scan, whose per-tick residuals with ``remat_stages=True`` are
just each tick's stage *input* — activation memory O(M · micro · L · D)
per device, the same order as non-pipelined rematerialized training.
1F1B's win over that is only the M/S factor on the stash; buying it
requires hand-scheduling interleaved forward/backward ticks under a
custom VJP (manual pipeline backprop with an O(S) recompute buffer),
whose complexity is not justified until profiling shows the stash —
not the bubble — is the binding constraint on real configs.

Two layers of API:

- :func:`gpipe_spmd` — the schedule primitive: (stage_fn, stacked params,
  (M, micro, ...) batch) -> (M, micro, ...) outputs.
- :class:`PipelinedTransformerLM` — a drop-in LM whose blocks run under
  the schedule (same math as ``TransformerLM`` with equal weights).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpuframe.core.runtime import DATA_AXIS, FSDP_AXIS, PIPELINE_AXIS
from tpuframe.core.runtime import shard_map
from tpuframe.parallel.comms_env import PP_SCHEDULE_CHOICES


@jax.custom_vjp
def _tick_barrier(xs):
    """``optimization_barrier`` with a gradient: identity math, but XLA
    may not move work across it.  ``lax.optimization_barrier`` has no
    autodiff rule, and the barriered schedule must be trainable (it is
    the serialized baseline arm of the schedule A/B) — the cotangents
    get the same barrier, pinning the backward hops to their tick
    boundaries too."""
    return lax.optimization_barrier(xs)


def _tick_barrier_fwd(xs):
    return lax.optimization_barrier(xs), None


def _tick_barrier_bwd(_, cts):
    return (lax.optimization_barrier(cts),)


_tick_barrier.defvjp(_tick_barrier_fwd, _tick_barrier_bwd)

#: The pipeline hop/compute interleave policies :func:`gpipe_spmd`
#: understands (resolved from ``ParallelPlan.pp_schedule`` /
#: ``TPUFRAME_PP_SCHEDULE``):
#:
#: - ``interleaved`` (default) — each tick's ``ppermute`` hop is
#:   dataflow-independent of the next tick's stage compute for every
#:   stage but the hop's consumer, so the latency-hiding scheduler slots
#:   the nearest-neighbour transfer behind compute (the PR-15 group-
#:   scheduler discipline applied to the pipeline wire).
#: - ``barriered`` — an ``optimization_barrier`` ties each hop to the
#:   tick boundary: hop-then-compute, strictly serialized.  Exists as
#:   the measured A/B baseline arm (``bench_collectives.py --pipeline``),
#:   not a production schedule.
#: - ``1f1b`` — interleaved hops plus per-tick stage rematerialization
#:   forced ON: the backward stash is bounded to each tick's stage
#:   *input* (the 1F1B-style O(S) stash bound this SPMD formulation can
#:   honestly buy — see the schedule-choice note above) regardless of
#:   the ``remat_stages`` flag.
#:
#: The tuple itself lives in the stdlib-only knob registry
#: (``comms_env.PP_SCHEDULE_CHOICES``) so doctor/aggregator can read it
#: from a jax-less process; this is the same object.
PP_SCHEDULES = PP_SCHEDULE_CHOICES


def gpipe_spmd(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh,
    axis: str = PIPELINE_AXIS,
    batch_axes: tuple = (DATA_AXIS, FSDP_AXIS),
    remat_stages: bool = False,
    schedule: str = "interleaved",
) -> jax.Array:
    """Run ``stage_fn`` as an S-stage GPipe pipeline over ``mesh[axis]``.

    Args:
      stage_fn: ``(params_s, y) -> y`` — one stage's computation; every
        stage must preserve the activation shape (transformer blocks do).
      stage_params: pytree whose leaves are stacked on a leading stage dim
        of size S = ``mesh.shape[axis]`` (sharded or shardable over it).
      x: microbatched input ``(M, micro, ...)``; ``M >= S`` required.
      batch_axes: mesh axes sharding the micro dim (dim 1).
      remat_stages: ``jax.checkpoint`` each stage call — the tick scan
        then saves only each tick's stage *input* instead of every
        intermediate inside the stage, cutting pipeline activation
        memory by roughly the stage depth at ~1/3 extra FLOPs (the
        standard trade for deep stages / long sequences).
      schedule: hop/compute interleave policy — one of
        :data:`PP_SCHEDULES`.  Every schedule computes the identical
        values (``barriered`` only constrains ordering; ``1f1b`` only
        changes what the backward stashes), so the A/B across schedules
        is bit-exact on outputs.

    Returns ``(M, micro, ...)`` outputs, numerically identical to applying
    stages 0..S-1 sequentially to each microbatch.
    """
    if schedule not in PP_SCHEDULES:
        raise ValueError(
            f"schedule must be one of {PP_SCHEDULES}, got {schedule!r}"
        )
    if remat_stages or schedule == "1f1b":
        stage_fn = jax.checkpoint(stage_fn)
    n_stages = mesh.shape[axis] if axis in mesh.shape else 1
    if n_stages == 1:
        def seq(params, y):
            for s in range(jax.tree.leaves(stage_params)[0].shape[0]):
                y = stage_fn(jax.tree.map(lambda a: a[s], params), y)
            return y

        return jax.vmap(lambda mb: seq(stage_params, mb))(x)

    n_micro = x.shape[0]
    if n_micro < n_stages:
        raise ValueError(
            f"n_microbatches ({n_micro}) must be >= pipeline stages "
            f"({n_stages}); the pipeline can't even fill"
        )

    data_axes = tuple(a for a in batch_axes if a in mesh.shape and mesh.shape[a] > 1)
    x_spec = P(None, data_axes if data_axes else None, *([None] * (x.ndim - 2)))
    param_spec = jax.tree.map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stage_params
    )

    def local(params_local, x_local):
        # params_local: this stage's slice, leading dim 1
        p = jax.tree.map(lambda a: a[0], params_local)
        s = lax.axis_index(axis)
        last = n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        state = jnp.zeros_like(x_local[0])  # activation entering this stage
        outputs = jnp.zeros_like(x_local)   # filled on the last stage

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t while t < M; later ticks drain
            feed = x_local[jnp.clip(t, 0, n_micro - 1)]
            y_in = jnp.where(s == 0, feed, state)
            y_out = stage_fn(p, y_in)
            # the last stage completes microbatch t-(S-1) at tick t
            done = t - last
            updated = lax.dynamic_update_index_in_dim(
                outputs, y_out, jnp.clip(done, 0, n_micro - 1), 0
            )
            outputs = jnp.where((s == last) & (done >= 0), updated, outputs)
            # hop: stage i's output becomes stage i+1's next input
            state = lax.ppermute(y_out, axis, perm)
            if schedule == "barriered":
                # pin the hop to the tick boundary: nothing in the next
                # tick may start until the transfer lands (the serialized
                # baseline the interleaved schedule is measured against)
                state, outputs = _tick_barrier((state, outputs))
            return (state, outputs), None

        (state, outputs), _ = lax.scan(
            tick, (state, outputs), jnp.arange(n_micro + n_stages - 1)
        )
        # outputs are only genuine on the last stage; psum replicates them
        # (every other stage contributes zeros)
        return lax.psum(jnp.where(s == last, outputs, 0.0), axis)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(param_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)


def stack_stage_params(per_stage: list) -> Any:
    """[stage0_params, stage1_params, ...] -> one pytree with a leading
    stage dim (what :func:`gpipe_spmd` consumes)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage)


def pipeline_param_spec(stage_params: Any, axis: str = PIPELINE_AXIS) -> Any:
    """PartitionSpec pytree placing the stage dim on the pipe axis."""
    return jax.tree.map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stage_params
    )


@dataclasses.dataclass
class PipelinedTransformerLM:
    """Decoder LM with its blocks executed as a GPipe pipeline.

    Same math as :class:`tpuframe.models.TransformerLM` (pre-norm blocks,
    learned positions, weight-untied head) with layers grouped into
    ``mesh.shape['pipe']`` stages.  Duck-types the flax ``init``/``apply``
    contract so ``create_train_state``/``make_train_step`` work unchanged;
    the batch enters as ``(B, L)`` and is internally split into
    ``n_microbatches`` along B.

    num_layers must be divisible by the stage count; B by n_microbatches.
    """

    vocab_size: int
    num_layers: int = 4
    num_heads: int = 8
    head_dim: int = 32
    max_len: int = 2048
    mlp_ratio: int = 4
    #: microbatches per step; None resolves ``TPUFRAME_PP_MICROBATCHES``
    #: (falling back to 4) — an explicit value (or a composed plan's
    #: ``pp_microbatches`` pin threaded here) wins over the env
    n_microbatches: int | None = None
    dtype: Any = jnp.float32
    #: rematerialize each stage in the backward (see gpipe_spmd)
    remat: bool = False
    #: hop/compute interleave policy (one of ``PP_SCHEDULES``); None
    #: resolves ``TPUFRAME_PP_SCHEDULE`` (default ``interleaved``) — an
    #: explicit value (or a plan pin threaded here) wins over the env
    schedule: str | None = None

    def __post_init__(self):
        import flax.linen as nn

        d_model = self.num_heads * self.head_dim

        class EmbedHead(nn.Module):
            vocab: int
            max_len: int
            d: int
            dtype: Any

            def setup(self):
                self.embed = nn.Embed(self.vocab, self.d, dtype=self.dtype)
                self.pos_embed = nn.Embed(self.max_len, self.d, dtype=self.dtype)
                self.ln_f = nn.LayerNorm(dtype=self.dtype)
                self.lm_head = nn.Dense(
                    self.vocab, use_bias=False, dtype=self.dtype
                )

            def __call__(self, tokens):
                x = self.embed(tokens)
                return x + self.pos_embed(jnp.arange(tokens.shape[1])[None, :])

            def head(self, x):
                return self.lm_head(self.ln_f(x)).astype(jnp.float32)

        from tpuframe.models.transformer import Block

        self._embed_head = EmbedHead(
            vocab=self.vocab_size, max_len=self.max_len, d=d_model, dtype=self.dtype
        )
        # one Block module reused for every layer; per-layer weights come
        # from the stacked params (attention stays the XLA full path —
        # ring attention composes with PP via the seq axis inside blocks)
        self._block = Block(
            self.num_heads, self.head_dim, mlp_ratio=self.mlp_ratio,
            causal=True, attn_impl="full", dtype=self.dtype,
            ln_use_mesh=False,  # runs inside gpipe's shard_map already
        )

    # -- flax-like contract -------------------------------------------------
    def init(self, rngs, tokens, train: bool = False):
        params_rng = rngs["params"] if isinstance(rngs, dict) else rngs
        eh = self._embed_head.init(params_rng, tokens)["params"]
        # head params initialize lazily via init-with-method
        head_vars = self._embed_head.init(
            params_rng, jnp.zeros(
                (1, tokens.shape[1], self.num_heads * self.head_dim), self.dtype
            ),
            method=self._embed_head.head,
        )["params"]
        eh = {**eh, **head_vars}
        d_model = self.num_heads * self.head_dim
        sample = jnp.zeros((1, tokens.shape[1], d_model), self.dtype)
        keys = jax.random.split(params_rng, self.num_layers)
        per_layer = [
            self._block.init(keys[i], sample)["params"]
            for i in range(self.num_layers)
        ]
        blocks = stack_stage_params(per_layer)  # leading dim = num_layers
        return {"params": {"embed_head": eh, "blocks": blocks}}

    def apply(self, variables, tokens, train: bool = False, rngs=None):
        params = variables["params"]
        x = self._embed_head.apply({"params": params["embed_head"]}, tokens)

        from tpuframe.core.runtime import current_runtime

        mesh = current_runtime().mesh
        n_stages = mesh.shape.get(PIPELINE_AXIS, 1)
        if self.num_layers % max(n_stages, 1):
            raise ValueError(
                f"num_layers={self.num_layers} must divide into "
                f"{n_stages} pipeline stages"
            )
        layers_per_stage = self.num_layers // max(n_stages, 1)

        # regroup the layer-stacked params as (S, layers_per_stage, ...)
        blocks = jax.tree.map(
            lambda a: a.reshape((n_stages, layers_per_stage) + a.shape[1:]),
            params["blocks"],
        )

        def stage_fn(stage_p, y):
            for i in range(layers_per_stage):
                layer_p = jax.tree.map(lambda a: a[i], stage_p)
                y = self._block.apply({"params": layer_p}, y, train=train)
            return y

        from tpuframe.parallel.comms_env import pp_microbatches, pp_schedule

        b = x.shape[0]
        n_micro = (
            self.n_microbatches if self.n_microbatches is not None
            else (pp_microbatches() or 4)
        )
        m = min(n_micro, b)
        if b % m:
            raise ValueError(
                f"batch size {b} must be divisible by n_microbatches={m}"
            )
        micro = x.reshape((m, b // m) + x.shape[1:])
        out = gpipe_spmd(
            stage_fn, blocks, micro, mesh=mesh, remat_stages=self.remat,
            schedule=self.schedule or pp_schedule(),
        )
        x = out.reshape((b,) + out.shape[2:])
        return self._embed_head.apply(
            {"params": params["embed_head"]}, x, method=self._embed_head.head
        )
