"""Quantized gradient all-reduce — bandwidth-cheap DP sync for DCN.

Over ICI the implicit GSPMD all-reduce is rarely the bottleneck; across
hosts (DCN) gradient bytes are.  EQuARX (arxiv 2506.17615) shows XLA
collectives carrying int8-quantized payloads at ~4x less traffic with
negligible quality loss; this is that idea in tpuframe form:

- symmetric per-tensor int8 quantization with a *globally agreed* scale
  (a tiny ``pmax`` of each shard's abs-max precedes the big transfer, so
  every shard quantizes into the same grid — summing mismatched grids
  would be meaningless),
- the wide transfer is ``psum`` over int32-held int8 values (int32
  accumulation: up to 2^23 shards before overflow), 1/4 the f32 bytes
  where it matters,
- dequantize + divide by shard count = the mean gradient.

Exposed two ways: :func:`quantized_pmean` for shard_map code, and
``make_train_step(..., grad_compression="int8")`` which builds the whole
step under ``shard_map`` with explicit quantized sync (pure-DP plans
only — ZeRO/TP re-shard gradients and own their collectives).

Caveat the factory enforces by construction: under shard_map, BatchNorm
statistics are shard-local (torch-DDP semantics, ``bn_stats="local"``),
not the global-batch moments the implicit-GSPMD path computes.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

__all__ = ["quantized_pmean", "QUANT_BITS"]

QUANT_BITS = 8
_QMAX = 127.0  # symmetric int8 grid


def quantized_pmean(tree: Any, axis_names: Sequence[str] | str) -> Any:
    """Mean-reduce a gradient pytree across ``axis_names`` with int8
    payloads.  Call inside ``shard_map``/``pmap`` only.

    Float leaves quantize; integer/bool leaves (step counters riding in a
    pytree) psum exactly.
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    axis_names = tuple(axis_names)
    world = 1
    for ax in axis_names:
        world = world * jax.lax.psum(1, ax)

    def reduce_leaf(g):
        if not jnp.issubdtype(g.dtype, jnp.floating):
            return jax.lax.psum(g, axis_names)
        # tiny pre-collective: agree on ONE scale so grids match
        amax = jax.lax.pmax(jnp.max(jnp.abs(g)), axis_names)
        scale = jnp.maximum(amax, jnp.finfo(jnp.float32).tiny) / _QMAX
        q = jnp.clip(jnp.round(g.astype(jnp.float32) / scale), -_QMAX, _QMAX)
        # int32 accumulation: int8 payload semantics, no overflow
        total = jax.lax.psum(q.astype(jnp.int32), axis_names)
        out = (total.astype(jnp.float32) * scale / world).astype(g.dtype)
        # an inf/nan gradient must DIVERGE like the exact psum would, not
        # silently quantize to zeros and skip the update unnoticed
        return jnp.where(jnp.isfinite(amax), out, jnp.nan)

    return jax.tree.map(reduce_leaf, tree)
