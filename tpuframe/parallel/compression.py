"""Wire-level gradient collectives: bucketed, error-feedback compressed.

Over ICI the implicit GSPMD all-reduce is rarely the bottleneck; across
hosts (DCN) gradient bytes are.  EQuARX (arxiv 2506.17615) shows XLA
collectives carrying int8-quantized payloads at ~4x less traffic with
negligible quality loss; arxiv 2004.13336 derives the sharded weight
update (ZeRO-1) mechanically from the data-parallel graph.  This module
is both ideas in tpuframe form:

- **bucketed transport** — float gradient leaves are flattened in a
  canonical (path-sorted) order into a small number of fixed-size
  buckets, each with its own *globally agreed* scale (a tiny ``pmax``
  of per-bucket abs-max precedes the big transfer, so every shard
  quantizes into the same grid).  Tiny leaves stop paying
  per-collective latency; big leaves stop sharing one scale.
- **wire formats** — symmetric int8 (the wide transfer is ``psum`` over
  int32-held int8 values: up to 2^23 shards before overflow) and
  fp8-e4m3 (amax mapped to the 448 grid; summation upcast).  Optional
  stochastic rounding on the int8 grid (``TPUFRAME_COMMS_STOCHASTIC``).
- **error feedback** (EF-SGD) — each shard's quantization error
  ``v - deq(Q(v))`` is carried in ``TrainState.comms`` and re-injected
  into the next step's gradient, so the compressed trajectory tracks
  the f32 one instead of accumulating bias.  The residual is ordinary
  checkpoint state: it rides the topology manifest, and
  reshard-on-restore folds it onto a different world size.
- **in-collective transport** (``TPUFRAME_COMMS_FUSED``) — the staged
  form stages encode/decode *around* one ``psum``; the fused form puts
  the compression *inside* the collective: a reduce-scatter /
  all-gather over the data axis whose hops carry the narrow 8-bit/int16
  containers (scales still agreed once up front by the tiny ``pmax``),
  partial sums accumulated exactly on arrival (int32 for int8; f32 for
  the fp8 grid, exact through world <= 73 since e4m3 values are
  multiples of 2^-9 bounded by 448).  The transport *form* is
  backend-dispatched by measurement (:func:`_form_default`): a manual
  hop-pipelined ring on TPU, one concurrent all-to-all + local grid
  sum on GPU, the backend's own single fused all-reduce thunk on CPU.
  Because the hop sums equal the staged psum bit-for-bit and the
  dequant expression is shared, the fused wire is bit-exact against
  staged in every mode and form — it changes *when and how narrow the
  bytes move*, never the arithmetic.  Falls back to staged on
  multi-axis meshes, world 1, and fp8 past the exact-sum bound.
- **plan-derived update sharding** — for ZeRO-1/2 plans the big leaves
  take a compressed ``psum_scatter`` (reduce-scatter) over the data
  axes, the optimizer updates only the owned slice against the plan's
  sharded state, and the f32 *update* is ``all_gather``-ed back onto
  the replicated params — the 2004.13336 pipeline, generated from
  ``ParallelPlan.update_shard_specs``.

Exposed three ways: :func:`quantized_pmean` (the legacy per-tensor
form) for shard_map code, :func:`make_compressed_pmean` as a
host-callable measured collective (``comms/allreduce_s`` histogram,
``comms/bytes_on_wire`` counter), and
``make_train_step(..., grad_compression="int8"|"fp8")`` which builds
the whole step under ``shard_map`` with explicit compressed sync
(:mod:`tpuframe.train.step` owns that factory; it calls back into
:func:`sync_gradients` here).

Caveat the factories enforce by construction: under shard_map,
BatchNorm statistics are shard-local (torch-DDP semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tpuframe.parallel.comms_env import COMMS_ENV_VARS, CommsConfig  # noqa: F401
from tpuframe.parallel.sharding import path_str

__all__ = [
    "quantized_pmean",
    "QUANT_BITS",
    "CommsConfig",
    "COMMS_ENV_VARS",
    "GradLayout",
    "grad_layout",
    "init_comms_state",
    "comms_template",
    "sync_gradients",
    "wire_plan",
    "make_compressed_pmean",
    "fused_active",
    "resolve_fused",
]

QUANT_BITS = 8
_QMAX = 127.0   # symmetric int8 grid
_FP8_MAX = 448.0  # e4m3 finite max


def _widen(x):
    """Narrow integer counters riding a pytree overflow their own dtype
    under ``psum`` (an int8 counter wraps at 128 shards' worth); widen
    to int32 for the collective."""
    if x.dtype in (jnp.int8, jnp.int16, jnp.uint8, jnp.uint16, jnp.bool_):
        return x.astype(jnp.int32)
    return x


def quantized_pmean(tree: Any, axis_names: Sequence[str] | str) -> Any:
    """Mean-reduce a gradient pytree across ``axis_names`` with int8
    payloads, one scale per tensor.  Call inside ``shard_map``/``pmap``
    only.  (The bucketed/EF path used by the train-step factories is
    :func:`sync_gradients`; this per-tensor form stays for ad-hoc
    shard_map code.)

    Float leaves quantize; integer/bool leaves (step counters riding in a
    pytree) psum exactly — narrow ints are widened to int32 for the
    collective so the sum cannot overflow the payload dtype, then cast
    back.
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    axis_names = tuple(axis_names)
    world = 1
    for ax in axis_names:
        world = world * jax.lax.psum(1, ax)

    def reduce_leaf(g):
        if not jnp.issubdtype(g.dtype, jnp.floating):
            return jax.lax.psum(_widen(g), axis_names).astype(g.dtype)
        # tiny pre-collective: agree on ONE scale so grids match
        amax = jax.lax.pmax(jnp.max(jnp.abs(g)), axis_names)
        scale = jnp.maximum(amax, jnp.finfo(jnp.float32).tiny) / _QMAX
        q = jnp.clip(jnp.round(g.astype(jnp.float32) / scale), -_QMAX, _QMAX)
        # int32 accumulation: int8 payload semantics, no overflow
        total = jax.lax.psum(q.astype(jnp.int32), axis_names)
        out = (total.astype(jnp.float32) * scale / world).astype(g.dtype)
        # an inf/nan gradient must DIVERGE like the exact psum would, not
        # silently quantize to zeros and skip the update unnoticed
        return jnp.where(jnp.isfinite(amax), out, jnp.nan)

    return jax.tree.map(reduce_leaf, tree)


# -- canonical flat layout ----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GradLayout:
    """Static description of how a gradient pytree maps onto the wire.

    Built once per (tree structure, config, plan) from abstract shapes —
    everything here is host-side Python, so the hot step never recomputes
    it.  ``flat`` leaves travel in the shared fixed-size buckets;
    ``sliced`` leaves (ZeRO plans only) each take a per-leaf compressed
    reduce-scatter along ``dim`` over ``axes``; ``exact`` leaves
    (integers) psum exactly.
    """

    #: [(path, shape, dtype, offset)] in path-sorted order — bucket
    #: assignment is a pure function of the sorted paths, so two trees
    #: with identical leaves in different insertion orders flatten
    #: bit-identically
    flat: tuple
    #: [(path, shape, dtype, dim)] — plan-sharded update leaves
    sliced: tuple
    #: [path] — non-float leaves, exact psum
    exact: tuple
    flat_elems: int
    n_buckets: int
    bucket_elems: int
    axes: tuple
    world: int
    #: [(start_bucket, stop_bucket)] in FIRE order — the bucket-group
    #: schedule.  Reverse path-sorted: path order approximates forward
    #: model order, backward produces the deepest (highest-offset)
    #: leaves first, so the group covering the top bucket range fires
    #: first and its collective hides behind the rest of the backward.
    #: Empty = single shot (equivalent to one group over everything).
    group_bounds: tuple = ()

    @property
    def padded_elems(self) -> int:
        return self.n_buckets * self.bucket_elems

    @property
    def n_groups(self) -> int:
        return len(self.group_bounds) or 1


def _bucket_layout(total: int, config: CommsConfig) -> tuple[int, int]:
    """(n_buckets, bucket_elems): fixed-size buckets covering ``total``
    elements with minimal tail padding (the last bucket pads to the
    common size; sizes round up to 64 lanes)."""
    if total <= 0:
        return 0, 0
    n = max(1, -(-total // config.bucket_elems))
    be = -(-total // n)
    be = -(-be // 64) * 64
    return n, be


def _group_bounds(n_buckets: int, groups: int) -> tuple:
    """Partition ``n_buckets`` into ``groups`` contiguous near-equal
    ranges, returned in FIRE order (reverse bucket order — the
    reverse-backward leaf order).  Clamped: more groups than buckets
    degenerates to one bucket per group."""
    g = max(1, min(int(groups), n_buckets)) if n_buckets else 0
    if not g:
        return ()
    base, rem = divmod(n_buckets, g)
    bounds, start = [], 0
    for i in range(g):
        stop = start + base + (1 if i < rem else 0)
        bounds.append((start, stop))
        start = stop
    return tuple(reversed(bounds))


def grad_layout(tree: Any, config: CommsConfig, plan: Any = None,
                group_buckets: int | None = None) -> GradLayout:
    """Derive the wire layout for ``tree`` (arrays or ShapeDtypeStructs)
    under ``plan``: ZeRO stage >= 1 routes every leaf the plan's
    ``update_shard_specs`` shards through the compressed reduce-scatter
    -> sharded-update -> all-gather pipeline; everything else through
    the shared buckets.

    ``group_buckets`` partitions the buckets into that many scheduled
    groups (``GradLayout.group_bounds``, fire order = reverse-backward).
    Default None resolves the plan's pinned ``comms_groups`` first,
    then ``config.groups`` (the ``TPUFRAME_COMMS_GROUPS`` env knob)."""
    mesh = getattr(plan, "mesh", None)
    if mesh is not None:
        axes = tuple(
            a for a in plan.data_axes if mesh.shape.get(a, 1) > 1
        ) or tuple(plan.data_axes[:1])
        world = int(np.prod([mesh.shape.get(a, 1) for a in axes]))
    else:
        axes, world = (), 1
    update_specs: dict[str, tuple] = {}
    if plan is not None and getattr(plan, "zero_stage", 0) in (1, 2, 3):
        update_specs = plan.update_shard_specs(tree)
    flat, sliced, exact = [], [], []
    offset = 0
    leaves = sorted(
        (
            (path_str(p), leaf)
            for p, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
        ),
        key=lambda kv: kv[0],
    )
    for path, leaf in leaves:
        shape = tuple(int(d) for d in leaf.shape)
        dtype = jnp.dtype(leaf.dtype)
        if not jnp.issubdtype(dtype, jnp.floating):
            exact.append(path)
        elif path in update_specs:
            dim = update_specs[path][0]
            sliced.append((path, shape, str(dtype), dim))
            continue
        else:
            flat.append((path, shape, str(dtype), offset))
            offset += int(np.prod(shape)) if shape else 1
    n, be = _bucket_layout(offset, config)
    if group_buckets is None:
        group_buckets = getattr(plan, "comms_groups", None)
    if group_buckets is None:
        group_buckets = getattr(config, "groups", 1) or 1
    return GradLayout(
        flat=tuple(flat),
        sliced=tuple(sliced),
        exact=tuple(exact),
        flat_elems=offset,
        n_buckets=n,
        bucket_elems=be,
        axes=axes,
        world=world,
        group_bounds=_group_bounds(n, group_buckets),
    )


def _leaf_key(path: str) -> str:
    """comms-dict key for a per-leaf residual ('/' would collide with
    orbax's path encoding)."""
    return "leaf." + path.replace("/", ".")


def comms_template(params: Any, config: CommsConfig | None, plan: Any) -> dict:
    """The expected ``TrainState.comms`` residual structure for
    ``params`` under ``config``/``plan``: {key: global shape}.  Empty
    when compression or error feedback is off."""
    if config is None or not config.error_feedback:
        return {}
    layout = grad_layout(params, config, plan)
    out: dict[str, tuple] = {}
    if layout.flat_elems:
        out["flat"] = (layout.world, layout.n_buckets, layout.bucket_elems)
    for path, shape, _, _ in layout.sliced:
        out[_leaf_key(path)] = (layout.world,) + shape
    return out


def init_comms_state(params: Any, plan: Any, config: CommsConfig | None) -> dict:
    """Zero-initialized EF residuals, placed sharded over the plan's data
    axes (leading dim = one full-size residual per data-parallel shard,
    EF-SGD style).  The dict is carried as ``TrainState.comms``, rides
    checkpoints and the topology manifest, and is folded (world-ratio-
    scaled group sums over the leading dim, preserving the mean deferred
    correction) by reshard-on-restore when the world size changes."""
    template = comms_template(params, config, plan)
    if not template:
        return {}
    from jax.sharding import NamedSharding, PartitionSpec as P

    layout = grad_layout(params, config, plan)
    sharding = NamedSharding(plan.mesh, P(layout.axes))
    return {
        key: jax.device_put(jnp.zeros(shape, jnp.float32), sharding)
        for key, shape in template.items()
    }


# -- quantization -------------------------------------------------------------


def _agreed_amax(amax, axes):
    """Abs-max every shard agrees on (the tiny pmax pre-collective that
    precedes the wide transfer — summing mismatched grids would be
    meaningless)."""
    return jax.lax.pmax(amax, axes) if axes else amax


def _encode(v, amax, config: CommsConfig, rng, noise=None):
    """Quantize ``v`` against ``amax`` (broadcast-ready): returns
    ``(payload, deq)`` where ``payload`` is what crosses the wire
    (int32-held int8 values, or f32-held fp8 values — one byte/elem in
    payload semantics either way) and ``deq`` is the per-element factor
    that maps *summed* payloads back to gradient units.

    int8: symmetric grid, optional unbiased stochastic rounding
    (``floor(x + u)``); fp8-e4m3: amax mapped onto the 448 grid,
    round-to-nearest-even via the dtype cast (the stochastic knob does
    not apply), summation upcast.

    ``noise`` (optional, ``v``-shaped uniforms) overrides the internal
    draw — the grouped sync draws ONCE over the full bucket array and
    slices per group, so the grouped schedule stays bit-exact against
    the single-shot reference under stochastic rounding."""
    denom = jnp.maximum(amax, jnp.finfo(jnp.float32).tiny)
    if config.mode == "fp8":
        q = ((v / denom) * _FP8_MAX).astype(jnp.float8_e4m3fn)
        return q.astype(jnp.float32), denom / _FP8_MAX
    scale = denom / _QMAX
    x = v / scale
    if config.stochastic_rounding and noise is not None:
        x = jnp.floor(x + noise)
    elif rng is not None and config.stochastic_rounding:
        x = jnp.floor(x + jax.random.uniform(rng, v.shape))
    else:
        x = jnp.round(x)
    q = jnp.clip(x, -_QMAX, _QMAX)
    return q.astype(jnp.int32), scale


# -- in-collective (fused ring) transport -------------------------------------

#: beyond this world size the fp8 wire's f32 partial sums could round:
#: e4m3 grid values are integer multiples of 2^-9 bounded by 448, so a
#: W-term sum stays exactly representable in f32 while
#: W * 448 * 512 <= 2^24.  Past that the fused path falls back to
#: staged rather than drift from bit-exactness.
_FP8_EXACT_WORLD = 73

#: below this world size there is no wire to fuse — one shard is the
#: no-wire identity on the staged path too
_MIN_FUSED_WORLD = 2


def fused_active(layout: GradLayout, config: CommsConfig) -> bool:
    """Does the in-collective (fused ring) transport engage for this
    layout?  Requires the knob, a single data axis with world > 1 (the
    manual ring is written over one named axis; W=1 is the no-wire
    identity either way), and — for fp8 — a world size inside the
    exact-partial-sum bound (:data:`_FP8_EXACT_WORLD`)."""
    if not getattr(config, "fused", False):
        return False
    if len(layout.axes) != 1 or layout.world < _MIN_FUSED_WORLD:
        return False
    if config.mode == "fp8" and layout.world > _FP8_EXACT_WORLD:
        return False
    return True


def resolve_fused(plan: Any, config: CommsConfig | None) -> CommsConfig | None:
    """Fold a pinned ``ParallelPlan.comms_fused`` into ``config`` — the
    plan wins over the env-resolved knob, same plan-first rule as
    ``comms_groups`` / ``comms_schedule``."""
    pinned = getattr(plan, "comms_fused", None)
    if config is None or pinned is None:
        return config
    return dataclasses.replace(config, fused=bool(pinned))


def _form_default() -> str:
    """Which fused transport form to build for this backend:

    - ``"ring"`` (TPU): hop-pipelined manual reduce-scatter/all-gather —
      per-hop sends the latency-hiding scheduler overlaps on real
      topology, hops carry narrowed (int16 partial) containers.
    - ``"concurrent"`` (GPU): one all-to-all of the true one-byte
      containers + a LOCAL grid sum the compiler schedules as compute +
      one all-gather — hop structure without sequential dispatch.
    - ``"single"`` (CPU and anything else without an async collective
      scheduler): the encoded payload rides ONE fused all-reduce thunk.
      Measured on the XLA:CPU thunk runtime, every manual decomposition
      only adds full-device rendezvous wall (exposed-comms ratios vs the
      single thunk: ring 1.69x, concurrent 1.26x, concurrent with
      narrowed containers 2.5x — each extra collective is a barrier and
      each cast an extra memory pass there), so the in-collective wire
      degenerates to the staged transport, by measurement not fiat."""
    backend = jax.default_backend()
    if backend == "tpu":
        return "ring"
    if backend == "gpu":
        return "concurrent"
    return "single"


#: int8-mode totals (and ring partial sums) fit int16 while
#: W * 128 <= 2**15: legit contributions are clipped to +-127, and even
#: a NaN-poisoned bucket's int8-wrapped garbage stays within +-128
_INT16_TOTAL_WORLD = 255


def _narrow_wire(buf):
    """The true wire container for *pre-accumulation* payloads.
    :func:`_encode` holds int8-grid values in int32 and e4m3-grid values
    in f32 — the accumulator dtypes the staged psum needs in flight —
    but a hop that carries UN-summed contributions can ship the one-byte
    container the payload semantics promise.  Returns ``(sent, widen)``;
    exact by the encode contract (ints clipped to the int8 grid, floats
    produced by an e4m3 cast — a NaN-poisoned bucket wraps arbitrarily
    but is masked to NaN by the non-finite amax flag on either path)."""
    if buf.dtype == jnp.int32:
        return buf.astype(jnp.int8), lambda g: g.astype(jnp.int32)
    if buf.dtype == jnp.float32:
        return (buf.astype(jnp.float8_e4m3fn),
                lambda g: g.astype(jnp.float32))
    return buf, (lambda g: g)


def _narrow_total(buf, W):
    """Container for summed int8-mode payloads: int16 while the wrap
    bound holds (:data:`_INT16_TOTAL_WORLD`).  fp8 totals leave the
    e4m3 grid, so f32 stays f32."""
    if buf.dtype == jnp.int32 and W <= _INT16_TOTAL_WORLD:
        return buf.astype(jnp.int16), lambda g: g.astype(jnp.int32)
    return buf, (lambda g: g)


def _canonical_zero(buf):
    """Canonicalize the zero sign to psum's: XLA's all-reduce folds
    contributions into a +0.0 identity accumulator, so a chunk whose
    every contribution is -0.0 (fp8 underflow payloads) sums to +0.0
    there, while a chained/treewise sum can keep -0.0.  (An explicit
    +0.0 seed would express this, but the algebraic simplifier folds
    x + 0.0 away; the select survives.)  No-op for integer payloads
    and for NaN (NaN == 0 is False, so NaN passes through)."""
    return jnp.where(buf == 0, jnp.zeros((), buf.dtype), buf)


def _ring_reduce_scatter(own, axis):
    """Exact ring reduce-scatter over named ``axis``: ``own`` is this
    shard's (W, ...) per-chunk contribution; returns this shard's fully
    reduced chunk, with ring position *i* ending up owning chunk *i* —
    the same tiled assignment ``psum_scatter`` uses.  W-1 hops, each
    carrying one chunk of encoded payload in the narrowed partial-sum
    container (:func:`_narrow_total`); arrivals widen and accumulate in
    the payload's accumulator dtype (int32 for int8, f32 for the fp8
    grid), so the partial sums equal the staged psum's exactly."""
    W = own.shape[0]
    if W == 1:
        return own[0]
    perm = [(i, (i + 1) % W) for i in range(W)]
    my = jax.lax.axis_index(axis)
    buf = jnp.take(own, (my - 1) % W, axis=0)
    for hop in range(W - 1):
        sent, widen = _narrow_total(buf, W)  # partials fit the same bound
        buf = widen(jax.lax.ppermute(sent, axis, perm))
        buf = buf + jnp.take(own, (my - 2 - hop) % W, axis=0)
    return _canonical_zero(buf)


def _a2a_reduce_scatter(own, axis):
    """Exact concurrent reduce-scatter: one all-to-all delivers every
    peer's contribution to my chunk (all "hops" fire at once), then a
    LOCAL sum over the peer dim reduces them — encoded bytes on the
    wire, and the reduction itself is compute the compiler can overlap
    instead of wall inside an opaque all-reduce thunk.  Same chunk
    assignment and exact grid arithmetic as the ring form."""
    W = own.shape[0]
    if W == 1:
        return own[0]
    sent, widen = _narrow_wire(own)
    got = jax.lax.all_to_all(sent, axis, split_axis=0, concat_axis=0)
    return _canonical_zero(jnp.sum(widen(got), axis=0))


def _reduce_scatter_chunks(own, axis, form: str | None = None):
    """The fused transport's reduce-scatter over the (W, ...) per-chunk
    contributions, form resolved per backend (``form`` overrides —
    tests pin every form bit-exact on CPU).  The single-thunk form IS
    the backend collective: ``psum_scatter`` over the peer dim — the
    same tiled assignment and fold-into-identity accumulation as the
    staged path."""
    if form is None:
        form = _form_default()
    if form == "ring":
        return _ring_reduce_scatter(own, axis)
    if form == "concurrent":
        return _a2a_reduce_scatter(own, axis)
    return jax.lax.psum_scatter(own, axis, scatter_dimension=0, tiled=False)


def _ring_all_gather(chunk, axis, W):
    """Exact ring all-gather: ``chunk`` owned by ring position *i* at
    index *i* circulates W-1 hops; every shard returns the identical
    stacked (W, ...) array.  Pure data movement, bit-exact by
    construction — the hops carry the already-reduced encoded totals."""
    if W == 1:
        return chunk[None]
    perm = [(i, (i + 1) % W) for i in range(W)]
    my = jax.lax.axis_index(axis)
    sent, widen = _narrow_total(chunk, W)
    out = jnp.zeros((W,) + sent.shape, sent.dtype)
    out = jax.lax.dynamic_update_index_in_dim(out, sent, my, 0)
    buf = sent
    for hop in range(W - 1):
        buf = jax.lax.ppermute(buf, axis, perm)
        out = jax.lax.dynamic_update_index_in_dim(
            out, buf, (my - 1 - hop) % W, 0
        )
    return widen(out)


def _all_gather_chunks(chunk, axis, W, form: str | None = None):
    """The fused transport's all-gather: the ring form hop-pipelines
    narrowed totals, the concurrent form is one native all-gather of
    the narrowed container, the single-thunk form one native all-gather
    as-is (casts are extra memory passes on a host backend).  Pure data
    movement every way — peer-index stacking, the same (W, ...)
    layout."""
    if form is None:
        form = _form_default()
    if form == "ring":
        return _ring_all_gather(chunk, axis, W)
    if form == "concurrent":
        sent, widen = _narrow_total(chunk, W)
        return widen(jax.lax.all_gather(sent, axis, axis=0, tiled=False))
    return jax.lax.all_gather(chunk, axis, axis=0, tiled=False)


def _fused_allreduce(q, axis, W, form: str | None = None):
    """In-collective all-reduce of an encoded payload: reduce-scatter of
    the 8-bit-grid values then an all-gather of the reduced chunks, with
    the manual forms shipping the NARROW container the payload semantics
    promise (:func:`_narrow_wire` / :func:`_narrow_total`) — one
    byte/elem for un-summed contributions, int16 for int8-mode totals —
    where the staged ``psum`` must carry its int32/f32 accumulator in
    flight.  Grid partial sums are exact, so the result is bit-identical
    to ``jax.lax.psum(q, axis)`` — the staged transport — in every form
    (:func:`_form_default`): the TPU ring carries one chunk per hop the
    scheduler overlaps, the concurrent form fires the hops as one
    all-to-all and hands the reduction to the compiler as schedulable
    compute, and the single-thunk form rides the backend's own fused
    reduce+transport collective."""
    if W == 1:
        return q
    if form is None:
        form = _form_default()
    if form == "single":
        return jax.lax.psum(q, (axis,))
    shape = q.shape
    size = int(np.prod(shape)) if shape else 1
    chunk = -(-size // W)
    flat = q.reshape(-1)
    pad = W * chunk - size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), q.dtype)])
    own = flat.reshape(W, chunk)
    mine = _reduce_scatter_chunks(own, axis, form)
    full = _all_gather_chunks(mine, axis, W, form).reshape(-1)
    if pad:
        full = full[:size]
    return full.reshape(shape)


# -- the in-shard_map sync ----------------------------------------------------


def sync_gradients(
    grads: Any,
    comms: Mapping[str, Any],
    layout: GradLayout,
    config: CommsConfig,
    rng=None,
):
    """Inside shard_map: compress + reduce this shard's gradient.

    The wire fires as ``layout.group_bounds`` prescribes: one collective
    per bucket group, emitted in reverse-backward order, each group's
    psum dataflow-independent of the later groups' quantization — the
    schedulable form of the single-shot sync, bit-exact against it.

    Returns ``(synced, new_comms)`` where ``synced`` matches the
    ``grads`` structure — full mean gradients for bucketed/exact leaves,
    the *owned slice* of the mean gradient for plan-sharded leaves (the
    compressed reduce-scatter half of the ZeRO pipeline; the caller runs
    the sharded update and gathers the f32 update back).

    ``comms`` carries each shard's EF residual view ``(1, ...)`` (the
    leading world dim is sharded away by the step's in_specs); empty
    dict = error feedback off.  Non-finite gradients propagate as NaN —
    divergence must look like divergence, and the poisoned residual is
    NOT committed (the bucket's residual resets to its previous value
    via the caller's health skip, or to zero here when EF is off for
    that bucket this step).
    """
    from tpuframe.ops.quant_wire import (
        bucket_abs_max, quant_decode, quant_encode,
    )

    axes, world = layout.axes, layout.world
    fused = fused_active(layout, config)
    ef = config.error_feedback and bool(comms)
    leaves = {
        path_str(p): leaf
        for p, leaf in jax.tree_util.tree_flatten_with_path(grads)[0]
    }
    out: dict[str, Any] = {}
    new_comms: dict[str, Any] = {}

    def subrng(tag: int):
        return None if rng is None else jax.random.fold_in(rng, tag)

    # ---- shared fixed-size buckets (per-bucket scales), fired as the
    # layout's bucket-group schedule: one psum per group, emitted in
    # reverse-backward order so group i's collective is dataflow-
    # independent of group i+1's quantization (XLA can put it in flight
    # while the later groups' gradients/encodes are still producing).
    # Every per-bucket quantity — pmax'd amax, quantize, psum,
    # non-finite propagation, EF residual — is elementwise over the
    # bucket dimension, so the partition changes the schedule, never
    # the arithmetic: grouped output is bit-exact vs the single shot.
    if layout.flat_elems:
        parts = [
            jnp.ravel(leaves[path].astype(jnp.float32))
            for path, _, _, _ in layout.flat
        ]
        flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        pad = layout.padded_elems - layout.flat_elems
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        v = flat.reshape(layout.n_buckets, layout.bucket_elems)
        if ef:
            v = v + comms["flat"][0]
        # ONE full-shape noise draw, sliced per group: the same uniforms
        # the single-shot _encode would draw from the same key
        noise = None
        if (rng is not None and config.stochastic_rounding
                and config.mode != "fp8"):
            noise = jax.random.uniform(subrng(0), v.shape)
        bounds = layout.group_bounds or ((0, layout.n_buckets),)
        # software-pipelined emission, group chains still independent:
        # each group's ops consume only its own bucket slice, so the
        # dataflow — and therefore what a latency-hiding scheduler may
        # put in flight while later groups' gradients are still
        # producing — is identical to a chain-at-a-time emission.  The
        # EMISSION order is tuned for backends that execute roughly in
        # program order (XLA:CPU): scales and encodes are staged up
        # front, the psums are emitted near-adjacently so the wire ops
        # pipeline against each other, and each group's off-wire math
        # (EF residual, which never depends on the psum, and the
        # PREVIOUS group's dequant) is slotted between psum launches so
        # every rendezvous window has compute to hide behind.
        amax_g: dict[tuple, Any] = {}
        enc_g: dict[tuple, Any] = {}
        for s, e in bounds:  # fire order: reverse-backward
            amax_g[(s, e)] = _agreed_amax(bucket_abs_max(v[s:e]), axes)
        for s, e in bounds:
            sr = config.stochastic_rounding and config.mode != "fp8"
            enc_g[(s, e)] = quant_encode(
                v[s:e], amax_g[(s, e)], config.mode,
                noise=noise[s:e] if (sr and noise is not None) else None,
            )
        total_g: dict[tuple, Any] = {}
        mean_seg: dict[tuple, Any] = {}
        resid_seg: dict[tuple, Any] = {}

        def _finish(se):
            # dequant + mean + per-bucket non-finite propagation
            # (matches exact psum), fused into one pass by quant_decode
            mean_seg[se] = quant_decode(
                total_g[se], amax_g[se], config.mode, world
            )

        for i, (s, e) in enumerate(bounds):
            q, deq = enc_g[(s, e)]
            # staged: one monolithic psum of the encoded payload.
            # fused: the payload rides a manual ring — W-1 reduce-
            # scatter hops + W-1 all-gather hops, each moving one
            # compressed chunk with exact on-arrival accumulation —
            # bit-identical totals, hop-granular overlap.
            total_g[(s, e)] = (
                _fused_allreduce(q, axes[0], world) if fused
                else jax.lax.psum(q, axes)
            )
            if ef:
                resid = v[s:e] - q.astype(jnp.float32) * deq
                resid_seg[(s, e)] = jnp.where(
                    jnp.isfinite(amax_g[(s, e)]), resid, 0.0
                )
            if i:
                _finish(bounds[i - 1])
        _finish(bounds[-1])
        order = sorted(bounds)  # reassemble in canonical bucket order
        mean = (
            jnp.concatenate([mean_seg[b] for b in order])
            if len(order) > 1 else mean_seg[order[0]]
        )
        if ef:
            new_comms["flat"] = (
                jnp.concatenate([resid_seg[b] for b in order])
                if len(order) > 1 else resid_seg[order[0]]
            )[None]
        mean = jnp.ravel(mean)
        for path, shape, dtype, offset in layout.flat:
            size = int(np.prod(shape)) if shape else 1
            out[path] = mean[offset:offset + size].reshape(shape).astype(dtype)

    # ---- plan-sharded leaves: compressed reduce-scatter ----
    if layout.sliced:
        idx = jnp.int32(0)
        for ax in axes:
            idx = idx * jax.lax.psum(1, ax) + jax.lax.axis_index(ax)
        # under a grouped schedule the per-leaf reduce-scatters emit in
        # reverse path order too (deepest leaves' grads exist first);
        # tag keeps the NATURAL index so the stochastic-rounding streams
        # are bit-identical to the single-shot emission order
        sliced_items = list(enumerate(layout.sliced))
        if layout.group_bounds[1:]:  # grouped schedule (static tuple)
            sliced_items.reverse()
        for tag, (path, shape, dtype, dim) in sliced_items:
            g = leaves[path].astype(jnp.float32)
            if ef:
                g = g + comms[_leaf_key(path)][0]
            chunk = shape[dim] // world
            # one scale per scatter chunk — the ZeRO equivalent of
            # per-bucket scales (every shard pmax-agrees per chunk)
            chunked = jnp.stack(jnp.split(g, world, axis=dim))
            amax_c = _agreed_amax(
                jnp.max(jnp.abs(chunked).reshape(world, -1), axis=1), axes
            )  # (world,)
            bshape = [1] * g.ndim
            bshape[dim] = shape[dim]
            amax_b = jnp.repeat(amax_c, chunk).reshape(bshape)
            q, deq_b = _encode(g, amax_b, config, subrng(tag + 1))
            # fused: in-collective reduce-scatter of the encoded chunks
            # (position i ends owning chunk i — psum_scatter's tiled
            # assignment), compressed bytes on the wire, exact
            # accumulation; staged: one psum_scatter.
            if fused:
                mine = _reduce_scatter_chunks(
                    jnp.stack(jnp.split(q, world, axis=dim)), axes[0]
                )
            else:
                mine = jax.lax.psum_scatter(
                    q, axes, scatter_dimension=dim, tiled=True
                )
            # my chunk's dequant factor (scalar — one scale per chunk,
            # same denom _encode used for that chunk on every shard)
            grid = _FP8_MAX if config.mode == "fp8" else _QMAX
            my_deq = jnp.take(
                jnp.maximum(amax_c, jnp.finfo(jnp.float32).tiny), idx
            ) / grid
            mean = mine.astype(jnp.float32) * my_deq / world
            finite = jnp.all(jnp.isfinite(amax_c))
            mean = jnp.where(finite, mean, jnp.nan)
            out[path] = mean.astype(dtype)
            if ef:
                resid = g - q.astype(jnp.float32) * deq_b
                new_comms[_leaf_key(path)] = jnp.where(finite, resid, 0.0)[None]

    # ---- exact integer leaves ----
    for path in layout.exact:
        g = leaves[path]
        out[path] = jax.lax.psum(_widen(g), axes).astype(g.dtype)

    synced = jax.tree_util.tree_map_with_path(
        lambda p, _: out[path_str(p)], grads
    )
    if ef:
        # structure must stay identical to the input comms dict
        new_comms = {k: new_comms.get(k, comms[k]) for k in comms}
    else:
        new_comms = dict(comms)
    return synced, new_comms


# -- static wire accounting ---------------------------------------------------


def wire_plan(layout: GradLayout, config: CommsConfig,
              exact_bytes: int = 0) -> dict:
    """Per-step bytes each participant puts on the wire, ring model:
    ``psum`` (all-reduce) moves ``2*(W-1)/W`` payloads, ``psum_scatter``
    / ``all_gather`` move ``(W-1)/W`` each.  The f32 column is the same
    reduction uncompressed — the committed ``reduction_x`` is the
    headline EQuARX-style saving.  Static per step signature, so the
    Trainer can meter ``comms/bytes_on_wire`` with one host add."""
    W = layout.world
    if W <= 1:
        return {
            "mode": config.mode, "world": W, "bytes_per_step": 0,
            "f32_bytes_per_step": 0, "reduction_x": None,
            "n_buckets": layout.n_buckets,
            "bucket_elems": layout.bucket_elems,
            "flat_elems": layout.flat_elems,
            "sliced_leaves": len(layout.sliced),
            "overlap_groups": layout.n_groups,
            "fused": False,
            "fused_hops": 0,
            "groups": [],
        }
    ar = 2.0 * (W - 1) / W   # all-reduce legs
    rs = 1.0 * (W - 1) / W   # reduce-scatter / all-gather leg
    bpe = config.wire_bytes_per_elem
    comp = 0.0
    f32 = 0.0
    # per-group breakdown (fire order).  Scales stay per-BUCKET under
    # grouping, so group payload+scale bytes sum to exactly the
    # single-shot flat contribution — the total below is computed from
    # the same layout-level quantities grouping cannot change, which is
    # what keeps comms/bytes_on_wire metering exact under any schedule.
    groups = []
    if layout.flat_elems:
        comp += ar * (layout.padded_elems * bpe + layout.n_buckets * 4)
        f32 += ar * layout.flat_elems * 4
        for s, e in (layout.group_bounds or ((0, layout.n_buckets),)):
            nb = e - s
            groups.append({
                "buckets": nb,
                "payload_bytes": int(round(ar * nb * layout.bucket_elems * bpe)),
                "scale_bytes": int(round(ar * nb * 4)),
            })
    for _, shape, _, _ in layout.sliced:
        size = int(np.prod(shape))
        # compressed RS of quantized grads + per-chunk scales, then f32
        # all-gather of the sharded optimizer's UPDATE slices
        comp += rs * size * bpe + ar * W * 4 + rs * size * 4
        f32 += ar * size * 4
    comp += ar * exact_bytes
    f32 += ar * exact_bytes
    return {
        "mode": config.mode,
        "world": W,
        "bytes_per_step": int(round(comp)),
        "f32_bytes_per_step": int(round(f32)),
        "reduction_x": round(f32 / comp, 3) if comp else None,
        "n_buckets": layout.n_buckets,
        "bucket_elems": layout.bucket_elems,
        "flat_elems": layout.flat_elems,
        "sliced_leaves": len(layout.sliced),
        "overlap_groups": layout.n_groups,
        # in-collective transport: bytes_per_step is INVARIANT under
        # fusion — the ring all-reduce moves the same 2*(W-1)/W payload
        # volume per participant the staged psum's ring does (this is
        # the same accounting rule that keeps bytes invariant under
        # grouping).  What fusion changes is hop granularity: 2*(W-1)
        # compressed chunk hops per group the scheduler can overlap,
        # recorded here as detail for the span/bench, never as a bytes
        # delta.
        "fused": fused_active(layout, config),
        "fused_hops": 2 * (W - 1) if fused_active(layout, config) else 0,
        "groups": groups,
    }


# -- host-callable measured collective ---------------------------------------


def make_compressed_pmean(plan, config: CommsConfig | str = "int8"):
    """A measured, host-callable bucketed compressed mean over the
    plan's data axes: ``fn(tree, residual={}) -> (mean_tree,
    new_residual)``.  Each call runs under a ``comms/allreduce`` span,
    observes ``comms/allreduce_s``, and meters ``comms/bytes_on_wire``
    — the benchmark/standalone face of the same primitive the
    compressed train step fuses.
    """
    from jax.sharding import PartitionSpec as P

    from tpuframe.core.runtime import shard_map
    from tpuframe.track.telemetry import get_telemetry

    if not isinstance(config, CommsConfig):
        config = CommsConfig(mode=config)
    config = resolve_fused(plan, config)
    cache: dict[tuple, Any] = {}

    def call(tree: Any, residual: Mapping[str, Any] | None = None):
        import time

        residual = dict(residual or {})
        layout = grad_layout(tree, config, plan)
        # the full layout identity: a same-structure tree with different
        # dtypes (or a different sliced/exact split) must build its own
        # program, not reuse a stale GradLayout's dtype column
        key = (
            jax.tree_util.tree_structure(tree),
            layout.flat,
            layout.sliced,
            layout.exact,
            layout.group_bounds,
            bool(residual),
        )
        if key not in cache:
            spec = P(layout.axes)
            comms_spec = {k: spec for k in residual}

            def run(t, r):
                return sync_gradients(t, r, layout, config)

            cache[key] = (
                jax.jit(
                    shard_map(
                        run,
                        mesh=plan.mesh,
                        in_specs=(P(), comms_spec),
                        out_specs=(P(), comms_spec),
                        check_vma=False,
                    )
                ),
                wire_plan(layout, config),
            )
        fn, plan_bytes = cache[key]
        tele = get_telemetry()
        t0 = time.perf_counter()
        with tele.span("comms/allreduce", mode=config.mode,
                       bytes=plan_bytes["bytes_per_step"]):
            if plan_bytes.get("fused"):
                # the fused transport's own span: one per call (the
                # hops live inside one jitted program — host code can't
                # bracket them individually), hop count as the attr
                with tele.span("comms/fused_hop",
                               hops=plan_bytes["fused_hops"],
                               world=plan_bytes["world"],
                               mode=config.mode):
                    out, new_resid = fn(tree, residual)
                    jax.block_until_ready(out)
            else:
                out, new_resid = fn(tree, residual)
                jax.block_until_ready(out)
        tele.registry.histogram("comms/allreduce_s").observe(
            time.perf_counter() - t0
        )
        tele.registry.counter("comms/bytes_on_wire").inc(
            plan_bytes["bytes_per_step"]
        )
        return out, new_resid

    return call
