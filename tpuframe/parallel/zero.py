"""ZeRO-style presets: DeepSpeed's stage ladder as sharding plans.

The reference authors four ZeRO configs but never engages them
(`/root/reference/02_deepspeed/deepspeed_config.py:52-105`; the distributor
call comments the config out at `/root/reference/02_deepspeed/
01_cifar_deepspeed_resnet.py:108`).  Here the ladder is real and declarative:
each stage is just a :class:`~tpuframe.parallel.sharding.ParallelPlan` with a
different sharding assignment, and the buckets/overlap/prefetch knobs from the
DeepSpeed dicts disappear — XLA schedules and overlaps its own collectives.

Stage-3's CPU offload (`deepspeed_config.py:87-105`, ``offload_optimizer/
offload_param -> cpu``) maps to JAX memory kinds: optimizer state pinned in
host memory (``pinned_host``) and streamed to HBM inside the update.  That is
only supported on real TPU backends, so it is a flag the Trainer applies when
the platform allows.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax
from jax.sharding import Mesh

from tpuframe.parallel.sharding import ParallelPlan, Rule, host_memory_available


@dataclasses.dataclass(frozen=True)
class ZeroConfig:
    """Config-file-friendly description of a ZeRO stage (what
    ``deepspeed_config.deepspeed_zero_N`` described, minus the dead knobs)."""

    stage: int = 0
    offload_optimizer: bool = False  # stage-3 'offload_optimizer.device: cpu'
    min_shard_elems: int = 2**14

    @classmethod
    def from_dict(cls, cfg: Mapping[str, Any]) -> "ZeroConfig":
        """Accept a DeepSpeed-shaped dict: ``{"zero_optimization": {"stage": N,
        "offload_optimizer": {"device": "cpu"}}}`` or the flat form."""
        zo = cfg.get("zero_optimization", cfg)
        offload = zo.get("offload_optimizer")
        if isinstance(offload, Mapping):
            offload = offload.get("device") not in (None, "none")
        return cls(
            stage=int(zo.get("stage", 0)),
            offload_optimizer=bool(offload),
            min_shard_elems=int(zo.get("min_shard_elems", 2**14)),
        )

    def plan(self, mesh: Mesh, rules: Sequence[Rule] = ()) -> ParallelPlan:
        return ParallelPlan(
            mesh=mesh,
            zero_stage=self.stage,
            rules=tuple(rules),
            min_shard_elems=self.min_shard_elems,
            offload_optimizer=self.offload_optimizer,
        )


def zero_0(mesh: Mesh, **kw) -> ParallelPlan:
    """Pure DP (DDP semantics: replicate everything, all-reduce grads)."""
    return ZeroConfig(stage=0).plan(mesh, **kw)


def zero_1(mesh: Mesh, **kw) -> ParallelPlan:
    """Optimizer-state sharding (`deepspeed_config.py:53-63`)."""
    return ZeroConfig(stage=1).plan(mesh, **kw)


def zero_2(mesh: Mesh, **kw) -> ParallelPlan:
    """Grad+optimizer sharding (`deepspeed_config.py:66-71`); identical plan to
    stage 1 under XLA — gradient lifetime is the compiler's to schedule."""
    return ZeroConfig(stage=2).plan(mesh, **kw)


def zero_3(mesh: Mesh, **kw) -> ParallelPlan:
    """Fully-sharded params, all-gather on use (`deepspeed_config.py:74-84`)."""
    return ZeroConfig(stage=3).plan(mesh, **kw)


def zero_3_offload(mesh: Mesh, **kw) -> ParallelPlan:
    """Stage 3 + optimizer state in pinned host memory
    (`deepspeed_config.py:87-105`).  EXPERIMENTAL: downgrades to plain
    stage 3 — with a loud ``UserWarning`` — on backends without a usable
    host memory space; validate with ``benchmarks/check_offload_tpu.py``
    (committed JSON in ``benchmarks/results/``) before relying on the
    HBM savings on a given backend."""
    return ZeroConfig(stage=3, offload_optimizer=True).plan(mesh, **kw)


def host_offload_sharding(sharding: jax.sharding.Sharding) -> jax.sharding.Sharding:
    """The same sharding, placed in pinned host memory (stage-3 offload).

    Raises if the backend has no host memory space (CPU simulation).
    """
    return sharding.with_memory_kind("pinned_host")


def supports_host_offload() -> bool:
    return host_memory_available()
