"""Mixed-precision policy: bf16 compute on the MXU, fp32 master params.

The reference engages bf16 only through DeepSpeed config
(`/root/reference/02_deepspeed/deepspeed_config.py:24-26`, ``bf16.enabled``).
On TPU, bf16 is the native MXU input format, so the policy is a first-class
object here: params/optimizer state stay float32 (master weights), activations
and matmul inputs are cast to bfloat16, and loss/reductions come back in
float32.  This is the same split DeepSpeed's bf16 engine performs, expressed
as pure dtype casts that XLA fuses into the surrounding ops for free.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def _cast_floating(tree: Any, dtype: jnp.dtype) -> Any:
    """Cast floating-point array leaves (jax *or* numpy — host batches from
    tpuframe.data arrive as numpy) to ``dtype``; leave ints/bools alone."""

    def cast(x):
        leaf_dtype = getattr(x, "dtype", None)
        if leaf_dtype is not None and jnp.issubdtype(leaf_dtype, np.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype assignment for the three tensor populations in a train step.

    - ``param_dtype``: master copies held between steps (and in checkpoints).
    - ``compute_dtype``: what the forward/backward runs in (MXU wants bf16).
    - ``output_dtype``: loss and metric accumulations.
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32

    def cast_params_for_compute(self, params: Any) -> Any:
        return _cast_floating(params, self.compute_dtype)

    def cast_batch(self, batch: Any) -> Any:
        return _cast_floating(batch, self.compute_dtype)

    def cast_outputs(self, outputs: Any) -> Any:
        return _cast_floating(outputs, self.output_dtype)

    def cast_to_param(self, tree: Any) -> Any:
        return _cast_floating(tree, self.param_dtype)


def align_model_dtype(model: Any, policy: Policy) -> Any:
    """Clone a flax model so its ``dtype`` knob matches the policy's compute
    dtype.

    The Policy casts params and batches at the step boundary, but modules
    with an explicit ``dtype`` (tpuframe models default to float32) silently
    up-cast right back inside every layer — a bf16 policy over an f32 model
    runs the whole graph in f32.  Measured on a v5e chip this is the
    difference between ~1.4k and ~2.3k ResNet50 train images/sec: the step
    is HBM-bandwidth-bound and f32 activations double the traffic.  The
    Trainer applies this automatically; low-level step users should call it
    (or set ``dtype=`` at model construction) themselves.

    Models without a ``dtype``/``clone`` surface pass through untouched.
    """
    dtype = getattr(policy, "compute_dtype", None)
    if (
        dtype is not None
        and hasattr(model, "dtype")
        and hasattr(model, "clone")
        and getattr(model, "dtype", None) != dtype
    ):
        try:
            return model.clone(dtype=dtype)
        except TypeError:  # not a flax Module / dtype not a field
            return model
    return model


def full_precision() -> Policy:
    return Policy()


def bf16_compute() -> Policy:
    """The standard TPU policy: fp32 master params, bf16 compute, fp32 loss."""
    return Policy(compute_dtype=jnp.bfloat16)


def pure_bf16() -> Policy:
    """Everything bf16 (max HBM savings; use only with loss-scale-free optimizers)."""
    return Policy(
        param_dtype=jnp.bfloat16,
        compute_dtype=jnp.bfloat16,
        output_dtype=jnp.float32,
    )


_NAMED = {
    "fp32": full_precision,
    "float32": full_precision,
    "bf16": bf16_compute,
    "bfloat16": bf16_compute,
    "pure_bf16": pure_bf16,
}


def get_policy(name: str | Policy) -> Policy:
    """Resolve a policy by name (config-file friendly)."""
    if isinstance(name, Policy):
        return name
    try:
        return _NAMED[name]()
    except KeyError:
        raise ValueError(
            f"unknown precision policy {name!r}; known: {sorted(_NAMED)}"
        ) from None
