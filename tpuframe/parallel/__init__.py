"""Parallelism: sharding plans (DP / ZeRO / TP), precision policies,
wire-level compressed collectives.

TPU-native re-expression of the reference's parallelism inventory
(SURVEY.md §2.2): DDP replication, DeepSpeed ZeRO stages, and tensor-parallel
hooks, all as declarative shardings over the core mesh — XLA inserts the
collectives the reference performed imperatively through NCCL.  The
``compression`` module adds explicit bucketed int8/fp8 gradient
collectives with error feedback where DCN bandwidth is the bill.

Exports are lazy (PEP 562, like ``tpuframe.serve``): the comms knob
registry (``comms_env.COMMS_ENV_VARS``) must stay importable without
jax — ``launch.remote.all_env_vars()`` and the doctor read it from
wedged-backend or jax-less processes.
"""

# tpuframe-lint: stdlib-only

_LAZY = {
    "Policy": "tpuframe.parallel.precision",
    "align_model_dtype": "tpuframe.parallel.precision",
    "bf16_compute": "tpuframe.parallel.precision",
    "full_precision": "tpuframe.parallel.precision",
    "get_policy": "tpuframe.parallel.precision",
    "pure_bf16": "tpuframe.parallel.precision",
    "ParallelPlan": "tpuframe.parallel.sharding",
    "Rule": "tpuframe.parallel.sharding",
    "infer_shard_dim": "tpuframe.parallel.sharding",
    "mesh_axes": "tpuframe.parallel.sharding",
    "path_str": "tpuframe.parallel.sharding",
    "spec_from_json": "tpuframe.parallel.sharding",
    "spec_to_json": "tpuframe.parallel.sharding",
    "PipelinedTransformerLM": "tpuframe.parallel.pipeline",
    "PP_SCHEDULES": "tpuframe.parallel.pipeline",
    "gpipe_spmd": "tpuframe.parallel.pipeline",
    "pipeline_param_spec": "tpuframe.parallel.pipeline",
    "stack_stage_params": "tpuframe.parallel.pipeline",
    "compose": "tpuframe.parallel.compose",
    "default_tp_rules": "tpuframe.parallel.compose",
    "pipeline_rules": "tpuframe.parallel.compose",
    "plan_memory": "tpuframe.parallel.memory",
    "suggest_fit": "tpuframe.parallel.memory",
    "quantized_pmean": "tpuframe.parallel.compression",
    "CommsConfig": "tpuframe.parallel.comms_env",
    "COMMS_ENV_VARS": "tpuframe.parallel.comms_env",
    "init_comms_state": "tpuframe.parallel.compression",
    "make_compressed_pmean": "tpuframe.parallel.compression",
    "ZeroConfig": "tpuframe.parallel.zero",
    "host_offload_sharding": "tpuframe.parallel.zero",
    "supports_host_offload": "tpuframe.parallel.zero",
    "zero_0": "tpuframe.parallel.zero",
    "zero_1": "tpuframe.parallel.zero",
    "zero_2": "tpuframe.parallel.zero",
    "zero_3": "tpuframe.parallel.zero",
    "zero_3_offload": "tpuframe.parallel.zero",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        val = getattr(importlib.import_module(_LAZY[name]), name)
        # cache the resolved attribute: for ``compose`` the function
        # must win over the same-named submodule the import just bound
        globals()[name] = val
        return val
    raise AttributeError(f"module 'tpuframe.parallel' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))
