"""Parallelism: sharding plans (DP / ZeRO / TP), precision policies.

TPU-native re-expression of the reference's parallelism inventory
(SURVEY.md §2.2): DDP replication, DeepSpeed ZeRO stages, and tensor-parallel
hooks, all as declarative shardings over the core mesh — XLA inserts the
collectives the reference performed imperatively through NCCL.
"""

from tpuframe.parallel.precision import (
    Policy,
    align_model_dtype,
    bf16_compute,
    full_precision,
    get_policy,
    pure_bf16,
)
from tpuframe.parallel.sharding import (
    ParallelPlan,
    Rule,
    infer_shard_dim,
    mesh_axes,
    path_str,
    spec_from_json,
    spec_to_json,
)
from tpuframe.parallel.pipeline import (
    PipelinedTransformerLM,
    gpipe_spmd,
    pipeline_param_spec,
    stack_stage_params,
)
from tpuframe.parallel.compression import quantized_pmean
from tpuframe.parallel.zero import (
    ZeroConfig,
    host_offload_sharding,
    supports_host_offload,
    zero_0,
    zero_1,
    zero_2,
    zero_3,
    zero_3_offload,
)

__all__ = [
    "quantized_pmean",
    "PipelinedTransformerLM",
    "gpipe_spmd",
    "pipeline_param_spec",
    "stack_stage_params",
    "Policy",
    "align_model_dtype",
    "bf16_compute",
    "full_precision",
    "get_policy",
    "pure_bf16",
    "ParallelPlan",
    "Rule",
    "infer_shard_dim",
    "mesh_axes",
    "path_str",
    "spec_from_json",
    "spec_to_json",
    "ZeroConfig",
    "host_offload_sharding",
    "supports_host_offload",
    "zero_0",
    "zero_1",
    "zero_2",
    "zero_3",
    "zero_3_offload",
]
