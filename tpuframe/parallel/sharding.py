"""Sharding planner: one object that decides where every tensor lives.

TPU-native replacement for the reference's parallelism stack (SURVEY.md §2.2):
DDP's replicate-and-allreduce (`/root/reference/01_torch_distributor/
01_basic_torch_distributor.py:285-291`) and DeepSpeed's ZeRO stage dicts
(`/root/reference/02_deepspeed/deepspeed_config.py:52-105`) both collapse into
*sharding assignments* here — XLA inserts the collectives (reduce-scatter,
all-gather, all-reduce over ICI) that DDP/ZeRO perform imperatively with NCCL.

The planner answers three questions for a train step:

1. Where do **params** live?  Replicated (DDP), sharded over ``fsdp``
   (ZeRO-3 / FSDP), and/or split by tensor-parallel rules on ``model``.
2. Where does **optimizer state** live?  With the params (stage 0/3) or
   sharded over ``fsdp`` even while params stay replicated (stage 1/2 —
   DeepSpeed's optimizer/gradient partitioning ≈ XLA weight-update sharding).
3. Where do **batches** live?  Split over every data-ish axis.

Everything is declarative: the plan produces ``NamedSharding`` pytrees that
are handed to ``jax.jit(in_shardings=..., out_shardings=...)``; no imperative
hooks, no bucketing, no ``overlap_comm`` knobs — XLA's scheduler overlaps the
collectives with compute on its own.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
import warnings
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuframe.core.runtime import DATA_AXIS, FSDP_AXIS

#: A tensor-parallel rule: (regex over the param path, PartitionSpec).
Rule = tuple[str, P]


def spec_to_json(spec: P) -> list:
    """A PartitionSpec as plain JSON: each entry None, a str, or a list
    of strs — the form checkpoint topology manifests store per leaf."""
    out: list = []
    for entry in spec:
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:  # tuple of axis names
            out.append(list(entry))
    return out


def spec_from_json(entries: Sequence) -> P:
    """Inverse of :func:`spec_to_json`."""
    return P(*(tuple(e) if isinstance(e, list) else e for e in entries))


def mesh_axes(mesh: Mesh) -> dict[str, int]:
    """``{axis_name: size}`` for a mesh — the manifest's topology key."""
    return {str(name): int(size) for name, size in mesh.shape.items()}


def host_memory_available(mesh: Mesh | None = None) -> bool:
    """True when host-offloaded placement actually works: a real TPU
    backend whose devices expose a ``pinned_host`` memory space.

    The CPU simulation backend *lists* pinned_host but cannot compile
    SPMD programs with host-placement annotations ("side-effect ops
    cannot be replicated"), so CPU always returns False — offload plans
    downgrade gracefully in tests/dryruns."""
    if jax.default_backend() != "tpu":
        return False
    try:
        devs = mesh.devices.flat if mesh is not None else jax.devices()
        dev = next(iter(devs))
        return any(m.kind == "pinned_host" for m in dev.addressable_memories())
    except Exception:  # pragma: no cover - backend-dependent
        return False


def path_str(path: tuple) -> str:
    """Render a jax tree path as ``a/b/c`` (DictKey/SequenceKey/attr agnostic)."""
    parts = []
    for key in path:
        if hasattr(key, "key"):
            parts.append(str(key.key))
        elif hasattr(key, "idx"):
            parts.append(str(key.idx))
        elif hasattr(key, "name"):
            parts.append(str(key.name))
        else:
            parts.append(str(key))
    return "/".join(parts)


def infer_shard_dim(shape: Sequence[int], axis_size: int, taken: Sequence[int] = ()) -> int | None:
    """Pick the dimension to shard ``axis_size``-ways: the largest divisible
    dim not already taken by another mesh axis.  None if nothing divides."""
    best = None
    for dim, size in enumerate(shape):
        if dim in taken or size % axis_size or size < axis_size:
            continue
        if best is None or size > shape[best]:
            best = dim
    return best


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """Declarative parallelism policy over a named mesh.

    ``zero_stage`` maps DeepSpeed's ladder onto XLA sharding:

    - 0: pure DP — params+opt state replicated, grads all-reduced (DDP).
    - 1/2: params replicated, **optimizer state sharded** over ``fsdp``;
      XLA turns the update into reduce-scatter(grads) -> sharded update ->
      all-gather(params), i.e. DeepSpeed's stage-1/2 comm pattern
      (`deepspeed_config.py:53-71`).  1 and 2 are one stage here because
      gradient lifetime is XLA's to schedule, not ours.
    - 3: **params sharded** over ``fsdp`` (all-gather on use), optimizer
      state sharded to match (`deepspeed_config.py:74-84`).

    ``rules`` add tensor parallelism: first regex matching a param's path
    assigns an explicit PartitionSpec (axes it names are layered on top of
    any fsdp sharding).  ``min_shard_elems`` keeps small tensors (biases, BN
    scales) replicated — sharding them costs more latency than HBM.
    """

    mesh: Mesh
    zero_stage: int = 0
    rules: Sequence[Rule] = ()
    min_shard_elems: int = 2**14
    fsdp_axis: str = FSDP_AXIS
    data_axes: Sequence[str] = (DATA_AXIS, FSDP_AXIS)
    #: DeepSpeed stage-3 CPU offload (`deepspeed_config.py:87-105`):
    #: optimizer-state leaves live in pinned host memory and stream to HBM
    #: inside the update.  EXPERIMENTAL: applied only when the backend has
    #: a usable ``pinned_host`` memory space (real TPUs — CPU simulation
    #: downgrades with a warning), and the pinned-host path has not yet
    #: been executed on real TPU hardware in this repo —
    #: ``benchmarks/check_offload_tpu.py`` is the acceptance harness and
    #: its committed JSON in ``benchmarks/results/`` is the proof of
    #: support on a given backend.
    offload_optimizer: bool = False
    #: bucket-group count for the scheduled compressed gradient sync
    #: (see ``parallel.compression.sync_gradients``): None defers to
    #: ``CommsConfig.groups`` (the ``TPUFRAME_COMMS_GROUPS`` env knob);
    #: an explicit value pins the schedule on the plan so it rides the
    #: plan signature, the topology manifest, and the compile labels.
    comms_groups: int | None = None
    #: in-collective compressed transport (see
    #: ``parallel.compression.fused_active``): None defers to
    #: ``CommsConfig.fused`` (the ``TPUFRAME_COMMS_FUSED`` env knob);
    #: an explicit bool pins the transport on the plan so it rides the
    #: plan signature and the AOT compile labels — a fused and a staged
    #: program are different programs.
    comms_fused: bool | None = None
    #: microbatch count for the pipeline schedule (``parallel.pipeline``):
    #: None defers to the model/``TPUFRAME_PP_MICROBATCHES`` env knob; an
    #: explicit value pins the schedule depth on the plan so it rides the
    #: plan signature and the AOT compile labels — a different microbatch
    #: count is a different scanned program.
    pp_microbatches: int | None = None
    #: pipeline hop/compute interleave policy (``parallel.pipeline``
    #: schedules): None defers to ``TPUFRAME_PP_SCHEDULE`` (default
    #: ``interleaved``); an explicit value pins it on the plan.
    #: ``interleaved`` lets the scheduler slot ``ppermute`` hops between
    #: stage compute, ``1f1b`` adds remat-bounded stage stashes, and
    #: ``barriered`` serializes hop-then-compute (the A/B baseline arm).
    pp_schedule: str | None = None

    def __post_init__(self):
        if self.zero_stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_stage must be 0..3, got {self.zero_stage}")
        if self.comms_groups is not None and self.comms_groups < 1:
            raise ValueError(
                f"comms_groups must be >= 1 (or None), got {self.comms_groups}"
            )
        if self.comms_fused not in (None, True, False):
            raise ValueError(
                f"comms_fused must be a bool or None, got {self.comms_fused!r}"
            )
        if self.pp_microbatches is not None and self.pp_microbatches < 1:
            raise ValueError(
                f"pp_microbatches must be >= 1 (or None), got {self.pp_microbatches}"
            )
        from tpuframe.parallel.pipeline import PP_SCHEDULES

        if self.pp_schedule is not None and self.pp_schedule not in PP_SCHEDULES:
            raise ValueError(
                f"pp_schedule must be one of {PP_SCHEDULES} (or None), "
                f"got {self.pp_schedule!r}"
            )
        if self.offload_optimizer and not host_memory_available(self.mesh):
            # loud, not silent: a user who asked for DeepSpeed-style CPU
            # offload must know their optimizer state is staying in HBM
            warnings.warn(
                "offload_optimizer=True requested but backend "
                f"{jax.default_backend()!r} has no usable pinned_host memory "
                f"space; downgrading to plain ZeRO-{self.zero_stage} "
                "(optimizer state stays in device HBM). Host offload is "
                "EXPERIMENTAL: run benchmarks/check_offload_tpu.py on the "
                "target backend to validate it before relying on the "
                "memory savings.",
                stacklevel=3,
            )

    def _offload_active(self) -> bool:
        return self.offload_optimizer and host_memory_available(self.mesh)

    # -- identity / topology ----------------------------------------------
    def signature(self) -> str:
        """Stable short digest of the plan's *policy + topology*: mesh
        axis names/sizes, ZeRO stage, TP rules, thresholds.  Two plans
        with equal signatures lower the same step program for the same
        batch signature, so this is the key the compile spine (and the
        checkpoint topology manifest) uses to tell "same plan, rebound"
        from "different plan".  Deliberately excludes device identities:
        the same logical shape on different physical chips is the same
        program."""
        payload = {
            "mesh": sorted(mesh_axes(self.mesh).items()),
            "zero_stage": self.zero_stage,
            "rules": [[pat, spec_to_json(spec)] for pat, spec in self.rules],
            "min_shard_elems": self.min_shard_elems,
            "fsdp_axis": self.fsdp_axis,
            "data_axes": list(self.data_axes),
            "offload": bool(self.offload_optimizer),
        }
        # schedule-bearing plans key their own programs; the default
        # (None / 1 = single-shot) is OMITTED so every pre-existing plan
        # signature — autotune store keys, topology manifests, compile
        # labels — is unchanged by the field's existence
        if self.comms_groups is not None and self.comms_groups != 1:
            payload["comms_groups"] = int(self.comms_groups)
        # same omit-the-default rule for the fused transport: only a
        # pinned True changes the program identity (pinned False is the
        # staged program every pre-existing signature already names)
        if self.comms_fused:
            payload["comms_fused"] = True
        # pipeline-schedule pins are program identity too (a different
        # microbatch count or interleave policy lowers a different scanned
        # program), but the defaults are omitted so pre-existing plan
        # signatures stay byte-stable
        if self.pp_microbatches is not None:
            payload["pp_microbatches"] = int(self.pp_microbatches)
        if self.pp_schedule is not None and self.pp_schedule != "interleaved":
            payload["pp_schedule"] = str(self.pp_schedule)
        blob = json.dumps(payload, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:12]

    def comms_schedule(self, config: Any = None) -> dict:
        """The plan's collective schedule as a first-class artifact:
        how many bucket groups the compressed gradient sync fires, and
        in what order.  ``config`` (a ``CommsConfig``) supplies the env
        default when the plan itself doesn't pin ``comms_groups``.
        ``order`` is fixed: groups fire in reverse path-sorted bucket
        order — the reverse-backward leaf order, so the group covering
        the gradients backward produces *first* goes on the wire first
        and hides behind the rest of the backward."""
        groups = self.comms_groups
        if groups is None:
            groups = int(getattr(config, "groups", 1) or 1)
        fused = self.comms_fused
        if fused is None:
            fused = bool(getattr(config, "fused", False))
        return {
            "groups": int(groups),
            "order": "reverse_backward",
            "pinned": self.comms_groups is not None,
            "fused": bool(fused),
            "fused_pinned": self.comms_fused is not None,
            "pp_schedule": self.pp_schedule or "interleaved",
            "pp_pinned": self.pp_schedule is not None,
        }

    def describe_topology(self) -> dict:
        """The plan's topology as manifest-shaped JSON (mesh axes, world
        size, signature) — what ``fault/world_resized`` events carry.
        The ``pipeline_stages``/``tp_size`` breakout names the composed
        N-D split explicitly so a plan-change restore (TP=4 saved,
        TP=2×PP=2 target) reads as a *plan* move, not just a mesh diff."""
        axes = mesh_axes(self.mesh)
        return {
            "mesh_axes": axes,
            "world_size": int(self.mesh.devices.size),
            "plan_signature": self.signature(),
            "zero_stage": self.zero_stage,
            "pipeline_stages": int(axes.get("pipe", 1)),
            "tp_size": int(axes.get("model", 1)),
        }

    def rebind(self, mesh: Mesh) -> "ParallelPlan":
        """Re-derive an equivalent plan over a different mesh (the elastic
        shrink/grow path): every policy knob — ZeRO stage, TP rules,
        thresholds — carries over; only the topology changes.  Axis
        *collapses* (an axis that was >1 now 1: ZeRO sharding vanishing
        when ``fsdp`` collapses, TP rules going inert when ``model``
        does) are loud — one ``parallel/plan_rebind`` event with the
        old/new axes plus a warning, because the memory/layout contract
        the old plan bought silently disappears otherwise."""
        from tpuframe.track.telemetry import get_telemetry

        old_axes, new_axes = mesh_axes(self.mesh), mesh_axes(mesh)
        new = dataclasses.replace(self, mesh=mesh)
        collapsed = sorted(
            a for a in old_axes
            if old_axes.get(a, 1) > 1 and new_axes.get(a, 1) == 1
        )
        get_telemetry().event(
            "parallel/plan_rebind",
            from_axes=old_axes,
            to_axes=new_axes,
            from_world=int(self.mesh.devices.size),
            to_world=int(mesh.devices.size),
            collapsed=collapsed,
            signature=new.signature(),
        )
        if collapsed:
            warnings.warn(
                f"plan rebind collapsed mesh axis(es) {collapsed} to size 1 "
                f"({old_axes} -> {new_axes}): sharding over those axes is "
                "now inert (ZeRO partitions gather to every replica when "
                "fsdp collapses; TP rules naming a collapsed axis "
                "replicate).  Expected when shrinking to survivors — but "
                "re-check the memory budget fits the new world.",
                stacklevel=2,
            )
        return new

    # -- axis helpers ------------------------------------------------------
    def axis_size(self, name: str) -> int:
        return self.mesh.shape[name] if name in self.mesh.shape else 1

    @property
    def dp_size(self) -> int:
        return int(np.prod([self.axis_size(a) for a in self.data_axes]))

    # -- batch -------------------------------------------------------------
    def batch_spec(self) -> P:
        axes = tuple(a for a in self.data_axes if self.axis_size(a) > 1)
        return P(axes) if axes else P()

    def batch_sharding(self, leading_microbatch: bool = False) -> NamedSharding:
        """``leading_microbatch=True`` for (n_micro, micro, ...) grad-accum
        batches: the microbatch dim leads, the batch axes shard dim 1."""
        spec = self.batch_spec()
        if leading_microbatch:
            spec = P(None, *spec)
        return NamedSharding(self.mesh, spec)

    # -- params ------------------------------------------------------------
    def _rule_spec(self, path: str) -> P | None:
        for pattern, spec in self.rules:
            if re.search(pattern, path):
                return spec
        return None

    def _maybe_fsdp(self, shape: Sequence[int], base: P) -> P:
        """Layer fsdp sharding onto ``base`` if the plan shards params."""
        size = self.axis_size(self.fsdp_axis)
        if size <= 1 or int(np.prod(shape)) < self.min_shard_elems:
            return base
        # a TP rule may already place fsdp; a duplicate axis is illegal
        named = {
            a for e in base if e is not None
            for a in (e if isinstance(e, tuple) else (e,))
        }
        if self.fsdp_axis in named:
            return base
        entries = list(base) + [None] * (len(shape) - len(base))
        taken = [i for i, e in enumerate(entries) if e is not None]
        dim = infer_shard_dim(shape, size, taken)
        if dim is None:
            return base
        entries[dim] = self.fsdp_axis
        return P(*entries)

    def param_spec(self, path: str, shape: Sequence[int]) -> P:
        spec = self._rule_spec(path) or P()
        if self.zero_stage == 3:
            spec = self._maybe_fsdp(shape, spec)
        return spec

    def _state_spec(self, path: str, shape: Sequence[int]) -> P:
        """Optimizer-state leaves: follow params, plus fsdp for stage>=1.

        A state leaf can have lower rank than the param it mirrors (e.g.
        adafactor's row/col factors); the param's TP rule spec is then
        meaningless for it, so it falls back to plain fsdp inference.
        """
        spec = self._rule_spec(path) or P()
        if len(spec) > len(shape):
            spec = P()
        if self.zero_stage >= 1:
            spec = self._maybe_fsdp(shape, spec)
        return spec

    def update_shard_specs(self, params: Any) -> dict[str, tuple]:
        """The plan-derived weight-update sharding (arXiv:2004.13336,
        mechanically from the data-parallel graph): for ZeRO stage 1/2/3,
        every param leaf big enough to shard (``min_shard_elems``) with
        a dimension divisible by the *combined* data-parallel world is
        assigned ``{path: (dim, axes)}`` — the compressed train step
        reduce-scatters its gradient along ``dim`` over ``axes``, runs
        the optimizer on the owned slice against the plan's sharded
        state, and all-gathers the update.  Leaves that don't qualify
        (small, or no divisible dim) stay replicated and travel in the
        shared transport buckets instead.
        """
        axes = tuple(a for a in self.data_axes if self.axis_size(a) > 1)
        world = int(np.prod([self.axis_size(a) for a in axes])) if axes else 1
        out: dict[str, tuple] = {}
        if world <= 1 or self.zero_stage not in (1, 2, 3):
            return out
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            shape = tuple(getattr(leaf, "shape", ()) or ())
            if not shape or int(np.prod(shape)) < self.min_shard_elems:
                continue
            dim = infer_shard_dim(shape, world)
            if dim is not None:
                out[path_str(path)] = (dim, axes)
        return out

    def param_shardings(self, params: Any) -> Any:
        """Pytree of NamedSharding matching ``params`` (arrays or ShapeDtypeStructs)."""

        def assign(path, leaf):
            if not hasattr(leaf, "shape") or leaf.shape == ():
                return self.replicated()
            return NamedSharding(self.mesh, self.param_spec(path_str(path), leaf.shape))

        return jax.tree_util.tree_map_with_path(assign, params)

    def state_shardings(self, state: Any, params: Any, with_offload: bool = True) -> Any:
        """Pytree of NamedSharding for an optax state mirroring ``params``.

        Param-shaped leaves inside the state (``mu``/``nu``/trace buffers —
        optax builds them with the params' own tree structure, so their tree
        paths end with the param's path) get the param-aligned spec with the
        ZeRO-stage fsdp sharding layered on; scalars (step counts) replicate.

        ``with_offload=False`` suppresses the pinned-host memory kind even
        when offload is active — used for shardings that must be legal
        inside a jit's ``out_shardings`` (XLA rejects memory-kind
        annotations there); the caller then ``device_put``s to the
        offloaded shardings afterwards.
        """
        param_paths = {
            path_str(p) for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]
        }
        offload = with_offload and self._offload_active()

        def place(sharding: NamedSharding) -> NamedSharding:
            # Scalars (step counts) stay on device: they gate control flow.
            return sharding.with_memory_kind("pinned_host") if offload else sharding

        def assign(path, leaf):
            if not hasattr(leaf, "shape") or leaf.shape == ():
                return self.replicated()
            full = path_str(path)
            # longest param-path suffix match identifies param-mirroring leaves
            parts = full.split("/")
            for start in range(len(parts)):
                if "/".join(parts[start:]) in param_paths:
                    return place(NamedSharding(
                        self.mesh, self._state_spec("/".join(parts[start:]), leaf.shape)
                    ))
            # non-param-mirroring leaves (EMA buffers etc.) follow the stage
            # gate too: stage 0 means *everything* in the state is replicated
            spec = self._maybe_fsdp(leaf.shape, P()) if self.zero_stage >= 1 else P()
            return place(NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map_with_path(assign, state)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- application -------------------------------------------------------
    def shard_params(self, params: Any) -> Any:
        """Place a live param pytree according to the plan (host -> devices)."""
        return jax.device_put(params, self.param_shardings(params))

    def shard_batch(self, batch: Any, leading_microbatch: bool = False) -> Any:
        """Host batch (this process's shard) -> global sharded Arrays.

        Multi-process runs assemble the global array from per-process
        locals via ``jax.make_array_from_process_local_data`` (each
        process passes *different* rows — a plain device_put would
        reject that); single-process is a straight device_put.
        """
        sharding = self.batch_sharding(leading_microbatch)
        if jax.process_count() > 1:
            put = lambda x: jax.make_array_from_process_local_data(  # noqa: E731
                sharding, np.asarray(x)
            )
        else:
            put = lambda x: jax.device_put(x, sharding)  # noqa: E731
        return jax.tree.map(put, batch)

    def describe(self, params: Any) -> dict[str, str]:
        """Human-readable spec per param path (for logging/debugging)."""
        out = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            p = path_str(path)
            shape = getattr(leaf, "shape", ())
            out[p] = f"{tuple(shape)} -> {self.param_spec(p, shape)}"
        return out
