"""Comms knob registry — the stdlib-only half of the compression spine.

The wire-level collective configuration (``tpuframe.parallel.compression``)
is env-tunable per fleet: every knob here ships to remote workers through
``launch.remote.all_env_vars()`` and prints in the doctor's ``comms``
section.  Kept jax-free (like ``serve.admission`` / ``core.workspace``)
so the aggregator and the doctor can read the registry from a
wedged-backend or jax-less process.

Knob semantics (the one table, mirrored in OBSERVABILITY.md):

- ``TPUFRAME_COMMS_COMPRESSION`` — gradient wire format: ``int8`` /
  ``fp8`` (e4m3) / empty = off.  The ``Trainer(grad_compression=...)``
  parameter overrides the env.
- ``TPUFRAME_COMMS_BUCKET_MB`` — transport bucket size in MiB of f32
  payload (default 4.0).  Leaves are flattened into a small number of
  fixed-size buckets, each with its own quantization scale.
- ``TPUFRAME_COMMS_STOCHASTIC`` — ``1`` enables stochastic rounding on
  the int8 grid (unbiased; fp8 uses round-to-nearest-even in hardware,
  the knob does not apply there).
- ``TPUFRAME_COMMS_EF`` — error feedback on/off (default on): the
  quantization residual is carried as a ``TrainState.comms`` leaf and
  re-injected next step, so the compressed trajectory tracks f32.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import dataclasses
import os

__all__ = ["COMMS_ENV_VARS", "CommsConfig", "COMPRESSION_MODES"]

#: the comms spine's env knobs — aggregated by
#: ``launch.remote.all_env_vars()`` and printed by the doctor
COMMS_ENV_VARS = (
    "TPUFRAME_COMMS_COMPRESSION",
    "TPUFRAME_COMMS_BUCKET_MB",
    "TPUFRAME_COMMS_STOCHASTIC",
    "TPUFRAME_COMMS_EF",
)

#: value domains for the knobs above (KN007).  All "restart":
#: ``CommsConfig.from_env`` is snapshotted when the train step is
#: built, and changing the wire format retraces the step anyway.
COMMS_ENV_DOMAINS = {
    "TPUFRAME_COMMS_COMPRESSION": {
        "type": "enum", "choices": ("", "int8", "fp8"), "apply": "restart"},
    "TPUFRAME_COMMS_BUCKET_MB": {
        "type": "float", "range": (0.25, 1024.0), "apply": "restart"},
    "TPUFRAME_COMMS_STOCHASTIC": {"type": "bool", "apply": "restart"},
    "TPUFRAME_COMMS_EF": {"type": "bool", "apply": "restart"},
}

#: wire formats the compressed collectives understand
COMPRESSION_MODES = ("int8", "fp8")

_FALSY = {"0", "false", "off", "no", ""}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSY


@dataclasses.dataclass(frozen=True)
class CommsConfig:
    """Resolved wire-compression policy for the gradient collectives.

    ``mode`` is one of :data:`COMPRESSION_MODES`; construction validates
    it so a typo'd env/param fails at build time, not mid-step.
    """

    mode: str = "int8"
    bucket_mb: float = 4.0
    stochastic_rounding: bool = False
    error_feedback: bool = True

    def __post_init__(self):
        if self.mode not in COMPRESSION_MODES:
            raise ValueError(
                f"unknown grad_compression {self.mode!r}; known: "
                + "/".join(COMPRESSION_MODES)
            )
        if self.bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be > 0, got {self.bucket_mb}")

    @property
    def bucket_elems(self) -> int:
        """Max f32 elements per transport bucket."""
        return max(64, int(self.bucket_mb * (1 << 20) / 4))

    @property
    def wire_bytes_per_elem(self) -> int:
        """Payload bytes per element on the wire (int8 and fp8-e4m3 are
        both one byte)."""
        return 1

    @classmethod
    def from_env(cls, mode: str | None = None) -> "CommsConfig | None":
        """The env-resolved config; ``mode`` (a Trainer/step parameter)
        overrides ``TPUFRAME_COMMS_COMPRESSION``.  None = compression
        off (no mode requested anywhere).  Malformed numeric/boolean
        knobs fall back to defaults (tolerant, like ``ServeKnobs``); an
        unknown *mode* still raises — silently training uncompressed
        when compression was asked for is the one failure that must be
        loud."""
        if mode is None:
            mode = os.environ.get("TPUFRAME_COMMS_COMPRESSION", "").strip()
        if isinstance(mode, CommsConfig):
            return mode
        if not mode:
            return None
        return cls(
            mode=str(mode).lower(),
            bucket_mb=_env_float("TPUFRAME_COMMS_BUCKET_MB", 4.0),
            stochastic_rounding=_env_bool("TPUFRAME_COMMS_STOCHASTIC", False),
            error_feedback=_env_bool("TPUFRAME_COMMS_EF", True),
        )
