"""Comms knob registry — the stdlib-only half of the compression spine.

The wire-level collective configuration (``tpuframe.parallel.compression``)
is env-tunable per fleet: every knob here ships to remote workers through
``launch.remote.all_env_vars()`` and prints in the doctor's ``comms``
section.  Kept jax-free (like ``serve.admission`` / ``core.workspace``)
so the aggregator and the doctor can read the registry from a
wedged-backend or jax-less process.

Knob semantics (the one table, mirrored in OBSERVABILITY.md):

- ``TPUFRAME_COMMS_COMPRESSION`` — gradient wire format: ``int8`` /
  ``fp8`` (e4m3) / empty = off.  The ``Trainer(grad_compression=...)``
  parameter overrides the env.
- ``TPUFRAME_COMMS_BUCKET_MB`` — transport bucket size in MiB of f32
  payload (default 4.0).  Leaves are flattened into a small number of
  fixed-size buckets, each with its own quantization scale.
- ``TPUFRAME_COMMS_STOCHASTIC`` — ``1`` enables stochastic rounding on
  the int8 grid (unbiased; fp8 uses round-to-nearest-even in hardware,
  the knob does not apply there).
- ``TPUFRAME_COMMS_EF`` — error feedback on/off (default on): the
  quantization residual is carried as a ``TrainState.comms`` leaf and
  re-injected next step, so the compressed trajectory tracks f32.
- ``TPUFRAME_COMMS_GROUPS`` — bucket-group count for the scheduled
  sync (default 1 = the single-shot collective).  Groups fire in
  reverse path-sorted order (the reverse-backward leaf order: the
  deepest layers' gradients are produced first), so group *i*'s
  quantized collective is dataflow-independent of group *i+1*'s
  quantization and can hide behind it.  Bit-exact against the
  single-shot reference — per-bucket scales/EF/non-finite handling are
  elementwise over the bucket dimension, so partitioning changes the
  schedule, never the arithmetic.  A ``ParallelPlan.comms_groups``
  override wins over the env (the plan is the first-class schedule
  artifact).
- ``TPUFRAME_COMMS_FUSED`` — ``1`` fuses the quantized wire *into* the
  collective: the staged single-``psum`` transport is replaced by a
  manual ring reduce-scatter / all-gather over the data axes whose hops
  carry the 8-bit payloads directly (per-bucket scales agreed once up
  front, partial sums accumulated exactly on arrival), so quantized
  bytes — not f32 — are what cross the wire on every hop.  Bit-exact
  against the staged path in every mode: int8 partials are integer
  sums, fp8-e4m3 grid values are multiples of 2^-9 bounded by 448 so
  f32 partial sums stay exact through world sizes <= 73 (beyond that
  the fp8 wire falls back to staged rather than drift).  Requires a
  single data axis; multi-axis meshes and world size 1 fall back to
  the staged path.  A ``ParallelPlan.comms_fused`` override wins over
  the env (same plan-first rule as ``comms_groups``).
- ``TPUFRAME_COMMS_FUSED_BLOCK`` — column-block element count for the
  ``ops.quant_wire`` Pallas encode/decode kernels (default 2048, lane
  multiple).  Larger blocks amortize grid overhead; smaller ones fit
  tighter VMEM budgets next to the ring buffers.
- ``TPUFRAME_COMMS_ASYNC`` — ``1`` turns on the backend's
  latency-hiding-scheduler / async-collective-fusion XLA flags at
  ``core.runtime.initialize`` (:func:`comms_async_flags` is the one
  resolver; the doctor prints the resolved set).  Restart-only: XLA
  reads the flags at backend init.  No-op on CPU — the CPU compiler
  rejects the TPU/GPU scheduler flags, so the resolver returns an
  empty set there rather than aborting the process.
- ``TPUFRAME_PP_MICROBATCHES`` — microbatches per pipeline step
  (default 0 = unset: the model's ``n_microbatches`` default applies).
  More microbatches shrink the GPipe bubble ``(S-1)/(M+S-1)``.  A
  composed ``ParallelPlan.pp_microbatches`` pin (or an explicit model
  field) wins over the env and rides the plan signature.
- ``TPUFRAME_PP_SCHEDULE`` — pipeline hop/compute interleave policy:
  ``interleaved`` (default; ``ppermute`` hops slot behind stage
  compute), ``1f1b`` (interleaved + remat-bounded backward stash), or
  ``barriered`` (hop-then-compute serialized — the A/B baseline arm of
  ``bench_collectives.py --pipeline``, not a production schedule).  A
  ``ParallelPlan.pp_schedule`` pin wins over the env.
- ``TPUFRAME_TP_SIZE`` — tensor-parallel (``model`` axis) size
  ``parallel.compose.compose`` builds its mesh with when the caller
  doesn't pass ``tp=`` (default 1 = no TP).  Restart-only: the mesh is
  laid out at ``initialize``.
- ``TPUFRAME_ZERO_STAGE`` — ZeRO stage [0, 3] ``compose`` uses when the
  caller doesn't pass ``zero_stage=`` (default 0 = pure DP).  The
  memory-bound autotune branch proposes stage moves through this knob;
  restart-only because the state shardings are laid out at plan build.
- ``TPUFRAME_OFFLOAD_OPTIMIZER`` — ``1`` defaults ``compose`` to
  host-offloaded optimizer state (the plan still downgrades loudly on
  backends without an addressable host space).  The estimator prices
  the offloaded bytes as ``host_total`` instead of HBM.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import dataclasses
import os

__all__ = [
    "COMMS_ENV_VARS",
    "CommsConfig",
    "COMPRESSION_MODES",
    "PP_SCHEDULE_CHOICES",
    "comms_async_enabled",
    "comms_async_flags",
    "comms_async_platform",
    "comms_fused_block",
    "offload_optimizer_default",
    "pp_microbatches",
    "pp_schedule",
    "tp_size",
    "zero_stage_default",
]

#: the comms spine's env knobs — aggregated by
#: ``launch.remote.all_env_vars()`` and printed by the doctor
COMMS_ENV_VARS = (
    "TPUFRAME_COMMS_COMPRESSION",
    "TPUFRAME_COMMS_BUCKET_MB",
    "TPUFRAME_COMMS_STOCHASTIC",
    "TPUFRAME_COMMS_EF",
    "TPUFRAME_COMMS_GROUPS",
    "TPUFRAME_COMMS_FUSED",
    "TPUFRAME_COMMS_FUSED_BLOCK",
    "TPUFRAME_COMMS_ASYNC",
    "TPUFRAME_PP_MICROBATCHES",
    "TPUFRAME_PP_SCHEDULE",
    "TPUFRAME_TP_SIZE",
    "TPUFRAME_ZERO_STAGE",
    "TPUFRAME_OFFLOAD_OPTIMIZER",
)

#: value domains for the knobs above (KN007).  All "restart":
#: ``CommsConfig.from_env`` is snapshotted when the train step is
#: built, and changing the wire format retraces the step anyway.
COMMS_ENV_DOMAINS = {
    "TPUFRAME_COMMS_COMPRESSION": {
        "type": "enum", "choices": ("", "int8", "fp8"), "apply": "restart"},
    "TPUFRAME_COMMS_BUCKET_MB": {
        "type": "float", "range": (0.25, 1024.0), "apply": "restart"},
    "TPUFRAME_COMMS_STOCHASTIC": {"type": "bool", "apply": "restart"},
    "TPUFRAME_COMMS_EF": {"type": "bool", "apply": "restart"},
    "TPUFRAME_COMMS_GROUPS": {
        "type": "int", "range": (1, 64), "apply": "restart"},
    "TPUFRAME_COMMS_FUSED": {"type": "bool", "apply": "restart"},
    "TPUFRAME_COMMS_FUSED_BLOCK": {
        "type": "int", "range": (128, 65536), "apply": "restart"},
    "TPUFRAME_COMMS_ASYNC": {"type": "bool", "apply": "restart"},
    "TPUFRAME_PP_MICROBATCHES": {
        "type": "int", "range": (0, 4096), "apply": "restart"},
    "TPUFRAME_PP_SCHEDULE": {
        "type": "enum",
        "choices": ("", "interleaved", "barriered", "1f1b"),
        "apply": "restart"},
    "TPUFRAME_TP_SIZE": {
        "type": "int", "range": (1, 64), "apply": "restart"},
    "TPUFRAME_ZERO_STAGE": {
        "type": "int", "range": (0, 3), "apply": "restart"},
    "TPUFRAME_OFFLOAD_OPTIMIZER": {"type": "bool", "apply": "restart"},
}

#: wire formats the compressed collectives understand
COMPRESSION_MODES = ("int8", "fp8")

#: pipeline schedules the env knob accepts — the one source of truth
#: (``parallel.pipeline.PP_SCHEDULES`` re-exports it); lives here,
#: stdlib-only, so the registry stays importable from a jax-less process
PP_SCHEDULE_CHOICES = ("interleaved", "barriered", "1f1b")

_FALSY = {"0", "false", "off", "no", ""}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSY


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


# -- TPUFRAME_COMMS_ASYNC: the XLA scheduler flag resolver --------------------

#: per-platform flag sets the async knob turns on.  TPU: the
#: latency-hiding scheduler (orders independent collectives into
#: compute gaps) + async-collective fusion (keeps the DMA in flight
#: across the fused region).  GPU: the LHS has its own flag name.
#: CPU has neither pass and the compiler aborts on unknown flags, so
#: its entry is the empty set — the knob degrades to a no-op there.
_ASYNC_FLAGS = {
    "tpu": (
        "--xla_tpu_enable_latency_hiding_scheduler=true",
        "--xla_tpu_enable_async_collective_fusion=true",
    ),
    "gpu": ("--xla_gpu_enable_latency_hiding_scheduler=true",),
    "cuda": ("--xla_gpu_enable_latency_hiding_scheduler=true",),
}


def comms_async_enabled(environ: dict | None = None) -> bool:
    """Is ``TPUFRAME_COMMS_ASYNC`` requested? (Whether it resolves to
    any flags is the platform's call — :func:`comms_async_flags`.)"""
    env = os.environ if environ is None else environ
    raw = env.get("TPUFRAME_COMMS_ASYNC")
    if raw is None:
        return False
    return raw.strip().lower() not in _FALSY


def comms_async_platform(environ: dict | None = None) -> str:
    """Best-effort backend guess WITHOUT importing jax (asking jax for
    its backend would initialize it — exactly what must not happen
    before the flags are merged into ``XLA_FLAGS``): the first
    ``JAX_PLATFORMS`` token when set, else "tpu" when libtpu is
    importable, else "cpu"."""
    env = os.environ if environ is None else environ
    plats = env.get("JAX_PLATFORMS", "").strip().lower()
    if plats:
        return plats.split(",")[0].strip() or "cpu"
    try:
        import importlib.util

        if importlib.util.find_spec("libtpu") is not None:
            return "tpu"
    except (ImportError, ValueError):
        pass
    return "cpu"


def comms_async_flags(platform: str | None = None,
                      environ: dict | None = None) -> tuple[str, ...]:
    """The resolved XLA flag set ``TPUFRAME_COMMS_ASYNC`` adds for
    ``platform`` (default: :func:`comms_async_platform`), or ``()``
    when the knob is off or the platform has no safe flags.  One
    resolver for ``core.runtime.initialize`` (applies it) and the
    doctor (prints it)."""
    if not comms_async_enabled(environ):
        return ()
    plat = platform if platform is not None else comms_async_platform(environ)
    return _ASYNC_FLAGS.get(plat, ())


@dataclasses.dataclass(frozen=True)
class CommsConfig:
    """Resolved wire-compression policy for the gradient collectives.

    ``mode`` is one of :data:`COMPRESSION_MODES`; construction validates
    it so a typo'd env/param fails at build time, not mid-step.
    """

    mode: str = "int8"
    bucket_mb: float = 4.0
    stochastic_rounding: bool = False
    error_feedback: bool = True
    #: bucket-group count for the scheduled sync (1 = single shot).
    #: More groups than buckets clamps down at layout build.
    groups: int = 1
    #: in-collective transport: ring reduce-scatter/all-gather whose
    #: hops carry the 8-bit payloads (False = staged psum around one
    #: encode/decode).  Falls back to staged on multi-axis meshes,
    #: world size 1, and fp8 beyond the exact-sum world bound.
    fused: bool = False

    def __post_init__(self):
        if self.mode not in COMPRESSION_MODES:
            raise ValueError(
                f"unknown grad_compression {self.mode!r}; known: "
                + "/".join(COMPRESSION_MODES)
            )
        if self.bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be > 0, got {self.bucket_mb}")
        if self.groups < 1:
            raise ValueError(f"groups must be >= 1, got {self.groups}")

    @property
    def bucket_elems(self) -> int:
        """Max f32 elements per transport bucket."""
        return max(64, int(self.bucket_mb * (1 << 20) / 4))

    @property
    def wire_bytes_per_elem(self) -> int:
        """Payload bytes per element on the wire (int8 and fp8-e4m3 are
        both one byte)."""
        return 1

    @classmethod
    def from_env(cls, mode: str | None = None) -> "CommsConfig | None":
        """The env-resolved config; ``mode`` (a Trainer/step parameter)
        overrides ``TPUFRAME_COMMS_COMPRESSION``.  None = compression
        off (no mode requested anywhere).  Malformed numeric/boolean
        knobs fall back to defaults (tolerant, like ``ServeKnobs``); an
        unknown *mode* still raises — silently training uncompressed
        when compression was asked for is the one failure that must be
        loud."""
        if mode is None:
            mode = os.environ.get("TPUFRAME_COMMS_COMPRESSION", "").strip()
        if isinstance(mode, CommsConfig):
            return mode
        if not mode:
            return None
        return cls(
            mode=str(mode).lower(),
            bucket_mb=_env_float("TPUFRAME_COMMS_BUCKET_MB", 4.0),
            stochastic_rounding=_env_bool("TPUFRAME_COMMS_STOCHASTIC", False),
            error_feedback=_env_bool("TPUFRAME_COMMS_EF", True),
            groups=max(1, _env_int("TPUFRAME_COMMS_GROUPS", 1)),
            fused=_env_bool("TPUFRAME_COMMS_FUSED", False),
        )


def comms_fused_block(environ: dict | None = None) -> int:
    """Column-block element count for the ``ops.quant_wire`` kernels
    (``TPUFRAME_COMMS_FUSED_BLOCK``), clamped to the declared domain and
    rounded down to a lane multiple.  Lives here — not in ops/ — so the
    knob's one read site sits next to its registry row."""
    env = os.environ if environ is None else environ
    raw = str(env.get("TPUFRAME_COMMS_FUSED_BLOCK", "") or "").strip()
    try:
        val = int(raw) if raw else 2048
    except ValueError:
        val = 2048
    val = max(128, min(65536, val))
    return (val // 128) * 128


def pp_microbatches(environ: dict | None = None) -> int:
    """``TPUFRAME_PP_MICROBATCHES`` resolved and clamped to its declared
    domain; 0 = unset (the model's ``n_microbatches`` default applies).
    A composed plan's ``pp_microbatches`` pin wins over this env value."""
    env = os.environ if environ is None else environ
    raw = str(env.get("TPUFRAME_PP_MICROBATCHES", "") or "").strip()
    try:
        val = int(raw) if raw else 0
    except ValueError:
        val = 0
    return max(0, min(4096, val))


def pp_schedule(environ: dict | None = None) -> str:
    """``TPUFRAME_PP_SCHEDULE`` resolved against
    :data:`PP_SCHEDULE_CHOICES`; unset/unknown values fall back to
    ``interleaved`` (tolerant like the other comms knobs — the pipeline
    primitive itself is the loud validator for programmatic schedules).
    A ``ParallelPlan.pp_schedule`` pin wins over this env value."""
    env = os.environ if environ is None else environ
    raw = str(env.get("TPUFRAME_PP_SCHEDULE", "") or "").strip().lower()
    return raw if raw in PP_SCHEDULE_CHOICES else "interleaved"


def tp_size(environ: dict | None = None) -> int:
    """``TPUFRAME_TP_SIZE`` resolved and clamped to its declared domain
    (default 1 = no tensor parallelism); ``parallel.compose.compose``
    reads it when the caller doesn't pass ``tp=`` explicitly."""
    env = os.environ if environ is None else environ
    raw = str(env.get("TPUFRAME_TP_SIZE", "") or "").strip()
    try:
        val = int(raw) if raw else 1
    except ValueError:
        val = 1
    return max(1, min(64, val))


def zero_stage_default(environ: dict | None = None) -> int:
    """``TPUFRAME_ZERO_STAGE`` resolved and clamped to [0, 3] (default 0
    = pure DP); ``parallel.compose.compose`` reads it when the caller
    doesn't pass ``zero_stage=`` explicitly — the memory-bound autotune
    branch proposes its moves through this knob."""
    env = os.environ if environ is None else environ
    raw = str(env.get("TPUFRAME_ZERO_STAGE", "") or "").strip()
    try:
        val = int(raw) if raw else 0
    except ValueError:
        val = 0
    return max(0, min(3, val))


def offload_optimizer_default(environ: dict | None = None) -> bool:
    """``TPUFRAME_OFFLOAD_OPTIMIZER`` as a bool (default off); the
    ``compose(offload_optimizer=...)`` parameter wins when passed
    explicitly.  The plan still downgrades loudly when the backend has
    no addressable host memory space."""
    env = os.environ if environ is None else environ
    raw = str(env.get("TPUFRAME_OFFLOAD_OPTIMIZER", "") or "").strip().lower()
    return raw not in _FALSY
