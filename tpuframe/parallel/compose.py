"""Plan composition: one declaration, one ``ParallelPlan``, N dimensions.

TorchTitan (arXiv:2410.06511) made composable N-D parallelism a
production requirement: DP, ZeRO sharding, tensor parallelism, pipeline
stages, and sequence sharding are *one* configuration, not five
subsystems glued per-run.  tpuframe's pieces all exist — the mesh axes
(``core.runtime.AXIS_ORDER``), the ZeRO ladder and TP rules
(``parallel.sharding``), the SPMD GPipe schedule (``parallel.pipeline``),
the compressed/fused wire (``parallel.compression``) — but each caller
had to assemble them by hand.  :func:`compose` is the one assembly
point:

>>> plan = compose(tp=2, pp=2, zero_stage=3, microbatches=8)

yields a :class:`~tpuframe.parallel.sharding.ParallelPlan` over a
``pipe × data × fsdp × seq × model`` mesh whose

- TP rules place the vocab-parallel embed/head matrices on ``model``
  (the GSPMD region outside the pipeline's ``shard_map`` — XLA inserts
  the TP collectives),
- pipeline rule stores layer-stacked block params sharded over ``pipe``
  (the exact layout ``gpipe_spmd``'s ``in_specs`` consume — no reshard
  on entry),
- ``pp_microbatches``/``pp_schedule`` pins ride the plan signature, so
  the composed fit AOT-precompiles under the compile spine and a
  schedule change is a *different plan*, never a silent recompile,
- ZeRO stage and the comms-wire knobs pass through untouched — the
  compressed gradient sync composes with stage-3 gather-on-use params
  (``train.step`` owns that math).

Everything downstream — topology manifests, checkpoints portable across
*plan* changes, ``rebind()``/shrink-to-survivors, autotune store keys —
keys on the composed plan's signature exactly as it does for hand-built
plans, because the result IS a plain ``ParallelPlan``.
"""

from __future__ import annotations

from typing import Any, Sequence

from jax.sharding import Mesh, PartitionSpec as P

from tpuframe.core.runtime import (
    DATA_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPELINE_AXIS,
    SEQUENCE_AXIS,
    MeshSpec,
)
from tpuframe.parallel.comms_env import (
    offload_optimizer_default,
    pp_microbatches,
    pp_schedule,
    tp_size,
    zero_stage_default,
)
from tpuframe.parallel.sharding import ParallelPlan, Rule, mesh_axes

__all__ = ["compose", "default_tp_rules", "pipeline_rules"]


def default_tp_rules(axis: str = MODEL_AXIS) -> tuple[Rule, ...]:
    """Vocab-parallel tensor-parallel rules for the transformer LMs:
    the embedding table splits its vocab rows and the (untied) LM head
    splits its vocab columns over ``axis``.  These leaves live in the
    GSPMD region (outside the pipeline's ``shard_map``), so XLA
    propagates the sharding and inserts the TP collectives; block
    params are deliberately NOT matched — inside the pipeline they are
    stage-sharded over ``pipe`` and replicated over ``model``."""
    return (
        (r"embed_head/embed/embedding$", P(axis, None)),
        (r"embed_head/lm_head/kernel$", P(None, axis)),
    )


def pipeline_rules(axis: str = PIPELINE_AXIS) -> tuple[Rule, ...]:
    """Stage-sharding rule for layer-stacked pipeline block params: the
    leading (layer) dim lives on ``pipe`` — the storage layout
    ``gpipe_spmd`` consumes directly, so checkpoint manifests, ZeRO
    state sharding, and the pipeline's ``in_specs`` all agree on where
    every stage's weights live."""
    return ((r"(^|/)blocks/", P(axis)),)


def compose(
    *,
    mesh: Mesh | None = None,
    dp: int = -1,
    fsdp: int = 1,
    tp: int | None = None,
    pp: int = 1,
    sp: int = 1,
    zero_stage: int | None = None,
    microbatches: int | None = None,
    schedule: str | None = None,
    rules: Sequence[Rule] = (),
    min_shard_elems: int = 2**14,
    offload_optimizer: bool | None = None,
    comms_groups: int | None = None,
    comms_fused: bool | None = None,
    devices: Any = None,
) -> ParallelPlan:
    """Declare "DP×ZeRO×TP×PP×SP over this topology"; get one plan.

    Args:
      mesh: an already-built mesh to compose over; when None, a
        ``MeshSpec(pipe=pp, data=dp, fsdp=fsdp, seq=sp, model=tp)`` is
        built over ``devices`` (default: all visible).  When a mesh IS
        passed, the dimension arguments must agree with its axis sizes
        (loud mismatch — a plan that silently ignores ``tp=4`` on a
        ``model=1`` mesh is exactly the composition bug this exists to
        prevent).
      dp / fsdp / tp / pp / sp: axis sizes (``dp=-1`` absorbs the
        remainder; ``tp=None`` resolves ``TPUFRAME_TP_SIZE``, default 1).
      zero_stage: the DeepSpeed ladder (0..3) — stage 3 shards params
        over ``fsdp`` with gather-on-use; composes with ``tp``/``pp``
        rules and with the compressed wire.  ``None`` resolves
        ``TPUFRAME_ZERO_STAGE`` (default 0) — the knob the memory-bound
        autotune branch moves; ``offload_optimizer=None`` likewise
        resolves ``TPUFRAME_OFFLOAD_OPTIMIZER`` (default off).
      microbatches: pipeline microbatch pin (None resolves
        ``TPUFRAME_PP_MICROBATCHES``; 0/unset leaves the model default).
      schedule: pipeline interleave pin (None resolves
        ``TPUFRAME_PP_SCHEDULE``).  Env-resolved values are written INTO
        the plan fields, so the signature names the program that
        actually runs.
      rules: extra TP rules, matched BEFORE the derived defaults (first
        match wins — a caller rule overrides the vocab-parallel default).

    Emits one ``parallel/compose`` event carrying the resolved
    dimensions and the composed signature.
    """
    from tpuframe.track.telemetry import get_telemetry

    if tp is None:
        tp = tp_size()
    if zero_stage is None:
        zero_stage = zero_stage_default()
    if offload_optimizer is None:
        offload_optimizer = offload_optimizer_default()
    if mesh is None:
        mesh = MeshSpec(
            pipe=pp, data=dp, fsdp=fsdp, seq=sp, model=tp
        ).build(devices)
    axes = mesh_axes(mesh)
    declared = {
        PIPELINE_AXIS: pp, FSDP_AXIS: fsdp, MODEL_AXIS: tp, SEQUENCE_AXIS: sp,
    }
    if dp != -1:
        declared[DATA_AXIS] = dp
    mismatch = {
        name: (size, axes.get(name, 1))
        for name, size in declared.items()
        if size != -1 and axes.get(name, 1) != size
    }
    if mismatch:
        raise ValueError(
            "composed dimensions disagree with the mesh: "
            + ", ".join(
                f"{name}={want} declared but mesh has {have}"
                for name, (want, have) in sorted(mismatch.items())
            )
            + " — pass a matching mesh or let compose() build one"
        )
    pp = int(axes.get(PIPELINE_AXIS, 1))
    tp = int(axes.get(MODEL_AXIS, 1))

    composed_rules: list[Rule] = list(rules)
    if tp > 1:
        composed_rules.extend(default_tp_rules())
    if pp > 1:
        composed_rules.extend(pipeline_rules())

    if microbatches is None:
        microbatches = pp_microbatches() or None
    if schedule is None:
        schedule = pp_schedule()
    plan = ParallelPlan(
        mesh=mesh,
        zero_stage=zero_stage,
        rules=tuple(composed_rules),
        min_shard_elems=min_shard_elems,
        offload_optimizer=offload_optimizer,
        comms_groups=comms_groups,
        comms_fused=comms_fused,
        # schedule pins only exist where there IS a pipeline: a pp=1
        # plan keeps the None defaults so its signature is byte-stable
        # with every pre-existing plan over the same mesh
        pp_microbatches=microbatches if pp > 1 else None,
        pp_schedule=schedule if pp > 1 else None,
    )
    get_telemetry().event(
        "parallel/compose",
        mesh_axes=axes,
        world=int(mesh.devices.size),
        dp=int(axes.get(DATA_AXIS, 1)),
        fsdp=int(axes.get(FSDP_AXIS, 1)),
        tp=tp,
        pp=pp,
        sp=int(axes.get(SEQUENCE_AXIS, 1)),
        zero_stage=int(zero_stage),
        microbatches=plan.pp_microbatches,
        schedule=plan.pp_schedule,
        rules=len(composed_rules),
        signature=plan.signature(),
    )
    return plan
