"""The serving SLO plane: declared objectives + rolling burn-rate gauges.

An SLO is a *declared* contract — "p99 under ``TPUFRAME_SLO_P99_MS``,
availability at least ``TPUFRAME_SLO_AVAILABILITY``" — and the fleet's
health is how fast it is spending the error budget that contract allows,
not a raw error count.  :class:`SloTracker` keeps a rolling window of
request outcomes and exports two gauges on the existing telemetry spine
(so they ride every ``/metrics`` page for free):

- ``slo/burn_rate`` — the rate the error budget is being consumed,
  normalized so 1.0 means "burning exactly the allowed budget" (a
  violation fraction of ``1 - availability``).  >1 is an incident
  brewing; sustained >>1 is the page.
- ``slo/error_budget`` — the remaining budget fraction over the window,
  ``max(0, 1 - burn_rate)``.

A request is *bad* when it failed (shed/rejected/errored) or when it
was served over the p99 objective — latency violations spend the same
budget as errors, which is what makes the burn rate a routing/promotion
signal rather than an uptime vanity metric.

Every tracker announces its contract as one ``slo/objectives`` event at
construction, so ``track analyze`` can score a telemetry dir against the
objectives that were actually in force (``skew_report.serve_trace.slo``)
instead of whatever env the analyzing host happens to have.

Deployed at both ends of the request path: each :class:`ServeEngine`
tracks its own served/shed outcomes, and the fleet :class:`Router`
tracks every routed request — the router's gauges are therefore the
fleet-wide aggregate (one scrape of the router ``/metrics`` answers "is
the fleet inside its SLO", no per-replica fan-out).

Stdlib-only, like the admission/router layer it instruments.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time

from tpuframe.fault.health import _env_float
from tpuframe.track.telemetry import get_telemetry

__all__ = ["SloObjectives", "SloTracker"]


def _strict_float(name: str, default: float) -> float:
    """Env float that *raises* on garbage — the doctor's strict read, so
    a malformed ``TPUFRAME_SLO_*`` is reported instead of silently
    replaced by the default the tolerant path would use."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None


@dataclasses.dataclass(frozen=True)
class SloObjectives:
    """The declared serving objectives (env-tunable, live-apply).

    Attributes:
      p99_ms: served-latency objective — a request slower than this is
        an SLO violation even though the client got an answer.
      availability: minimum good-request fraction; ``1 - availability``
        is the error budget the burn rate is normalized against.
    """

    p99_ms: float = 500.0
    availability: float = 0.999

    @classmethod
    def from_env(cls, *, strict: bool = False) -> "SloObjectives":
        """Tolerant by default (malformed/out-of-range env reads as the
        default — a typo'd objective must not take a serving box down);
        ``strict=True`` raises ``ValueError`` instead, for the doctor's
        report-don't-crash idiom."""
        d = cls()
        if strict:
            p99_ms = _strict_float("TPUFRAME_SLO_P99_MS", d.p99_ms)
            availability = _strict_float(
                "TPUFRAME_SLO_AVAILABILITY", d.availability
            )
            if not p99_ms >= 1.0:
                raise ValueError(
                    f"TPUFRAME_SLO_P99_MS={p99_ms} must be >= 1.0"
                )
            if not 0.0 < availability <= 1.0:
                raise ValueError(
                    f"TPUFRAME_SLO_AVAILABILITY={availability} must be in "
                    "(0, 1]"
                )
            return cls(p99_ms=p99_ms, availability=availability)
        p99_ms = _env_float("TPUFRAME_SLO_P99_MS", d.p99_ms)
        availability = _env_float("TPUFRAME_SLO_AVAILABILITY", d.availability)
        if not p99_ms >= 1.0:
            p99_ms = d.p99_ms
        if not 0.0 < availability <= 1.0:
            availability = d.availability
        return cls(p99_ms=p99_ms, availability=availability)


class SloTracker:
    """Rolling-window burn-rate/error-budget gauges for one vantage point.

    ``observe()`` is called once per request outcome (engine: served /
    shed / rejected; router: every routed reply) and is cheap enough for
    the hot path — one deque append + two gauge stores under a lock.
    """

    def __init__(self, objectives: SloObjectives | None = None, *,
                 window_s: float = 60.0, source: str | None = None):
        self.objectives = objectives or SloObjectives.from_env()
        self.window_s = float(window_s)
        self._samples: collections.deque = collections.deque()  # (mono, bad)
        self._bad = 0
        self._lock = threading.Lock()
        tele = get_telemetry()
        self._g_burn = tele.registry.gauge("slo/burn_rate")
        self._g_budget = tele.registry.gauge("slo/error_budget")
        # announce the contract in force — the analyzer scores the dir
        # against this record, not the analyzing host's env
        tele.event(
            "slo/objectives",
            p99_ms=self.objectives.p99_ms,
            availability=self.objectives.availability,
            window_s=self.window_s,
            **({"source": source} if source else {}),
        )

    def observe(self, latency_s: float | None = None, *,
                ok: bool = True) -> None:
        """Record one request outcome: ``ok=False`` for shed/rejected/
        errored, otherwise bad iff the served latency broke the p99
        objective."""
        bad = (not ok) or (
            latency_s is not None
            and latency_s * 1e3 > self.objectives.p99_ms
        )
        now = time.monotonic()
        with self._lock:
            self._samples.append((now, bad))
            if bad:
                self._bad += 1
            self._evict_locked(now)
            burn, budget = self._rates_locked()
        self._g_burn.set(burn)
        self._g_budget.set(budget)

    def _evict_locked(self, now: float) -> None:
        horizon = now - self.window_s
        while self._samples and self._samples[0][0] < horizon:
            _, bad = self._samples.popleft()
            if bad:
                self._bad -= 1

    def _rates_locked(self) -> tuple[float, float]:
        total = len(self._samples)
        if total == 0:
            return 0.0, 1.0
        allowed = max(1e-9, 1.0 - self.objectives.availability)
        burn = (self._bad / total) / allowed
        return burn, max(0.0, 1.0 - burn)

    def snapshot(self) -> dict:
        """Current window state (doctor/tests): objectives + counts +
        the two gauge values."""
        with self._lock:
            self._evict_locked(time.monotonic())
            total = len(self._samples)
            bad = self._bad
            burn, budget = self._rates_locked()
        return {
            "p99_ms": self.objectives.p99_ms,
            "availability": self.objectives.availability,
            "window_s": self.window_s,
            "requests": total,
            "violations": bad,
            "burn_rate": round(burn, 4),
            "error_budget_remaining": round(budget, 4),
        }
