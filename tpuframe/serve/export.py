"""StableHLO model export/load (the torchscript/ONNX-export analogue).

``jax.export`` serializes the jitted inference function — model code,
weights (as constants), and any fused preprocessing — into one portable
StableHLO blob with versioning guarantees.  The batch dimension is
symbolic by default, so one artifact serves any batch size.

Why this shape: a TPU-trained model usually ships to a serving runtime
that has neither the training repo nor flax installed.  A checkpoint
(`tpuframe.ckpt`) needs the model class to rebuild; the exported artifact
needs only jax.  (For torch serving, `models/interop.export_torch_resnet`
is the other exit.)
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax import export as jax_export

_MAGIC = "tpuframe-export"
_VERSION = 1


class ExportedModel:
    """A loaded artifact: ``__call__`` runs inference on numpy/jax arrays."""

    def __init__(self, exported: jax_export.Exported, meta: dict):
        self._exported = exported
        self.meta = meta

    def __call__(self, x: Any) -> jax.Array:
        return self._exported.call(x)

    @property
    def input_shape(self) -> tuple:
        return tuple(self.meta["input_shape"])


def export_model(
    model: Any,
    variables: Any,
    sample_input: np.ndarray | jax.Array,
    path: str | os.PathLike,
    *,
    preprocess: Callable | None = None,
    batch_polymorphic: bool = True,
    apply_kwargs: dict | None = None,
    platforms: Sequence[str] | None = None,
) -> str:
    """Serialize eval-mode ``model.apply(variables, preprocess(x))`` to ``path``.

    Args:
      model: flax module (``apply(variables, x, **apply_kwargs)``).
      variables: the trained variables pytree (baked into the artifact).
      sample_input: one example batch — fixes dtype and trailing shape;
        its leading dim becomes symbolic when ``batch_polymorphic``.
      preprocess: optional fn fused in FRONT of the model (e.g. the
        uint8 ``ops.normalize_images`` transform), so the artifact takes
        raw bytes and owns its own normalization constants.
      batch_polymorphic: one artifact for any batch size (default).
      apply_kwargs: extra kwargs for ``model.apply``.  ``train=False`` is
        added automatically when the module's ``__call__`` accepts a
        ``train`` parameter (modules without one export as-is).
      platforms: lowering platforms, e.g. ``("cpu", "tpu")``; default is
        the current backend only.

    Returns the written path.  The artifact is self-contained: load it
    with :func:`load_model` anywhere jax runs.
    """
    kwargs = dict(apply_kwargs or {})
    if "train" not in kwargs:
        import inspect

        try:
            params = inspect.signature(type(model).__call__).parameters
            takes_train = "train" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
            )
        except (TypeError, ValueError):  # exotic callables: assume flax norm
            takes_train = True
        if takes_train:
            kwargs["train"] = False

    def infer(x):
        if preprocess is not None:
            x = preprocess(x)
        return model.apply(variables, x, **kwargs)

    sample = np.asarray(sample_input)
    if batch_polymorphic:
        dims = ", ".join(["b"] + [str(d) for d in sample.shape[1:]])
        shape = jax_export.symbolic_shape(dims)
    else:
        shape = sample.shape
    spec = jax.ShapeDtypeStruct(shape, sample.dtype)
    exported = jax_export.export(
        jax.jit(infer),
        platforms=tuple(platforms) if platforms else None,
    )(spec)
    blob = exported.serialize()

    meta = {
        "magic": _MAGIC,
        "version": _VERSION,
        "input_shape": list(sample.shape),
        "input_dtype": str(sample.dtype),
        "batch_polymorphic": batch_polymorphic,
        "model": type(model).__name__,
        "platforms": list(exported.platforms),
        "param_bytes": int(
            sum(
                np.asarray(jax.device_get(leaf)).nbytes
                for leaf in jax.tree.leaves(variables)
            )
        ),
    }
    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    header = json.dumps(meta).encode("utf-8")
    with open(path, "wb") as f:
        f.write(len(header).to_bytes(8, "little"))
        f.write(header)
        f.write(blob)
    return path


_MAX_HEADER = 1 << 20  # far above any real meta; rejects garbage lengths


def load_model(path: str | os.PathLike) -> ExportedModel:
    """Load an :func:`export_model` artifact; no model code needed.

    Any non-artifact file raises ``ValueError`` — the first 8 bytes of
    arbitrary binaries decode to arbitrary "header lengths", so the
    length is bounds-checked and header parse failures are wrapped
    rather than surfacing as MemoryError/UnicodeDecodeError.
    """
    path = os.fspath(path)
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        header_len = int.from_bytes(f.read(8), "little")
        if not 2 <= header_len <= min(_MAX_HEADER, size):
            raise ValueError(f"{path} is not a tpuframe export artifact")
        try:
            meta = json.loads(f.read(header_len).decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(
                f"{path} is not a tpuframe export artifact"
            ) from e
        if not isinstance(meta, dict) or meta.get("magic") != _MAGIC:
            raise ValueError(f"{path} is not a tpuframe export artifact")
        if meta.get("version") != _VERSION:
            raise ValueError(
                f"unsupported artifact version {meta.get('version')}"
            )
        blob = f.read()
    return ExportedModel(jax_export.deserialize(blob), meta)
