"""StableHLO model export/load (the torchscript/ONNX-export analogue).

``jax.export`` serializes the jitted inference function — model code,
weights (as constants), and any fused preprocessing — into one portable
StableHLO blob with versioning guarantees.  The batch dimension is
symbolic by default, so one artifact serves any batch size.

Why this shape: a TPU-trained model usually ships to a serving runtime
that has neither the training repo nor flax installed.  A checkpoint
(`tpuframe.ckpt`) needs the model class to rebuild; the exported artifact
needs only jax.  (For torch serving, `models/interop.export_torch_resnet`
is the other exit.)

jax is imported lazily: the module (and the header parse it shares with
the doctor via ``serve.admission.read_export_meta``) must stay usable
while the backend is wedged.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Sequence

import numpy as np

from tpuframe.serve.admission import InvalidRequest, read_export_meta

_MAGIC = "tpuframe-export"
_VERSION = 1


class ExportedModel:
    """A loaded artifact: ``__call__`` runs inference on numpy/jax arrays.

    Calls are validated against the exported signature first: a wrong
    dtype or trailing shape raises a ``ValueError`` naming what the
    artifact expects, instead of surfacing as an opaque XLA shape error
    deep inside ``exported.call`` (or worse, a silent implicit cast).
    """

    def __init__(self, exported: Any, meta: dict):
        self._exported = exported
        self.meta = meta

    def _validate(self, x: Any) -> None:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        want_trailing = tuple(self.meta["input_shape"][1:])
        want_dtype = self.meta["input_dtype"]
        lead = "b" if self.meta.get("batch_polymorphic", True) \
            else self.meta["input_shape"][0]
        expected = f"({lead}, {', '.join(map(str, want_trailing))}) {want_dtype}"
        if shape is None or dtype is None:
            raise ValueError(
                f"expected an array of shape {expected}; got "
                f"{type(x).__name__}"
            )
        if len(shape) != 1 + len(want_trailing) \
                or tuple(shape[1:]) != want_trailing \
                or (not self.meta.get("batch_polymorphic", True)
                    and int(shape[0]) != int(self.meta["input_shape"][0])):
            raise ValueError(
                f"input shape {tuple(shape)} does not match the exported "
                f"signature {expected} (model={self.meta.get('model')})"
            )
        if str(dtype) != want_dtype:
            raise ValueError(
                f"input dtype {dtype} does not match the exported "
                f"signature {expected} — cast before calling "
                f"(model={self.meta.get('model')})"
            )

    def __call__(self, x: Any) -> Any:
        self._validate(x)
        return self._exported.call(x)

    @property
    def input_shape(self) -> tuple:
        return tuple(self.meta["input_shape"])


def export_model(
    model: Any,
    variables: Any,
    sample_input: "np.ndarray | Any",
    path: str | os.PathLike,
    *,
    preprocess: Callable | None = None,
    batch_polymorphic: bool = True,
    apply_kwargs: dict | None = None,
    platforms: Sequence[str] | None = None,
) -> str:
    """Serialize eval-mode ``model.apply(variables, preprocess(x))`` to ``path``.

    Args:
      model: flax module (``apply(variables, x, **apply_kwargs)``).
      variables: the trained variables pytree (baked into the artifact).
      sample_input: one example batch — fixes dtype and trailing shape;
        its leading dim becomes symbolic when ``batch_polymorphic``.
      preprocess: optional fn fused in FRONT of the model (e.g. the
        uint8 ``ops.normalize_images`` transform), so the artifact takes
        raw bytes and owns its own normalization constants.
      batch_polymorphic: one artifact for any batch size (default).
      apply_kwargs: extra kwargs for ``model.apply``.  ``train=False`` is
        added automatically when the module's ``__call__`` accepts a
        ``train`` parameter (modules without one export as-is).
      platforms: lowering platforms, e.g. ``("cpu", "tpu")``; default is
        the current backend only.

    Returns the written path.  The artifact is self-contained: load it
    with :func:`load_model` anywhere jax runs.
    """
    import jax
    from jax import export as jax_export

    kwargs = dict(apply_kwargs or {})
    if "train" not in kwargs:
        import inspect

        try:
            params = inspect.signature(type(model).__call__).parameters
            takes_train = "train" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
            )
        except (TypeError, ValueError):  # exotic callables: assume flax norm
            takes_train = True
        if takes_train:
            kwargs["train"] = False

    def infer(x):
        if preprocess is not None:
            x = preprocess(x)
        return model.apply(variables, x, **kwargs)

    sample = np.asarray(sample_input)
    if batch_polymorphic:
        dims = ", ".join(["b"] + [str(d) for d in sample.shape[1:]])
        shape = jax_export.symbolic_shape(dims)
    else:
        shape = sample.shape
    spec = jax.ShapeDtypeStruct(shape, sample.dtype)
    exported = jax_export.export(
        jax.jit(infer),
        platforms=tuple(platforms) if platforms else None,
    )(spec)
    blob = exported.serialize()

    meta = {
        "magic": _MAGIC,
        "version": _VERSION,
        "input_shape": list(sample.shape),
        "input_dtype": str(sample.dtype),
        "batch_polymorphic": batch_polymorphic,
        "model": type(model).__name__,
        "platforms": list(exported.platforms),
        "param_bytes": int(
            sum(
                np.asarray(jax.device_get(leaf)).nbytes
                for leaf in jax.tree.leaves(variables)
            )
        ),
    }
    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    header = json.dumps(meta).encode("utf-8")
    with open(path, "wb") as f:
        f.write(len(header).to_bytes(8, "little"))
        f.write(header)
        f.write(blob)
    return path


def load_model(path: str | os.PathLike) -> ExportedModel:
    """Load an :func:`export_model` artifact; no model code needed.

    Any non-artifact file raises ``ValueError`` (the bounds-checked
    header parse is shared with the doctor:
    :func:`tpuframe.serve.admission.read_export_meta`).  The meta
    version is checked with direction-aware messages: a NEWER blob says
    "upgrade tpuframe", not just "unsupported".
    """
    from jax import export as jax_export

    path = os.fspath(path)
    meta = read_export_meta(path)
    version = meta.pop("version", None)
    offset = meta.pop("_blob_offset")
    if version != _VERSION:
        if isinstance(version, int) and version > _VERSION:
            raise ValueError(
                f"{path} was written by a newer tpuframe (artifact "
                f"version {version} > supported {_VERSION}) — upgrade "
                "tpuframe on this serving host to load it"
            )
        raise ValueError(
            f"unsupported artifact version {version}"
        )
    meta["version"] = version
    with open(path, "rb") as f:
        f.seek(offset)
        blob = f.read()
    return ExportedModel(jax_export.deserialize(blob), meta)


# re-exported for callers that validated payloads at the engine door
# before reaching the model (one exception type across the serve stack)
__all__ = ["ExportedModel", "InvalidRequest", "export_model", "load_model"]
