"""Deadline-aware dynamic-batching inference engine on the existing spines.

The repo's training side survives kills, shrinks, and divergence (PRs
3/6/7); this is the same robustness discipline applied to the request
path.  :class:`ServeEngine` turns an exported model (or any jit-able
callable) into a bounded-latency server component:

- **Bucketed dynamic batching.**  Requests batch into a small closed set
  of padded bucket shapes (``ServeKnobs.buckets``), every bucket
  AOT-precompiled at :meth:`start` via ``compile.precompile`` — the
  armed :class:`~tpuframe.compile.precompile.ShapeGuard` makes any stray
  runtime shape one loud ``compile/recompile`` event.  Host-side batch
  assembly reuses :class:`~tpuframe.data.loader.BatchBufferPool` leases
  (one small pool per bucket; steady-state assembly allocations are
  zero, and the pool's aliasing guards carry over unchanged).
- **Deadlines propagated into scheduling.**  Every request carries a
  deadline (client-set, default the SLO); a request whose deadline
  expired *in the queue* is shed before it wastes a batch slot on an
  answer the client already abandoned.
- **Admission control.**  The bounded queue + explicit verdicts live in
  :class:`~tpuframe.serve.admission.AdmissionController`; door-side
  validation (:func:`~tpuframe.serve.admission.validate_payload`)
  rejects malformed/poison payloads before they can NaN a batch.
- **Graceful drain.**  ``drain()`` — or a SIGTERM via the process-wide
  :class:`~tpuframe.fault.preempt.PreemptionWatcher`, polled at batch
  boundaries — flips admission to reject-new, finishes every in-flight
  request, flushes telemetry, and stops.  Zero dropped in-flight work.
- **Watchdog lease.**  Each backend inference call runs under a
  ``serve/infer`` watchdog guard, so a wedged backend produces an
  attributed stall report instead of a silent hang.
- **Isolation.**  A backend error fails only the requests in that batch
  (``serve/errors``); the loop keeps serving.

Chaos sites (``fault.chaos``): ``serve/submit`` (PoisonRequest corrupts
the payload upstream of validation), ``serve/enqueue`` (QueueFlood
floods the queue with synthetic load), ``serve/infer`` (SlowConsumer /
RaiseAt wedge or fail the backend call) — every degradation path is
deterministically testable on CPU.

Telemetry: ``serve/latency`` + ``serve/batch_occupancy`` histograms,
``serve/queue_depth``/``serve/draining`` gauges, admit/shed/reject/
invalid/error counters, one ``serve/request`` event per served request
(what ``track analyze`` builds its ``serve_latency`` block from), and a
rate-limited ``serve/rejected``/``serve/shed`` event stream (first
occurrence per verdict always logs; steady-state overload counts
instead of flooding the JSONL log).

Request-path tracing: a request submitted with a ``trace`` id (the
router mints one; clients can supply ``X-Trace-Id``) gets per-hop spans
— ``serve/door`` (validation), ``serve/queue_wait`` (submit to batch
assembly), ``serve/assemble``/``serve/infer`` (batch-scoped, fanned out
to every member trace) — that ``track analyze`` stitches into the
``serve_trace`` block and the Perfetto timeline.  Untraced requests pay
nothing.  Every outcome also feeds the :class:`~tpuframe.serve.slo.
SloTracker` burn-rate/error-budget gauges.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time
from typing import Any

import numpy as np

from tpuframe.data.loader import BatchBufferPool
from tpuframe.fault import chaos
from tpuframe.serve.admission import (
    AdmissionController,
    InvalidRequest,
    RequestRejected,
    RequestShed,
    ServeKnobs,
    validate_payload,
)
from tpuframe.serve.slo import SloTracker
from tpuframe.track.telemetry import get_telemetry

__all__ = ["ServeEngine", "ServeResult"]


class ServeResult:
    """Future-like handle for one submitted request.

    ``result(timeout)`` blocks for the value; a shed request raises
    :class:`RequestShed`, a backend failure re-raises the batch's error.
    """

    __slots__ = ("id", "verdict", "latency_s", "_event", "_value", "_error")

    def __init__(self, rid: int):
        self.id = rid
        self.verdict: str | None = None
        self.latency_s: float | None = None
        self._event = threading.Event()
        self._value = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} not completed in {timeout}s")
        if self._error is not None:
            raise self._error
        return self._value

    def _complete(self, value, verdict: str, latency_s: float) -> None:
        self._value = value
        self.verdict = verdict
        self.latency_s = latency_s
        self._event.set()

    def _fail(self, error: BaseException, verdict: str) -> None:
        self._error = error
        self.verdict = verdict
        self._event.set()


class _Request:
    __slots__ = ("payload", "res", "t_submit", "deadline", "synthetic",
                 "trace")

    def __init__(self, payload, res: ServeResult | None, t_submit: float,
                 deadline: float, synthetic: bool = False,
                 trace: str | None = None):
        self.payload = payload
        self.res = res
        self.t_submit = t_submit
        self.deadline = deadline
        self.synthetic = synthetic
        # request-path trace id (router-minted or client-supplied);
        # None means untraced — the hot path emits nothing extra
        self.trace = trace


class _RateLimitedEvents:
    """At most one JSONL event per (name, verdict) per ``interval_s`` —
    overload is precisely when per-occurrence events would bury the log;
    counters carry the volume, the first event carries the news."""

    def __init__(self, interval_s: float = 1.0):
        self.interval_s = interval_s
        self._last: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def emit(self, tele, name: str, **fields) -> None:
        key = (name, fields.get("verdict"))
        now = time.monotonic()
        with self._lock:
            if now - self._last.get(key, -1e9) < self.interval_s:
                return
            self._last[key] = now
        tele.event(name, **fields)


class ServeEngine:
    """Dynamic-batching engine over an exported model or jit-able callable.

    Args:
      model: an :class:`~tpuframe.serve.export.ExportedModel` (item
        shape/dtype come from its meta) or any callable traced by
        ``jax.jit`` taking one batched array; plain callables must also
        pass ``item_shape=`` and ``dtype=``.
      knobs: :class:`ServeKnobs` (default: from env).
      item_shape / dtype: per-request payload signature (required for
        plain callables; overrides the export meta when given).
      preemption: poll the process-wide preemption watcher at batch
        boundaries and auto-drain on SIGTERM/maintenance notice
        (default True — the serve loop's graceful-exit contract).

    Lifecycle: ``start()`` AOT-precompiles every bucket and starts the
    batcher thread; ``submit()`` returns a :class:`ServeResult`;
    ``drain()`` finishes in-flight work and stops.  Context-managed::

        with ServeEngine(load_model(path)) as eng:
            out = eng.submit(x).result(timeout=5)
    """

    def __init__(
        self,
        model: Any,
        *,
        knobs: ServeKnobs | None = None,
        item_shape: tuple | None = None,
        dtype: Any = None,
        preemption: bool = True,
        replica: int | str | None = None,
    ):
        self.knobs = knobs or ServeKnobs.from_env()
        self.preemption = preemption
        # fleet identity: when set, every serve/request event carries it
        # so the analyzer can break serve_latency out per replica
        self.replica = replica
        meta = getattr(model, "meta", None)
        # model identity for the analyzer's per-model trace breakout
        self.model_name = (meta.get("model") if isinstance(meta, dict)
                           else None)
        if item_shape is None and isinstance(meta, dict):
            item_shape = tuple(meta["input_shape"][1:])
        if dtype is None and isinstance(meta, dict):
            dtype = meta["input_dtype"]
        if item_shape is None or dtype is None:
            raise ValueError(
                "item_shape= and dtype= are required when model is not an "
                "ExportedModel (no meta to derive the request signature from)"
            )
        self.item_shape = tuple(int(s) for s in item_shape)
        self.dtype = np.dtype(dtype)
        # the request signature is fixed per engine, so the pixel budget
        # is decidable ONCE, here — a misconfigured engine fails at
        # construction instead of rejecting 100% of requests at the door
        n_elems = 1
        for s in self.item_shape:
            n_elems *= s
        if n_elems > self.knobs.max_pixels:
            raise ValueError(
                f"request shape {self.item_shape} has {n_elems} elements, "
                f"over the {self.knobs.max_pixels}-element budget "
                "(TPUFRAME_SERVE_MAX_PIXELS)"
            )
        self._fn = model._exported.call if hasattr(model, "_exported") else model
        self._jit = None        # built at start()
        self._compiled: dict[int, Any] = {}
        self._guard = None
        self.buckets = tuple(sorted(self.knobs.buckets))
        self._pools = {
            b: BatchBufferPool(2) for b in self.buckets
        }
        self._admission = AdmissionController(
            cap=self.knobs.queue_cap, policy=self.knobs.shed_policy
        )
        self._rid = itertools.count()
        self._submitted = 0     # chaos-site step counter (door side)
        self._batches = 0       # chaos-site step counter (batcher side)
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._thread: threading.Thread | None = None
        self._started = False
        self._limited = _RateLimitedEvents()
        reg = get_telemetry().registry
        self._c_admitted = reg.counter("serve/admitted")
        self._c_rejected = reg.counter("serve/rejected")
        self._c_shed = reg.counter("serve/shed")
        self._c_invalid = reg.counter("serve/invalid")
        self._c_served = reg.counter("serve/requests_served")
        self._c_batches = reg.counter("serve/batches")
        self._c_errors = reg.counter("serve/errors")
        self._h_latency = reg.histogram("serve/latency")
        self._h_occupancy = reg.histogram("serve/batch_occupancy")
        self._g_draining = reg.gauge("serve/draining")
        # SLO plane: every outcome (served/shed/rejected) feeds the
        # rolling burn-rate/error-budget gauges on this replica's
        # /metrics page; the router keeps the fleet-wide aggregate
        self._slo = SloTracker(source="engine")
        # observed request-batch sizes (bounded; batcher thread appends,
        # the autotuner reads a snapshot) — the empirical distribution
        # tpuframe.autotune.derive_serve_knobs turns into a bucket set
        self._observed_sizes: collections.deque = collections.deque(
            maxlen=4096
        )

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ServeEngine":
        """AOT-precompile every bucket shape, arm the shape guard, start
        the batcher thread.  Idempotent."""
        if self._started:
            return self
        import jax

        from tpuframe.compile.precompile import (
            ShapeGuard,
            batch_signature,
            precompile_call,
        )

        tele = get_telemetry()
        self._jit = jax.jit(self._fn)
        self._guard = ShapeGuard()
        for b in self.buckets:
            spec = jax.ShapeDtypeStruct((b,) + self.item_shape, self.dtype)
            self._compiled[b] = precompile_call(
                self._jit, (spec,), label=f"serve/bucket{b}"
            )
            self._guard.expect("serve", batch_signature({"image": spec}))
        tele.event(
            "serve/started",
            buckets=list(self.buckets),
            slo_ms=self.knobs.slo_ms,
            queue_cap=self.knobs.queue_cap,
            shed_policy=self.knobs.shed_policy,
        )
        self._thread = threading.Thread(
            target=self._loop, name="tpuframe-serve-batcher", daemon=True
        )
        self._started = True
        self._thread.start()
        return self

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.drain()

    @property
    def draining(self) -> bool:
        return self._admission.draining

    def queue_depth(self) -> int:
        return self._admission.depth()

    # -- autotune ------------------------------------------------------------
    def observed_request_sizes(self) -> list[int]:
        """Snapshot of recently observed request-batch sizes (bounded
        window) — the input ``tpuframe.autotune.derive_serve_knobs``
        shapes the bucket set and ``batch_wait_ms`` from."""
        return list(self._observed_sizes)

    def apply_knobs(self, env: dict) -> dict:
        """Apply a derived/tuned serve config to the running engine.

        The live subset (``batch_wait_ms``/``slo_ms``/``watchdog_s``/
        ``shed_policy`` — everything the loop reads off ``self.knobs``
        per call) lands by swapping the frozen knobs object; the
        restart-only subset (``buckets``/``queue_cap``/``max_pixels``,
        baked into the pools and the AOT-compiled set at
        :meth:`start`) is returned unapplied so the caller can export
        it for the next engine.  Returns the same ``{"applied": ...,
        "restart_only": ...}`` shape as ``Trainer.apply_tuned``.
        """
        import dataclasses as _dc

        live_fields = {
            "TPUFRAME_SERVE_BATCH_WAIT_MS": ("batch_wait_ms", float),
            "TPUFRAME_SERVE_SLO_MS": ("slo_ms", float),
            "TPUFRAME_SERVE_WATCHDOG_S": ("watchdog_s", float),
            "TPUFRAME_SERVE_SHED_POLICY": ("shed_policy", str),
        }
        applied: dict[str, str] = {}
        restart_only: dict[str, str] = {}
        updates: dict[str, Any] = {}
        for knob, value in env.items():
            target = live_fields.get(knob)
            if target is None:
                restart_only[knob] = str(value)
                continue
            field, cast = target
            try:
                cast_value = cast(value)
            except (TypeError, ValueError):
                continue
            if field == "shed_policy" and cast_value not in (
                "reject-new", "shed-oldest"
            ):
                continue
            updates[field] = cast_value
            applied[knob] = str(value)
        if updates:
            self.knobs = _dc.replace(self.knobs, **updates)
            if "shed_policy" in updates:
                self._admission.policy = updates["shed_policy"]
        if applied or restart_only:
            get_telemetry().event(
                "autotune/apply", applied=len(applied),
                restart_only=len(restart_only), side="serve",
            )
        return {"applied": applied, "restart_only": restart_only}

    # -- door ----------------------------------------------------------------
    def submit(self, x: Any, *, deadline_ms: float | None = None,
               trace: str | None = None) -> ServeResult:
        """Validate, admit, and enqueue one request.

        Raises :class:`InvalidRequest` (malformed/poison payload) or
        :class:`RequestRejected` (queue full under reject-new, or
        draining) synchronously; otherwise returns a
        :class:`ServeResult` whose ``result()`` yields this request's
        row of the model output.  Under ``shed-oldest`` an admission may
        evict the oldest queued request — *that* request's future fails
        with :class:`RequestShed`.

        ``trace``: request-path trace id (router-minted or client
        ``X-Trace-Id``).  When set, the door validation and every
        downstream hop emit spans tagged with it; when None the request
        path pays nothing extra.
        """
        if not self._started:
            raise RuntimeError("ServeEngine.start() first")
        step = self._submitted
        self._submitted += 1
        tele = get_telemetry()
        # poison injection point: upstream of validation, exactly where
        # a corrupt client payload would enter
        chaos.maybe_fire("serve/submit", step, payload=x, engine=self)
        door = (tele.span("serve/door", trace=trace)
                if trace is not None else contextlib.nullcontext())
        try:
            with door:
                validate_payload(
                    x, item_shape=self.item_shape, dtype=self.dtype,
                    max_pixels=self.knobs.max_pixels,
                )
        except InvalidRequest as e:
            self._c_invalid.inc()
            self._slo.observe(ok=False)
            self._limited.emit(
                tele, "serve/rejected", verdict="invalid", error=str(e)[:300]
            )
            raise
        chaos.maybe_fire("serve/enqueue", step, engine=self)
        now = time.monotonic()
        slo_s = (self.knobs.slo_ms if deadline_ms is None
                 else float(deadline_ms)) / 1e3
        res = ServeResult(next(self._rid))
        req = _Request(x, res, now, now + slo_s, trace=trace)
        verdict, shed = self._admission.offer(req)
        if shed is not None:
            self._shed(shed, "shed-oldest")
        if verdict != "admitted":
            self._c_rejected.inc()
            self._slo.observe(ok=False)
            self._limited.emit(tele, "serve/rejected", verdict=verdict)
            raise RequestRejected(
                f"request rejected: {verdict} (queue_cap="
                f"{self.knobs.queue_cap}, policy={self.knobs.shed_policy})",
                verdict=verdict,
            )
        self._c_admitted.inc()
        return res

    def flood(self, n: int, *, deadline_ms: float | None = None) -> int:
        """Enqueue ``n`` synthetic zero requests straight through
        admission (the :class:`~tpuframe.fault.chaos.QueueFlood`
        injector's hook — deterministic overload without n client
        threads).  Returns how many were admitted; their results are
        discarded."""
        tele = get_telemetry()
        now = time.monotonic()
        slo_s = (self.knobs.slo_ms if deadline_ms is None
                 else float(deadline_ms)) / 1e3
        payload = np.zeros(self.item_shape, self.dtype)
        admitted = 0
        for _ in range(int(n)):
            req = _Request(payload, None, now, now + slo_s, synthetic=True)
            verdict, shed = self._admission.offer(req)
            if shed is not None:
                self._shed(shed, "shed-oldest")
            if verdict == "admitted":
                admitted += 1
                self._c_admitted.inc()
            else:
                self._c_rejected.inc()
                self._limited.emit(tele, "serve/rejected", verdict=verdict,
                                   flood=True)
        tele.event("serve/flood", n=int(n), admitted=admitted)
        return admitted

    # -- drain / stop --------------------------------------------------------
    def drain(self, timeout: float | None = 30.0, *,
              reason: str = "drain") -> bool:
        """Graceful exit: reject new requests, finish every in-flight
        one, flush telemetry.  Returns True when the queue fully
        drained inside ``timeout``."""
        if not self._started:
            return True
        tele = get_telemetry()
        if not self._admission.draining:
            self._g_draining.set(1.0)
            tele.event("serve/drain", reason=reason,
                       queue_depth=self._admission.depth())
            self._admission.start_drain()
        ok = self._drained.wait(timeout)
        if ok and self._thread is not None:
            self._thread.join(timeout=5.0)
        tele.event(
            "serve/drained",
            ok=ok,
            served=int(self._c_served.value),
            shed=int(self._c_shed.value),
            rejected=int(self._c_rejected.value),
        )
        return ok

    def stop(self) -> None:
        """Hard stop (tests/teardown): no new batches after the current
        one; queued requests are shed, not silently dropped."""
        self._stop.set()
        self._admission.start_drain()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        while True:
            req = self._admission.pop_nowait()
            if req is None:
                break
            self._shed(req, "shed-stopped")

    # -- internals -----------------------------------------------------------
    def _shed(self, req: _Request, verdict: str) -> None:
        self._c_shed.inc()
        self._slo.observe(ok=False)
        self._limited.emit(get_telemetry(), "serve/shed", verdict=verdict)
        if req.res is not None:
            req.res._fail(
                RequestShed(f"request shed: {verdict}", verdict=verdict),
                verdict,
            )

    def _maybe_auto_drain(self) -> None:
        if not self.preemption or self._admission.draining:
            return
        from tpuframe.fault import preempt

        w = preempt.active_watcher()
        if w is not None and w.requested:
            get_telemetry().event(
                "serve/drain", reason=f"preempt:{w.reason}",
                queue_depth=self._admission.depth(),
            )
            self._g_draining.set(1.0)
            self._admission.start_drain()

    def _gather(self) -> list[_Request] | None:
        """One batch's worth of live requests (deadline-expired ones
        shed on the way), or None when idle/drained."""
        req = self._admission.pop(timeout=0.05)
        if req is None:
            return None
        now = time.monotonic()
        if now >= req.deadline:
            self._shed(req, "shed-deadline")
            return []
        batch = [req]
        max_bucket = self.buckets[-1]
        hold_until = now + self.knobs.batch_wait_ms / 1e3
        while len(batch) < max_bucket:
            remaining = hold_until - time.monotonic()
            nxt = (self._admission.pop_nowait() if remaining <= 0
                   else self._admission.pop(timeout=min(remaining, 0.005)))
            if nxt is None:
                if remaining <= 0:
                    break
                continue
            if time.monotonic() >= nxt.deadline:
                self._shed(nxt, "shed-deadline")
                continue
            batch.append(nxt)
        return batch

    def _loop(self) -> None:
        import jax

        from tpuframe.compile.precompile import batch_signature

        tele = get_telemetry()
        while True:
            if self._stop.is_set():
                break  # hard stop: stop() sheds the queued remainder
            self._maybe_auto_drain()
            batch = self._gather()
            if batch is None:
                if self._admission.draining and self._admission.depth() == 0:
                    break
                continue
            if not batch:
                continue
            bidx = self._batches
            self._batches += 1
            n = len(batch)
            bucket = next(b for b in self.buckets if b >= n)
            # per-hop attribution for traced members: queue wait ends
            # when this batch starts assembling, so queue_wait + assemble
            # + infer tiles the engine-side request path with no gaps
            traces = [r.trace for r in batch if r.trace is not None]
            if traces:
                t_asm = time.monotonic()
                for r in batch:
                    if r.trace is not None:
                        tele.event(
                            "serve/queue_wait", kind="span",
                            dur_s=round(max(0.0, t_asm - r.t_submit), 6),
                            trace=r.trace, batch=bidx,
                        )
            try:
                chaos.maybe_fire("serve/batch", bidx, n=n, bucket=bucket,
                                 engine=self)
                # batch-scoped spans carry the member trace ids so the
                # analyzer can fan one assemble/infer out to every
                # request that rode the batch
                asm = (tele.span("serve/assemble", batch=bidx, n=n,
                                 traces=traces)
                       if traces else contextlib.nullcontext())
                with asm:
                    pool = self._pools[bucket]
                    lease = pool.acquire(bucket, self.item_shape, self.dtype,
                                         with_valid=False)
                    for i, r in enumerate(batch):
                        np.copyto(lease.images[i], r.payload,
                                  casting="same_kind")
                    for i in range(n, bucket):  # pad by cycling live payloads
                        np.copyto(lease.images[i], batch[i % n].payload,
                                  casting="same_kind")
                    sig = batch_signature({"image": lease.images})
                    self._guard.check("serve", sig)
                # watchdog_s=0 means DISABLED, including any process-wide
                # default deadline — passing None would fall back to it
                wd = (tele.guard("serve/infer", self.knobs.watchdog_s)
                      if self.knobs.watchdog_s > 0 else contextlib.nullcontext())
                with tele.span("serve/infer", batch=bidx, bucket=bucket, n=n,
                               **({"traces": traces} if traces else {})), \
                        wd:
                    chaos.maybe_fire("serve/infer", bidx, engine=self)
                    xd = jax.device_put(lease.images)
                    compiled = self._compiled.get(bucket)
                    out = np.asarray(compiled(xd) if compiled is not None
                                     else self._jit(xd))
                pool.release(lease, device_arrays=xd)
            except Exception as e:  # noqa: BLE001 - batch-scoped isolation
                # OOM forensics first: a RESOURCE_EXHAUSTED on the infer
                # path gets its memory/oom attribution event before the
                # generic batch_error narration
                from tpuframe.track.memory import maybe_oom_event

                maybe_oom_event(e, where="serve/infer", step=bidx)
                self._c_errors.inc()
                tele.event("serve/batch_error", batch=bidx,
                           error=f"{type(e).__name__}: {e}"[:300])
                for r in batch:
                    self._slo.observe(ok=False)
                    if r.res is not None:
                        r.res._fail(e, "error")
                continue
            done = time.monotonic()
            self._h_occupancy.observe(n / bucket)
            self._observed_sizes.append(n)
            self._c_batches.inc()
            for i, r in enumerate(batch):
                lat = done - r.t_submit
                self._h_latency.observe(lat)
                self._c_served.inc()
                self._slo.observe(lat)
                tele.event("serve/request", latency_s=round(lat, 6),
                           batch=bidx, verdict="ok",
                           **({"replica": self.replica}
                              if self.replica is not None else {}),
                           **({"trace": r.trace}
                              if r.trace is not None else {}),
                           **({"model": self.model_name}
                              if self.model_name else {}),
                           **({"synthetic": True} if r.synthetic else {}))
                if r.res is not None:
                    r.res._complete(out[i], "ok", lat)
        self._drained.set()


# one import surface for the typed errors callers catch around submit()
ServeEngine.InvalidRequest = InvalidRequest
ServeEngine.RequestRejected = RequestRejected
ServeEngine.RequestShed = RequestShed
