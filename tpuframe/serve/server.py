"""HTTP front for the serve engine: predict + health + metrics.

A thin stdlib ``ThreadingHTTPServer`` over :class:`ServeEngine` (the
same shape as ``track.http_store.MetricsServer`` — no framework deps on
a serving box).  Endpoints:

- ``POST /predict`` — body is an ``.npy`` blob (``np.save`` of one
  request payload; content-type anything).  Optional header
  ``X-Deadline-Ms`` propagates the client deadline into scheduling;
  optional ``X-Trace-Id`` (router-minted or client-supplied, sanitized
  at the door) arms per-hop request tracing through the engine and is
  echoed back on the response.
  Responses carry the admission verdict as an HTTP status: 200 served
  (JSON ``{"output": [...], "latency_ms": ...}``), 400 invalid payload,
  429 shed/rejected under load (clients should back off), 503 draining
  (the replica is going away — retry elsewhere).  429/503 carry a
  ``Retry-After`` header derived from the current queue depth and batch
  wait — well-behaved clients back off for roughly one queue-drain
  instead of hammering a shedding replica.
- ``GET /healthz`` — ``{"status": "ok"|"draining", "draining": bool,
  "queue_depth": N}``; a load balancer (the fleet :class:`Router`) drops
  a draining replica from rotation and least-loads on ``queue_depth``.
- ``GET /metrics`` — Prometheus text from the process registry (the
  serve histograms/gauges/counters ride the existing telemetry spine).

``run_forever()`` installs the process-wide preemption watcher, so a
platform SIGTERM follows the graceful ladder: stop admitting, finish
in-flight requests, flush telemetry, exit 0.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import io
import json
import threading
from typing import Any

from tpuframe.serve.admission import (
    InvalidRequest,
    RequestRejected,
    RequestShed,
    sanitize_trace_id,
)

__all__ = ["ServingServer"]


class ServingServer:
    """Serve ``engine`` over HTTP from a daemon thread.

    ``port=0`` picks a free port; read it back from ``.port``/``.url``.
    """

    def __init__(self, engine: Any, *, host: str = "127.0.0.1", port: int = 0,
                 result_timeout_s: float = 60.0):
        import numpy as np
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from tpuframe.track.telemetry import get_telemetry

        self.engine = engine
        self.result_timeout_s = float(result_timeout_s)
        # one request payload, exactly: item bytes + .npy header slack
        item = np.zeros(engine.item_shape, engine.dtype)
        self.max_body_bytes = int(item.nbytes) + 4096
        tele = get_telemetry()
        registry = tele.registry
        server_self = self

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, obj: dict,
                      headers: dict | None = None) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                if path == "/metrics":
                    body = registry.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/healthz":
                    eng = server_self.engine
                    self._send(200, {
                        "status": "draining" if eng.draining else "ok",
                        "draining": bool(eng.draining),
                        "queue_depth": eng.queue_depth(),
                    })
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] != "/predict":
                    self.send_error(404)
                    return
                n = int(self.headers.get("Content-Length") or 0)
                # transport-level size door: the body is bounded by the
                # engine's fixed request signature BEFORE any read/parse
                # allocates it — a declared 16 GB Content-Length must
                # not OOM the box on its way to validate_payload
                if not 0 < n <= server_self.max_body_bytes:
                    self._send(413, {
                        "error": f"body must be 1..{server_self.max_body_bytes}"
                                 " bytes (one .npy request payload)",
                        "verdict": "invalid",
                    })
                    return
                raw = self.rfile.read(n)
                try:
                    payload = np.load(io.BytesIO(raw), allow_pickle=False)
                except Exception:
                    self._send(400, {"error": "body must be an .npy blob "
                                              "(np.save of one payload)"})
                    return
                deadline = self.headers.get("X-Deadline-Ms")
                try:
                    deadline_ms = float(deadline) if deadline else None
                except ValueError:
                    deadline_ms = None
                trace = sanitize_trace_id(self.headers.get("X-Trace-Id"))
                # only pass trace= when a trace id actually arrived:
                # duck-typed engines (tests, wrappers) predating the
                # kwarg keep working, and the untraced path is identical
                # to before
                kw = {"deadline_ms": deadline_ms}
                if trace is not None:
                    kw["trace"] = trace
                thdrs = {"X-Trace-Id": trace} if trace is not None else None
                try:
                    res = server_self.engine.submit(payload, **kw)
                    out = res.result(timeout=server_self.result_timeout_s)
                except InvalidRequest as e:
                    self._send(400, {"error": str(e), "verdict": "invalid"},
                               headers=thdrs)
                except RequestRejected as e:
                    code = 503 if e.verdict == "rejected-draining" else 429
                    self._send(code, {"error": str(e), "verdict": e.verdict},
                               headers={**server_self._retry_after(),
                                        **(thdrs or {})})
                except RequestShed as e:
                    self._send(429, {"error": str(e), "verdict": e.verdict},
                               headers={**server_self._retry_after(),
                                        **(thdrs or {})})
                except TimeoutError as e:
                    self._send(504, {"error": str(e), "verdict": "timeout"},
                               headers=thdrs)
                else:
                    doc = {
                        "output": np.asarray(out).tolist(),
                        "latency_ms": round((res.latency_s or 0.0) * 1e3, 3),
                        "verdict": res.verdict,
                    }
                    if trace is not None:
                        # the final hop: serialization + socket write
                        with tele.span("serve/respond", trace=trace):
                            self._send(200, doc, headers=thdrs)
                    else:
                        self._send(200, doc)

            def log_message(self, *args):  # requests must not spam stderr
                pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpuframe-serve-http", daemon=True,
        )
        self._thread.start()

    def _retry_after(self) -> dict:
        """``Retry-After`` for a shedding/draining reply: roughly one
        queue-drain from now — queued items over the largest batch shape,
        one batch wait each — clamped to [1, 30] s.  An estimate to space
        client retries out, not a promise of capacity."""
        import math

        eng = self.engine
        batches = math.ceil(max(1, eng.queue_depth()) / max(eng.buckets))
        wait_s = batches * (eng.knobs.batch_wait_ms / 1e3)
        return {"Retry-After": str(max(1, min(30, math.ceil(wait_s))))}

    def run_forever(self, poll_s: float = 0.25) -> None:
        """Block until a preemption notice, then drain gracefully.

        Installs the process-wide watcher (SIGTERM); on notice: the
        engine drains (reject new, finish in-flight, flush telemetry)
        and the HTTP server shuts down.
        """
        from tpuframe.fault import preempt

        watcher = preempt.install()
        while not watcher.wait(poll_s):
            pass
        self.engine.drain(reason=f"preempt:{watcher.reason}")
        self.close()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)
