"""Admission control for the serving spine: bounded queue, explicit
load-shedding verdicts, deadline bookkeeping, door-side input validation.

An inference server under overload has exactly three honest options per
request: serve it within its deadline, shed it loudly, or reject it at
the door — the dishonest fourth (queue it forever and serve it after the
client gave up) is what this module exists to prevent.  Everything here
is host-side policy, stdlib-only, and never imports jax: admission
verdicts must keep landing (and the doctor must keep reading serve
state) while the backend is wedged — the serve-path analogue of the
telemetry/preempt discipline.

Pieces:

- :data:`SERVE_ENV_VARS` / :class:`ServeKnobs` — THE serve knob list
  (shipped to every worker via ``launch.remote.all_env_vars()``, printed
  by the doctor's ``serve`` section), with the same tolerant env parsing
  as the health sentinel.
- :class:`AdmissionController` — the bounded request queue.  ``offer``
  returns an explicit verdict (``admitted`` / ``rejected-queue-full`` /
  ``rejected-draining``) and, under the ``shed-oldest`` policy, the
  oldest request it evicted to make room; ``pop`` feeds the batcher.
  Queue depth rides the ``serve/queue_depth`` gauge.
- :func:`validate_payload` — shape/dtype/pixel-budget/finiteness checks
  at the door, mirroring the decode guards (`core/native.py` rejects
  header-declared dims over the pixel budget *before* allocating; this
  rejects a poison request *before* it can NaN a whole batch or pin a
  pathological allocation).
- :func:`read_export_meta` — the bounds-checked artifact header parse,
  stdlib-only so the doctor can describe an export against a wedged
  backend (``serve.export.load_model`` reuses it).
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time
from typing import Any

from tpuframe.fault.health import _env_float, _env_int
from tpuframe.track.telemetry import get_telemetry

__all__ = [
    "SERVE_ENV_VARS",
    "AdmissionController",
    "InvalidRequest",
    "RequestRejected",
    "RequestShed",
    "ServeKnobs",
    "read_export_meta",
    "sanitize_trace_id",
    "validate_payload",
]

#: charset a request-path trace id may use — the id is echoed into
#: telemetry JSONL and response headers, so a hostile ``X-Trace-Id``
#: must not smuggle newlines/control bytes through the front door
_TRACE_ID_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_."
)


def sanitize_trace_id(raw: Any) -> str | None:
    """A usable trace id (bounded length, safe charset) or None — the
    door check both :class:`~tpuframe.serve.server.ServingServer` and
    the fleet router apply to a client-supplied ``X-Trace-Id``."""
    if not isinstance(raw, str):
        return None
    raw = raw.strip()
    if 0 < len(raw) <= 64 and all(c in _TRACE_ID_CHARS for c in raw):
        return raw
    return None

#: every env knob the serving spine reads — THE list, consumed by
#: ``launch.remote.all_env_vars()`` (shipped to every host) and by the
#: doctor's ``serve`` section.  Add new knobs here, not in the consumers.
SERVE_ENV_VARS = (
    "TPUFRAME_SERVE_BUCKETS",
    "TPUFRAME_SERVE_SLO_MS",
    "TPUFRAME_SERVE_QUEUE_CAP",
    "TPUFRAME_SERVE_SHED_POLICY",
    "TPUFRAME_SERVE_BATCH_WAIT_MS",
    "TPUFRAME_SERVE_MAX_PIXELS",
    "TPUFRAME_SERVE_WATCHDOG_S",
    "TPUFRAME_SERVE_EXPORT",
    # fleet layer (read by serve.router.FleetKnobs.from_env)
    "TPUFRAME_ROUTER_PROBE_MS",
    "TPUFRAME_ROUTER_RETRIES",
    "TPUFRAME_ROUTER_RETRY_BUDGET",
    "TPUFRAME_FLEET_REPLICAS",
    "TPUFRAME_FLEET_SHADOW_REQUESTS",
    "TPUFRAME_FLEET_GATE_AGREEMENT",
    # SLO plane (read by serve.slo.SloObjectives.from_env)
    "TPUFRAME_SLO_P99_MS",
    "TPUFRAME_SLO_AVAILABILITY",
)

#: value domains for the knobs above (KN007).  ``apply``: buckets /
#: queue_cap / max_pixels shape the pools and the AOT-compiled set at
#: ``ServeEngine.start()`` -> "restart"; the wait/SLO/shed/watchdog
#: policy rides on the knobs object ``ServeEngine.apply_knobs`` can
#: swap on a running engine -> "live".
SERVE_ENV_DOMAINS = {
    "TPUFRAME_SERVE_BUCKETS": {"type": "str", "apply": "restart"},
    "TPUFRAME_SERVE_SLO_MS": {
        "type": "float", "range": (1.0, None), "apply": "live"},
    "TPUFRAME_SERVE_QUEUE_CAP": {
        "type": "int", "range": (1, None), "apply": "restart"},
    "TPUFRAME_SERVE_SHED_POLICY": {
        "type": "enum", "choices": ("reject-new", "shed-oldest"),
        "apply": "live"},
    "TPUFRAME_SERVE_BATCH_WAIT_MS": {
        "type": "float", "range": (0, None), "apply": "live"},
    "TPUFRAME_SERVE_MAX_PIXELS": {
        "type": "int", "range": (1, None), "apply": "restart"},
    "TPUFRAME_SERVE_WATCHDOG_S": {
        "type": "float", "range": (0, None), "apply": "live"},
    "TPUFRAME_SERVE_EXPORT": {"type": "path", "apply": "live"},
    # fleet knobs shape the router/replica-set at construction -> restart
    "TPUFRAME_ROUTER_PROBE_MS": {
        "type": "float", "range": (1.0, None), "apply": "restart"},
    "TPUFRAME_ROUTER_RETRIES": {
        "type": "int", "range": (0, 8), "apply": "restart"},
    "TPUFRAME_ROUTER_RETRY_BUDGET": {
        "type": "float", "range": (0, 1.0), "apply": "restart"},
    "TPUFRAME_FLEET_REPLICAS": {
        "type": "int", "range": (1, 64), "apply": "restart"},
    "TPUFRAME_FLEET_SHADOW_REQUESTS": {
        "type": "int", "range": (1, None), "apply": "restart"},
    "TPUFRAME_FLEET_GATE_AGREEMENT": {
        "type": "float", "range": (0, 1.0), "apply": "restart"},
    # SLO objectives are read per tracker construction -> live
    "TPUFRAME_SLO_P99_MS": {
        "type": "float", "range": (1.0, None), "apply": "live"},
    "TPUFRAME_SLO_AVAILABILITY": {
        "type": "float", "range": (0, 1.0), "apply": "live"},
}

#: pixel budget default — PIL's ``MAX_IMAGE_PIXELS`` (the same ceiling
#: the native decode guard enforces), hardcoded so this module stays
#: stdlib-only on hosts without PIL
_DEFAULT_MAX_PIXELS = 178_956_970

_SHED_POLICIES = ("reject-new", "shed-oldest")


class RequestRejected(RuntimeError):
    """The request never entered the queue — overload (queue full under
    ``reject-new``) or drain (the server is finishing in-flight work
    before exit).  ``verdict`` says which; clients should back off or
    retry against another replica."""

    def __init__(self, msg: str, *, verdict: str):
        super().__init__(msg)
        self.verdict = verdict


class RequestShed(RuntimeError):
    """The request was admitted but dropped before serving — evicted by
    a newer request under ``shed-oldest``, or its deadline expired in
    the queue (shed *before* wasting a batch slot on an answer the
    client has already abandoned)."""

    def __init__(self, msg: str, *, verdict: str):
        super().__init__(msg)
        self.verdict = verdict


class InvalidRequest(ValueError):
    """The payload failed door-side validation (shape/dtype/pixel
    budget/non-finite values) — a malformed or poison request, rejected
    before it can reach a batch.  A ValueError: this is a client bug,
    not a load condition."""


@dataclasses.dataclass(frozen=True)
class ServeKnobs:
    """Serve-spine policy, env-tunable via ``TPUFRAME_SERVE_*``.

    Attributes:
      buckets: padded batch shapes the engine precompiles — every
        request batch pads up to the smallest bucket that fits, so the
        backend only ever sees this closed set of shapes (the armed
        ShapeGuard makes anything else loud).
      slo_ms: the latency objective; also the default per-request
        deadline when a client sends none.
      queue_cap: bounded admission queue length — the knee of the
        latency curve under overload (queue wait is ~cap/throughput).
      shed_policy: ``reject-new`` (full queue refuses arrivals — fair
        to waiters) or ``shed-oldest`` (evict the request most likely
        to be past caring — better p99 for the served).
      batch_wait_ms: how long the batcher holds an underfull batch open
        for more arrivals (the classic latency/occupancy trade).
      max_pixels: door-side payload size budget (elements per request),
        defaulting to the decode guard's PIL ceiling.
      watchdog_s: stall-watchdog deadline on each backend inference
        call — a wedged backend produces an attributed stall report,
        not a silent hang (0 disables).
    """

    buckets: tuple = (1, 4, 16)
    slo_ms: float = 500.0
    queue_cap: int = 256
    shed_policy: str = "reject-new"
    batch_wait_ms: float = 2.0
    max_pixels: int = _DEFAULT_MAX_PIXELS
    watchdog_s: float = 30.0

    @classmethod
    def from_env(cls) -> "ServeKnobs":
        """Tolerant like every observability knob: malformed env reads
        as the default, never as a crash in the serving loop."""
        d = cls()
        raw = os.environ.get("TPUFRAME_SERVE_BUCKETS", "").strip()
        buckets = d.buckets
        if raw:
            try:
                parsed = tuple(sorted({int(p) for p in raw.split(",") if p.strip()}))
                if parsed and all(b > 0 for b in parsed):
                    buckets = parsed
            except ValueError:
                pass
        policy = os.environ.get("TPUFRAME_SERVE_SHED_POLICY", "").strip().lower()
        if policy not in _SHED_POLICIES:
            policy = d.shed_policy
        return cls(
            buckets=buckets,
            slo_ms=max(1.0, _env_float("TPUFRAME_SERVE_SLO_MS", d.slo_ms)),
            queue_cap=max(1, _env_int("TPUFRAME_SERVE_QUEUE_CAP", d.queue_cap)),
            shed_policy=policy,
            batch_wait_ms=max(
                0.0, _env_float("TPUFRAME_SERVE_BATCH_WAIT_MS", d.batch_wait_ms)
            ),
            max_pixels=max(1, _env_int("TPUFRAME_SERVE_MAX_PIXELS",
                                       d.max_pixels)),
            watchdog_s=max(0.0, _env_float("TPUFRAME_SERVE_WATCHDOG_S",
                                           d.watchdog_s)),
        )


def validate_payload(x: Any, *, item_shape: tuple, dtype: str,
                     max_pixels: int = _DEFAULT_MAX_PIXELS) -> None:
    """Door-side request validation; raises :class:`InvalidRequest`.

    Checks, in cheapest-first order: the payload is array-like with the
    expected trailing shape and dtype (one clear message naming the
    expected signature, instead of an opaque XLA error three layers
    down), its element count is inside the pixel budget (the decode
    guard's ceiling, applied before any batch buffer is touched), and —
    for float payloads — every value is finite, so one poison request
    cannot NaN the batch it would have shared with innocent neighbors.
    """
    shape = getattr(x, "shape", None)
    got_dtype = getattr(x, "dtype", None)
    if shape is None or got_dtype is None:
        raise InvalidRequest(
            f"payload must be an array of shape {tuple(item_shape)} "
            f"{dtype}; got {type(x).__name__}"
        )
    expected = tuple(int(s) for s in item_shape)
    if tuple(shape) != expected:
        raise InvalidRequest(
            f"payload shape {tuple(shape)} != expected per-request shape "
            f"{expected} (one request = one item; the engine batches)"
        )
    if str(got_dtype) != str(dtype):
        raise InvalidRequest(
            f"payload dtype {got_dtype} != expected {dtype} (the exported "
            "signature is fixed; cast at the client)"
        )
    n = 1
    for s in expected:
        n *= s
    if n > max_pixels:
        raise InvalidRequest(
            f"payload has {n} elements, over the {max_pixels}-element "
            "budget (TPUFRAME_SERVE_MAX_PIXELS)"
        )
    kind = getattr(got_dtype, "kind", None)
    if kind == "f":
        # lazy numpy: this module must import (and the doctor must run)
        # without it, but a float payload only exists where numpy does
        import numpy as np

        if not bool(np.isfinite(x).all()):
            raise InvalidRequest(
                "payload contains non-finite values (NaN/Inf) — rejected "
                "at the door so it cannot poison its batch-mates"
            )


class AdmissionController:
    """Bounded FIFO of admitted requests + the explicit-verdict door.

    Thread-safe: the server's request threads ``offer`` while the
    engine's batcher thread ``pop``s.  The queue-depth gauge is updated
    on both sides, so ``/metrics`` shows the backlog live.
    """

    def __init__(self, *, cap: int, policy: str = "reject-new"):
        if policy not in _SHED_POLICIES:
            raise ValueError(
                f"shed_policy must be one of {_SHED_POLICIES}, got {policy!r}"
            )
        self.cap = max(1, int(cap))
        self.policy = policy
        self._q: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._draining = False
        self._depth_gauge = get_telemetry().registry.gauge("serve/queue_depth")

    @property
    def draining(self) -> bool:
        return self._draining

    def start_drain(self) -> None:
        """Flip the door to reject-new-forever; queued requests still
        serve (the graceful-drain contract: zero dropped in-flight)."""
        with self._lock:
            self._draining = True
            self._nonempty.notify_all()

    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def offer(self, req: Any) -> tuple[str, Any]:
        """Admit ``req`` or say exactly why not.

        Returns ``(verdict, shed)``: verdict is ``admitted`` /
        ``rejected-draining`` / ``rejected-queue-full``; ``shed`` is the
        evicted oldest request under ``shed-oldest`` (the caller owns
        failing its future), else None.
        """
        with self._lock:
            if self._draining:
                return "rejected-draining", None
            shed = None
            if len(self._q) >= self.cap:
                if self.policy == "reject-new":
                    return "rejected-queue-full", None
                shed = self._q.popleft()
            self._q.append(req)
            self._depth_gauge.set(len(self._q))
            self._nonempty.notify()
            return "admitted", shed

    def pop(self, timeout: float | None = None) -> Any:
        """Oldest admitted request, or None on timeout/empty-drain."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while not self._q:
                if self._draining:
                    return None
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._nonempty.wait(remaining)
            req = self._q.popleft()
            self._depth_gauge.set(len(self._q))
            return req

    def pop_nowait(self) -> Any:
        with self._lock:
            if not self._q:
                return None
            req = self._q.popleft()
            self._depth_gauge.set(len(self._q))
            return req


# -- stdlib artifact-meta reader ---------------------------------------------

_MAX_HEADER = 1 << 20  # far above any real meta; rejects garbage lengths


def read_export_meta(path: str | os.PathLike) -> dict:
    """The export artifact's meta header, parsed without jax.

    The doctor's ``serve`` section describes an export (model, input
    signature, bucket shapes) against a wedged backend, so the header
    parse lives here, stdlib-only; ``serve.export.load_model`` reuses it
    (one bounds-checked parser — the first 8 bytes of arbitrary binaries
    decode to arbitrary "header lengths", so the length is checked and
    parse failures read as ValueError, never MemoryError).
    """
    path = os.fspath(path)
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        header_len = int.from_bytes(f.read(8), "little")
        if not 2 <= header_len <= min(_MAX_HEADER, size):
            raise ValueError(f"{path} is not a tpuframe export artifact")
        try:
            meta = json.loads(f.read(header_len).decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"{path} is not a tpuframe export artifact") from e
    if not isinstance(meta, dict) or meta.get("magic") != "tpuframe-export":
        raise ValueError(f"{path} is not a tpuframe export artifact")
    meta["_blob_offset"] = 8 + header_len
    return meta
