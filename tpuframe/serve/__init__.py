"""Deployment: portable serialized inference artifacts.

The reference's serving story is an in-notebook demo (single-image
predict after training, `02_cifar_torch_distributor_resnet.py:370-387`);
tpuframe keeps that (``train.make_predict_fn``) and adds the deployable
half: :func:`export_model` freezes (model, variables, preprocessing) into
a version-stable StableHLO artifact via ``jax.export`` that any JAX
runtime — CPU serving box or TPU — loads and calls without the model
code, flax, or the checkpoint being present.
"""

from tpuframe.serve.export import ExportedModel, export_model, load_model

__all__ = ["ExportedModel", "export_model", "load_model"]
