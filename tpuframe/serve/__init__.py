"""Deployment: portable serialized inference artifacts + the serving spine.

Two halves:

- **Export** (:func:`export_model` / :func:`load_model`): freeze (model,
  variables, preprocessing) into a version-stable StableHLO artifact via
  ``jax.export`` that any JAX runtime loads and calls without the model
  code, flax, or the checkpoint being present.
- **Serving** (:class:`ServeEngine` / :class:`ServingServer`): a
  deadline-aware dynamic-batching engine over that artifact — bucketed
  AOT-precompiled batch shapes, bounded-queue admission control with
  explicit shed verdicts, door-side poison-input validation, graceful
  SIGTERM drain, and a watchdog lease on every backend call.  SERVE.md
  is the runbook.
- **Fleet** (:class:`Router` / :class:`ReplicaSet`): N supervised
  replicas behind a health-aware least-loaded router, with zero-drop
  rolling checkpoint promotion (:meth:`ReplicaSet.promote`) gated on
  the checkpoint health stamp and a shadow-replica accuracy/latency
  check.  SERVE.md "Fleet" section is the runbook.
- **SLO plane** (:class:`SloObjectives` / :class:`SloTracker`):
  declared latency/availability objectives with rolling burn-rate and
  error-budget gauges at both the replica and (fleet-aggregated) router
  vantage points, scoring the per-hop request traces the router mints.

Exports are lazy (PEP 562): the knob list / admission policy / artifact
header reader stay importable while the jax backend is wedged — the
doctor and the remote launcher depend on that.
"""

# tpuframe-lint: stdlib-only

_LAZY = {
    "AdmissionController": "tpuframe.serve.admission",
    "ExportedModel": "tpuframe.serve.export",
    "FleetKnobs": "tpuframe.serve.router",
    "InvalidRequest": "tpuframe.serve.admission",
    "PromotionRefused": "tpuframe.serve.fleet",
    "ReplicaSet": "tpuframe.serve.fleet",
    "RequestRejected": "tpuframe.serve.admission",
    "RequestShed": "tpuframe.serve.admission",
    "Router": "tpuframe.serve.router",
    "SERVE_ENV_VARS": "tpuframe.serve.admission",
    "ServeEngine": "tpuframe.serve.engine",
    "ServeKnobs": "tpuframe.serve.admission",
    "ServeResult": "tpuframe.serve.engine",
    "ServingServer": "tpuframe.serve.server",
    "SloObjectives": "tpuframe.serve.slo",
    "SloTracker": "tpuframe.serve.slo",
    "export_model": "tpuframe.serve.export",
    "load_model": "tpuframe.serve.export",
    "read_export_meta": "tpuframe.serve.admission",
    "sanitize_trace_id": "tpuframe.serve.admission",
    "validate_payload": "tpuframe.serve.admission",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module 'tpuframe.serve' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))
