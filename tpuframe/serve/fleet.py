"""Supervised replica sets + zero-drop checkpoint promotion.

The fleet layer over the serving spine: N :class:`ServeEngine` +
:class:`ServingServer` replicas behind one :class:`Router`, each replica
owned by a per-slot :class:`~tpuframe.fault.supervisor.Supervisor` so the
fleet heals itself with the same budgets/backoff/classification
discipline as the training loop:

- **Lifecycle.**  A chaos-killed or wedged replica's serve loop raises;
  the slot's supervisor classifies it (``ChaosError`` → retryable),
  backs off, and rebuilds the replica **warm** — the supervisor enables
  the persistent compile cache before attempt 1, and the rebuilt
  engine's AOT bucket precompile reads every program back instead of
  recompiling.  The replica re-enters routing only after its own
  ``/healthz`` answers green (the re-admission gate), so the router
  never routes into a replica that is still compiling.
- **Promotion** (:meth:`ReplicaSet.promote`).  A candidate model is
  swapped in only after two gates: its checkpoint health stamp must be
  clean (:func:`tpuframe.ckpt.meta.ckpt_health_verdict` — strict:
  meta refuses loudly, it never crashes the router on a corrupt
  candidate), and a **shadow replica** must pass the accuracy/latency
  gate against live-mirrored traffic (the router's recent-payload ring
  replayed through the shadow engine and a live replica; argmax
  agreement ≥ ``TPUFRAME_FLEET_GATE_AGREEMENT``, shadow p95 under the
  SLO).  Then replicas swap **one at a time** through the existing
  drain machinery: rotate out of the router, drain (every admitted
  request completes — ``dropped_in_flight`` is counted and must be 0),
  rebuild on the candidate, re-admit on green.  A refused promotion is
  one loud ``fleet/promotion_refused`` event + :class:`PromotionRefused`
  — the old model keeps serving.

Chaos drives both stories deterministically: ``ReplicaKill`` fires at
the ``fleet/replica`` site (the supervisor tick), ``UnhealthyPromotion``
taints the candidate at ``fleet/promote`` (see FAULT.md).
"""

from __future__ import annotations

import io
import threading
import time
import urllib.request
from typing import Any

from tpuframe.ckpt.meta import ckpt_health_verdict
from tpuframe.fault import chaos
from tpuframe.fault.supervisor import RestartPolicy, Supervisor
from tpuframe.serve.admission import ServeKnobs
from tpuframe.serve.router import FleetKnobs, Router
from tpuframe.track.telemetry import get_telemetry

__all__ = ["PromotionRefused", "ReplicaSet"]


class PromotionRefused(RuntimeError):
    """The promotion gate said no — dirty/unreadable health stamp, chaos
    taint, or a failed shadow accuracy/latency gate.  The old model keeps
    serving; the reason is in the message and the
    ``fleet/promotion_refused`` event."""


class _Slot:
    """One replica slot: the persistent identity a supervisor keeps
    rebuilding attempts into.  Mutable attempt state under ``lock``."""

    def __init__(self, idx: int, model: Any):
        self.idx = idx
        self.model = model
        self.gen = 1
        self.lock = threading.Lock()
        self.engine: Any = None
        self.server: Any = None
        self.url: str | None = None
        self.dead = threading.Event()
        self.error: BaseException | None = None
        self.shutdown = False

    def alive(self) -> bool:
        with self.lock:
            return self.url is not None and not self.dead.is_set()

    def kill(self, error: BaseException) -> None:
        """Abrupt replica death (the ``ReplicaKill`` injector's hook):
        yank the HTTP listener so new connections refuse, record the
        failure, and wake the serve loop to crash with it."""
        with self.lock:
            if self.dead.is_set():
                return
            self.error = error
            srv = self.server
        if srv is not None:
            try:
                srv._server.shutdown()
                srv._server.server_close()
            except Exception:
                pass
        self.dead.set()

    def retire(self) -> None:
        """Graceful attempt end (swap/shutdown): no error recorded, the
        serve loop drains and either rebuilds (swap) or returns."""
        with self.lock:
            self.error = None
        self.dead.set()


class ReplicaSet:
    """N supervised serving replicas behind a least-loaded router.

    Args:
      model: what each replica serves — an
        :class:`~tpuframe.serve.export.ExportedModel` or a jit-able
        callable (plain callables also need ``item_shape``/``dtype``,
        exactly like :class:`ServeEngine`).
      n: fleet size (default ``TPUFRAME_FLEET_REPLICAS``).
      serve_knobs / fleet_knobs: per-replica engine policy and
        router/fleet policy (default: from env).

    ``start()`` brings the router and every replica up;
    ``router.url + "/predict"`` is the fleet's front door.
    Context-managed: ``with ReplicaSet(model, n=3) as fleet: ...``.
    """

    def __init__(self, model: Any, n: int | None = None, *,
                 serve_knobs: ServeKnobs | None = None,
                 fleet_knobs: FleetKnobs | None = None,
                 item_shape: tuple | None = None, dtype: Any = None,
                 host: str = "127.0.0.1",
                 restart_policy: RestartPolicy | None = None):
        self.knobs = fleet_knobs or FleetKnobs.from_env()
        self.serve_knobs = serve_knobs or ServeKnobs.from_env()
        self.n = int(n) if n is not None else self.knobs.replicas
        self._model = model
        self._item_shape = item_shape
        self._dtype = dtype
        self._host = host
        # replica restarts are local rebuilds, not cross-host reschedules:
        # short backoff, generous retryable budget (each chaos kill is one
        # RETRYABLE failure; a fleet drill kills more than twice)
        self._policy = restart_policy or RestartPolicy(
            max_restarts=8, backoff_base_s=0.05, backoff_max_s=0.5,
        )
        self.router = Router(knobs=self.knobs, host=host)
        self._slots: list[_Slot] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._monitor: threading.Thread | None = None
        self._tick = 0
        self._promote_attempts = 0
        self._promote_lock = threading.Lock()
        reg = get_telemetry().registry
        self._c_restarts = reg.counter("fleet/restarts")
        self._c_promotions = reg.counter("fleet/promotions")
        self._c_refused = reg.counter("fleet/promotions_refused")

    # -- lifecycle -----------------------------------------------------------
    def start(self, wait_s: float = 30.0) -> "ReplicaSet":
        """Start the router, spawn every supervised replica, and wait
        until the whole fleet is green (raises on timeout — a fleet that
        can't come up should fail loudly, not serve at half strength)."""
        if self._threads:
            return self
        self.router.start()
        for i in range(self.n):
            slot = _Slot(i, self._model)
            self._slots.append(slot)
            t = threading.Thread(
                target=self._supervise_slot, args=(slot,),
                name=f"tpuframe-fleet-replica{i}", daemon=True,
            )
            self._threads.append(t)
            t.start()
        deadline = time.monotonic() + wait_s
        while len(self.router.healthy_backends()) < self.n:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet failed to come up: "
                    f"{len(self.router.healthy_backends())}/{self.n} "
                    f"replicas green after {wait_s}s"
                )
            time.sleep(0.01)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="tpuframe-fleet-monitor",
            daemon=True,
        )
        self._monitor.start()
        get_telemetry().event(
            "fleet/started", replicas=self.n, router=self.router.url,
        )
        return self

    def __enter__(self) -> "ReplicaSet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        for slot in self._slots:
            slot.shutdown = True
            slot.retire()
        for t in self._threads:
            t.join(timeout=10.0)
        self.router.close()

    # -- introspection -------------------------------------------------------
    @property
    def generation(self) -> int:
        """The model generation every replica has reached (bumps on a
        completed promotion)."""
        return min((s.gen for s in self._slots), default=1)

    def replica_urls(self) -> list[str]:
        return [s.url for s in self._slots if s.url is not None]

    # -- the per-slot supervised serve loop ----------------------------------
    def _supervise_slot(self, slot: _Slot) -> None:
        sup = Supervisor(
            self._policy,
            on_restart=lambda attempt, e: self._c_restarts.inc(),
        )
        try:
            sup.run(lambda: self._slot_body(slot))
        except BaseException:
            # budget exhausted or fatal: the slot stays down; the router
            # has already rotated it out and the gauge shows the hole
            if not slot.shutdown:
                get_telemetry().event(
                    "fleet/replica_down", url=slot.url or f"slot{slot.idx}",
                    via="supervisor-giveup",
                )

    def _slot_body(self, slot: _Slot) -> None:
        """One supervised run: serve attempts until shutdown.  A kill
        raises out to the supervisor (classify → backoff → re-entry);
        a graceful retire loops straight into the next generation."""
        while not slot.shutdown:
            self._run_attempt(slot)
        return None

    def _run_attempt(self, slot: _Slot) -> None:
        from tpuframe.serve.engine import ServeEngine
        from tpuframe.serve.server import ServingServer

        engine = ServeEngine(
            slot.model, knobs=self.serve_knobs,
            item_shape=self._item_shape, dtype=self._dtype,
            replica=slot.idx,
        )
        engine.start()  # AOT bucket precompile — warm off the shared cache
        server = ServingServer(engine, host=self._host, port=0)
        url = server.url
        # re-admission gate: the replica enters routing only after its
        # own /healthz answers green over real HTTP
        self._wait_green(url, timeout_s=10.0)
        with slot.lock:
            slot.engine, slot.server, slot.url = engine, server, url
            slot.error = None
            slot.dead = threading.Event()
        self.router.add_backend(url)
        slot.dead.wait()
        self.router.remove_backend(url)
        err = slot.error
        if err is not None:
            # crashed attempt: queued work sheds (the kill's collateral —
            # the router's retry budget covers the clients), then the
            # supervisor takes it from here
            with slot.lock:
                slot.engine = slot.server = slot.url = None
            try:
                engine.stop()
                server.close()
            except Exception:
                pass
            raise err
        # graceful retire (swap/shutdown): every admitted request
        # completes before the replica goes away
        engine.drain(timeout=30.0)
        with slot.lock:
            slot.engine = slot.server = slot.url = None
        engine.stop()
        server.close()

    @staticmethod
    def _wait_green(url: str, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url + "/healthz", timeout=1.0) as r:
                    import json as _json

                    doc = _json.loads(r.read().decode())
                if doc.get("status") == "ok" and not doc.get("draining"):
                    return
            except Exception:
                pass
            time.sleep(0.01)
        raise TimeoutError(f"replica at {url} never went green")

    # -- chaos tick ----------------------------------------------------------
    def _monitor_loop(self) -> None:
        interval = self.knobs.probe_ms / 1e3
        while not self._stop.wait(interval):
            self._tick += 1
            live = [s for s in self._slots if s.alive()]
            chaos.maybe_fire(
                "fleet/replica", self._tick, fleet=self, replicas=live,
            )

    # -- promotion -----------------------------------------------------------
    def promote(self, model: Any, *, ckpt_dir: str | None = None,
                step: int | None = None, timeout_s: float = 60.0) -> dict:
        """Swap ``model`` into every replica, zero-drop, or refuse loudly.

        Gate 1 — health stamp: with ``ckpt_dir`` given, the candidate
        step's stamp must be clean (strict
        :func:`~tpuframe.ckpt.meta.ckpt_health_verdict`: a dirty stamp,
        uncommitted step, or unreadable/corrupt meta refuses — it never
        crashes the router).  Gate 2 — shadow replica: the candidate
        serves the router's live-mirrored payloads next to a live
        replica; argmax agreement and shadow p95 latency must clear the
        knobs.  Then a rolling swap through the drain machinery, one
        replica at a time.

        Returns ``{"swapped", "dropped_in_flight", "agreement",
        "shadow_p95_ms", "generation"}``.  Raises
        :class:`PromotionRefused` (and the old model keeps serving) on
        any gate failure.
        """
        tele = get_telemetry()
        with self._promote_lock:
            attempt = self._promote_attempts
            self._promote_attempts += 1
            candidate = {"ckpt_dir": ckpt_dir, "step": step}
            chaos.maybe_fire(
                "fleet/promote", attempt, fleet=self, candidate=candidate,
            )
            taint = candidate.get("taint")
            if taint:
                self._refuse(str(taint))
            if ckpt_dir is not None:
                ok, reason = ckpt_health_verdict(ckpt_dir, step)
                if not ok:
                    self._refuse(f"health stamp: {reason}")
            agreement, p95_ms = self._shadow_gate(model)
            if agreement < self.knobs.gate_agreement:
                self._refuse(
                    f"shadow gate: agreement {agreement:.3f} < "
                    f"{self.knobs.gate_agreement} against live traffic"
                )
            if p95_ms > self.serve_knobs.slo_ms:
                self._refuse(
                    f"shadow gate: p95 {p95_ms:.1f}ms over the "
                    f"{self.serve_knobs.slo_ms}ms SLO"
                )
            # both gates green: rolling swap, one replica at a time
            dropped = 0
            swapped = 0
            for slot in self._slots:
                dropped += self._swap_slot(slot, model, timeout_s)
                swapped += 1
                tele.event(
                    "fleet/swap", replica=slot.idx, gen=slot.gen,
                    dropped_in_flight=dropped,
                )
            self._model = model
            self._c_promotions.inc()
            tele.event(
                "fleet/promoted", replicas=swapped,
                dropped_in_flight=dropped,
                agreement=round(agreement, 4),
                shadow_p95_ms=round(p95_ms, 3),
                ckpt_dir=ckpt_dir, step=step,
            )
            return {
                "swapped": swapped,
                "dropped_in_flight": dropped,
                "agreement": round(agreement, 4),
                "shadow_p95_ms": round(p95_ms, 3),
                "generation": self.generation,
            }

    def _refuse(self, reason: str) -> None:
        self._c_refused.inc()
        get_telemetry().event("fleet/promotion_refused", reason=reason)
        raise PromotionRefused(f"promotion refused: {reason}")

    def _swap_slot(self, slot: _Slot, model: Any, timeout_s: float) -> int:
        """Drain-swap one replica onto ``model``; returns how many
        admitted requests failed to complete (must be 0)."""
        old_engine = slot.engine
        old_url = slot.url
        slot.model = model
        slot.gen += 1
        if old_url is not None:
            # rotate out FIRST so no new request lands mid-drain
            self.router.remove_backend(old_url)
        dropped = 0
        if old_engine is not None:
            ok = old_engine.drain(timeout=timeout_s)
            dropped = old_engine.queue_depth() if not ok else 0
        slot.retire()  # the serve loop rebuilds on the new generation
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with slot.lock:
                fresh = (slot.url is not None and not slot.dead.is_set()
                         and slot.engine is not None)
            if fresh and slot.url in self.router.healthy_backends():
                return dropped
            time.sleep(0.01)
        raise RuntimeError(
            f"replica {slot.idx} never came back green after swap "
            f"(gen {slot.gen})"
        )

    # -- shadow gate ---------------------------------------------------------
    def _mirrored_payloads(self) -> list:
        import numpy as np

        ref = self._ref_engine()
        shape, dtype = ref.item_shape, ref.dtype
        payloads = []
        for raw in self.router.recent_payloads()[-self.knobs.shadow_requests:]:
            try:
                arr = np.load(io.BytesIO(raw), allow_pickle=False)
            except Exception:
                continue
            if tuple(arr.shape) == tuple(shape):
                payloads.append(np.asarray(arr, dtype=dtype))
        while len(payloads) < self.knobs.shadow_requests:
            payloads.append(np.zeros(shape, dtype))  # cold-fleet filler
        return payloads

    def _ref_engine(self):
        for slot in self._slots:
            with slot.lock:
                if slot.engine is not None and not slot.dead.is_set():
                    return slot.engine
        raise PromotionRefused(
            "promotion refused: no live replica to mirror traffic against"
        )

    def _shadow_gate(self, model: Any) -> tuple[float, float]:
        """(argmax agreement fraction, shadow p95 ms) of the candidate
        vs a live replica over the mirrored payload set."""
        import numpy as np

        from tpuframe.serve.engine import ServeEngine

        payloads = self._mirrored_payloads()
        shadow = ServeEngine(
            model, knobs=self.serve_knobs,
            item_shape=self._item_shape, dtype=self._dtype,
            preemption=False, replica="shadow",
        )
        shadow.start()
        try:
            agree = 0
            lats: list[float] = []
            for p in payloads:
                ref = self._ref_engine()
                s_res = shadow.submit(p)
                r_res = ref.submit(p)
                s_out = np.asarray(s_res.result(timeout=30.0))
                r_out = np.asarray(r_res.result(timeout=30.0))
                if int(np.argmax(s_out)) == int(np.argmax(r_out)):
                    agree += 1
                lats.append(float(s_res.latency_s or 0.0))
        finally:
            shadow.drain(timeout=10.0)
            shadow.stop()
        lats.sort()
        p95 = lats[min(len(lats) - 1, int(0.95 * len(lats)))] if lats else 0.0
        return agree / max(1, len(payloads)), p95 * 1e3
