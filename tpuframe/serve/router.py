"""Health-aware HTTP router/load-balancer over N serving replicas.

One replica wedging, draining, or dying must read as a blip, not an
outage: the router fronts N :class:`~tpuframe.serve.server.ServingServer`
replicas and keeps the fleet answering while individual replicas come
and go.  Three mechanisms, all bounded:

- **Least-loaded routing.**  A probe thread scrapes every replica's
  ``/healthz`` (the ``draining`` + ``queue_depth`` fields the server
  publishes for exactly this consumer) and ``/metrics`` (the
  ``serve/queue_depth`` gauge as fallback when an older replica's health
  body lacks the field) every ``TPUFRAME_ROUTER_PROBE_MS``.  Requests go
  to the healthy, non-draining replica with the lowest score —
  queue depth plus an EWMA of the latency the router itself observed
  against that backend.
- **Health rotation within a bounded window.**  A replica that fails a
  probe (connection refused, non-200, draining) leaves rotation on the
  next tick — detection is bounded by one probe interval — and an
  in-band forwarding failure marks it down *immediately*, so the window
  never waits on the prober.  It re-enters only after ``/healthz`` goes
  green again.
- **Bounded retry with a budget.**  Connection-refused / 5xx / 429 from
  one replica retries on the next-best *other* replica, at most
  ``TPUFRAME_ROUTER_RETRIES`` times — and only while total retries stay
  under ``TPUFRAME_ROUTER_RETRY_BUDGET`` × total requests.  A sick fleet
  therefore degrades to honest shedding (503 + ``Retry-After``), never a
  retry storm that finishes off the survivors.

The router also keeps a small ring of recent request bodies —
``recent_payloads()`` — which is the live-mirrored traffic
:meth:`tpuframe.serve.fleet.ReplicaSet.promote` replays through a shadow
replica's accuracy/latency gate.

Observability (this is the fleet's one front door, so it narrates):
every ``/predict`` is traced — the router mints a trace id (or honors a
sane client ``X-Trace-Id``), forwards it, and emits one ``fleet/route``
span plus a ``fleet/hop`` span per forward attempt; mark-down/mark-up
transitions emit ``fleet/markdown``/``fleet/markup`` events (replica +
reason) and bump the ``fleet/markdowns`` counter; a fleet-wide
:class:`~tpuframe.serve.slo.SloTracker` scores every routed reply so
the router's ``/metrics`` burn-rate gauge is the aggregate SLO signal;
and ``/metrics`` appends per-replica ``replica``-labeled gauge lines so
one scrape covers the fleet.

Stdlib-only (urllib + http.server + threading), like the server it
fronts: the fleet's front door must keep routing while the jax backend
of any one replica is wedged.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import collections
import dataclasses
import json
import math
import threading
import time
import urllib.error
import urllib.request
import uuid

from tpuframe.fault.health import _env_float, _env_int
from tpuframe.serve.admission import sanitize_trace_id
from tpuframe.serve.slo import SloTracker
from tpuframe.track.telemetry import get_telemetry

__all__ = ["FleetKnobs", "Router"]


@dataclasses.dataclass(frozen=True)
class FleetKnobs:
    """Router + fleet policy, env-tunable via ``TPUFRAME_ROUTER_*`` /
    ``TPUFRAME_FLEET_*`` (declared in
    :data:`~tpuframe.serve.admission.SERVE_ENV_VARS`, shipped by
    ``launch.remote.all_env_vars()``, printed by the doctor's ``fleet``
    section).

    Attributes:
      probe_ms: health/load probe cadence — the routing detection window
        is bounded by one probe interval (in-band failures mark a
        replica down faster).
      retries: max *other* replicas tried per request on
        connection-refused/5xx/429 before giving the client the verdict.
      retry_budget: global retries-per-request ratio cap.  Past it the
        router stops retrying (shed, not storm): when most requests need
        a retry the fleet is sick, and N× traffic amplification would
        finish it off.
      replicas: default fleet size (``ReplicaSet``/bench).
      shadow_requests: how many live-mirrored requests the promotion
        shadow gate replays (padded with zeros on a cold fleet).
      gate_agreement: min argmax-agreement fraction between the shadow
        replica and the serving model for a promotion to pass.
    """

    probe_ms: float = 50.0
    retries: int = 2
    retry_budget: float = 0.2
    replicas: int = 3
    shadow_requests: int = 32
    gate_agreement: float = 0.99

    @classmethod
    def from_env(cls) -> "FleetKnobs":
        """Tolerant like every serve knob: malformed env reads as the
        default — a typo'd knob must not take the fleet's front door
        down."""
        d = cls()
        return cls(
            probe_ms=max(
                1.0, _env_float("TPUFRAME_ROUTER_PROBE_MS", d.probe_ms)
            ),
            retries=max(0, _env_int("TPUFRAME_ROUTER_RETRIES", d.retries)),
            retry_budget=min(1.0, max(0.0, _env_float(
                "TPUFRAME_ROUTER_RETRY_BUDGET", d.retry_budget))),
            replicas=max(1, _env_int("TPUFRAME_FLEET_REPLICAS", d.replicas)),
            shadow_requests=max(1, _env_int(
                "TPUFRAME_FLEET_SHADOW_REQUESTS", d.shadow_requests)),
            gate_agreement=min(1.0, max(0.0, _env_float(
                "TPUFRAME_FLEET_GATE_AGREEMENT", d.gate_agreement))),
        )


class _Backend:
    """Router-side view of one replica (all fields under Router._lock)."""

    __slots__ = ("url", "healthy", "draining", "queue_depth", "ewma_s",
                 "fails")

    def __init__(self, url: str):
        self.url = url
        self.healthy = False     # down until the first green probe
        self.draining = False
        self.queue_depth = 0
        self.ewma_s = 0.0        # router-observed forward latency
        self.fails = 0

    def score(self) -> float:
        # queue depth dominates; the latency EWMA breaks ties between
        # equally-idle replicas toward the one that answers fastest
        return self.queue_depth + self.ewma_s * 100.0


class Router:
    """Serve ``/predict`` over the healthiest of N replica URLs.

    ``start()`` binds port 0 (real port on ``.port``/``.url``) and
    launches the probe thread; replicas are added/removed live
    (``add_backend``/``remove_backend`` — the :class:`ReplicaSet`
    supervisor drives these around restarts and promotion swaps).
    """

    #: ring of recent request bodies for promotion's shadow-mirror gate
    MIRROR_RING = 256

    def __init__(self, backends: list[str] | None = None, *,
                 knobs: FleetKnobs | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 60.0):
        self.knobs = knobs or FleetKnobs.from_env()
        self.request_timeout_s = float(request_timeout_s)
        self._lock = threading.Lock()
        self._backends: dict[str, _Backend] = {}
        for url in backends or []:
            self._backends[url.rstrip("/")] = _Backend(url.rstrip("/"))
        self._mirror: collections.deque = collections.deque(
            maxlen=self.MIRROR_RING
        )
        self._stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        reg = get_telemetry().registry
        self._c_requests = reg.counter("fleet/requests")
        self._c_retries = reg.counter("fleet/retries")
        self._c_no_backend = reg.counter("fleet/no_backend")
        self._c_markdowns = reg.counter("fleet/markdowns")
        self._g_healthy = reg.gauge("fleet/healthy_replicas")
        self._g_size = reg.gauge("fleet/size")
        # fleet-wide SLO aggregate: every routed reply is one outcome,
        # so the router's /metrics burn-rate gauge answers "is the
        # fleet inside its SLO" in one scrape
        self._slo = SloTracker(source="router")
        self._server = None
        self.host = host
        self._requested_port = port
        self.port: int | None = None
        self.url: str | None = None

    # -- membership ----------------------------------------------------------
    def add_backend(self, url: str) -> None:
        url = url.rstrip("/")
        with self._lock:
            if url not in self._backends:
                self._backends[url] = _Backend(url)
                self._g_size.set(float(len(self._backends)))
        self._probe_once()  # admit a green replica without waiting a tick

    def remove_backend(self, url: str) -> None:
        url = url.rstrip("/")
        with self._lock:
            self._backends.pop(url, None)
            self._g_size.set(float(len(self._backends)))
            self._g_healthy.set(
                float(sum(1 for b in self._backends.values() if b.healthy))
            )

    def backends(self) -> list[str]:
        with self._lock:
            return list(self._backends)

    def healthy_backends(self) -> list[str]:
        with self._lock:
            return [u for u, b in self._backends.items()
                    if b.healthy and not b.draining]

    def recent_payloads(self) -> list[bytes]:
        """Recent raw request bodies (``.npy`` blobs) — the mirrored
        traffic the promotion shadow gate replays."""
        with self._lock:
            return list(self._mirror)

    # -- probing -------------------------------------------------------------
    def _probe_backend(self, b: _Backend) -> tuple[bool, bool, int]:
        """(healthy, draining, queue_depth) for one replica, from its
        ``/healthz`` with the ``/metrics`` queue-depth gauge as fallback.
        Any transport/parse failure reads as unhealthy."""
        timeout = max(0.05, self.knobs.probe_ms / 1e3)
        try:
            with urllib.request.urlopen(
                b.url + "/healthz", timeout=timeout
            ) as resp:
                doc = json.loads(resp.read().decode())
        except Exception:
            return False, False, 0
        draining = bool(doc.get("draining",
                                doc.get("status") == "draining"))
        depth = doc.get("queue_depth")
        if not isinstance(depth, (int, float)):
            depth = self._scrape_queue_depth(b, timeout)
        return doc.get("status") in ("ok", "draining"), draining, int(depth)

    def _scrape_queue_depth(self, b: _Backend, timeout: float) -> int:
        """Fallback load signal: the ``serve/queue_depth`` gauge off the
        replica's Prometheus ``/metrics`` page."""
        try:
            with urllib.request.urlopen(
                b.url + "/metrics", timeout=timeout
            ) as resp:
                text = resp.read().decode()
        except Exception:
            return 0
        for line in text.splitlines():
            if line.startswith("tpuframe_serve_queue_depth "):
                try:
                    return int(float(line.split()[1]))
                except (IndexError, ValueError):
                    return 0
        return 0

    def _probe_once(self) -> None:
        with self._lock:
            backends = list(self._backends.values())
        tele = get_telemetry()
        for b in backends:
            healthy, draining, depth = self._probe_backend(b)
            with self._lock:
                was = b.healthy
                b.healthy, b.draining, b.queue_depth = healthy, draining, depth
                b.fails = 0 if healthy else b.fails + 1
            if healthy and not was:
                tele.event("fleet/replica_up", url=b.url)
                tele.event("fleet/markup", replica=b.url, reason="probe")
            elif was and not healthy:
                tele.event("fleet/replica_down", url=b.url, via="probe")
                self._c_markdowns.inc()
                tele.event("fleet/markdown", replica=b.url, reason="probe")
        with self._lock:
            self._g_healthy.set(
                float(sum(1 for x in self._backends.values() if x.healthy))
            )

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.knobs.probe_ms / 1e3):
            self._probe_once()

    def _mark_down(self, url: str, reason: str) -> None:
        """In-band failure: rotate the replica out NOW, not at the next
        probe tick — the detection window must not wait on the prober."""
        with self._lock:
            b = self._backends.get(url)
            if b is None or not b.healthy:
                return
            b.healthy = False
            b.fails += 1
            self._g_healthy.set(
                float(sum(1 for x in self._backends.values() if x.healthy))
            )
        tele = get_telemetry()
        tele.event("fleet/replica_down", url=url, via=reason)
        self._c_markdowns.inc()
        tele.event("fleet/markdown", replica=url, reason=reason)

    def _fleet_metrics_text(self) -> str:
        """Per-replica gauge lines with a ``replica`` label, appended to
        the router's own Prometheus page — one scrape of the router
        returns the whole fleet's load/health view (from probe state; no
        per-replica fan-out on the scrape path).  The labeled
        ``tpuframe_serve_queue_depth`` lines never collide with the
        unlabeled gauge a replica's own page serves, and never match the
        ``_scrape_queue_depth`` fallback (which requires the unlabeled
        form), so a router is safe to scrape as if it were a replica."""
        with self._lock:
            reps = [(b.url, b.healthy, b.draining, b.queue_depth, b.ewma_s)
                    for b in self._backends.values()]
        lines = []
        for url, healthy, draining, depth, ewma in reps:
            label = '{replica="' + url + '"}'
            lines.append(f"tpuframe_serve_queue_depth{label} {int(depth)}")
            lines.append(
                f"tpuframe_fleet_replica_healthy{label} {int(healthy)}"
            )
            lines.append(
                f"tpuframe_fleet_replica_draining{label} {int(draining)}"
            )
            lines.append(
                f"tpuframe_fleet_replica_ewma_seconds{label} "
                f"{round(ewma, 6)}"
            )
        return "".join(line + "\n" for line in lines)

    # -- request path --------------------------------------------------------
    def _pick(self, exclude: set[str]) -> str | None:
        with self._lock:
            live = [b for u, b in self._backends.items()
                    if b.healthy and not b.draining and u not in exclude]
            if not live:
                return None
            return min(live, key=_Backend.score).url

    def _retry_allowed(self) -> bool:
        # budget: total retries must stay under budget * total requests
        # (+1 grace so the very first failure may retry)
        return self._c_retries.value < (
            self.knobs.retry_budget * self._c_requests.value + 1
        )

    def _forward(self, url: str, body: bytes, headers: dict,
                 timeout: float) -> tuple[int, bytes, dict]:
        req = urllib.request.Request(
            url + "/predict", data=body, method="POST",
            headers={"Content-Type": "application/octet-stream", **headers},
        )
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                out = resp.read()
                code, hdrs = resp.status, dict(resp.headers)
        except urllib.error.HTTPError as e:
            out = e.read()
            code, hdrs = e.code, dict(e.headers)
        dt = time.monotonic() - t0
        with self._lock:
            b = self._backends.get(url)
            if b is not None:
                b.ewma_s = 0.8 * b.ewma_s + 0.2 * dt
        return code, out, hdrs

    def handle_predict(self, body: bytes, headers: dict,
                       trace: str | None = None) -> tuple[int, bytes, dict]:
        """Route one request: least-loaded replica, bounded budgeted
        retry-on-other for connection-refused/5xx/429.  Returns
        ``(status, body, relay_headers)``.

        ``trace``: request-path trace id.  When set, the routing pass
        emits one ``fleet/route`` span (total router time, final status,
        attempt count) plus one ``fleet/hop`` span per forward attempt,
        and the id is echoed on the relay headers.
        """
        t0 = time.monotonic()
        code, out, relay = self._route(body, headers, trace)
        dt = time.monotonic() - t0
        # fleet-wide SLO outcome: what the client saw at the front door
        self._slo.observe(dt, ok=code < 400)
        if trace is not None:
            get_telemetry().event(
                "fleet/route", kind="span", dur_s=round(dt, 6),
                trace=trace, status=code,
            )
            relay = {**relay, "X-Trace-Id": trace}
        return code, out, relay

    def _route(self, body: bytes, headers: dict,
               trace: str | None) -> tuple[int, bytes, dict]:
        tele = get_telemetry()
        self._c_requests.inc()
        with self._lock:
            self._mirror.append(body)
        tried: set[str] = set()
        attempts = 0
        last: tuple[int, bytes, dict] | None = None
        while attempts <= self.knobs.retries:
            url = self._pick(tried)
            if url is None:
                break
            tried.add(url)
            t_hop = time.monotonic()
            try:
                code, out, hdrs = self._forward(
                    url, body, headers, self.request_timeout_s
                )
            except Exception as e:  # refused/reset/timeout: replica is gone
                if trace is not None:
                    tele.event(
                        "fleet/hop", kind="span",
                        dur_s=round(time.monotonic() - t_hop, 6),
                        trace=trace, replica=url, attempt=attempts,
                        status=0, error=type(e).__name__,
                    )
                self._mark_down(url, f"forward:{type(e).__name__}")
                last = None
            else:
                if trace is not None:
                    tele.event(
                        "fleet/hop", kind="span",
                        dur_s=round(time.monotonic() - t_hop, 6),
                        trace=trace, replica=url, attempt=attempts,
                        status=code,
                    )
                relay = {"X-Fleet-Replica": url}
                if "Retry-After" in hdrs:
                    relay["Retry-After"] = hdrs["Retry-After"]
                if code < 500 and code != 429:
                    return code, out, relay
                last = (code, out, relay)
                if code >= 500:
                    # 5xx: the replica answered but can't serve — rotate
                    # it out until its next green probe
                    self._mark_down(url, f"forward:{code}")
            attempts += 1
            if attempts > self.knobs.retries or not self._retry_allowed():
                break
            self._c_retries.inc()
        if last is not None:
            return last  # relay the backend's own verdict (shed, not storm)
        self._c_no_backend.inc()
        tele.event(
            "fleet/no_backend", tried=len(tried),
            healthy=len(self.healthy_backends()),
        )
        body_out = json.dumps({
            "error": "no healthy replica available",
            "verdict": "no-backend",
        }).encode()
        return 503, body_out, {
            "Retry-After": str(max(1, math.ceil(self.knobs.probe_ms / 1e3))),
        }

    # -- HTTP front ----------------------------------------------------------
    def start(self) -> "Router":
        """Bind the front door (port 0 → real port on ``.port``) and
        start probing.  Idempotent."""
        if self._server is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        router_self = self
        registry = get_telemetry().registry

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, headers: dict) -> None:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                if path == "/healthz":
                    with router_self._lock:
                        reps = [{
                            "url": b.url, "healthy": b.healthy,
                            "draining": b.draining,
                            "queue_depth": b.queue_depth,
                        } for b in router_self._backends.values()]
                    body = json.dumps({
                        "status": "ok",
                        "replicas": reps,
                        "healthy": sum(1 for r in reps if r["healthy"]),
                        # green = actually routable (healthy AND not
                        # draining) — what a supervisor should alert on
                        "green": sum(1 for r in reps
                                     if r["healthy"] and not r["draining"]),
                    }).encode()
                    self._send(200, body, {})
                elif path == "/metrics":
                    body = (registry.prometheus_text()
                            + router_self._fleet_metrics_text()).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] != "/predict":
                    self.send_error(404)
                    return
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n > 0 else b""
                fwd = {}
                deadline = self.headers.get("X-Deadline-Ms")
                if deadline:
                    fwd["X-Deadline-Ms"] = deadline
                # trace mint: honor a sane client X-Trace-Id, else mint —
                # every request routed through the fleet front door is
                # traced end to end
                trace = sanitize_trace_id(self.headers.get("X-Trace-Id"))
                if trace is None:
                    trace = uuid.uuid4().hex[:16]
                fwd["X-Trace-Id"] = trace
                code, out, hdrs = router_self.handle_predict(
                    body, fwd, trace=trace
                )
                self._send(code, out, hdrs)

            def log_message(self, *args):  # requests must not spam stderr
                pass

        self._server = ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler
        )
        self.port = self._server.server_address[1]
        self.url = f"http://{self.host}:{self.port}"
        self._http_thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpuframe-fleet-router", daemon=True,
        )
        self._http_thread.start()
        self._probe_once()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="tpuframe-fleet-probe", daemon=True,
        )
        self._probe_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=2.0)
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._http_thread.join(timeout=2.0)
            self._server = None
