"""Environment doctor: one-command report of what this install can do.

``python -m tpuframe`` is the CLI face of the reference's setup cell —
`/root/reference/setup/00_setup.py:105-123` prints worker counts, GPU
topology and debug-env state at bootstrap; this prints the tpuframe
equivalents (backend, devices, mesh hint, native extensions, codecs,
compile cache) as one JSON report a user can paste into a bug report.

The device probe runs in a TIMEOUT-BOUNDED subprocess: on a wedged
remote backend ``jax.devices()`` hangs forever rather than erroring
(the axon-tunnel failure mode), and a diagnostics tool that hangs on
exactly the environment it should diagnose is useless.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import importlib
import json
import shlex
import shutil
import os
import subprocess
import sys

_PROBE_SRC = (
    "import json, jax; d = jax.devices(); "
    "print(json.dumps({'backend': jax.default_backend(), "
    "'device_count': jax.device_count(), "
    "'local_device_count': jax.local_device_count(), "
    "'process_index': jax.process_index(), "
    "'process_count': jax.process_count(), "
    "'device_kinds': sorted({dev.device_kind for dev in d}), "
    "'jax_version': jax.__version__}))"
)


def _module_version(name: str) -> str | None:
    try:
        mod = importlib.import_module(name)
        return getattr(mod, "__version__", "installed")
    except Exception:
        return None


def probe_devices(timeout_s: float = 30.0) -> dict:
    """Backend/topology via a bounded child (never hangs the doctor).

    The probe runs under a telemetry span, and every outcome — including
    the wedged-timeout path — carries ``probe_wall_s``: a wedged-probe
    report should say how long the hang was given, not just that it hung.
    """
    from tpuframe.track.telemetry import get_telemetry

    with get_telemetry().span("doctor/device_probe", timeout_s=timeout_s) as sp:
        rec = _probe_devices(timeout_s)
    rec["probe_wall_s"] = round(sp.elapsed, 3)
    return rec


def _probe_devices(timeout_s: float) -> dict:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {
            "error": f"device probe hung > {timeout_s:.0f}s — backend "
            "wedged (the axon-tunnel failure mode); CPU fallback: "
            "JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="
        }
    if proc.returncode != 0:
        detail = (proc.stderr or proc.stdout).strip()[-500:]
        # never an empty/falsy error: a silently-killed child (OOM,
        # segfault) must still read as a failed probe
        return {"error": f"probe exited rc={proc.returncode}: "
                         f"{detail or '(no output)'}"}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"unparseable probe output: {proc.stdout[-200:]}"}


def _telemetry_env_vars() -> tuple[str, ...]:
    from tpuframe.track.telemetry import OBSERVABILITY_ENV_VARS

    return OBSERVABILITY_ENV_VARS


def telemetry_section() -> dict:
    """State of the telemetry spine (`tpuframe.track.telemetry`): where the
    event log goes, whether a stall watchdog is armed, which exporters are
    live — pasted into bug reports next to the device probe so a "wedged"
    report also says what diagnostics were (or weren't) running."""
    from tpuframe.track.telemetry import get_telemetry

    tele = get_telemetry()
    wd = tele.watchdog
    exporters = ["memory_ring"]
    if tele.jsonl_path:
        exporters.append("jsonl")
    return {
        "event_log": tele.jsonl_path,
        # the fleet-analysis one-liner for THIS run's telemetry dir —
        # paste-ready next to the bug report (track/analyze.py), so it
        # must survive pasting: quote the dir, '.' when path-less
        "analyze": (
            "python -m tpuframe.track analyze "
            f"{shlex.quote(os.path.dirname(tele.jsonl_path) or '.')} --report"
            if tele.jsonl_path else
            "set TPUFRAME_TELEMETRY_DIR, then: "
            "python -m tpuframe.track analyze <dir> --report"
        ),
        "events_buffered": len(tele.recent_events(10**9)),
        "exporters": exporters,
        "watchdog": {
            "active": wd is not None,
            "default_deadline_s": getattr(wd, "default_deadline_s", None),
            "deadlines": dict(getattr(wd, "deadlines", {}) or {}),
            "stalls_reported": len(getattr(wd, "reports", ())),
        },
        "env": {
            k: os.environ[k]
            for k in _telemetry_env_vars()
            if k in os.environ
        },
    }


def compile_section() -> dict:
    """State of the compile spine (`tpuframe.compile`): where the
    persistent compilation cache lives (or would, were it enabled), how
    many entries / MB it holds, the eviction knobs bounding it, and the
    ``TPUFRAME_COMPILE_*`` env — so a "slow cold start / slow recovery"
    report says up front whether warm-start was even on."""
    from tpuframe.compile.cache import COMPILE_ENV_VARS, cache_info

    info = cache_info()
    info["env"] = {
        k: os.environ[k] for k in COMPILE_ENV_VARS if k in os.environ
    }
    return info


def ckpt_section(directory: str | None = None,
                 device_count: int | None = None) -> dict | None:
    """State of a checkpoint directory (``--ckpt-dir`` /
    ``TPUFRAME_CKPT_DIR``): committed steps, quarantined torn steps, and
    the latest committed step's **topology manifest** — the mesh shape
    the checkpoint was saved under.  When the manifest's world size
    disagrees with the probed backend, the section carries a warning
    with the reshard-restore one-liner: the checkpoint is still usable,
    it just restores onto a rebound plan (FAULT.md "Elastic recovery").
    Stdlib-only reads — works against a wedged backend."""
    directory = directory or os.environ.get("TPUFRAME_CKPT_DIR")
    if not directory:
        return None
    from tpuframe.ckpt.meta import read_manifest, valid_steps

    steps = valid_steps(directory)
    qdir = os.path.join(directory, "_quarantine")
    try:
        quarantined = sorted(os.listdir(qdir))
    except (FileNotFoundError, NotADirectoryError):
        quarantined = []
    out: dict = {
        "directory": os.path.abspath(directory),
        "committed_steps": steps[-5:],
        "latest_step": steps[-1] if steps else None,
        "quarantined": quarantined,
    }
    manifest = read_manifest(directory, steps[-1]) if steps else None
    if manifest is not None:
        out["topology"] = {
            "mesh_axes": manifest.get("mesh_axes"),
            "world_size": manifest.get("world_size"),
            "process_count": manifest.get("process_count"),
            "plan_signature": manifest.get("plan_signature"),
            "zero_stage": manifest.get("zero_stage"),
            "leaves": len(manifest.get("leaves") or {}),
        }
        saved_world = manifest.get("world_size")
        if (
            isinstance(device_count, int)
            and isinstance(saved_world, int)
            and device_count != saved_world
        ):
            out["warning"] = (
                f"checkpoint topology (world={saved_world}, mesh="
                f"{manifest.get('mesh_axes')}) != current backend "
                f"({device_count} device(s)): restore reshards at load — "
                "build the survivor mesh, plan = old_plan.rebind(mesh), "
                "then Checkpointer.restore(template, plan=plan) (or "
                "launch.run_elastic, which does all three)"
            )
    elif steps:
        out["topology"] = None  # pre-manifest checkpoint (or host-numpy state)
    return out


def health_section(directory: str | None = None) -> dict:
    """State of the training-health sentinel (``tpuframe.fault.health``):
    whether it is on, the live thresholds (env overrides applied), the
    ``TPUFRAME_HEALTH_*`` env, and — when a checkpoint directory is
    known — the newest committed step's health stamp plus the rollback
    target, so a "my run diverged" report says up front what the
    sentinel would do about it.  Stdlib-only reads, like
    ``read_manifest``."""
    import dataclasses

    from tpuframe.fault.health import (
        HEALTH_ENV_VARS,
        HealthPolicy,
        enabled_by_env,
    )

    # malformed env (TPUFRAME_HEALTH_WINDOW=0, ...) must not crash the
    # report that exists to surface it: show the error WITH the env
    try:
        thresholds = dataclasses.asdict(HealthPolicy.from_env())
    except ValueError as e:
        thresholds = {"error": str(e)}
    out: dict = {
        "enabled": enabled_by_env(),
        "thresholds": thresholds,
        "env": {
            k: os.environ[k] for k in HEALTH_ENV_VARS if k in os.environ
        },
    }
    directory = directory or os.environ.get("TPUFRAME_CKPT_DIR")
    if directory:
        from tpuframe.ckpt.meta import (
            latest_healthy_step,
            latest_step,
            read_health,
        )

        latest = latest_step(directory)
        healthy = latest_healthy_step(directory)
        out["latest_checkpoint"] = {
            "step": latest,
            "health": read_health(directory, latest) if latest is not None
            else None,
            "latest_healthy_step": healthy,
        }
        if latest is not None and healthy != latest:
            out["latest_checkpoint"]["warning"] = (
                f"newest committed step {latest} is stamped unhealthy; a "
                f"divergence rollback would resume at {healthy} "
                "(fault.Supervisor does this automatically; by hand: "
                "tpuframe.ckpt.rollback_to_last_healthy(dir))"
            )
    return out


def serve_section(export_path: str | None = None) -> dict:
    """State of the serving spine (``tpuframe.serve``): the live SLO /
    queue / shed-policy knobs (env overrides applied), the
    ``TPUFRAME_SERVE_*`` env, and — given an export artifact
    (``--export`` / ``TPUFRAME_SERVE_EXPORT``) — its meta plus the
    padded bucket shapes the engine would AOT-precompile for it, with
    the paste-ready ``bench_serve`` one-liner.  Stdlib-only reads
    (:func:`~tpuframe.serve.admission.read_export_meta`) — works
    against a wedged backend, like the ckpt/health sections."""
    import dataclasses

    from tpuframe.serve.admission import SERVE_ENV_VARS, ServeKnobs

    knobs = ServeKnobs.from_env()
    out: dict = {
        "knobs": dataclasses.asdict(knobs),
        "env": {
            k: os.environ[k] for k in SERVE_ENV_VARS if k in os.environ
        },
        "bench": "python benchmarks/bench_serve.py",
    }
    export_path = export_path or os.environ.get("TPUFRAME_SERVE_EXPORT")
    if export_path:
        from tpuframe.serve.admission import read_export_meta

        out["bench"] = (
            f"python benchmarks/bench_serve.py --export "
            f"{shlex.quote(export_path)}"
        )
        try:
            meta = read_export_meta(export_path)
        except (OSError, ValueError) as e:
            out["export"] = {"path": export_path, "error": str(e)}
        else:
            trailing = list(meta.get("input_shape") or [])[1:]
            out["export"] = {
                "path": os.path.abspath(export_path),
                "model": meta.get("model"),
                "version": meta.get("version"),
                "input_shape": meta.get("input_shape"),
                "input_dtype": meta.get("input_dtype"),
                "batch_polymorphic": meta.get("batch_polymorphic"),
                "platforms": meta.get("platforms"),
                # the closed shape set the engine precompiles at start();
                # anything else at runtime is one loud compile/recompile
                "bucket_shapes": [[b] + trailing for b in knobs.buckets],
                "aot_precompile": (
                    "armed at ServeEngine.start() via compile.precompile "
                    "(persistent cache warm; ShapeGuard loud on stray "
                    "shapes)"
                ),
            }
    return out


def fleet_section() -> dict:
    """State of the fleet layer (``tpuframe.serve.fleet``): the
    router/replica-set knobs (env overrides applied), the
    ``TPUFRAME_ROUTER_*``/``TPUFRAME_FLEET_*`` env subset, the bounded
    detection window those knobs imply, and the paste-ready fleet bench
    one-liner.  Stdlib-only (:class:`~tpuframe.serve.router.FleetKnobs`
    never touches jax), like the serve section."""
    import dataclasses

    from tpuframe.serve.admission import SERVE_ENV_VARS
    from tpuframe.serve.router import FleetKnobs

    knobs = FleetKnobs.from_env()
    return {
        "knobs": dataclasses.asdict(knobs),
        "env": {
            k: os.environ[k] for k in SERVE_ENV_VARS
            if k.startswith(("TPUFRAME_ROUTER_", "TPUFRAME_FLEET_"))
            and k in os.environ
        },
        # worst-case probe-driven rotation delay; in-band forwarding
        # failures rotate a replica out immediately, ahead of this
        "detection_window_ms": knobs.probe_ms,
        "bench": "python benchmarks/bench_serve.py --fleet",
    }


def slo_section() -> dict:
    """State of the serving SLO plane (``tpuframe.serve.slo``): the
    declared objectives (strict env parse — a malformed
    ``TPUFRAME_SLO_*`` is *reported*, not crashed on, mirroring the
    health section's threshold idiom), the live burn-rate/error-budget
    gauges off this process's registry, the ``TPUFRAME_SLO_*`` env
    subset, and the paste-ready analyze one-liner whose ``serve_trace``
    block scores a telemetry dir against the objectives that were in
    force.  Stdlib-only, like the serve/fleet sections."""
    import dataclasses

    from tpuframe.serve.admission import SERVE_ENV_VARS
    from tpuframe.serve.slo import SloObjectives
    from tpuframe.track.telemetry import get_telemetry

    try:
        objectives = dataclasses.asdict(SloObjectives.from_env(strict=True))
    except ValueError as e:
        objectives = {"error": str(e)}
    reg = get_telemetry().registry
    return {
        "objectives": objectives,
        # live window state — 0.0 until something observes outcomes
        "burn_rate": reg.gauge("slo/burn_rate").value,
        "error_budget_remaining": reg.gauge("slo/error_budget").value,
        "env": {
            k: os.environ[k] for k in SERVE_ENV_VARS
            if k.startswith("TPUFRAME_SLO_") and k in os.environ
        },
        "analyze": ("python -m tpuframe.track analyze "
                    "$TPUFRAME_TELEMETRY_DIR --report"),
    }


def comms_section() -> dict:
    """State of the wire-compression spine
    (``tpuframe.parallel.compression``): the resolved compression config
    (env knobs applied — mode/buckets/stochastic/EF), the
    ``TPUFRAME_COMMS_*`` env that is set, and the paste-ready
    ``bench_collectives`` one-liner.  Stdlib-only reads
    (``parallel.comms_env``) — works against a wedged backend, like the
    serve/ckpt sections."""
    import dataclasses

    from tpuframe.parallel.comms_env import (
        COMMS_ENV_VARS,
        CommsConfig,
        comms_async_enabled,
        comms_async_flags,
        comms_async_platform,
    )

    out: dict = {
        "env": {
            k: os.environ[k] for k in COMMS_ENV_VARS if k in os.environ
        },
        "bench": "python benchmarks/bench_collectives.py",
    }
    # the async-scheduler knob resolves per-platform (restart-only):
    # print exactly the XLA flag set initialize() would merge, so "why
    # is my overlap not overlapping" is answerable from the report
    plat = comms_async_platform()
    out["async"] = {
        "enabled": comms_async_enabled(),
        "platform": plat,
        "flags": list(comms_async_flags(plat)),
    }
    try:
        config = CommsConfig.from_env()
    except ValueError as e:  # typo'd mode: report it, don't crash the doctor
        out["error"] = str(e)
        return out
    out["enabled"] = config is not None
    if config is not None:
        out["config"] = dataclasses.asdict(config)
    # in-collective wire: fused is resolved off the same config (it is
    # a no-op without a compressed mode), and the A/B arm is the proof
    out["fused"] = {
        "enabled": bool(config is not None and config.fused),
        "bench": "python benchmarks/bench_collectives.py --fused",
    }
    return out


def parallel_section() -> dict:
    """State of the composed-parallelism knobs (``parallel.compose``):
    the resolved pipeline/TP env (``TPUFRAME_PP_*``/``TPUFRAME_TP_SIZE``),
    the legal schedules, and the paste-ready pipeline A/B one-liner.
    Stdlib-only reads (``parallel.comms_env``) — works against a wedged
    backend; what mesh the plan actually composed is a runtime question
    the ``pp/schedule`` event answers."""
    from tpuframe.parallel.comms_env import (
        PP_SCHEDULE_CHOICES,
        pp_microbatches,
        pp_schedule,
        tp_size,
    )

    return {
        "pp_microbatches": pp_microbatches() or None,
        "pp_schedule": pp_schedule(),
        "tp_size": tp_size(),
        "schedules": list(PP_SCHEDULE_CHOICES),
        "env": {
            k: os.environ[k]
            for k in ("TPUFRAME_PP_MICROBATCHES", "TPUFRAME_PP_SCHEDULE",
                      "TPUFRAME_TP_SIZE")
            if k in os.environ
        },
        "bench": "python benchmarks/bench_collectives.py --pipeline",
    }


def profile_section() -> dict:
    """State of the device-time capture path (`track/profiler.py` +
    `track/device_time.py`): the ``TPUFRAME_PROFILE_*`` knobs (malformed
    values reported, not crashed on), the newest surviving capture dir
    with its parsed ``device_time`` summary (stdlib gzip+json — works
    against a wedged backend), and the paste-ready analyze one-liner —
    so a "my step is slow" report says up front whether on-device
    evidence exists and what it already attributes."""
    from tpuframe.track.device_time import (
        PROFILE_ENV_VARS,
        device_time_report,
        list_captures,
        profile_env,
    )

    env = profile_env()
    errors = env.pop("errors")
    out: dict = {
        "armed": bool(env["TPUFRAME_PROFILE_STEPS"]),
        "knobs": env,
        "env": {
            k: os.environ[k] for k in PROFILE_ENV_VARS if k in os.environ
        },
        "analyze": (
            "python -m tpuframe.track analyze "
            "$TPUFRAME_TELEMETRY_DIR --report"
        ),
    }
    if errors:
        out["errors"] = errors
    profile_dir = env["TPUFRAME_PROFILE_DIR"]
    captures = list_captures(profile_dir) if profile_dir else []
    out["captures"] = len(captures)
    if captures:
        newest = captures[-1]
        out["newest_capture"] = newest
        try:
            summary = device_time_report(newest)
        except (OSError, ValueError) as e:  # torn capture ≠ doctor crash
            out["parse_error"] = f"{type(e).__name__}: {e}"
            summary = None
        if summary is not None:
            # the headline numbers, not the whole record (top-op table
            # and per-class breakdown come from the analyze one-liner)
            out["device_time"] = {
                "window_s": summary["window_s"],
                "exposed_comms_s": summary["exposed_comms_s"],
                "overlap_efficiency": summary["overlap_efficiency"],
                "device_tracks": summary["device_tracks"],
                "top_op": (
                    summary["top_ops"][0]["name"]
                    if summary["top_ops"] else None
                ),
            }
    return out


def memory_section() -> dict:
    """State of the memory plane (`track/memory.py` +
    `parallel/memory.py`): the ``TPUFRAME_MEMORY_*`` knobs (malformed
    values reported, not crashed on), the persisted executable-memory
    records next to the compile cache (stdlib json — works against a
    wedged backend), the process-wide watermarks, and a fits /
    doesn't-fit verdict of the known peak against the resolved budget —
    plus the paste-ready estimator one-liner, so a "will it fit" report
    starts from numbers, not a recompile."""
    from tpuframe.track.memory import (
        MEMORY_ENV_VARS,
        executable_records,
        memory_env,
        peaks,
    )

    env = memory_env()
    errors = env.pop("errors")
    out: dict = {
        "knobs": env,
        "env": {
            k: os.environ[k] for k in MEMORY_ENV_VARS if k in os.environ
        },
        # the paste-ready capacity check: price the composed plan's
        # budget before anything compiles
        "estimate": (
            "python -c \"from tpuframe.parallel import compose, plan_memory; "
            "print(plan_memory(compose(), "
            "{'w': ((4096, 4096), 'float32')})['per_device_mb'])\""
        ),
    }
    if errors:
        out["errors"] = errors
    recs = executable_records()
    live = peaks()
    out["executables"] = len(recs)
    out["watermarks"] = {k: round(v, 3) for k, v in live.items() if v}
    # best known per-device peak: live watermark when the backend
    # reports device stats, else the biggest compiled executable
    peak = max(
        (float(r.get("peak_mb") or 0.0) for r in recs.values()), default=0.0
    )
    peak = max(peak, float(live.get("hbm_peak_mb") or 0.0))
    budget = (
        float(env["TPUFRAME_MEMORY_BUDGET_MB"])
        or float(live.get("hbm_limit_mb") or 0.0)
    )
    out["peak_known_mb"] = round(peak, 3) or None
    out["budget_mb"] = round(budget, 3) or None
    if peak and budget:
        # 10% headroom for allocator fragmentation, same margin as
        # suggest_fit
        out["verdict"] = (
            "fits" if peak <= 0.9 * budget
            else "tight" if peak <= budget
            else "does-not-fit"
        )
    else:
        out["verdict"] = "unknown (no budget or no recorded peak — run " \
                         "the estimator one-liner)"
    return out


def autotune_section(devices: dict | None = None) -> dict:
    """State of the self-tuning loop (``tpuframe.autotune``): whether it
    is armed, where the per-``(host, topology, signature)`` configs
    persist, every config stored for THIS host (the plan signature is
    run-scoped, so the doctor lists all of the host's entries and marks
    which match the probed topology), and the paste-ready one-liners —
    so a "my run is slow" report says up front whether a tuned config
    exists and what it would set.  Stdlib-only reads — works against a
    wedged backend, like the serve/ckpt sections."""
    from tpuframe.autotune.config import (
        AUTOTUNE_ENV_VARS,
        autotune_dir,
        autotune_enabled,
        default_host,
        list_tuned,
    )

    host = default_host()
    topology = None
    if devices and isinstance(devices.get("device_count"), int):
        topology = (f"{devices.get('process_count', 1)}x"
                    f"{devices['device_count']}")
    out: dict = {
        "enabled": autotune_enabled(),
        "store": autotune_dir(),
        "host": host,
        "topology": topology,
        "env": {
            k: os.environ[k] for k in AUTOTUNE_ENV_VARS if k in os.environ
        },
        # the paste-ready pair, consistent with the other sections: what
        # is persisted, and how to (re)tune this host
        "show": "python -m tpuframe.autotune --json",
        "tune": ("TPUFRAME_AUTOTUNE=1 python benchmarks/bench_autotune.py "
                 "--json"),
    }
    configs = []
    for cfg in list_tuned():
        if cfg.host != host:
            continue
        configs.append({
            "topology": cfg.topology,
            "signature": cfg.signature,
            "source": cfg.source,
            "env": dict(cfg.env),
            "convergence_ratio": cfg.convergence_ratio,
            "matches_probed_topology": (
                None if topology is None else cfg.topology == topology
            ),
        })
    out["configs"] = configs
    return out


def kernels_section(devices: dict | None = None) -> dict:
    """State of the kernel dispatch plane (``tpuframe.ops``): which
    Pallas execution mode the env + probed backend would pick, the
    ``TPUFRAME_KERNELS`` dispatch mode, the live tile-knob values (as
    the domain-clamped reads the kernels will actually use), every
    registered dispatchable op, and this host's persisted A/B verdicts
    with their shape classes — so a "kernels feel off" report says up
    front what would dispatch and whose measurement decided it.
    Stdlib-only reads (the ledger module never imports jax); the Pallas
    mode is recomputed from env + the subprocess probe's backend rather
    than calling ``ops.dispatch.pallas_mode()``, which needs jax."""
    from tpuframe.ops.ledger import (
        KERNEL_ENV_VARS,
        OPS_REGISTRY,
        attn_block,
        ce_rows,
        kernels_mode,
        ledger_dir,
        list_ledgers,
        norm_tile_rows,
    )
    from tpuframe.autotune.config import default_host

    falsy = {"", "0", "false", "no", "off"}
    disabled = os.environ.get(
        "TPUFRAME_DISABLE_PALLAS", "").strip().lower() not in falsy
    interpret = os.environ.get(
        "TPUFRAME_PALLAS_INTERPRET", "").strip().lower() not in falsy
    backend = (devices or {}).get("backend")
    if disabled:
        pallas = None
    elif interpret:
        pallas = "interpret"
    elif backend is None:
        pallas = "unprobed"  # backend probe failed; can't tell
    else:
        pallas = "compiled" if backend == "tpu" else None

    host = default_host()
    ledgers = []
    for led in list_ledgers():
        if led.host != host:
            continue
        ops = {}
        for op, classes in sorted(led.verdicts.items()):
            ops[op] = {
                cls: {
                    k: v for k, v in verdict.items()
                    if k in ("enable", "choice", "env", "ratio")
                }
                for cls, verdict in sorted(classes.items())
            }
        ledgers.append({
            "backend": led.backend,
            "signature": led.signature,
            "matches_probed_backend": (
                None if backend is None else led.backend == backend
            ),
            "verdicts": ops,
        })
    return {
        "mode": kernels_mode(),
        "pallas": pallas,
        "registry": sorted(OPS_REGISTRY),
        "tiles": {
            "TPUFRAME_KERNEL_CE_ROWS": ce_rows(),
            "TPUFRAME_KERNEL_NORM_TILE_ROWS": norm_tile_rows(),
            "TPUFRAME_KERNEL_ATTN_BLOCK": attn_block(),
        },
        "env": {
            k: os.environ[k] for k in KERNEL_ENV_VARS if k in os.environ
        },
        "store": ledger_dir(),
        "ledgers": ledgers,
        # the paste-ready pair: how to (re)price this host's kernels and
        # how to price the attention round
        "price": "python benchmarks/bench_kernels.py --json",
        "attention": "python benchmarks/bench_attention.py --json",
    }


def lint_section() -> dict:
    """State of the invariant linter (``tpuframe.lint``): the full pass
    run in-process over the installed tree — finding count per rule and
    the paste-ready one-liner, consistent with the compile/serve/ckpt
    sections.  A bug report whose ``lint`` section is dirty says up
    front that the tree's own contracts (jax-free modules, knob
    shipping, telemetry schema) were already broken before whatever is
    being reported.  Stdlib-only like the pass itself."""
    from tpuframe.lint import run_lint

    try:
        result = run_lint()
    except (OSError, SyntaxError, ValueError) as e:  # unreadable tree ≠
        # doctor crash (ValueError covers UnicodeDecodeError/null bytes)
        return {"error": f"{type(e).__name__}: {e}", "cmd": "python -m tpuframe.lint --json"}
    return {
        "findings": len(result.findings),
        "clean": not result.findings,
        "by_rule": result.rule_counts(),
        "files_scanned": result.files_scanned,
        "rules_run": result.rules_run,
        # the paste-ready reproduction next to the verdict, like the
        # telemetry section's analyze one-liner
        "cmd": "python -m tpuframe.lint --json",
    }


def report(probe_timeout_s: float = 30.0, ckpt_dir: str | None = None,
           export_path: str | None = None) -> dict:
    """Collect the full environment report (pure data; printing is main's)."""
    import tpuframe

    from tpuframe.core import native

    devices = probe_devices(probe_timeout_s)
    built = []
    build_dir = os.path.join(os.path.dirname(native.__file__), os.pardir,
                             "_native", "build")
    if os.path.isdir(build_dir):
        built = sorted(f for f in os.listdir(build_dir) if f.endswith(".so"))
    mesh_hint = None
    n = devices.get("device_count")
    if isinstance(n, int) and n > 0:
        mesh_hint = (f"MeshSpec(data=-1) -> {n}-way DP; "
                     f"MeshSpec(data={max(1, n // 8)}, fsdp=8) for ZeRO" if n >= 8
                     else f"MeshSpec(data=-1) -> {n}-way DP")
    return {
        "tpuframe": tpuframe.__version__,
        "python": sys.version.split()[0],
        "devices": devices,
        "mesh_hint": mesh_hint,
        "native_extensions": {
            # toolchain probed independently of the codecs so "g++ there
            # but libzstd/libjpeg missing" reads as exactly that
            "toolchain_available": shutil.which("g++") is not None,
            "zstd_codec": native.native_available(),
            "jpeg_decoder": native.jpeg_native_available(),
            "built": built,
        },
        "optional_deps": {
            name: _module_version(name)
            for name in ("zstandard", "PIL", "torch", "orbax.checkpoint",
                         "cloudpickle", "msgpack")
        },
        "telemetry": telemetry_section(),
        # the compile section's "dir" supersedes the old env-sourced
        # compile_cache_dir key: the spine enables the cache via
        # jax.config, so the env var being unset says nothing
        "compile": compile_section(),
        "ckpt": ckpt_section(ckpt_dir, devices.get("device_count")),
        "health": health_section(ckpt_dir),
        "serve": serve_section(export_path),
        "fleet": fleet_section(),
        "slo": slo_section(),
        "comms": comms_section(),
        "parallel": parallel_section(),
        "profile": profile_section(),
        "memory": memory_section(),
        "autotune": autotune_section(devices),
        "kernels": kernels_section(devices),
        "lint": lint_section(),
        "env": {
            k: os.environ[k]
            for k in ("JAX_PLATFORMS", "XLA_FLAGS", "PALLAS_AXON_POOL_IPS",
                      "TPUFRAME_DEBUG")
            if k in os.environ
        },
        # every spine knob that is actually set, off the one aggregated
        # registry (launch.remote.all_env_vars — the same list shipped
        # to remote workers), so a bug report carries the full config
        "knobs_set": _knobs_set(),
    }


def _knobs_set() -> dict:
    from tpuframe.launch.remote import all_env_vars

    return {k: os.environ[k] for k in all_env_vars() if k in os.environ}


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tpuframe",
        description="tpuframe environment doctor (one JSON report)",
    )
    ap.add_argument("--probe-timeout", type=float, default=30.0,
                    help="seconds before declaring the backend wedged")
    ap.add_argument("--ckpt-dir", default=None,
                    help="checkpoint directory to report on (committed "
                         "steps + the latest step's topology manifest; "
                         "default: TPUFRAME_CKPT_DIR)")
    ap.add_argument("--export", default=None, dest="export_path",
                    help="serve export artifact to report on (meta + "
                         "AOT bucket shapes + the bench_serve one-liner; "
                         "default: TPUFRAME_SERVE_EXPORT)")
    args = ap.parse_args(argv)
    rec = report(args.probe_timeout, args.ckpt_dir, args.export_path)
    print(json.dumps(rec, indent=2))
    return 1 if "error" in rec["devices"] else 0


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    raise SystemExit(main())
