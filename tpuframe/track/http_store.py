"""Remote MLflow tracking over the REST API (http/https tracking URIs).

The reference logs to a *remote* Databricks-hosted MLflow server, with every
worker re-authenticating from env credentials
(`/root/reference/setup/00_setup.py:86-101`,
`/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:184-189`).
The local file store (mlflow_store.py) covers the mlruns-directory world;
this module keeps the same ``Run``/tracker duck-type against any stock
MLflow server using only stdlib HTTP — no mlflow package needed:

- MLflow REST 2.0: experiments/get-by-name|create, runs/create,
  runs/log-batch (params+metrics, batched), runs/set-tag, runs/update.
- Artifacts: the ``mlflow-artifacts`` proxy (``mlflow server
  --serve-artifacts``) via HTTP PUT; servers without the proxy get the
  upload skipped with a recorded ``tpuframe.artifact_skipped`` tag rather
  than a crashed run.
- Auth from env, the reference's re-auth pattern: Bearer
  ``MLFLOW_TRACKING_TOKEN`` (or ``DATABRICKS_TOKEN``), else Basic
  ``MLFLOW_TRACKING_USERNAME``/``MLFLOW_TRACKING_PASSWORD``.

Select by URI scheme: ``make_tracker("http://host:5000")`` (or pass the
URI to ``MLflowLogger``/``set_experiment``) routes here automatically.
"""

from __future__ import annotations

import base64
import json
import os
import urllib.error
import urllib.request
from typing import Any, Mapping

_API = "/api/2.0/mlflow"


def _now_ms() -> int:
    import time

    return int(time.time() * 1000)


class HttpError(RuntimeError):
    def __init__(self, status: int, body: str, url: str):
        super().__init__(f"HTTP {status} from {url}: {body[:300]}")
        self.status = status


class _Client:
    """Tiny JSON-over-HTTP client with env-credential auth."""

    def __init__(self, base_url: str, timeout_s: float = 30.0):
        self.base = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def _headers(self) -> dict[str, str]:
        h = {"Content-Type": "application/json"}
        token = os.environ.get("MLFLOW_TRACKING_TOKEN") or os.environ.get(
            "DATABRICKS_TOKEN"
        )
        user = os.environ.get("MLFLOW_TRACKING_USERNAME")
        if token:
            h["Authorization"] = f"Bearer {token}"
        elif user:
            pw = os.environ.get("MLFLOW_TRACKING_PASSWORD", "")
            cred = base64.b64encode(f"{user}:{pw}".encode()).decode()
            h["Authorization"] = f"Basic {cred}"
        return h

    def call(self, method: str, path: str, payload: Mapping | None = None) -> dict:
        url = self.base + path
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            url, data=data, method=method, headers=self._headers()
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                body = resp.read()
                return json.loads(body) if body else {}
        except urllib.error.HTTPError as e:
            raise HttpError(e.code, e.read().decode(errors="replace"), url) from None

    def put_bytes(self, path: str, blob: bytes) -> None:
        url = self.base + path
        headers = self._headers()
        headers["Content-Type"] = "application/octet-stream"
        req = urllib.request.Request(url, data=blob, method="PUT", headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                return
        except urllib.error.HTTPError as e:
            raise HttpError(e.code, e.read().decode(errors="replace"), url) from None


class HttpRun:
    """Duck-type of :class:`tpuframe.track.mlflow_store.Run` over REST."""

    #: MLflow's runs/log-batch caps: 100 params, 1000 total entities per
    #: request (metrics effectively 1000; we stay under both).
    PARAM_BATCH = 100
    METRIC_BATCH = 900

    def __init__(self, client: _Client, experiment_id: str,
                 run_id: str | None = None, run_name: str | None = None):
        self._client = client
        self.experiment_id = experiment_id
        if run_id is None:
            payload = {
                "experiment_id": experiment_id,
                "start_time": _now_ms(),
                "run_name": run_name or "",
            }
            info = client.call("POST", f"{_API}/runs/create", payload)["run"]["info"]
            run_id = info["run_id"]
            run_name = info.get("run_name", run_name)
        self.run_id = run_id
        self.run_name = run_name or f"run-{run_id[:8]}"

    # -- params / metrics / tags ------------------------------------------
    def _log_batch(self, params=(), metrics=()) -> None:
        params, metrics = list(params), list(metrics)
        while params or metrics:
            take_p, params = params[: self.PARAM_BATCH], params[self.PARAM_BATCH:]
            take_m, metrics = metrics[: self.METRIC_BATCH], metrics[self.METRIC_BATCH:]
            self._client.call(
                "POST", f"{_API}/runs/log-batch",
                {"run_id": self.run_id, "params": take_p, "metrics": take_m},
            )

    def log_param(self, key: str, value: Any) -> None:
        self._log_batch(params=[{"key": key, "value": str(value)}])

    def log_params(self, params: Mapping[str, Any]) -> None:
        self._log_batch(
            params=[{"key": k, "value": str(v)} for k, v in params.items()]
        )

    def log_metric(self, key: str, value: float, step: int = 0) -> None:
        self.log_metrics({key: value}, step)

    def log_metrics(self, metrics: Mapping[str, float], step: int = 0) -> None:
        ts = _now_ms()
        self._log_batch(metrics=[
            {"key": k, "value": float(v), "timestamp": ts, "step": int(step)}
            for k, v in metrics.items()
        ])

    def set_tag(self, key: str, value: Any) -> None:
        self._client.call(
            "POST", f"{_API}/runs/set-tag",
            {"run_id": self.run_id, "key": key, "value": str(value)},
        )

    # -- artifacts ---------------------------------------------------------
    def log_artifact(self, local_path: str, artifact_path: str | None = None) -> str:
        name = os.path.basename(local_path)
        rel = f"{artifact_path}/{name}" if artifact_path else name
        with open(local_path, "rb") as f:
            blob = f.read()
        try:
            self._client.put_bytes(
                f"/api/2.0/mlflow-artifacts/artifacts/"
                f"{self.experiment_id}/{self.run_id}/artifacts/{rel}",
                blob,
            )
        except (HttpError, urllib.error.URLError):
            # server has no artifact proxy: record the gap, don't crash the fit
            self.set_tag("tpuframe.artifact_skipped", rel)
        return rel

    def log_text(self, text: str, artifact_file: str) -> str:
        import shutil
        import tempfile

        d = tempfile.mkdtemp(prefix="tpuframe_http_art_")
        try:
            local = os.path.join(d, os.path.basename(artifact_file))
            with open(local, "w") as f:
                f.write(text)
            sub = os.path.dirname(artifact_file) or None
            return self.log_artifact(local, sub)
        finally:
            shutil.rmtree(d, ignore_errors=True)

    def log_dict(self, data: Mapping[str, Any], artifact_file: str) -> str:
        return self.log_text(
            json.dumps(dict(data), indent=2, default=str), artifact_file
        )

    def log_state_dict(self, tree: Any, artifact_path: str = "state_dict") -> str:
        import shutil
        import tempfile

        from tpuframe.ckpt import save_pytree

        d = tempfile.mkdtemp(prefix="tpuframe_http_art_")
        try:
            local = os.path.join(d, "state.msgpack")
            save_pytree(local, tree)
            return self.log_artifact(local, artifact_path)
        finally:
            shutil.rmtree(d, ignore_errors=True)

    def log_model(self, state: Any, artifact_path: str = "model",
                  meta: Mapping[str, Any] | None = None) -> str:
        import shutil
        import tempfile

        from tpuframe.ckpt import save_pytree

        d = tempfile.mkdtemp(prefix="tpuframe_http_model_")
        try:
            tree = {
                "params": getattr(state, "params", state),
                "batch_stats": getattr(state, "batch_stats", {}),
            }
            save_pytree(os.path.join(d, "model.msgpack"), tree)
            self.log_artifact(os.path.join(d, "model.msgpack"), artifact_path)
        finally:
            shutil.rmtree(d, ignore_errors=True)
        self.log_dict(
            {"flavors": {"tpuframe": {"format": "flax-msgpack",
                                      "data": "model.msgpack",
                                      **dict(meta or {})}},
             "run_id": self.run_id},
            f"{artifact_path}/MLmodel.json",
        )
        return artifact_path

    # -- lifecycle ---------------------------------------------------------
    def end(self, status: str = "FINISHED") -> None:
        self._client.call(
            "POST", f"{_API}/runs/update",
            {"run_id": self.run_id, "status": status, "end_time": _now_ms()},
        )

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        self.end("FAILED" if exc_type else "FINISHED")


class HttpExperimentTracker:
    """Duck-type of :class:`ExperimentTracker` against a remote server."""

    def __init__(self, tracking_uri: str):
        self.tracking_uri = tracking_uri
        self._client = _Client(tracking_uri)
        self.experiment_id: str | None = None
        self.experiment_name: str | None = None

    def set_experiment(self, name: str) -> str:
        try:
            exp = self._client.call(
                "GET",
                f"{_API}/experiments/get-by-name?experiment_name="
                + urllib.request.quote(name, safe=""),
            )["experiment"]
            self.experiment_id = exp["experiment_id"]
        except HttpError as e:
            if e.status != 404:
                raise
            self.experiment_id = self._client.call(
                "POST", f"{_API}/experiments/create", {"name": name}
            )["experiment_id"]
        self.experiment_name = name
        return self.experiment_id

    def start_run(self, run_name: str | None = None,
                  run_id: str | None = None) -> HttpRun:
        if self.experiment_id is None:
            self.set_experiment("Default")
        return HttpRun(
            self._client, self.experiment_id, run_id=run_id, run_name=run_name
        )


def is_http_uri(tracking_uri: str) -> bool:
    return tracking_uri.startswith(("http://", "https://"))


def make_tracker(tracking_uri: str):
    """File store for paths/file:// URIs, REST client for http(s)://."""
    if is_http_uri(tracking_uri):
        return HttpExperimentTracker(tracking_uri)
    from tpuframe.track.mlflow_store import ExperimentTracker

    return ExperimentTracker(tracking_uri)


class MetricsServer:
    """Prometheus-style scrape endpoint over the telemetry metrics registry.

    Serves ``GET /metrics`` (exposition text from
    ``MetricsRegistry.prometheus_text``) and ``GET /healthz`` from a daemon
    thread — the pull-based half of the telemetry spine's export story
    (the push half is the logger bridge, ``telemetry.publish_to_loggers``).
    ``port=0`` picks a free port; read it back from ``.port``/``.url``.
    """

    def __init__(self, registry=None, host: str = "127.0.0.1", port: int = 0):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        if registry is None:
            from tpuframe.track.telemetry import get_telemetry

            registry = get_telemetry().registry
        self.registry = registry
        server_self = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] == "/metrics":
                    body = server_self.registry.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?")[0] == "/healthz":
                    body, ctype = b'{"status": "ok"}', "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tpuframe-metrics", daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
