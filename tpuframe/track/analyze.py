"""Fleet-level trace analysis: cross-rank JSONL merge, skew, stragglers.

A multi-host tpuframe job is only as fast as its slowest rank, and the
telemetry spine (`track/telemetry.py`) already gives every rank an
attributed ``events-rank<N>.jsonl`` log — but nothing read those logs
*together*.  This module is the fleet layer on top of the spine, the
capability the reference repo delegates to Ray's dashboard and MLflow
system metrics (SURVEY.md §5) and profiling-driven TPU work treats as
table stakes:

- :func:`load_dir` merges a ``TPUFRAME_TELEMETRY_DIR`` of per-rank logs
  (rotated segments included, oldest-first) and aligns ranks on the
  wall/monotonic **anchor pair** from each log's ``meta`` first line —
  a rank whose wall clock steps mid-run (NTP) still lands on the shared
  timeline, because placement uses its steady monotonic clock.
- :func:`build_trace` renders the merged fleet as a Chrome/Perfetto
  ``trace.json``: one process track per rank (named ``rank N @ host``),
  one thread track per instrumented thread, spans as complete events,
  stalls/faults/stragglers as instant events.
- :func:`skew_report` builds the per-step cross-rank skew table: for
  each ``train/step`` batch index, min/median/max wall time, the
  slowest rank, time lost to the straggler, and an input-bound vs
  compute-bound vs checkpoint-bound classification derived from the
  ``train/step`` span (+ its ``data_wait_s`` attribute) and ``ckpt/*``
  spans.
- :func:`baseline_diff` compares the run's step-time distribution
  against committed ``benchmarks/results/*.json`` records (any record
  carrying a ``step_time`` block, e.g. ``analyze_selftest_cpu.json``).
- :class:`StragglerMonitor` is the *live* counterpart, wired into the
  Trainer: each rank keeps a rolling step-time EWMA in the registry
  (``train/step_ewma_s``), and every ``sync_steps`` steps the fleet
  compares EWMAs through a tiny ``agree()``-style all-gather (same
  degradation ladder as ``fault/preempt.py``).  A rank exceeding the
  fleet median by ``factor`` emits a ``train/straggler`` event and the
  ``train/skew_ratio`` gauge.  Single-process topologies degrade to a
  self-baseline: the current EWMA against the rank's own median step
  time, which still catches a rank *going* slow (thermal throttle, a
  dying disk feeding the loader).

CLI: ``python -m tpuframe.track analyze <dir> [--trace out.json]
[--report] [--baseline results/]`` — stdlib-only, never imports jax
(analyzing a wedged fleet's logs must not require a working backend).
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import argparse
import bisect
import glob
import json
import os
import re
import statistics
import sys
import time
from collections import deque
from typing import Any, Callable, Iterable, Sequence

from tpuframe.track.device_time import device_time_report, device_trace_events
from tpuframe.track.telemetry import Histogram, get_telemetry

__all__ = [
    "RankLog",
    "StragglerMonitor",
    "baseline_diff",
    "build_trace",
    "fleet_allgather",
    "fleet_degraded",
    "format_report",
    "load_dir",
    "load_dirs",
    "load_rank",
    "main",
    "reset_fleet_degraded",
    "skew_report",
]

_RANK_RE = re.compile(r"events-rank(\d+)\.jsonl$")

#: envelope keys every record carries; everything else is event payload
_ENVELOPE = ("v", "ts", "mono", "rank", "pid", "thread", "kind", "name")

#: span names that mark checkpoint I/O for boundedness classification
_CKPT_SPANS = ("ckpt/save", "ckpt/restore", "fault/preempt_checkpoint")

#: records that carry compile wall: AOT spans from the precompiler plus
#: the cache listener's per-real-compile events (the listener suppresses
#: its event inside an explicit compile span, so summing both never
#: double-counts one compile)
_COMPILE_RECORDS = ("compile/lower", "compile/backend_compile")


# -- loading + clock alignment ------------------------------------------------


class RankLog:
    """One rank's merged event stream + its clock-alignment offsets.

    ``meta`` is the log's first ``meta`` record (or None for pre-meta
    logs).  With a meta anchor pair, :meth:`end_time` places a record at
    ``mono + (anchor_wall - anchor_mono)`` — the rank's steady monotonic
    clock mapped onto the wall timeline fixed at configure time, immune
    to mid-run wall-clock steps.  Anchors are kept **per pid**: a
    restarted process appending to the same log brings a fresh monotonic
    epoch (near zero after a host reboot), so its events must align with
    *its own* meta, not the dead predecessor's.  Records with no usable
    anchor fall back to their raw ``ts``.
    """

    def __init__(self, rank: int, events: list[dict], *,
                 meta: dict | None = None, path: str | None = None,
                 metas: Sequence[dict] = ()):
        self.rank = rank
        self.events = events
        self.meta = meta
        self.path = path
        # pid -> (anchor_wall - anchor_mono); the newest meta per pid
        # wins (a re-configure within one process is a re-calibration)
        self.pid_offsets: dict[Any, float] = {}
        for m in list(metas) or ([meta] if meta else []):
            aw, am = m.get("anchor_wall"), m.get("anchor_mono")
            if aw is not None and am is not None:
                self.pid_offsets[m.get("pid")] = float(aw) - float(am)
        self.mono_offset: float | None = None
        if meta is not None:
            aw, am = meta.get("anchor_wall"), meta.get("anchor_mono")
            if aw is not None and am is not None:
                self.mono_offset = float(aw) - float(am)

    @property
    def hostname(self) -> str:
        return (self.meta or {}).get("hostname", "") or ""

    def end_time(self, rec: dict) -> float:
        """Fleet-aligned wall-clock time a record was written at."""
        mono = rec.get("mono")
        offset = self.pid_offsets.get(rec.get("pid"), self.mono_offset)
        if mono is not None and offset is not None:
            return float(mono) + offset
        return float(rec.get("ts", 0.0))

    def __repr__(self):
        return (f"RankLog(rank={self.rank}, events={len(self.events)}, "
                f"host={self.hostname!r})")


def _segments(base: str) -> list[str]:
    """A log's files oldest-first: ``base.K`` .. ``base.1``, then ``base``
    (the rotation order `telemetry.Telemetry._rotate_locked` produces)."""
    suffixes = []
    for p in glob.glob(base + ".*"):
        suf = p[len(base) + 1:]
        if suf.isdigit():
            suffixes.append(int(suf))
    return [f"{base}.{n}" for n in sorted(suffixes, reverse=True)] + [base]


def load_rank(base: str) -> RankLog:
    """Parse one rank's log (rotated segments in order).  Torn trailing
    lines (a crash mid-write) and blank lines are skipped, not fatal —
    the analyzer's whole job is reading logs of runs that died."""
    events: list[dict] = []
    metas: list[dict] = []
    for path in _segments(base):
        try:
            f = open(path)
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn line
                if not isinstance(rec, dict):
                    continue
                if rec.get("kind") == "meta":
                    # every meta kept: a restarted process appended its
                    # own anchors, and RankLog aligns per pid
                    metas.append(rec)
                else:
                    events.append(rec)
    meta = metas[0] if metas else None
    m = _RANK_RE.search(base)
    if m:
        rank = int(m.group(1))
    elif meta is not None:
        rank = int(meta.get("rank", 0))
    else:
        rank = int(events[0].get("rank", 0)) if events else 0
    return RankLog(rank, events, meta=meta, path=base, metas=metas)


def load_dir(d: str) -> list[RankLog]:
    """All ranks under a telemetry dir, rank-ordered."""
    bases = sorted(
        p for p in glob.glob(os.path.join(d, "events-rank*.jsonl"))
        if _RANK_RE.search(p)
    )
    if not bases:
        raise FileNotFoundError(
            f"no events-rank*.jsonl under {d!r} — is this a "
            "TPUFRAME_TELEMETRY_DIR?"
        )
    ranks = [load_rank(b) for b in bases]
    ranks.sort(key=lambda r: r.rank)
    return ranks


def load_dirs(dirs: Sequence[str]) -> list[RankLog]:
    """Multiple telemetry dirs stitched into one rank list.

    The multi-process serve topology (router + N replica servers, each
    its own process with its own ``TPUFRAME_TELEMETRY_DIR``) logs rank 0
    in every dir; loading them together must not collapse those onto one
    Perfetto track.  Colliding rank numbers from later dirs are offset
    by +1000 per collision — each process keeps its own pid lane — while
    the per-pid wall/mono anchors (which travel inside each log) do the
    cross-process time alignment, so one trace id lines up across all of
    them.  A single dir loads exactly like :func:`load_dir`.
    """
    all_ranks: list[RankLog] = []
    used: set[int] = set()
    for d in dirs:
        for rl in load_dir(d):
            r = rl.rank
            while r in used:
                r += 1000
            rl.rank = r
            used.add(r)
            all_ranks.append(rl)
    all_ranks.sort(key=lambda r: r.rank)
    return all_ranks


# -- Perfetto / Chrome trace --------------------------------------------------


def _fleet_t0(ranks: Sequence[RankLog]) -> float:
    """Earliest aligned instant across the fleet (span starts included)."""
    t0 = None
    for rl in ranks:
        for rec in rl.events:
            t = rl.end_time(rec)
            if rec.get("kind") == "span":
                t -= float(rec.get("dur_s", 0.0))
            if t0 is None or t < t0:
                t0 = t
    return t0 or 0.0


def _clip(v: Any, cap: int = 400) -> Any:
    return v[:cap] if isinstance(v, str) and len(v) > cap else v


def build_trace(ranks: Sequence[RankLog]) -> dict:
    """Chrome Trace Event JSON (Perfetto/chrome://tracing loadable).

    One ``pid`` per rank, one ``tid`` per thread; spans become complete
    ("X") events at microsecond resolution, everything else becomes an
    instant ("i") event — stalls, faults, stragglers, bench attempts.
    """
    t0 = _fleet_t0(ranks)
    out: list[dict] = []
    for rl in ranks:
        pid = rl.rank
        label = f"rank {rl.rank}" + (f" @ {rl.hostname}" if rl.hostname else "")
        out.append({"ph": "M", "pid": pid, "name": "process_name",
                    "args": {"name": label}})
        out.append({"ph": "M", "pid": pid, "name": "process_sort_index",
                    "args": {"sort_index": rl.rank}})
        tids: dict[str, int] = {}

        def tid_for(thread: str) -> int:
            if thread not in tids:
                # MainThread pinned to tid 0; helpers in appearance order
                tids[thread] = 0 if thread == "MainThread" else len(tids) + 1
            return tids[thread]

        for rec in rl.events:
            t_end = rl.end_time(rec)
            tid = tid_for(str(rec.get("thread", "?")))
            name = str(rec.get("name", "?"))
            payload = {k: _clip(v) for k, v in rec.items()
                       if k not in _ENVELOPE and k != "attrs"}
            payload.update(
                {k: _clip(v) for k, v in (rec.get("attrs") or {}).items()}
            )
            if rec.get("kind") == "span":
                dur = float(rec.get("dur_s", 0.0))
                ev = {
                    "ph": "X", "pid": pid, "tid": tid, "name": name,
                    "cat": name.split("/")[0],
                    "ts": round((t_end - dur - t0) * 1e6, 1),
                    "dur": round(dur * 1e6, 1),
                    "args": {k: v for k, v in payload.items()
                             if k not in ("dur_s", "stack", "ok")},
                }
                if not rec.get("ok", True):
                    ev["cname"] = "terrible"  # failed spans read red
            else:
                ev = {
                    "ph": "i", "pid": pid, "tid": tid, "name": name,
                    "cat": str(rec.get("kind", "event")),
                    "ts": round((t_end - t0) * 1e6, 1),
                    "s": "t",  # thread-scoped flag
                    "args": payload,
                }
            out.append(ev)
        for thread, tid in tids.items():
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name", "args": {"name": thread}})
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_sort_index",
                        "args": {"sort_index": tid}})
        # device tracks: the rank's newest surviving profiler capture
        # merges under the SAME pid, so host spans and device ops share
        # one timeline.  Trace timestamps are µs offsets from capture
        # start; the profile/capture event recorded that start as a
        # wall/mono anchor pair, aligned exactly like any other record.
        cap = None
        for rec in rl.events:
            if rec.get("name") == "profile/capture" and rec.get("dir"):
                if os.path.isdir(str(rec["dir"])):
                    cap = rec
        if cap is not None:
            cap_t0 = rl.end_time({
                "mono": cap.get("mono_start"),
                "ts": cap.get("wall_start") or 0.0,
                "pid": cap.get("pid"),
            })
            dev_tids: dict[str, int] = {}
            for dev_ev in device_trace_events(str(cap["dir"])):
                key = f"{dev_ev['device']} {dev_ev['thread']}"
                # device tids live above 1000: no collision with the
                # appearance-ordered host thread tids
                tid = dev_tids.setdefault(key, 1000 + len(dev_tids))
                out.append({
                    "ph": "X", "pid": pid, "tid": tid,
                    "name": dev_ev["name"],
                    "cat": f"device/{dev_ev['class']}",
                    "ts": round(
                        (cap_t0 + dev_ev["ts_us"] / 1e6 - t0) * 1e6, 1
                    ),
                    "dur": round(dev_ev["dur_us"], 1),
                    "args": {"class": dev_ev["class"]},
                })
            for key, tid in dev_tids.items():
                out.append({"ph": "M", "pid": pid, "tid": tid,
                            "name": "thread_name", "args": {"name": key}})
                out.append({"ph": "M", "pid": pid, "tid": tid,
                            "name": "thread_sort_index",
                            "args": {"sort_index": tid}})
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "tpuframe.track.analyze",
            "ranks": len(ranks),
            "t0_unix_s": round(t0, 6),
        },
    }


# -- cross-rank skew ----------------------------------------------------------


# ONE quantile convention repo-wide: whatever the registry histograms
# report on /metrics is what baseline_diff ratios against — a fix to the
# index rule must land in telemetry.Histogram and flow here
_pctl = Histogram._quantile


def _step_rows(rl: RankLog) -> dict[int, dict]:
    """This rank's ``train/step`` spans keyed by batch index, with the
    inter-step period (``wall_s``) that captures everything between step
    boundaries — data wait, dispatch, mid-epoch checkpoints, GC pauses,
    callbacks.  However large: a 10 s checkpoint stall between 0.1 s
    steps is exactly what the skew report exists to surface, so the
    period is only rejected on *structural* grounds — a different pid
    (restart appended to the same log) or an epoch boundary in between
    (eval/epoch turnover time is not one step's cost) — never because
    it is "too big"."""
    epoch_ends = sorted(
        rl.end_time(rec) for rec in rl.events
        if rec.get("kind") == "span" and rec.get("name") == "train/epoch"
    )

    def crosses_epoch_boundary(a: float, b: float) -> bool:
        i = bisect.bisect_right(epoch_ends, a)
        return i < len(epoch_ends) and epoch_ends[i] < b

    rows: dict[int, dict] = {}
    prev_end: float | None = None
    prev_batch: int | None = None
    prev_pid: Any = None
    for rec in rl.events:
        if rec.get("kind") != "span" or rec.get("name") != "train/step":
            continue
        attrs = rec.get("attrs") or {}
        batch = attrs.get("batch")
        if batch is None:
            continue
        batch = int(batch)
        end = rl.end_time(rec)
        dur = float(rec.get("dur_s", 0.0))
        wait = float(attrs.get("data_wait_s", 0.0))
        wall = dur + wait
        if (
            prev_end is not None
            and prev_batch == batch - 1
            and rec.get("pid") == prev_pid
            and not crosses_epoch_boundary(prev_end, end)
        ):
            period = end - prev_end
            if period >= wall:
                wall = period
        rows[batch] = {"dur_s": dur, "data_wait_s": wait, "end": end,
                       "wall_s": wall}
        prev_end, prev_batch = end, batch
        prev_pid = rec.get("pid")
    return rows


def _ckpt_windows(rl: RankLog) -> list[tuple[float, float]]:
    wins = []
    for rec in rl.events:
        if rec.get("kind") == "span" and rec.get("name") in _CKPT_SPANS:
            end = rl.end_time(rec)
            wins.append((end - float(rec.get("dur_s", 0.0)), end))
    return wins


def _classify(entry: dict, ckpt_wins: list[tuple[float, float]]) -> str:
    """Why was the slowest rank's step slow?  Checkpoint overlap beats
    input wait beats the compute default."""
    start = entry["end"] - entry["wall_s"]
    for a, b in ckpt_wins:
        if b > start and a < entry["end"]:
            return "checkpoint"
    if entry["data_wait_s"] >= 0.5 * max(entry["wall_s"], 1e-12):
        return "input"
    return "compute"


def _compile_wall(rl: RankLog) -> dict:
    """Measured compile wall in this rank's log: ``compile/lower`` +
    ``compile/backend_compile`` spans (the AOT path) and
    ``compile/backend_compile`` events (implicit runtime compiles, each
    a real backend compile — persistent-cache hits emit none)."""
    wall, n = 0.0, 0
    for rec in rl.events:
        if rec.get("name") not in _COMPILE_RECORDS:
            continue
        try:
            wall += float(rec.get("dur_s", 0.0))
        except (TypeError, ValueError):
            continue
        n += 1
    return {"wall_s": round(wall, 6), "records": n}


def _health_info(rl: RankLog) -> dict:
    """Training-health sentinel records in this rank's log: skipped
    (bad) steps, divergences raised, rollbacks performed — the
    skip -> escalate -> rollback ladder's event trail."""
    bad_steps, bad_events, divergences = 0, 0, 0
    rollbacks: list[dict] = []
    for rec in rl.events:
        name = rec.get("name")
        if name == "health/bad_step":
            bad_events += 1
            try:
                bad_steps += int(rec.get("bad_in_window", 1) or 1)
            except (TypeError, ValueError):
                bad_steps += 1
        elif name == "health/divergence":
            divergences += 1
        elif name == "fault/rollback":
            rollbacks.append({
                "to_step": rec.get("to_step"),
                "quarantined": rec.get("quarantined"),
            })
    return {
        "bad_steps": bad_steps,
        "bad_step_events": bad_events,
        "divergences": divergences,
        "rollbacks": rollbacks,
    }


def _device_time_info(ranks: Sequence[RankLog]) -> dict | None:
    """The parsed ``device_time`` block: the NEWEST ``profile/capture``
    event whose trace dir still exists on disk (the cadence callback
    rotates old captures away; a one-shot temp capture is zipped into an
    artifact and its dir deleted — both read as "no parseable capture",
    not an error).  Parsing is the stdlib gzip+json path in
    `track/device_time.py` — no jax, so a wedged fleet's capture still
    attributes."""
    best: tuple[int, dict] | None = None
    captures = 0
    for rl in ranks:
        for rec in rl.events:
            if rec.get("name") != "profile/capture":
                continue
            captures += 1
            d = rec.get("dir")
            if d and os.path.isdir(str(d)):
                best = (rl.rank, rec)
    if best is None:
        return None
    rank, rec = best
    try:
        steps = int(rec.get("steps") or 0) or None
    except (TypeError, ValueError):
        steps = None
    dt = device_time_report(str(rec["dir"]), steps=steps)
    if dt is None:
        return None
    dt["rank"] = rank
    dt["captures"] = captures
    dt["partial"] = bool(rec.get("partial"))
    return dt


def _time_to_first_step(rl: RankLog) -> float | None:
    """Seconds from this rank's first telemetry record to the end of its
    first ``train/step`` span — what a cold start actually cost the rank
    (loader spin-up, compile, restore, the step itself)."""
    t0: float | None = None
    first_step: float | None = None
    for rec in rl.events:
        t = rl.end_time(rec)
        if rec.get("kind") == "span":
            t -= float(rec.get("dur_s", 0.0))
        if t0 is None or t < t0:
            t0 = t
        if (
            first_step is None
            and rec.get("kind") == "span"
            and rec.get("name") == "train/step"
        ):
            first_step = rl.end_time(rec)
    if t0 is None or first_step is None:
        return None
    return max(0.0, first_step - t0)


# -- request-path trace attribution -------------------------------------------

#: serve_trace block schema (versioned like device_time: additive ->
#: minor bump, rename/removal -> major bump + consumer update)
SERVE_TRACE_VERSION = "1.0"

#: span name -> hop key, in request-path order.  fleet/route and
#: fleet/hop come from the router (route = total front-door time, hop =
#: one forward attempt); door/queue_wait are per-request engine spans;
#: assemble/infer are batch-scoped (a ``traces`` list fans the one span
#: out to every member request); respond is the server's response write.
_TRACE_HOP_SPANS = {
    "fleet/route": "route",
    "fleet/hop": "hop",
    "serve/door": "door",
    "serve/queue_wait": "queue_wait",
    "serve/assemble": "assemble",
    "serve/infer": "infer",
    "serve/respond": "respond",
}

_TRACE_HOP_ORDER = (
    "route", "hop", "door", "queue_wait", "assemble", "infer", "respond",
)


def _span_field(rec: dict, key: str) -> Any:
    """A span attribute wherever it lives: ``tele.span`` nests kwargs in
    the ``attrs`` sub-dict, synthetic span records (``tele.event(...,
    kind="span")`` — cross-thread hops whose outcome is only known after
    the fact) carry them top-level."""
    v = rec.get(key)
    if v is None:
        v = (rec.get("attrs") or {}).get(key)
    return v


def _quantile_block(vals: list[float]) -> dict:
    vals = sorted(vals)
    return {
        "count": len(vals),
        "p50": round(_pctl(vals, 0.50), 6),
        "p95": round(_pctl(vals, 0.95), 6),
        "p99": round(_pctl(vals, 0.99), 6),
    }


def _serve_trace_info(ranks: Sequence[RankLog]) -> dict | None:
    """Per-hop request-path attribution from the trace-tagged spans the
    router/server/engine emit; None when the run traced nothing.

    Durations accumulate **per trace id** first (a retried request's two
    ``fleet/hop`` spans sum; a batch-scoped ``serve/infer`` charges its
    full duration to every member trace — the batch is the unit of
    device work each rider waits for), then quantile per hop, so the
    hop p50/p95/p99 are distributions over *requests*, comparable with
    the end-to-end latency distribution: ``queue_wait + assemble +
    infer`` tiles the engine-side path, and e2e minus the hop sum is
    unattributed transport/scheduling time.
    """
    per_trace: dict[str, dict[str, float]] = {}
    route_spans = 0
    hop_spans = 0
    objectives: dict | None = None
    for rl in ranks:
        for rec in rl.events:
            if rec.get("name") == "slo/objectives":
                objectives = rec
                continue
            if rec.get("kind") != "span":
                continue
            hop = _TRACE_HOP_SPANS.get(rec.get("name"))
            if hop is None:
                continue
            try:
                dur = float(rec.get("dur_s", 0.0))
            except (TypeError, ValueError):
                continue
            traces = _span_field(rec, "traces")
            if not isinstance(traces, (list, tuple)):
                t = _span_field(rec, "trace")
                traces = [t] if t is not None else []
            if not traces:
                continue
            if hop == "route":
                route_spans += 1
            elif hop == "hop":
                hop_spans += 1
            for t in traces:
                hops = per_trace.setdefault(str(t), {})
                hops[hop] = hops.get(hop, 0.0) + dur
    if not per_trace:
        return None

    # end-to-end + breakouts from the serve/request events that carry a
    # trace id (engine-side served latency, replica/model tagged)
    e2e: dict[str, float] = {}
    by_replica: dict[str, list[float]] = {}
    by_model: dict[str, list[float]] = {}
    all_lats: list[float] = []
    for rl in ranks:
        for rec in rl.events:
            if rec.get("name") != "serve/request":
                continue
            lat = rec.get("latency_s")
            if not isinstance(lat, (int, float)):
                continue
            all_lats.append(float(lat))
            t = rec.get("trace")
            if t is None:
                continue
            e2e[str(t)] = float(lat)
            rep = rec.get("replica")
            if rep is not None:
                by_replica.setdefault(str(rep), []).append(float(lat))
            mdl = rec.get("model")
            if mdl is not None:
                by_model.setdefault(str(mdl), []).append(float(lat))

    hops_block = {
        hop: _quantile_block(
            [v[hop] for v in per_trace.values() if hop in v]
        )
        for hop in _TRACE_HOP_ORDER
        if any(hop in v for v in per_trace.values())
    }
    e2e_vals = list(e2e.values())
    e2e_sum = sum(e2e_vals)
    qw_sum = sum(v.get("queue_wait", 0.0) for t, v in per_trace.items()
                 if t in e2e)

    # SLO scoring against the objectives that were in force during the
    # run (the slo/objectives event), over every served request
    slo_block = None
    if objectives is not None and all_lats:
        p99_ms = objectives.get("p99_ms")
        availability = objectives.get("availability")
        if isinstance(p99_ms, (int, float)) \
                and isinstance(availability, (int, float)):
            bad = sum(1 for v in all_lats if v * 1e3 > p99_ms)
            frac = bad / len(all_lats)
            burn = frac / max(1e-9, 1.0 - float(availability))
            slo_block = {
                "p99_ms": p99_ms,
                "availability": availability,
                "requests": len(all_lats),
                "violations": bad,
                "violation_fraction": round(frac, 6),
                "burn_rate": round(burn, 4),
                "error_budget_remaining": round(max(0.0, 1.0 - burn), 4),
            }

    return {
        "version": SERVE_TRACE_VERSION,
        "traces": len(per_trace),
        "hops": hops_block,
        "e2e": _quantile_block(e2e_vals) if e2e_vals else None,
        # fraction of traced end-to-end time spent waiting in the queue
        # — the autoscaler's "add capacity" signal
        "queue_wait_share": (
            round(qw_sum / e2e_sum, 4) if e2e_sum > 0 else None
        ),
        # forward attempts per routed request; 1.0 = no retries
        "retry_amplification": (
            round(hop_spans / route_spans, 4) if route_spans else None
        ),
        "per_replica": {
            rep: _quantile_block(ls)
            for rep, ls in sorted(by_replica.items())
        } or None,
        "per_model": {
            mdl: _quantile_block(ls)
            for mdl, ls in sorted(by_model.items())
        } or None,
        "slo": slo_block,
    }


# -- skew_report as a library API ---------------------------------------------
# The autotuner (tpuframe.autotune.diagnosis) and the baseline differ
# both consume skew_report's dict as a stable contract.  The key sets
# below ARE that contract: adding a key is backwards-compatible (bump
# the minor), removing or renaming one breaks consumers (bump the major
# and update tpuframe/autotune + the golden structural test together).
# 1.1: + device_time (parsed profiler capture)
# 1.2: + serve_trace (per-hop request-path attribution + SLO scoring)
# 1.3: + memory (watermarks, compiled executables, OOM forensics)
SKEW_REPORT_VERSION = "1.3"

# Top-level keys, always present (value may be None for the optional
# blocks: time_to_first_step, health, comms, serve_latency, serve_trace,
# device_time, memory, slowest).
SKEW_REPORT_KEYS = (
    "schema_version", "ranks", "hosts", "steps", "warmup_steps_skipped",
    "compile", "time_to_first_step", "health", "straggler_factor",
    "comms", "serve_latency", "serve_trace", "device_time", "memory",
    "step_time", "step_wall", "total_lost_s", "straggler_lost_s",
    "straggling_steps", "lost_by_bound", "slowest", "per_rank", "per_step",
)

# Memory block keys (1.3) — built from memory/watermark,
# memory/executable, and memory/oom events; the block is None when the
# run emitted none of them (memory plane off = incomparable, not zero).
SKEW_REPORT_MEMORY_KEYS = (
    "hbm_peak_mb", "host_peak_mb", "hbm_limit_mb", "hbm_peak_util",
    "peak_executable_mb", "executables", "ooms", "last_oom", "budget_mb",
)

# Row contracts for the two per-entity tables.
SKEW_REPORT_PER_RANK_KEYS = (
    "rank", "host", "steps", "excess_s", "straggling_steps",
    "data_wait_total_s",
)
SKEW_REPORT_PER_STEP_KEYS = (
    "batch", "n_ranks", "min_s", "median_s", "max_s", "slowest_rank",
    "lost_s", "bound", "straggling",
)

# The decomposition classes lost_by_bound always carries.
SKEW_REPORT_BOUNDS = ("input", "compute", "checkpoint")


def skew_report(ranks: Sequence[RankLog], *,
                straggler_factor: float = 1.5,
                warmup_steps: int = 1) -> dict:
    """The per-step cross-rank skew table + fleet aggregates.

    For every ``train/step`` batch index: min/median/max per-rank wall
    time, the slowest rank, ``lost_s`` (max - median: wall-clock the
    fleet spent waiting on the straggler that step, under synchronous
    data parallelism), and the boundedness class of the slowest rank.

    The first ``warmup_steps`` batch indices are dropped, for the same
    reason the live monitor's ``skip_first`` exists: on jax they carry
    the JIT compile, whose cross-rank jitter would read as a spurious
    compute straggler and whose hundreds-of-ms duration would pollute
    the ``step_time`` distribution committed as a regression baseline.
    """
    per_rank_rows = {rl.rank: _step_rows(rl) for rl in ranks}
    ckpt_wins = {rl.rank: _ckpt_windows(rl) for rl in ranks}
    all_batches = sorted({b for rows in per_rank_rows.values() for b in rows})
    all_batches = all_batches[max(0, int(warmup_steps)):]

    per_step: list[dict] = []
    excess: dict[int, float] = {rl.rank: 0.0 for rl in ranks}
    slow_count: dict[int, int] = {rl.rank: 0 for rl in ranks}
    lost_by_bound = {"input": 0.0, "compute": 0.0, "checkpoint": 0.0}
    all_durs: list[float] = []
    all_walls: list[float] = []

    for b in all_batches:
        walls = {r: rows[b]["wall_s"] for r, rows in per_rank_rows.items()
                 if b in rows}
        for r in walls:
            all_durs.append(per_rank_rows[r][b]["dur_s"])
            all_walls.append(walls[r])
        slowest = max(walls, key=lambda r: walls[r])
        med = statistics.median(walls.values())
        lost = max(0.0, walls[slowest] - med)
        bound = _classify(per_rank_rows[slowest][b], ckpt_wins[slowest])
        row = {
            "batch": b,
            "n_ranks": len(walls),
            "min_s": round(min(walls.values()), 6),
            "median_s": round(med, 6),
            "max_s": round(walls[slowest], 6),
            "slowest_rank": slowest,
            "lost_s": round(lost, 6),
            "bound": bound,
            "straggling": walls[slowest] > straggler_factor * max(med, 1e-12),
        }
        per_step.append(row)
        excess[slowest] += lost
        if row["straggling"]:
            slow_count[slowest] += 1
            lost_by_bound[bound] += lost

    durs = sorted(all_durs)
    walls = sorted(all_walls)
    step_time = {}
    if durs:
        step_time = {
            "count": len(durs),
            "mean": round(sum(durs) / len(durs), 6),
            "p50": round(_pctl(durs, 0.50), 6),
            "p95": round(_pctl(durs, 0.95), 6),
            "p99": round(_pctl(durs, 0.99), 6),
        }
    # serve-path latency: present only when the run served requests
    # (ServeEngine emits one serve/request event per served request).
    # Shaped like step_time so baseline_diff gates a p99 latency
    # regression with the same exit-3 discipline as a step-time one.
    serve_recs = [
        rec
        for rl in ranks for rec in rl.events
        if rec.get("name") == "serve/request"
        and isinstance(rec.get("latency_s"), (int, float))
    ]
    serve_lats = sorted(float(rec["latency_s"]) for rec in serve_recs)
    serve_latency = None
    if serve_lats:
        serve_latency = {
            "count": len(serve_lats),
            "mean": round(sum(serve_lats) / len(serve_lats), 6),
            "p50": round(_pctl(serve_lats, 0.50), 6),
            "p95": round(_pctl(serve_lats, 0.95), 6),
            "p99": round(_pctl(serve_lats, 0.99), 6),
        }
        # fleet runs tag each serve/request with the replica that served
        # it (ServeEngine(replica=...)); break the aggregate out so a
        # skewed replica is visible, while the gate stays on the
        # fleet-wide p99 above
        by_rep: dict = {}
        for rec in serve_recs:
            rep = rec.get("replica")
            if rep is not None:
                by_rep.setdefault(str(rep), []).append(
                    float(rec["latency_s"])
                )
        if by_rep:
            serve_latency["replicas"] = len(by_rep)
            serve_latency["per_replica"] = {
                rep: {
                    "count": len(ls),
                    "p50": round(_pctl(sorted(ls), 0.50), 6),
                    "p99": round(_pctl(sorted(ls), 0.99), 6),
                }
                for rep, ls in sorted(by_rep.items())
            }
    # comms block: present only when the run declared a wire plan (the
    # compressed train step emits one comms/wire_plan event at build).
    # bytes_per_step is static per signature; the run total multiplies
    # by the steps each rank dispatched.  allreduce_s quantiles appear
    # when the run timed standalone compressed collectives
    # (make_compressed_pmean / bench_collectives emit comms/allreduce
    # spans) — fused train steps carry the collective inside the step
    # program, so no per-collective wall exists to report there.
    comms_info = None
    wire_events = [
        rec for rl in ranks for rec in rl.events
        if rec.get("name") == "comms/wire_plan"
    ]
    if wire_events:
        w = wire_events[-1]
        steps_total = sum(len(rows) for rows in per_rank_rows.values())
        ar_durs = sorted(
            float(rec.get("dur_s", 0.0))
            for rl in ranks for rec in rl.events
            if rec.get("kind") == "span" and rec.get("name") == "comms/allreduce"
        )
        comms_info = {
            "mode": w.get("mode"),
            "world": w.get("world"),
            "error_feedback": w.get("error_feedback"),
            "bytes_per_step": w.get("bytes_per_step"),
            "f32_bytes_per_step": w.get("f32_bytes_per_step"),
            "reduction_x": w.get("reduction_x"),
            # the declared collective schedule (bucket groups fired in
            # reverse-backward order); bytes are invariant under it,
            # exposed-comms in the device_time block is what it moves
            "overlap_groups": w.get("overlap_groups"),
            "steps": steps_total,
            "bytes_on_wire": (
                (w.get("bytes_per_step") or 0) * steps_total
            ),
            "allreduce_s": {
                "count": len(ar_durs),
                "p50": round(_pctl(ar_durs, 0.50), 6),
                "p95": round(_pctl(ar_durs, 0.95), 6),
                "p99": round(_pctl(ar_durs, 0.99), 6),
            } if ar_durs else None,
        }
    # memory block: present only when the memory plane left a trail —
    # ratcheted memory/watermark events (live HBM/host peaks),
    # memory/executable records (AOT compiled truth), or memory/oom
    # forensics.  A run with the plane off keeps its report byte-stable.
    memory_info = None
    mem_execs: dict[str, float] = {}
    mem_hbm = mem_host = mem_limit = 0.0
    mem_ooms = 0
    mem_last_oom = None
    mem_budget = None
    for rl in ranks:
        for rec in rl.events:
            name = rec.get("name")
            if name == "memory/executable" and rec.get("label"):
                mem_execs[rec["label"]] = float(rec.get("peak_mb") or 0.0)
            elif name == "memory/watermark":
                mem_hbm = max(mem_hbm, float(rec.get("hbm_peak_mb") or 0.0))
                mem_host = max(mem_host, float(rec.get("host_peak_mb") or 0.0))
                mem_limit = max(mem_limit, float(rec.get("hbm_limit_mb") or 0.0))
            elif name == "memory/oom":
                mem_ooms += 1
                if rec.get("budget_mb"):
                    mem_budget = rec["budget_mb"]
                mem_last_oom = {
                    "where": rec.get("where"),
                    "step": rec.get("step"),
                    "estimate_total_mb": rec.get("estimate_total_mb"),
                    "suggestion": (rec.get("fit") or {}).get("suggestion"),
                }
    if mem_execs or mem_ooms or mem_hbm or mem_host:
        peak_exec = max(mem_execs.values(), default=0.0)
        memory_info = {
            "hbm_peak_mb": round(mem_hbm, 3) or None,
            "host_peak_mb": round(mem_host, 3) or None,
            "hbm_limit_mb": round(mem_limit, 3) or None,
            "hbm_peak_util": (
                round(mem_hbm / mem_limit, 4) if mem_hbm and mem_limit
                else None
            ),
            "peak_executable_mb": round(peak_exec, 3) or None,
            "executables": {
                label: round(v, 3) for label, v in sorted(mem_execs.items())
            },
            "ooms": mem_ooms,
            "last_oom": mem_last_oom,
            "budget_mb": mem_budget,
        }
    worst = max(excess, key=lambda r: excess[r]) if excess else None
    # measured compile wall: the warmup skip exists because the first
    # step carries the compile — report WHAT it carried instead of
    # silently dropping it ("first step cost X s of compile")
    per_rank_compile = {rl.rank: _compile_wall(rl) for rl in ranks}
    compile_info = {
        "wall_s": round(
            sum(c["wall_s"] for c in per_rank_compile.values()), 6
        ),
        "records": sum(c["records"] for c in per_rank_compile.values()),
        "per_rank": {r: c["wall_s"] for r, c in per_rank_compile.items()},
    }
    ttfs = {rl.rank: _time_to_first_step(rl) for rl in ranks}
    ttfs_vals = [t for t in ttfs.values() if t is not None]
    # training-health block: present only when the sentinel left a trail
    # (skipped steps / divergences / rollbacks) — a healthy run's report
    # stays exactly as it was
    per_rank_health = {rl.rank: _health_info(rl) for rl in ranks}
    health_info = None
    if any(
        h["bad_step_events"] or h["divergences"] or h["rollbacks"]
        for h in per_rank_health.values()
    ):
        health_info = {
            "bad_steps": sum(h["bad_steps"] for h in per_rank_health.values()),
            "divergences": sum(
                h["divergences"] for h in per_rank_health.values()
            ),
            "rollbacks": [
                rb for h in per_rank_health.values() for rb in h["rollbacks"]
            ],
            "per_rank": {
                r: h["bad_steps"] for r, h in per_rank_health.items()
            },
        }
    return {
        "schema_version": SKEW_REPORT_VERSION,
        "ranks": len(ranks),
        "hosts": sorted({rl.hostname for rl in ranks if rl.hostname}),
        "steps": len(per_step),
        "warmup_steps_skipped": max(0, int(warmup_steps)),
        "compile": compile_info,
        # the fleet is up when its SLOWEST rank takes its first step —
        # baseline-diffable like step_time (compile regressions gate)
        "time_to_first_step": {
            "s": round(max(ttfs_vals), 6),
            "per_rank": {
                r: (None if t is None else round(t, 6))
                for r, t in ttfs.items()
            },
        } if ttfs_vals else None,
        "health": health_info,
        "straggler_factor": straggler_factor,
        "comms": comms_info,             # wire traffic (baseline diffs)
        "serve_latency": serve_latency,  # request path (baseline diffs)
        # per-hop request-path attribution from trace-tagged spans
        # (queue-wait p99 + SLO burn rate gate via baseline diffs)
        "serve_trace": _serve_trace_info(ranks),
        # parsed profiler capture: per-class device wall, exposed comms,
        # the top-op table (baseline diffs on exposed/device-step)
        "device_time": _device_time_info(ranks),
        # watermarks + compiled executables + OOM forensics (baseline
        # diffs on ratio_peak_hbm)
        "memory": memory_info,
        "step_time": step_time,          # dispatch-only (baseline diffs)
        "step_wall": {                   # boundary-to-boundary
            "p50": round(_pctl(walls, 0.50), 6) if walls else None,
            "p95": round(_pctl(walls, 0.95), 6) if walls else None,
        },
        # total skew (max-median summed over EVERY step: jitter included)
        # vs the straggler share (only over-factor steps — this is the
        # number lost_by_bound decomposes, so the two always agree)
        "total_lost_s": round(sum(r["lost_s"] for r in per_step), 6),
        "straggler_lost_s": round(
            sum(r["lost_s"] for r in per_step if r["straggling"]), 6),
        "straggling_steps": sum(1 for r in per_step if r["straggling"]),
        "lost_by_bound": {k: round(v, 6) for k, v in lost_by_bound.items()},
        "slowest": None if worst is None else {
            "rank": worst,
            "excess_s": round(excess[worst], 6),
            "times_slowest": slow_count[worst],
        },
        "per_rank": [
            {
                "rank": rl.rank,
                "host": rl.hostname,
                "steps": len(per_rank_rows[rl.rank]),
                "excess_s": round(excess[rl.rank], 6),
                "straggling_steps": slow_count[rl.rank],
                "data_wait_total_s": round(
                    sum(e["data_wait_s"]
                        for e in per_rank_rows[rl.rank].values()), 6),
            }
            for rl in ranks
        ],
        "per_step": per_step,
    }


# -- baseline regression diff -------------------------------------------------


def baseline_diff(report: dict, baseline: str, *,
                  threshold: float = 1.25, backend: str | None = None) -> dict:
    """Compare this run's step-time distribution against committed bench
    records — any ``benchmarks/results/*.json`` file whose top-level
    object carries a ``step_time`` block with ``p50`` (the
    ``bench_analyze.py`` self-test commits one per backend).

    ``ratio_p50 > threshold`` lands the pair in ``regressions``.
    Records carrying a ``time_to_first_step`` block (``bench_compile.py``
    commits one) diff the same way against the report's measured
    time-to-first-step — a compile-time regression gates exactly like a
    step-time regression (exit 3).  Records carrying a ``serve_latency``
    block with ``p99`` (``bench_serve.py`` commits one) diff against the
    report's serve-path latency distribution: a p99 latency regression
    on the request path gates the same way.  Records carrying a
    ``serve_trace`` block (``bench_serve.py --fleet`` commits one) diff
    the per-hop queue-wait p99 (``ratio_queue_wait_p99``) and the SLO
    burn rate (``ratio_burn_rate``) under the same discipline.  Records
    carrying a ``memory`` block (``bench_memory.py`` commits one) diff
    the peak HBM watermark — live when the backend reports device
    stats, else the compiled ``peak_executable_mb`` — as
    ``ratio_peak_hbm``: a plan whose footprint grew past threshold
    gates exactly like a slower step (exit 3).  ``backend`` filters the baselines
    compared (``"cpu"``/``"tpu"``): without it a CPU run diffed against
    a results dir that also holds TPU records would read ~10x "slower"
    and trip the regression exit code spuriously — pass the backend the
    run actually used (records with no ``backend`` field are always
    compared).
    """
    if os.path.isfile(baseline):
        paths = [baseline]
    else:
        paths = sorted(glob.glob(os.path.join(baseline, "*.json")))
    cur = report.get("step_time") or {}
    cur_ttfs = (report.get("time_to_first_step") or {}).get("s")
    cur_serve = (report.get("serve_latency") or {}).get("p99")
    cur_comms = report.get("comms") or {}
    cur_bytes = cur_comms.get("bytes_per_step")
    cur_ar = (cur_comms.get("allreduce_s") or {}).get("p50")
    cur_dt = report.get("device_time") or {}
    # per-step values when the capture knew its step count, else the
    # whole-window values (both sides of a diff commit the same shape)
    cur_exposed = (cur_dt.get("exposed_comms_per_step_s")
                   or cur_dt.get("exposed_comms_s"))
    cur_dstep = cur_dt.get("device_step_s")
    cur_st_block = report.get("serve_trace") or {}
    cur_qw = ((cur_st_block.get("hops") or {}).get("queue_wait")
              or {}).get("p99")
    cur_burn = (cur_st_block.get("slo") or {}).get("burn_rate")
    cur_mem = report.get("memory") or {}
    # live watermark when the backend reports device stats, else the
    # compiled peak (CPU: memory_analysis works, memory_stats doesn't) —
    # both sides of a diff commit the same shape
    cur_hbm = cur_mem.get("hbm_peak_mb") or cur_mem.get("peak_executable_mb")
    out: dict = {"threshold": threshold, "backend": backend,
                 "baselines": [], "regressions": []}
    for p in paths:
        try:
            with open(p) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict):
            continue
        st = rec.get("step_time")
        st = st if isinstance(st, dict) and st.get("p50") else None
        tt = rec.get("time_to_first_step")
        tt = tt if isinstance(tt, dict) and tt.get("s") else None
        sv = rec.get("serve_latency")
        sv = sv if isinstance(sv, dict) and sv.get("p99") else None
        cm = rec.get("comms")
        cm = cm if isinstance(cm, dict) and (
            cm.get("bytes_per_step") or (cm.get("allreduce_s") or {}).get("p50")
        ) else None
        dt = rec.get("device_time")
        dt = dt if isinstance(dt, dict) and (
            dt.get("exposed_comms_per_step_s") or dt.get("exposed_comms_s")
            or dt.get("device_step_s")
        ) else None
        tr = rec.get("serve_trace")
        tr = tr if isinstance(tr, dict) and (
            ((tr.get("hops") or {}).get("queue_wait") or {}).get("p99")
            or (tr.get("slo") or {}).get("burn_rate")
        ) else None
        mm = rec.get("memory")
        mm = mm if isinstance(mm, dict) and (
            mm.get("hbm_peak_mb") or mm.get("peak_executable_mb")
        ) else None
        if st is None and tt is None and sv is None and cm is None \
                and dt is None and tr is None and mm is None:
            continue
        if backend and rec.get("backend") and rec["backend"] != backend:
            continue
        entry: dict = {"file": os.path.basename(p),
                       "backend": rec.get("backend")}
        if st is not None:
            entry["baseline_p50_s"] = st["p50"]
            entry["current_p50_s"] = cur.get("p50")
            for q in ("p50", "p95"):
                if cur.get(q) and st.get(q):
                    entry[f"ratio_{q}"] = round(cur[q] / st[q], 4)
        if tt is not None and cur_ttfs:
            entry["baseline_ttfs_s"] = tt["s"]
            entry["current_ttfs_s"] = cur_ttfs
            entry["ratio_ttfs"] = round(cur_ttfs / tt["s"], 4)
        if sv is not None and cur_serve:
            entry["baseline_serve_p99_s"] = sv["p99"]
            entry["current_serve_p99_s"] = cur_serve
            entry["ratio_serve_p99"] = round(cur_serve / sv["p99"], 4)
        if cm is not None:
            # wire regressions gate like step-time ones: a compressed
            # run that puts more bytes on the wire than its baseline
            # (bucket layout ballooned, mode downgraded) or whose
            # standalone collective wall grew past threshold exits 3.
            # A run with NO comms block is incomparable, not a
            # regression — every f32 run diffs against a results dir
            # that also holds the comms record, and flagging those
            # would make the gate useless; compression-off shows as
            # the comms line missing from --report instead
            base_bytes = cm.get("bytes_per_step")
            if base_bytes and cur_bytes:
                entry["baseline_bytes_per_step"] = base_bytes
                entry["current_bytes_per_step"] = cur_bytes
                entry["ratio_bytes_on_wire"] = round(cur_bytes / base_bytes, 4)
            base_ar = (cm.get("allreduce_s") or {}).get("p50")
            if base_ar and cur_ar:
                entry["baseline_allreduce_p50_s"] = base_ar
                entry["current_allreduce_p50_s"] = cur_ar
                entry["ratio_allreduce_p50"] = round(cur_ar / base_ar, 4)
        if dt is not None:
            # device-time regressions gate like step-time ones: comms
            # time that STOPPED hiding behind compute (exposed grew past
            # threshold at flat bytes-on-wire) or a slower device step.
            # A run with NO device_time block — capture off — is
            # incomparable, not a regression, same discipline as comms.
            base_exp = (dt.get("exposed_comms_per_step_s")
                        or dt.get("exposed_comms_s"))
            if base_exp and cur_exposed:
                entry["baseline_exposed_comms_s"] = base_exp
                entry["current_exposed_comms_s"] = cur_exposed
                entry["ratio_exposed_comms"] = round(
                    cur_exposed / base_exp, 4
                )
            base_dstep = dt.get("device_step_s")
            if base_dstep and cur_dstep:
                entry["baseline_device_step_s"] = base_dstep
                entry["current_device_step_s"] = cur_dstep
                entry["ratio_device_step"] = round(
                    cur_dstep / base_dstep, 4
                )
        if tr is not None:
            # request-path regressions gate like step-time ones: queue
            # wait growing past threshold at flat load (capacity eroded
            # — the autoscaler's signal regressed) or the SLO burn rate
            # growing past it (the fleet is spending budget faster than
            # its baseline).  A run with NO serve_trace block — tracing
            # off — is incomparable, not a regression, same discipline
            # as comms/device_time; a zero-burn baseline is likewise
            # incomparable (no budget was being spent to ratio against).
            base_qw = ((tr.get("hops") or {}).get("queue_wait")
                       or {}).get("p99")
            if base_qw and cur_qw:
                entry["baseline_queue_wait_p99_s"] = base_qw
                entry["current_queue_wait_p99_s"] = cur_qw
                entry["ratio_queue_wait_p99"] = round(cur_qw / base_qw, 4)
            base_burn = (tr.get("slo") or {}).get("burn_rate")
            if base_burn and cur_burn:
                entry["baseline_burn_rate"] = base_burn
                entry["current_burn_rate"] = cur_burn
                entry["ratio_burn_rate"] = round(cur_burn / base_burn, 4)
        if mm is not None:
            # memory regressions gate like step-time ones: the peak HBM
            # watermark (or, backends without device stats, the compiled
            # executable peak) growing past threshold means the plan's
            # footprint ballooned — the capacity headroom the estimator
            # promised eroded.  A run with NO memory block — plane off —
            # is incomparable, not a regression, same discipline as
            # comms/device_time.
            base_hbm = mm.get("hbm_peak_mb") or mm.get("peak_executable_mb")
            if base_hbm and cur_hbm:
                entry["baseline_peak_hbm_mb"] = base_hbm
                entry["current_peak_hbm_mb"] = cur_hbm
                entry["ratio_peak_hbm"] = round(cur_hbm / base_hbm, 4)
        out["baselines"].append(entry)
        if (entry.get("ratio_p50") and entry["ratio_p50"] > threshold) or (
            entry.get("ratio_ttfs") and entry["ratio_ttfs"] > threshold
        ) or (
            entry.get("ratio_serve_p99")
            and entry["ratio_serve_p99"] > threshold
        ) or (
            entry.get("ratio_bytes_on_wire")
            and entry["ratio_bytes_on_wire"] > threshold
        ) or (
            entry.get("ratio_allreduce_p50")
            and entry["ratio_allreduce_p50"] > threshold
        ) or (
            entry.get("ratio_exposed_comms")
            and entry["ratio_exposed_comms"] > threshold
        ) or (
            entry.get("ratio_device_step")
            and entry["ratio_device_step"] > threshold
        ) or (
            entry.get("ratio_queue_wait_p99")
            and entry["ratio_queue_wait_p99"] > threshold
        ) or (
            entry.get("ratio_burn_rate")
            and entry["ratio_burn_rate"] > threshold
        ) or (
            entry.get("ratio_peak_hbm")
            and entry["ratio_peak_hbm"] > threshold
        ):
            out["regressions"].append(entry)
    return out


# -- human-readable report ----------------------------------------------------


def format_report(report: dict, diff: dict | None = None, *,
                  max_rows: int = 20) -> str:
    """The ``--report`` text: fleet summary, the worst skew rows, per-rank
    attribution, optional baseline verdicts (runbook: OBSERVABILITY.md
    "Reading a skew report")."""
    lines = []
    hosts = f" on {len(report['hosts'])} host(s)" if report.get("hosts") else ""
    warm = report.get("warmup_steps_skipped", 0)
    comp = report.get("compile") or {}
    warm_note = ""
    if warm:
        warm_note = f" ({warm} warmup/compile step(s) skipped"
        if comp.get("records"):
            # the skipped first step's cost, measured, not dropped
            warm_note += (
                f"; measured compile wall {comp['wall_s']:.3f}s "
                f"across {comp['records']} compile record(s)"
            )
        warm_note += ")"
    lines.append(
        f"fleet skew report: {report['ranks']} rank(s){hosts}, "
        f"{report['steps']} step(s)" + warm_note
    )
    ttfs = report.get("time_to_first_step") or {}
    if ttfs.get("s") is not None:
        # compile wall is summed fleet-wide (ranks compile in parallel),
        # so label it that way — printing 8s of compile inside a 3s
        # startup would read as inconsistent otherwise
        lines.append(
            f"  time to first step: {ttfs['s']:.3f}s (slowest rank; "
            f"fleet compile wall {comp.get('wall_s', 0.0):.3f}s)"
        )
    st = report.get("step_time") or {}
    if st:
        lines.append(
            f"  step time (dispatch): p50={st['p50'] * 1e3:.1f}ms "
            f"p95={st['p95'] * 1e3:.1f}ms mean={st['mean'] * 1e3:.1f}ms "
            f"over {st['count']} rank-steps"
        )
    sv = report.get("serve_latency") or {}
    if sv:
        lines.append(
            f"  serve latency: p50={sv['p50'] * 1e3:.1f}ms "
            f"p95={sv['p95'] * 1e3:.1f}ms p99={sv['p99'] * 1e3:.1f}ms "
            f"over {sv['count']} served request(s)"
        )
    tr = report.get("serve_trace") or {}
    if tr:
        hops = tr.get("hops") or {}
        hop_parts = [
            f"{h}={hops[h]['p99'] * 1e3:.1f}ms"
            for h in _TRACE_HOP_ORDER if h in hops
        ]
        lines.append(
            f"  request path ({tr['traces']} traced request(s)), "
            "p99 by hop: " + " ".join(hop_parts)
        )
        extras = []
        if tr.get("queue_wait_share") is not None:
            extras.append(f"queue-wait share {tr['queue_wait_share']:.0%}")
        if tr.get("retry_amplification") is not None:
            extras.append(
                f"retry amplification x{tr['retry_amplification']:.2f}"
            )
        if extras:
            lines.append("    " + ", ".join(extras))
        slo = tr.get("slo") or {}
        if slo:
            lines.append(
                f"  slo: p99 objective {slo['p99_ms']:.0f}ms, "
                f"availability {slo['availability']}, "
                f"{slo['violations']}/{slo['requests']} violation(s), "
                f"burn rate {slo['burn_rate']:.2f} "
                f"(budget remaining {slo['error_budget_remaining']:.0%})"
            )
    cm = report.get("comms") or {}
    if cm:
        red = (
            f" ({cm['reduction_x']}x under f32)"
            if cm.get("reduction_x") else ""
        )
        og = cm.get("overlap_groups")
        grp = (
            f", {og} bucket group(s) (reverse-backward fire order)"
            if og and og > 1 else ""
        )
        lines.append(
            f"  comms: {cm.get('mode')} wire, "
            f"{(cm.get('bytes_per_step') or 0) / 1e6:.3f} MB/step{red}, "
            f"{(cm.get('bytes_on_wire') or 0) / 1e6:.1f} MB over "
            f"{cm.get('steps', 0)} rank-step(s)"
            + grp
            + (
                f", allreduce p50="
                f"{cm['allreduce_s']['p50'] * 1e3:.2f}ms"
                if cm.get("allreduce_s") else ""
            )
        )
    dt = report.get("device_time") or {}
    if dt:
        cls = dt.get("classes") or {}

        def _ms(c):
            return ((cls.get(c) or {}).get("wall_s") or 0.0) * 1e3

        part = "" if not dt.get("partial") else ", partial"
        lines.append(
            f"  device time (rank {dt.get('rank')}, "
            f"{dt.get('steps') or '?'} step(s), "
            f"{dt.get('device_tracks')} track(s){part}): "
            f"window={dt['window_s'] * 1e3:.1f}ms "
            f"compute={_ms('compute'):.1f}ms "
            f"collective={_ms('collective'):.1f}ms "
            f"transfer={_ms('transfer'):.1f}ms "
            f"idle={dt['idle_s'] * 1e3:.1f}ms"
        )
        oe = dt.get("overlap_efficiency")
        exposed = (
            f"  exposed comms: {dt['exposed_comms_s'] * 1e3:.2f}ms"
        )
        if dt.get("exposed_comms_per_step_s") is not None:
            exposed += (
                f" ({dt['exposed_comms_per_step_s'] * 1e3:.2f}ms/step)"
            )
        if oe is not None:
            exposed += f", overlap efficiency {oe:.0%}"
        lines.append(exposed)
        if dt.get("top_ops"):
            lines.append(
                "  top device ops (the fused-kernel target list):"
            )
            lines.append("      pct   total_ms  count  op")
            for op in dt["top_ops"]:
                lines.append(
                    f"    {op['pct']:>5.1f} {op['total_s'] * 1e3:>10.2f} "
                    f"{op['count']:>6}  {op['name']} [{op['class']}]"
                )
    mem = report.get("memory") or {}
    if mem:
        parts = []
        if mem.get("hbm_peak_mb"):
            util = (
                f" ({mem['hbm_peak_util']:.0%} of "
                f"{mem['hbm_limit_mb']:.0f}MB)"
                if mem.get("hbm_peak_util") else ""
            )
            parts.append(f"hbm peak {mem['hbm_peak_mb']:.1f}MB{util}")
        if mem.get("host_peak_mb"):
            parts.append(f"host peak {mem['host_peak_mb']:.1f}MB")
        if mem.get("peak_executable_mb"):
            parts.append(
                f"compiled peak {mem['peak_executable_mb']:.1f}MB over "
                f"{len(mem.get('executables') or {})} executable(s)"
            )
        lines.append("  memory: " + ", ".join(parts or ["(no samples)"]))
        if mem.get("ooms"):
            oom = mem.get("last_oom") or {}
            sug = oom.get("suggestion") or {}
            sug_txt = ""
            if sug:
                knobs = ", ".join(
                    f"{k}={v}" for k, v in sug.items()
                    if k in ("zero_stage", "microbatches", "offload_optimizer")
                )
                sug_txt = (
                    f"; nearest fitting plan: {knobs} "
                    f"(est {sug.get('total_mb', 0):.1f}MB)"
                )
            lines.append(
                f"  OOM: {mem['ooms']} event(s), last at "
                f"{oom.get('where')} step {oom.get('step')}" + sug_txt
            )
    lines.append(
        f"  time lost to stragglers: {report['straggler_lost_s']:.3f}s "
        f"across {report['straggling_steps']} straggling step(s) "
        f"(factor > {report['straggler_factor']}); total cross-rank skew "
        f"incl. jitter: {report['total_lost_s']:.3f}s"
    )
    lb = report["lost_by_bound"]
    lines.append(
        "  straggler time by cause: "
        + "  ".join(f"{k}={v:.3f}s" for k, v in lb.items())
    )
    if report.get("slowest"):
        s = report["slowest"]
        lines.append(
            f"  slowest rank: {s['rank']} (excess {s['excess_s']:.3f}s, "
            f"slowest on {s['times_slowest']} straggling step(s))"
        )
    rows = report["per_step"]
    shown = sorted(rows, key=lambda r: r["lost_s"], reverse=True)[:max_rows]
    shown.sort(key=lambda r: r["batch"])
    if len(rows) > len(shown):
        lines.append(f"  -- worst {len(shown)} of {len(rows)} steps by lost_s --")
    lines.append(
        "  batch   min_s   med_s   max_s  slowest  lost_s  bound"
    )
    for r in shown:
        flag = " *" if r["straggling"] else ""
        lines.append(
            f"  {r['batch']:>5} {r['min_s']:>7.3f} {r['median_s']:>7.3f} "
            f"{r['max_s']:>7.3f}  rank {r['slowest_rank']:<3} "
            f"{r['lost_s']:>6.3f}  {r['bound']}{flag}"
        )
    lines.append("  per-rank:")
    for pr in report["per_rank"]:
        host = f" @ {pr['host']}" if pr["host"] else ""
        lines.append(
            f"    rank {pr['rank']}{host}: {pr['steps']} steps, "
            f"excess {pr['excess_s']:.3f}s, straggling "
            f"{pr['straggling_steps']}, data_wait {pr['data_wait_total_s']:.3f}s"
        )
    if diff is not None:
        lines.append(
            f"  baseline diff (regression = ratio_p50 > {diff['threshold']}):"
        )
        if not diff["baselines"]:
            lines.append("    no comparable step_time baselines found")
        for b in diff["baselines"]:
            verdict = (
                "REGRESSION" if b in diff["regressions"] else "ok"
            )
            parts = []
            if b.get("ratio_p50") is not None:
                parts.append(
                    f"p50 {b['baseline_p50_s'] * 1e3:.1f}ms -> "
                    f"{(b.get('current_p50_s') or 0) * 1e3:.1f}ms "
                    f"(x{b['ratio_p50']:.2f})"
                )
            if b.get("ratio_ttfs") is not None:
                parts.append(
                    f"ttfs {b['baseline_ttfs_s']:.3f}s -> "
                    f"{b['current_ttfs_s']:.3f}s (x{b['ratio_ttfs']:.2f})"
                )
            if b.get("ratio_bytes_on_wire") is not None:
                parts.append(
                    f"bytes/step {b['baseline_bytes_per_step'] / 1e6:.3f}MB -> "
                    f"{b['current_bytes_per_step'] / 1e6:.3f}MB "
                    f"(x{b['ratio_bytes_on_wire']:.2f})"
                )
            if b.get("ratio_allreduce_p50") is not None:
                parts.append(
                    f"allreduce_p50 {b['baseline_allreduce_p50_s'] * 1e3:.2f}ms -> "
                    f"{b['current_allreduce_p50_s'] * 1e3:.2f}ms "
                    f"(x{b['ratio_allreduce_p50']:.2f})"
                )
            if b.get("ratio_serve_p99") is not None:
                parts.append(
                    f"serve_p99 {b['baseline_serve_p99_s'] * 1e3:.1f}ms -> "
                    f"{b['current_serve_p99_s'] * 1e3:.1f}ms "
                    f"(x{b['ratio_serve_p99']:.2f})"
                )
            if b.get("ratio_exposed_comms") is not None:
                parts.append(
                    f"exposed_comms "
                    f"{b['baseline_exposed_comms_s'] * 1e3:.2f}ms -> "
                    f"{b['current_exposed_comms_s'] * 1e3:.2f}ms "
                    f"(x{b['ratio_exposed_comms']:.2f})"
                )
            if b.get("ratio_device_step") is not None:
                parts.append(
                    f"device_step {b['baseline_device_step_s'] * 1e3:.2f}ms"
                    f" -> {b['current_device_step_s'] * 1e3:.2f}ms "
                    f"(x{b['ratio_device_step']:.2f})"
                )
            if b.get("ratio_queue_wait_p99") is not None:
                parts.append(
                    f"queue_wait_p99 "
                    f"{b['baseline_queue_wait_p99_s'] * 1e3:.2f}ms -> "
                    f"{b['current_queue_wait_p99_s'] * 1e3:.2f}ms "
                    f"(x{b['ratio_queue_wait_p99']:.2f})"
                )
            if b.get("ratio_burn_rate") is not None:
                parts.append(
                    f"burn_rate {b['baseline_burn_rate']:.2f} -> "
                    f"{b['current_burn_rate']:.2f} "
                    f"(x{b['ratio_burn_rate']:.2f})"
                )
            lines.append(
                f"    vs {b['file']} [{b.get('backend')}]: "
                + " ".join(parts) + f" {verdict}" if parts else
                f"    vs {b['file']}: incomparable"
            )
    return "\n".join(lines)


# -- live straggler detection -------------------------------------------------


#: Wall bound (seconds) on the fleet gather when a peer is dead — a lost
#: rank must degrade the ladder, not hang every healthy survivor at the
#: step boundary forever.  ``TPUFRAME_FLEET_TIMEOUT_S``; 0 disables.
FLEET_TIMEOUT_ENV = "TPUFRAME_FLEET_TIMEOUT_S"
_FLEET_TIMEOUT_DEFAULT_S = 60.0

#: Sticky local-only mode after a gather timed out: the wedged collective
#: left a dangling thread inside the runtime, and re-entering it every
#: boundary would leak one thread per step while the fleet is broken.
_FLEET_DEGRADED = False


def fleet_degraded() -> bool:
    """True once a fleet gather timed out on a lost peer (local-only mode
    until :func:`reset_fleet_degraded` — typically the supervised restart
    into a rebuilt world)."""
    return _FLEET_DEGRADED


def reset_fleet_degraded() -> None:
    """Re-arm fleet gathers (a restart into a rebuilt/shrunken world has
    a live fleet again; tests)."""
    global _FLEET_DEGRADED
    _FLEET_DEGRADED = False


def _gather_values(value: float) -> list[float]:
    """The real cross-process gather (factored for bounding + tests)."""
    import numpy as np
    from jax.experimental import multihost_utils

    vals = multihost_utils.process_allgather(
        np.asarray([value], dtype=np.float64)
    )
    return [float(v) for v in np.asarray(vals).ravel()]


def _fleet_timeout_s() -> float:
    raw = os.environ.get(FLEET_TIMEOUT_ENV, "").strip()
    if not raw:
        return _FLEET_TIMEOUT_DEFAULT_S
    try:
        return float(raw)
    except ValueError:
        return _FLEET_TIMEOUT_DEFAULT_S


def _bounded_gather(value: float, timeout_s: float | None = None) -> list[float]:
    """Run the gather with a wall bound: on timeout (or a transport
    error — a dead peer surfaces as either), emit ONE ``fault/peer_lost``
    event, flip the ladder to sticky local-only, and return the local
    value so the step boundary completes instead of hanging.  The
    timed-out gather thread is a daemon parked inside the runtime; it
    dies with the process (which the supervisor is about to restart
    anyway — a hung collective means the fleet is already broken)."""
    global _FLEET_DEGRADED
    timeout_s = _fleet_timeout_s() if timeout_s is None else float(timeout_s)
    if timeout_s <= 0:
        return _gather_values(value)
    import threading

    box: dict[str, Any] = {}

    def work() -> None:
        try:
            box["result"] = _gather_values(value)
        except BaseException as e:  # noqa: BLE001 - reported, not swallowed
            box["error"] = e

    t = threading.Thread(target=work, name="tpuframe-fleet-gather", daemon=True)
    t.start()
    t.join(timeout_s)
    if "result" in box:
        return box["result"]
    _FLEET_DEGRADED = True
    tele = get_telemetry()
    tele.registry.counter("fault/peer_losses").inc()
    tele.event(
        "fault/peer_lost",
        timeout_s=timeout_s,
        error=(repr(box["error"])[:300] if "error" in box
               else f"gather exceeded {timeout_s}s wall bound"),
        degraded_to="local",
    )
    return [float(value)]


def fleet_allgather(value: float) -> list[float]:
    """All ranks' values, rank-ordered — THE tiny fleet collective, with
    one degradation ladder shared by straggler detection and
    :func:`tpuframe.fault.preempt.agree` (which delegates here): a
    process that never imported jax is by definition not part of a
    multi-host jax runtime (local-only, without importing jax or
    initializing its backend); with jax live, single-process
    short-circuits; the multi-process-CPU test topology degrades to
    local rather than crash the loop it is watching (XLA's CPU backend
    cannot run multiprocess computations — real pods are TPU/GPU); and
    on a real pod the gather is **wall-bounded**
    (``TPUFRAME_FLEET_TIMEOUT_S``, default 60 s): a dead peer degrades
    the ladder to local with one ``fault/peer_lost`` event instead of
    stalling every healthy survivor's step boundary indefinitely."""
    if _FLEET_DEGRADED:
        return [float(value)]
    jax = sys.modules.get("jax")
    if jax is None:
        return [float(value)]
    if jax.process_count() == 1 or jax.default_backend() == "cpu":
        return [float(value)]
    return _bounded_gather(float(value))


class StragglerMonitor:
    """Rolling step-time EWMA + periodic fleet comparison.

    Call :meth:`mark` at a loop boundary (epoch start) and
    :meth:`observe` after every step: with no explicit duration it
    measures boundary-to-boundary wall time, which charges the straggler
    whatever actually slowed it — input wait, dispatch, a checkpoint, a
    GC pause, a chaos stall.

    Every ``sync_steps`` observed steps (after ``min_steps`` warmup) the
    fleet's EWMAs cross ranks through ``gather``:

    - **fleet mode** (>1 rank): ``skew_ratio = max(ewma) / median(ewma)``;
      when the worst rank exceeds ``factor``x the median, rank 0 emits
      one ``train/straggler`` event naming it (rank-0 discipline — one
      event per fleet verdict, in rank 0's log).
    - **self mode** (gather degraded to this rank alone):
      ``skew_ratio = ewma / median(own recent step times)`` — detects a
      rank *becoming* slow against its own history; the event is emitted
      locally.

    Knobs default from the env so launch propagation is free:
    ``TPUFRAME_STRAGGLER_STEPS`` (cadence, 0 disables, default 32) and
    ``TPUFRAME_STRAGGLER_FACTOR`` (default 2.0).  The first observed
    interval after construction is discarded (``skip_first``) — on jax
    it is the compile step, and an 800x compile outlier would poison the
    EWMA for the whole warmup window.
    """

    def __init__(
        self,
        *,
        factor: float | None = None,
        sync_steps: int | None = None,
        alpha: float = 0.25,
        min_steps: int = 8,
        skip_first: int = 1,
        baseline_window: int = 512,
        gather: Callable[[float], Iterable[float]] | None = None,
        rank: int | None = None,
        telemetry: Any = None,
    ):
        if factor is None:
            try:
                factor = float(os.environ.get("TPUFRAME_STRAGGLER_FACTOR", 2.0))
            except ValueError:
                factor = 2.0
        if sync_steps is None:
            try:
                sync_steps = int(os.environ.get("TPUFRAME_STRAGGLER_STEPS", 32))
            except ValueError:
                sync_steps = 32
        self.factor = float(factor)
        self.sync_steps = int(sync_steps)
        self.alpha = float(alpha)
        self.min_steps = int(min_steps)
        self.skip_first = int(skip_first)
        self._gather = gather or fleet_allgather
        self._telemetry = telemetry
        self._rank = rank
        self._times: deque[float] = deque(maxlen=baseline_window)
        self._t_last: float | None = None
        self._skipped = 0
        self.ewma: float | None = None
        self.steps = 0
        self.last: dict | None = None  # most recent detection

    @property
    def enabled(self) -> bool:
        return self.sync_steps > 0 and self.factor > 0

    def _tele(self):
        return self._telemetry if self._telemetry is not None else get_telemetry()

    @property
    def rank(self) -> int:
        return self._tele().rank if self._rank is None else self._rank

    def mark(self) -> None:
        """Reset the interval boundary (epoch start: the gap spanning
        eval/checkpoint/epoch turnover must not read as a slow step)."""
        self._t_last = time.monotonic()

    def observe(self, step_s: float | None = None) -> dict | None:
        """Record one step; returns the detection dict when this call's
        fleet check fired, else None."""
        now = time.monotonic()
        if step_s is None:
            if self._t_last is None:
                self._t_last = now
                return None
            step_s = now - self._t_last
        self._t_last = now
        if self._skipped < self.skip_first:
            self._skipped += 1
            return None
        self.steps += 1
        self._times.append(float(step_s))
        self.ewma = (
            float(step_s) if self.ewma is None
            else self.alpha * float(step_s) + (1 - self.alpha) * self.ewma
        )
        tele = self._tele()
        tele.registry.gauge("train/step_ewma_s").set(self.ewma)
        if (
            not self.enabled
            or self.steps < self.min_steps
            or self.steps % self.sync_steps
        ):
            return None
        return self._check(tele)

    def _check(self, tele) -> dict | None:
        fleet = [float(v) for v in self._gather(self.ewma)]
        if len(fleet) > 1:
            med = statistics.median(fleet)
            worst = max(range(len(fleet)), key=fleet.__getitem__)
            worst_ewma = fleet[worst]
            mode = "fleet"
        else:
            med = statistics.median(self._times)
            worst = self.rank
            worst_ewma = self.ewma
            mode = "self"
        ratio = worst_ewma / max(med, 1e-12)
        tele.registry.gauge("train/skew_ratio").set(ratio)
        if ratio <= self.factor:
            self.last = None
            return None
        det = {
            "rank": worst,
            "ewma_s": round(worst_ewma, 6),
            "median_s": round(med, 6),
            "ratio": round(ratio, 4),
            "mode": mode,
            "step": self.steps,
            "factor": self.factor,
        }
        self.last = det
        # one event per fleet verdict: rank 0 speaks for the fleet; in
        # self mode the verdict only exists on this rank, so it speaks
        if mode == "self" or self.rank == 0:
            tele.registry.counter("train/stragglers").inc()
            tele.event("train/straggler", **det)
        return det


# -- CLI ----------------------------------------------------------------------


def main(argv: Sequence[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuframe.track analyze",
        description=(
            "Fleet-level telemetry analysis: merge a dir of per-rank "
            "events-rank*.jsonl logs into a Perfetto timeline and a "
            "cross-rank skew report."
        ),
    )
    ap.add_argument("dir", nargs="+",
                    help="TPUFRAME_TELEMETRY_DIR of a finished run; give "
                         "several (router + replicas of a multi-process "
                         "serve fleet) to stitch them onto one timeline "
                         "keyed by trace id")
    ap.add_argument("--trace", metavar="OUT.json",
                    help="write a Chrome/Perfetto trace.json here")
    ap.add_argument("--report", action="store_true",
                    help="print the human-readable skew report")
    ap.add_argument("--baseline", metavar="DIR_OR_FILE",
                    help="diff step times vs committed bench records "
                         "(e.g. benchmarks/results/)")
    ap.add_argument("--baseline-backend", metavar="BACKEND",
                    help="only diff against baselines recorded on this "
                         "backend (cpu/tpu) — a CPU run vs a TPU record "
                         "is not a regression")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report (+diff) as JSON instead")
    ap.add_argument("--straggler-factor", type=float, default=1.5,
                    help="a step straggles when max > FACTOR * median "
                         "(default 1.5)")
    ap.add_argument("--warmup-steps", type=int, default=1,
                    help="drop the first N batch indices (compile; "
                         "default 1)")
    ap.add_argument("--regression-threshold", type=float, default=1.25,
                    help="baseline diff flags ratio_p50 above this "
                         "(default 1.25)")
    args = ap.parse_args(argv)

    try:
        ranks = load_dirs(args.dir)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    report = skew_report(ranks, straggler_factor=args.straggler_factor,
                         warmup_steps=args.warmup_steps)
    diff = None
    if args.baseline:
        diff = baseline_diff(report, args.baseline,
                             threshold=args.regression_threshold,
                             backend=args.baseline_backend)
    # regressions are an actionable exit code for CI rungs — decided
    # BEFORE printing, so `... | head` closing the pipe mid-report
    # cannot swallow the verdict
    rc = 3 if diff and diff["regressions"] else 0
    try:
        if args.trace:
            trace = build_trace(ranks)
            with open(args.trace, "w") as f:
                json.dump(trace, f)
            print(
                f"wrote {args.trace}: {len(trace['traceEvents'])} events, "
                f"{report['ranks']} rank track(s) — load in ui.perfetto.dev "
                "or chrome://tracing"
            )
        if args.json:
            print(json.dumps({"report": report, "diff": diff}, indent=2))
        elif args.report or not args.trace:
            print(format_report(report, diff))
    except BrokenPipeError:
        # normal CLI usage, not an error; silence the interpreter's
        # close-time complaint about the dead stdout
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return rc


if __name__ == "__main__":  # pragma: no cover - exercised via track.__main__
    raise SystemExit(main())
