"""Profiling/tracing: programmatic ``jax.profiler`` capture + step timing.

The TPU-native equivalent of the reference's tracing toolbox (SURVEY.md §5
"Tracing / profiling"): DeepSpeed's ``wall_clock_breakdown: True`` +
``steps_per_print`` (`/root/reference/02_deepspeed/deepspeed_config.py:47-48`),
the CUDA debug env flags (`/root/reference/setup/00_setup.py:66-67,117-123`),
and the ``nvidia-smi``/screenshot evidence (`/root/reference/README.md:18-20`)
— replaced by real XLA traces:

- :func:`trace` — context manager around any region; produces a TensorBoard-
  loadable trace directory (per-op device timeline, HLO, memory viewer).
- :class:`ProfilerCallback` — Trainer callback that captures steps
  [skip_steps, skip_steps + num_steps) of the fit, then logs the zipped
  trace as an artifact to the run (rank-0 only).

Per-step wall-clock breakdown (data-wait vs dispatch vs host-block) is
measured by the Trainer loop itself and reported in every epoch summary —
see ``Trainer._run_epoch``.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import time
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:  # pragma: no cover
    from tpuframe.train.trainer import Trainer

from tpuframe.train.callbacks import Callback


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a ``jax.profiler`` trace of the enclosed region to ``logdir``.

    The caller is responsible for blocking on async work it wants included
    (``jax.block_until_ready``) before the region closes.
    """
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def trace_step_window(fn, n_steps: int, logdir: str, *args, **kwargs) -> str:
    """Run ``fn(*args, **kwargs)`` ``n_steps`` times under a trace.

    ``fn``'s return value is blocked on each step so device work lands in
    the trace.  Returns ``logdir``.
    """
    import jax

    with trace(logdir):
        for _ in range(n_steps):
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
    return logdir


class ProfilerCallback(Callback):
    """Capture an XLA trace of a window of train steps, log it as an artifact.

    Args:
      logdir: where to write the trace (default: a temp dir, removed after
        the artifact is logged).
      skip_steps: batches to skip first (warmup/compile noise).
      num_steps: batches to capture.
    After capture, the trace directory is zipped and handed to every logger
    exposing a ``run.log_artifact`` (tpuframe's MLflowLogger) or
    ``log_artifact`` — rank-0 only, matching the logging discipline.
    """

    def __init__(
        self,
        logdir: str | None = None,
        skip_steps: int = 3,
        num_steps: int = 5,
    ):
        self.logdir = logdir
        self.skip_steps = skip_steps
        self.num_steps = num_steps
        self._tmp: str | None = None
        self._active = False
        self._done = False
        self.trace_dir: str | None = None
        self.artifact: str | None = None
        #: True when the fit ended inside the capture window (the logged
        #: trace covers fewer than ``num_steps`` steps)
        self.partial = False

    def _target(self) -> str:
        if self.logdir is None and self._tmp is None:
            self._tmp = tempfile.mkdtemp(prefix="tpuframe_trace_")
        return self.logdir or self._tmp

    def on_step_start(self, trainer: "Trainer") -> None:
        if self._done or self._active or trainer.batches_seen < self.skip_steps:
            return
        import jax

        target = self._target()
        os.makedirs(target, exist_ok=True)
        jax.profiler.start_trace(target)
        self._active = True
        self._start_batch = trainer.batches_seen

    def on_step_end(self, trainer: "Trainer") -> None:
        if not self._active:
            return
        if trainer.batches_seen - self._start_batch < self.num_steps:
            return
        self._finalize(trainer, partial=False)

    def on_fit_end(self, trainer: "Trainer") -> None:
        # fit ended mid-capture (duration reached / early stop): close the
        # trace so the profiler isn't left running across fits, then KEEP
        # the evidence — a partial window is still a real trace of real
        # steps, and a fit short enough to end inside the window is
        # exactly the fit whose trace would otherwise never exist.  Marked
        # ``partial`` and logged like a full capture (rank-0 discipline);
        # ``_done`` stays set so a later fit can't mix a fresh session
        # into the same directory.
        if self._active:
            self._finalize(trainer, partial=True)

    def _finalize(self, trainer: "Trainer", *, partial: bool) -> None:
        import jax

        jax.block_until_ready(trainer.state)
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        self.partial = partial
        if trainer.is_main:
            self._log_artifact(trainer)
        if self._tmp is not None:
            # the temp capture dir is deleted below: publish the zipped
            # artifact (``self.artifact``) instead of a dangling path
            shutil.rmtree(self._tmp, ignore_errors=True)
            self._tmp = None
            self.trace_dir = None
        else:
            self.trace_dir = self.logdir

    def _log_artifact(self, trainer: "Trainer") -> None:
        src = self._target()
        base = os.path.join(
            tempfile.mkdtemp(prefix="tpuframe_trace_zip_"), "xla_trace"
        )
        archive = shutil.make_archive(base, "zip", src)
        for lg in trainer.loggers:
            run = getattr(lg, "run", None)
            target: Any = None
            if run is not None and hasattr(run, "log_artifact"):
                target = run
            elif hasattr(lg, "log_artifact"):
                target = lg
            if target is not None:
                self.artifact = target.log_artifact(archive, "profile")
        shutil.rmtree(os.path.dirname(archive), ignore_errors=True)


class StepTimer(Callback):
    """Lightweight per-step wall-clock sampler (host side).

    Records the host time of each dispatched step; ``summary()`` gives
    mean/p50/p95/p99 step wall time over the sampled window.  The window
    is a **ring** of the most recent ``max_samples`` steps (the old capped
    list stopped sampling after the first ``max_samples`` and reported a
    10-hour run's first minutes forever), and every sample is also folded
    into the process telemetry registry (``callback/step_time_s``) so the
    spine's exporters — logger bridge, Prometheus page, JSONL snapshot —
    see the same distribution.

    Largely superseded by the Trainer's own ``train/step`` spans (the
    ``span/train/step`` histogram is recorded unconditionally); kept for
    explicit-callback workflows and any duck-typed loop that drives
    callbacks without the Trainer.
    """

    def __init__(self, max_samples: int = 4096):
        from collections import deque

        self.max_samples = max_samples
        self.samples: "deque[float]" = deque(maxlen=max_samples)
        self.steps_seen = 0
        self._t0: float | None = None

    def on_step_start(self, trainer: "Trainer") -> None:
        self._t0 = time.perf_counter()

    def on_step_end(self, trainer: "Trainer") -> None:
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self.samples.append(dt)
        self.steps_seen += 1
        self._t0 = None
        from tpuframe.track.telemetry import get_telemetry

        get_telemetry().registry.histogram(
            "callback/step_time_s", max_samples=self.max_samples
        ).observe(dt)

    def summary(self) -> dict[str, float]:
        if not self.samples:
            return {}
        s = sorted(self.samples)
        n = len(s)
        return {
            "step_time_mean_s": sum(s) / n,
            "step_time_p50_s": s[n // 2],
            "step_time_p95_s": s[min(n - 1, int(n * 0.95))],
            "step_time_p99_s": s[min(n - 1, int(n * 0.99))],
            "steps_sampled": float(n),
            "steps_seen": float(self.steps_seen),
        }
