"""Profiling/tracing: programmatic ``jax.profiler`` capture + step timing.

The TPU-native equivalent of the reference's tracing toolbox (SURVEY.md §5
"Tracing / profiling"): DeepSpeed's ``wall_clock_breakdown: True`` +
``steps_per_print`` (`/root/reference/02_deepspeed/deepspeed_config.py:47-48`),
the CUDA debug env flags (`/root/reference/setup/00_setup.py:66-67,117-123`),
and the ``nvidia-smi``/screenshot evidence (`/root/reference/README.md:18-20`)
— replaced by real XLA traces:

- :func:`trace` — context manager around any region; produces a TensorBoard-
  loadable trace directory (per-op device timeline, HLO, memory viewer).
- :class:`ProfilerCallback` — Trainer callback that captures a window of
  train steps.  Two modes: one-shot (capture steps [skip_steps,
  skip_steps + num_steps) then log the zipped trace as a run artifact,
  rank-0 only) and **sampled continuous capture** (``every_steps > 0``:
  capture ``num_steps`` steps every ``every_steps`` steps into rotated
  ``capture-b<batch>`` dirs, newest ``keep`` retained — bounded
  on-device evidence for long runs, armed from the env via
  :meth:`ProfilerCallback.from_env` / ``TPUFRAME_PROFILE_*``).

Every completed capture emits one ``profile/capture`` telemetry event
(dir, steps, bytes, the wall/mono anchor pair of its start) and bumps
the ``profile/captures`` counter — the breadcrumbs
``tpuframe.track.analyze`` follows to attach a parsed ``device_time``
block (see `track/device_time.py`) to the skew report and merge device
ops into the Perfetto timeline.

Per-step wall-clock breakdown (data-wait vs dispatch vs host-block) is
measured by the Trainer loop itself and reported in every epoch summary —
see ``Trainer._run_epoch``.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import time
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:  # pragma: no cover
    from tpuframe.train.trainer import Trainer

from tpuframe.train.callbacks import Callback


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a ``jax.profiler`` trace of the enclosed region to ``logdir``.

    The caller is responsible for blocking on async work it wants included
    (``jax.block_until_ready``) before the region closes.  The trace is
    stopped on the error path too — and a stop failure there is swallowed
    so it can neither mask the real exception nor leave the profiler
    started and wedge the next capture.
    """
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    except BaseException:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        raise
    else:
        jax.profiler.stop_trace()


def trace_step_window(fn, n_steps: int, logdir: str, *args, **kwargs) -> str:
    """Run ``fn(*args, **kwargs)`` ``n_steps`` times under a trace.

    ``fn``'s return value is blocked on each step so device work lands in
    the trace.  A raising step still closes the trace (see :func:`trace`)
    — the partial window is real evidence of the step that raised.
    Returns ``logdir``.
    """
    import jax

    with trace(logdir):
        for _ in range(n_steps):
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
    return logdir


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                continue
    return total


class ProfilerCallback(Callback):
    """Capture XLA traces of train-step windows, with an optional cadence.

    Args:
      logdir: where to write traces.  One-shot mode defaults to a temp
        dir (removed after the artifact is logged); cadence mode needs a
        stable home and defaults to ``<tmp>/tpuframe_profile_<pid>``.
      skip_steps: batches to skip first (warmup/compile noise).
      num_steps: batches per capture window.
      every_steps: 0 (default) = one capture then done; N > 0 = start a
        fresh ``num_steps``-step capture every N batches, each into its
        own ``capture-b<batch>`` subdir of ``logdir``, oldest dirs
        dropped past ``keep`` (rotation mirrors the telemetry log's
        ``TPUFRAME_TELEMETRY_KEEP`` discipline).
      keep: capture dirs retained in cadence mode (default 3).
      rank0_only: capture on the main process only (default True — one
        host's trace prices the fleet; every rank tracing would multiply
        the overhead and the disk for identical programs).

    One-shot captures are zipped and handed to every logger exposing a
    ``run.log_artifact`` (tpuframe's MLflowLogger) or ``log_artifact`` —
    rank-0 only, matching the logging discipline.  Cadence captures stay
    on disk as parseable evidence instead (artifact-zipping every window
    of a week-long run would flood the tracker).
    """

    def __init__(
        self,
        logdir: str | None = None,
        skip_steps: int = 3,
        num_steps: int = 5,
        *,
        every_steps: int = 0,
        keep: int | None = None,
        rank0_only: bool = True,
    ):
        self.logdir = logdir
        self.skip_steps = skip_steps
        self.num_steps = max(1, int(num_steps))
        self.every_steps = max(0, int(every_steps))
        self.keep = 3 if keep is None else max(1, int(keep))
        self.rank0_only = rank0_only
        self._tmp: str | None = None
        self._active = False
        self._done = False
        self._next_start = None  # cadence: earliest batch to start at
        self._capture_dir: str | None = None
        self._anchor: tuple[float, float] | None = None  # (wall, mono)
        self.trace_dir: str | None = None
        self.artifact: str | None = None
        #: completed captures, newest last: {dir, steps, bytes, partial}
        self.captures: list[dict] = []
        #: True when the fit ended inside the capture window (the logged
        #: trace covers fewer than ``num_steps`` steps)
        self.partial = False

    @classmethod
    def from_env(cls) -> "ProfilerCallback | None":
        """The env-armed instance (``TPUFRAME_PROFILE_STEPS`` > 0 arms
        it; EVERY/KEEP/DIR refine), or None when capture is off.  The
        Trainer auto-attaches this so a launch env flag is all a long
        run needs to carry bounded device-time evidence."""
        from tpuframe.track.device_time import profile_env

        env = profile_env()
        steps = env["TPUFRAME_PROFILE_STEPS"]
        if not steps:
            return None
        return cls(
            logdir=env["TPUFRAME_PROFILE_DIR"] or None,
            num_steps=steps,
            every_steps=env["TPUFRAME_PROFILE_EVERY"],
            keep=env["TPUFRAME_PROFILE_KEEP"],
        )

    @property
    def cadence(self) -> bool:
        return self.every_steps > 0

    def _base_dir(self) -> str:
        if self.logdir is None and self._tmp is None:
            if self.cadence:
                # cadence evidence must outlive the callback: a stable
                # per-process home, not a remove-after-artifact temp dir
                self._tmp = os.path.join(
                    tempfile.gettempdir(), f"tpuframe_profile_{os.getpid()}"
                )
            else:
                self._tmp = tempfile.mkdtemp(prefix="tpuframe_trace_")
        return self.logdir or self._tmp

    def _target(self) -> str:
        base = self._base_dir()
        if self.cadence:
            return os.path.join(base, f"capture-b{self._start_batch:08d}")
        return base

    def on_step_start(self, trainer: "Trainer") -> None:
        if self._done or self._active:
            return
        if self.rank0_only and not trainer.is_main:
            self._done = True  # never arms on this rank; stop checking
            return
        start_at = (
            self._next_start if self._next_start is not None
            else self.skip_steps
        )
        if trainer.batches_seen < start_at:
            return
        import jax

        self._start_batch = trainer.batches_seen
        target = self._target()
        os.makedirs(target, exist_ok=True)
        self._anchor = (time.time(), time.monotonic())
        jax.profiler.start_trace(target)
        self._active = True
        self._capture_dir = target

    def on_step_end(self, trainer: "Trainer") -> None:
        if not self._active:
            return
        if trainer.batches_seen - self._start_batch < self.num_steps:
            return
        self._finalize(trainer, partial=False)

    def on_fit_end(self, trainer: "Trainer") -> None:
        # fit ended mid-capture (duration reached / early stop / a step
        # that RAISED — on_fit_end fires from fit()'s finally): close the
        # trace so the profiler isn't left running across fits, then KEEP
        # the evidence — a partial window is still a real trace of real
        # steps, and the window containing the raising step is exactly
        # the trace someone debugging it wants.  Marked ``partial`` and
        # logged like a full capture (rank-0 discipline).
        if self._active:
            self._finalize(trainer, partial=True)
            self._done = True  # no fresh session after the fit ended

    def _finalize(self, trainer: "Trainer", *, partial: bool) -> None:
        import jax

        try:
            # include in-flight device work; a poisoned state (the step
            # raised) must not leave the profiler started
            jax.block_until_ready(trainer.state)
        except Exception:
            pass
        try:
            jax.profiler.stop_trace()
        finally:
            self._active = False
        self.partial = partial
        steps = max(0, trainer.batches_seen - self._start_batch)
        cap_dir = self._capture_dir
        cap = {
            "dir": cap_dir,
            "steps": steps,
            "bytes": _dir_bytes(cap_dir) if cap_dir else 0,
            "partial": partial,
        }
        self.captures.append(cap)
        self._emit_capture_event(cap)
        if self.cadence:
            self.trace_dir = cap_dir
            self._rotate()
            # schedule the next window from this one's START, so the
            # cadence is "every N steps", not "N steps of gap"
            self._next_start = self._start_batch + max(
                self.every_steps, self.num_steps
            )
        else:
            self._done = True
            if trainer.is_main:
                self._log_artifact(trainer)
            if self._tmp is not None:
                # the temp capture dir is deleted below: publish the zipped
                # artifact (``self.artifact``) instead of a dangling path
                shutil.rmtree(self._tmp, ignore_errors=True)
                self._tmp = None
                self.trace_dir = None
            else:
                self.trace_dir = self.logdir

    def _emit_capture_event(self, cap: dict) -> None:
        from tpuframe.track.telemetry import get_telemetry

        tele = get_telemetry()
        tele.registry.counter("profile/captures").inc()
        wall, mono = self._anchor or (None, None)
        tele.event(
            "profile/capture",
            dir=cap["dir"],
            steps=cap["steps"],
            bytes=cap["bytes"],
            partial=cap["partial"],
            wall_start=wall,
            mono_start=mono,
        )

    def _rotate(self) -> None:
        """Drop capture dirs past ``keep``, oldest first (the batch-
        numbered names sort chronologically)."""
        from tpuframe.track.device_time import list_captures

        caps = list_captures(self._base_dir())
        for stale in caps[: max(0, len(caps) - self.keep)]:
            shutil.rmtree(stale, ignore_errors=True)

    def _log_artifact(self, trainer: "Trainer") -> None:
        src = self._capture_dir or self._base_dir()
        base = os.path.join(
            tempfile.mkdtemp(prefix="tpuframe_trace_zip_"), "xla_trace"
        )
        archive = shutil.make_archive(base, "zip", src)
        for lg in trainer.loggers:
            run = getattr(lg, "run", None)
            target: Any = None
            if run is not None and hasattr(run, "log_artifact"):
                target = run
            elif hasattr(lg, "log_artifact"):
                target = lg
            if target is not None:
                self.artifact = target.log_artifact(archive, "profile")
        shutil.rmtree(os.path.dirname(archive), ignore_errors=True)


class StepTimer(Callback):
    """Lightweight per-step wall-clock sampler (host side).

    Records the host time of each dispatched step; ``summary()`` gives
    mean/p50/p95/p99 step wall time over the sampled window.  The window
    is a **ring** of the most recent ``max_samples`` steps (the old capped
    list stopped sampling after the first ``max_samples`` and reported a
    10-hour run's first minutes forever), and every sample is also folded
    into the process telemetry registry (``callback/step_time_s``) so the
    spine's exporters — logger bridge, Prometheus page, JSONL snapshot —
    see the same distribution.

    Largely superseded by the Trainer's own ``train/step`` spans (the
    ``span/train/step`` histogram is recorded unconditionally); kept for
    explicit-callback workflows and any duck-typed loop that drives
    callbacks without the Trainer.
    """

    def __init__(self, max_samples: int = 4096):
        from collections import deque

        self.max_samples = max_samples
        self.samples: "deque[float]" = deque(maxlen=max_samples)
        self.steps_seen = 0
        self._t0: float | None = None

    def on_step_start(self, trainer: "Trainer") -> None:
        self._t0 = time.perf_counter()

    def on_step_end(self, trainer: "Trainer") -> None:
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self.samples.append(dt)
        self.steps_seen += 1
        self._t0 = None
        from tpuframe.track.telemetry import get_telemetry

        get_telemetry().registry.histogram(
            "callback/step_time_s", max_samples=self.max_samples
        ).observe(dt)

    def summary(self) -> dict[str, float]:
        if not self.samples:
            return {}
        s = sorted(self.samples)
        n = len(s)
        return {
            "step_time_mean_s": sum(s) / n,
            "step_time_p50_s": s[n // 2],
            "step_time_p95_s": s[min(n - 1, int(n * 0.95))],
            "step_time_p99_s": s[min(n - 1, int(n * 0.99))],
            "steps_sampled": float(n),
            "steps_seen": float(self.steps_seen),
        }
