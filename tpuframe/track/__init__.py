"""Experiment tracking: MLflow-compatible params/metrics/artifacts/models.

TPU-native replacement for the reference's MLflow wiring (SURVEY.md §5
"Metrics / logging"): experiment-per-notebook setup
(`/root/reference/setup/00_setup.py:96-101`), per-epoch ``log_metric(step=)``
(`/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:258-260`),
param logging (`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:275-276`),
state-dict/model artifacts (`/root/reference/04_accelerate/01_cifar_accelerate.ipynb:cell-18`),
system metrics (`02_cifar_torch_distributor_resnet.py:186`), and the rank-0 +
run-id-broadcast discipline for multi-process logging (`cell-18`'s char-tensor
hack becomes :func:`broadcast_run_id` on the control plane).

Backend-neutral: writes the MLflow ``mlruns/`` file-store layout natively, so
runs and artifacts are readable by any stock MLflow UI/client pointed at the
same directory — no mlflow package required.
"""

from tpuframe.track.mlflow_store import (
    ExperimentTracker,
    MLflowLogger,
    Run,
    broadcast_run_id,
    set_experiment,
    start_run,
)
from tpuframe.track.http_store import HttpExperimentTracker, HttpRun, make_tracker
from tpuframe.track.profiler import ProfilerCallback, StepTimer, trace, trace_step_window
from tpuframe.track.registry import (
    HttpModelRegistry,
    ModelRegistry,
    ModelVersion,
    load_model,
)
from tpuframe.track.tensorboard import TensorBoardLogger
from tpuframe.track.system_metrics import SystemMetricsMonitor

__all__ = [
    "ExperimentTracker",
    "MLflowLogger",
    "Run",
    "broadcast_run_id",
    "set_experiment",
    "start_run",
    "SystemMetricsMonitor",
    "HttpExperimentTracker",
    "HttpRun",
    "HttpModelRegistry",
    "ModelRegistry",
    "ModelVersion",
    "load_model",
    "make_tracker",
    "TensorBoardLogger",
    "ProfilerCallback",
    "StepTimer",
    "trace",
    "trace_step_window",
]
