"""Experiment tracking: MLflow-compatible params/metrics/artifacts/models.

TPU-native replacement for the reference's MLflow wiring (SURVEY.md §5
"Metrics / logging"): experiment-per-notebook setup
(`/root/reference/setup/00_setup.py:96-101`), per-epoch ``log_metric(step=)``
(`/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:258-260`),
param logging (`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:275-276`),
state-dict/model artifacts (`/root/reference/04_accelerate/01_cifar_accelerate.ipynb:cell-18`),
system metrics (`02_cifar_torch_distributor_resnet.py:186`), and the rank-0 +
run-id-broadcast discipline for multi-process logging (`cell-18`'s char-tensor
hack becomes :func:`broadcast_run_id` on the control plane).

Backend-neutral: writes the MLflow ``mlruns/`` file-store layout natively, so
runs and artifacts are readable by any stock MLflow UI/client pointed at the
same directory — no mlflow package required.

Exports resolve lazily (PEP 562): the telemetry spine (``telemetry``,
``watchdog`` — stdlib-only, usable while jax is wedged) must be importable
without dragging in the profiler's train-package (and therefore jax)
imports.  ``from tpuframe.track import X`` works exactly as before.
"""

# tpuframe-lint: stdlib-only

import importlib

# name -> submodule it lives in (all under tpuframe.track)
_EXPORTS = {
    "RankLog": "analyze",
    "StragglerMonitor": "analyze",
    "baseline_diff": "analyze",
    "build_trace": "analyze",
    "load_trace_dir": "analyze",
    "skew_report": "analyze",
    "PROFILE_ENV_VARS": "device_time",
    "PROFILE_ENV_DOMAINS": "device_time",
    "classify_op": "device_time",
    "device_time_report": "device_time",
    "device_trace_events": "device_time",
    "profile_env": "device_time",
    "MEMORY_ENV_VARS": "memory",
    "MEMORY_ENV_DOMAINS": "memory",
    "memory_env": "memory",
    "record_executable_memory": "memory",
    "executable_records": "memory",
    "update_watermarks": "memory",
    "maybe_oom_event": "memory",
    "is_oom": "memory",
    "ExperimentTracker": "mlflow_store",
    "MLflowLogger": "mlflow_store",
    "Run": "mlflow_store",
    "broadcast_run_id": "mlflow_store",
    "set_experiment": "mlflow_store",
    "start_run": "mlflow_store",
    "HttpExperimentTracker": "http_store",
    "HttpRun": "http_store",
    "MetricsServer": "http_store",
    "make_tracker": "http_store",
    "ProfilerCallback": "profiler",
    "StepTimer": "profiler",
    "trace": "profiler",
    "trace_step_window": "profiler",
    "HttpModelRegistry": "registry",
    "ModelRegistry": "registry",
    "ModelVersion": "registry",
    "load_model": "registry",
    "TensorBoardLogger": "tensorboard",
    "SystemMetricsMonitor": "system_metrics",
    "MetricsExportCallback": "telemetry",
    "MetricsRegistry": "telemetry",
    "Telemetry": "telemetry",
    "configure_telemetry": "telemetry",
    "get_telemetry": "telemetry",
    "publish_to_loggers": "telemetry",
    "start_metrics_server": "telemetry",
    "Watchdog": "watchdog",
}

# a few exports carry a different name in their home module
_ALIASES = {"configure_telemetry": "configure", "load_trace_dir": "load_dir"}

_SUBMODULES = (
    "analyze",
    "device_time",
    "http_store",
    "memory",
    "mlflow_store",
    "profiler",
    "registry",
    "system_metrics",
    "telemetry",
    "tensorboard",
    "watchdog",
)

__all__ = sorted(_EXPORTS) + list(_SUBMODULES)


def __getattr__(name):
    if name in _EXPORTS:
        mod = importlib.import_module(f"tpuframe.track.{_EXPORTS[name]}")
        value = getattr(mod, _ALIASES.get(name, name))
        globals()[name] = value  # cache: resolve once
        return value
    if name in _SUBMODULES:
        return importlib.import_module(f"tpuframe.track.{name}")
    raise AttributeError(f"module 'tpuframe.track' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + __all__))
