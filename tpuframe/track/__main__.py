"""``python -m tpuframe.track`` — observability CLI.

Subcommands:

    analyze <dir> [--trace out.json] [--report] [--baseline results/]
        Merge a TPUFRAME_TELEMETRY_DIR of per-rank events-rank*.jsonl
        logs into a Perfetto-loadable trace and a cross-rank skew
        report (tpuframe.track.analyze).

Stdlib-only: analyzing a wedged fleet's logs must not need jax.
"""

# tpuframe-lint: stdlib-only

import sys


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "analyze":
        from tpuframe.track.analyze import main as analyze_main

        return analyze_main(argv[1:])
    prog = "python -m tpuframe.track"
    if argv and argv[0] not in ("-h", "--help"):
        print(f"{prog}: unknown command {argv[0]!r}", file=sys.stderr)
    print(
        f"usage: {prog} analyze <telemetry-dir> "
        "[--trace out.json] [--report] [--baseline results/] [--json]",
        file=sys.stderr,
    )
    return 0 if argv and argv[0] in ("-h", "--help") else 2


if __name__ == "__main__":
    raise SystemExit(main())
