"""MLflow-compatible experiment tracking on a file store.

Implements the ``mlruns/`` file-store layout (experiment dirs with
``meta.yaml``, run dirs with ``params/``, ``metrics/``, ``tags/``,
``artifacts/``) natively, so runs written here open in any stock MLflow UI —
wire-compat without requiring the mlflow package (BASELINE.md: "MLflow logging
from setup/ stays intact").  Remote/Databricks tracking URIs are out of scope
for the file store; point a stock mlflow client at the same ``mlruns/`` dir
to sync runs wherever you like.

Reference behaviors reproduced:
- experiment-per-name setup: ``mlflow.set_experiment(experiment_path)``
  (`/root/reference/setup/00_setup.py:96-101`);
- ``log_params`` once + ``log_metric(key, value, step=epoch)`` per epoch
  (`/root/reference/01_torch_distributor/01_basic_torch_distributor.py:275-276`,
  `/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:258-260`);
- state-dict and model artifacts per epoch / best
  (`/root/reference/04_accelerate/01_cifar_accelerate.ipynb:cell-18`);
- run-id propagation to non-zero ranks — the reference broadcasts the run-id
  as a char tensor over NCCL (`cell-18`); here :func:`broadcast_run_id` rides
  the jax control plane.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import uuid
from typing import Any, Mapping

from tpuframe.core import runtime as rt

_INVALID = set('\\#?%:"<>|')

#: MLflow's RunStatus int enum, as persisted by its file store.
_STATUS = {"RUNNING": 1, "SCHEDULED": 2, "FINISHED": 3, "FAILED": 4, "KILLED": 5}


def _sanitize(key: str) -> str:
    """Key -> relative path.  '/' is legal in MLflow keys and maps to nested
    directories in the file store ('system/cpu' -> metrics/system/cpu);
    path-escape segments are neutralized."""
    cleaned = "".join("_" if c in _INVALID else c for c in str(key))
    parts = [p for p in cleaned.split("/") if p not in ("", ".", "..")]
    return "/".join(parts) or "_"


def _flat_key_path(base: str, rel: str) -> str:
    """Flat fallback name for a nested key.  Leading '%' keeps it disjoint
    from every sanitized key (% is in _INVALID, so no sanitized name starts
    with it)."""
    return os.path.join(base, "%" + rel.replace("/", "%2F"))


def _key_file(base: str, key: str) -> str:
    """Writable path for ``key``.  '/' keys nest into directories; when a
    nested path collides with an existing flat key (file where a directory
    is needed, or vice versa — e.g. metric 'system' logged before
    'system/cpu'), the key degrades to a flat percent-encoded file."""
    rel = _sanitize(key)
    nested = os.path.join(base, *rel.split("/"))
    flat = _flat_key_path(base, rel)
    if os.path.isfile(nested):
        return nested
    if os.path.isfile(flat):
        return flat
    try:
        os.makedirs(os.path.dirname(nested), exist_ok=True)
        if os.path.isdir(nested):
            raise IsADirectoryError(nested)
        return nested
    except (FileExistsError, NotADirectoryError, IsADirectoryError):
        os.makedirs(base, exist_ok=True)
        return flat


def _find_key_file(base: str, key: str) -> str:
    """Read-side twin of :func:`_key_file`: nested location if present,
    else the flat fallback."""
    rel = _sanitize(key)
    nested = os.path.join(base, *rel.split("/"))
    if os.path.isfile(nested):
        return nested
    return _flat_key_path(base, rel)


def _now_ms() -> int:
    return int(time.time() * 1000)


def _write_yaml(path: str, data: Mapping[str, Any]) -> None:
    import yaml

    with open(path, "w") as f:
        yaml.safe_dump(dict(data), f, default_flow_style=False)


class Run:
    """One tracked run (≈ ``mlflow.start_run()`` handle).

    All writes are append-safe and idempotent-friendly; callers are expected
    to gate on rank 0 (`MLflowLogger` and the Trainer do this for you).
    """

    def __init__(self, root: str, experiment_id: str, run_id: str | None = None,
                 run_name: str | None = None):
        self.experiment_id = experiment_id
        self.run_id = run_id or uuid.uuid4().hex
        self.run_name = run_name or f"run-{self.run_id[:8]}"
        self._dir = os.path.join(root, experiment_id, self.run_id)
        self.artifact_dir = os.path.join(self._dir, "artifacts")
        for sub in ("metrics", "params", "tags", "artifacts"):
            os.makedirs(os.path.join(self._dir, sub), exist_ok=True)
        self._start = _now_ms()
        self._write_meta(status="RUNNING", end_time=None)
        self.set_tag("mlflow.runName", self.run_name)

    def _write_meta(self, status: str, end_time: int | None) -> None:
        _write_yaml(
            os.path.join(self._dir, "meta.yaml"),
            {
                "artifact_uri": "file://" + os.path.abspath(self.artifact_dir),
                "end_time": end_time,
                "entry_point_name": "",
                "experiment_id": self.experiment_id,
                "lifecycle_stage": "active",
                "run_id": self.run_id,
                "run_name": self.run_name,
                "run_uuid": self.run_id,
                "source_name": "",
                "source_type": 4,
                "source_version": "",
                "start_time": self._start,
                "status": _STATUS.get(status, status),
                "user_id": os.environ.get("USER", "tpuframe"),
            },
        )

    # -- params / metrics / tags ------------------------------------------
    def log_param(self, key: str, value: Any) -> None:
        with open(_key_file(os.path.join(self._dir, "params"), key), "w") as f:
            f.write(str(value))

    def log_params(self, params: Mapping[str, Any]) -> None:
        for k, v in params.items():
            self.log_param(k, v)

    def log_metric(self, key: str, value: float, step: int = 0) -> None:
        with open(_key_file(os.path.join(self._dir, "metrics"), key), "a") as f:
            f.write(f"{_now_ms()} {float(value)} {int(step)}\n")

    def log_metrics(self, metrics: Mapping[str, float], step: int = 0) -> None:
        for k, v in metrics.items():
            self.log_metric(k, v, step)

    def set_tag(self, key: str, value: Any) -> None:
        with open(_key_file(os.path.join(self._dir, "tags"), key), "w") as f:
            f.write(str(value))

    # -- artifacts ---------------------------------------------------------
    def log_artifact(self, local_path: str, artifact_path: str | None = None) -> str:
        dest_dir = self.artifact_dir
        if artifact_path:
            dest_dir = os.path.join(dest_dir, artifact_path)
        os.makedirs(dest_dir, exist_ok=True)
        dest = os.path.join(dest_dir, os.path.basename(local_path))
        shutil.copy2(local_path, dest)
        return dest

    def log_text(self, text: str, artifact_file: str) -> str:
        dest = os.path.join(self.artifact_dir, artifact_file)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with open(dest, "w") as f:
            f.write(text)
        return dest

    def log_dict(self, data: Mapping[str, Any], artifact_file: str) -> str:
        return self.log_text(json.dumps(dict(data), indent=2, default=str), artifact_file)

    def log_state_dict(self, tree: Any, artifact_path: str = "state_dict") -> str:
        """Per-epoch state-dict artifact (≈ ``mlflow.pytorch.log_state_dict``,
        `/root/reference/04_accelerate/01_cifar_accelerate.ipynb:cell-18`)."""
        from tpuframe.ckpt import save_pytree

        dest = os.path.join(self.artifact_dir, artifact_path, "state.msgpack")
        save_pytree(dest, tree)
        return dest

    def log_model(self, state: Any, artifact_path: str = "model",
                  meta: Mapping[str, Any] | None = None) -> str:
        """Log a servable model artifact: params(+batch_stats) msgpack + an
        ``MLmodel`` descriptor (≈ ``mlflow.pytorch.log_model``,
        `/root/reference/01_torch_distributor/01_basic_torch_distributor.py:302-304`)."""
        from tpuframe.ckpt import save_pytree

        model_dir = os.path.join(self.artifact_dir, artifact_path)
        tree = {
            "params": getattr(state, "params", state),
            "batch_stats": getattr(state, "batch_stats", {}),
        }
        save_pytree(os.path.join(model_dir, "model.msgpack"), tree)
        _write_yaml(
            os.path.join(model_dir, "MLmodel"),
            {
                "artifact_path": artifact_path,
                "flavors": {
                    "tpuframe": {
                        "format": "flax-msgpack",
                        "data": "model.msgpack",
                        **dict(meta or {}),
                    }
                },
                "run_id": self.run_id,
                "utc_time_created": time.strftime(
                    "%Y-%m-%d %H:%M:%S", time.gmtime()
                ),
            },
        )
        return model_dir

    # -- lifecycle ---------------------------------------------------------
    def end(self, status: str = "FINISHED") -> None:
        self._write_meta(status=status, end_time=_now_ms())

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        self.end("FAILED" if exc_type else "FINISHED")

    # -- reads (for tests / reload paths) ----------------------------------
    def get_metric_history(self, key: str) -> list[tuple[int, float, int]]:
        path = _find_key_file(os.path.join(self._dir, "metrics"), key)
        out = []
        try:
            with open(path) as f:
                for line in f:
                    ts, val, step = line.split()
                    out.append((int(ts), float(val), int(step)))
        except FileNotFoundError:
            pass
        return out

    def get_param(self, key: str) -> str | None:
        try:
            with open(_find_key_file(os.path.join(self._dir, "params"), key)) as f:
                return f.read()
        except FileNotFoundError:
            return None

    def artifact_path(self, *parts: str) -> str:
        return os.path.join(self.artifact_dir, *parts)


class ExperimentTracker:
    """Experiment registry over an ``mlruns/`` root (≈ the mlflow client).

    >>> tracker = ExperimentTracker("./mlruns")
    >>> tracker.set_experiment("/Users/me/experiments/cifar")
    >>> with tracker.start_run(run_name="baseline") as run:
    ...     run.log_params({"lr": 1e-3}); run.log_metric("loss", 0.5, step=0)
    """

    def __init__(self, tracking_uri: str = "./mlruns"):
        self.root = os.path.abspath(tracking_uri.removeprefix("file://"))
        os.makedirs(self.root, exist_ok=True)
        self.experiment_id: str | None = None
        self.experiment_name: str | None = None

    def _experiments(self) -> dict[str, str]:
        """name -> experiment_id for existing experiments."""
        import yaml

        out = {}
        for entry in sorted(os.listdir(self.root)):
            meta = os.path.join(self.root, entry, "meta.yaml")
            if entry.isdigit() and os.path.exists(meta):
                with open(meta) as f:
                    data = yaml.safe_load(f) or {}
                if "name" in data and "run_id" not in data:
                    out[data["name"]] = entry
        return out

    def set_experiment(self, name: str) -> str:
        """Get-or-create an experiment by name; returns its id.  Mirrors the
        idempotent ``mlflow.set_experiment`` in `setup/00_setup.py:96-101`."""
        existing = self._experiments()
        if name in existing:
            self.experiment_id = existing[name]
        else:
            next_id = str(max((int(i) for i in existing.values()), default=-1) + 1)
            exp_dir = os.path.join(self.root, next_id)
            os.makedirs(exp_dir, exist_ok=True)
            _write_yaml(
                os.path.join(exp_dir, "meta.yaml"),
                {
                    "artifact_location": "file://" + exp_dir,
                    "creation_time": _now_ms(),
                    "experiment_id": next_id,
                    "last_update_time": _now_ms(),
                    "lifecycle_stage": "active",
                    "name": name,
                },
            )
            self.experiment_id = next_id
        self.experiment_name = name
        return self.experiment_id

    def start_run(self, run_name: str | None = None, run_id: str | None = None) -> Run:
        if self.experiment_id is None:
            self.set_experiment("Default")
        return Run(self.root, self.experiment_id, run_id=run_id, run_name=run_name)

    def runs(self, experiment_name: str | None = None) -> list[str]:
        import yaml

        exp_id = self.experiment_id
        if experiment_name is not None:
            exp_id = self._experiments().get(experiment_name)
        if exp_id is None:
            return []
        exp_dir = os.path.join(self.root, exp_id)
        return [
            e for e in sorted(os.listdir(exp_dir))
            if os.path.isdir(os.path.join(exp_dir, e))
            and os.path.exists(os.path.join(exp_dir, e, "meta.yaml"))
        ]


class MLflowLogger:
    """Trainer logger plugin (≈ Composer's ``MLFlowLogger``,
    `/root/reference/03_composer/01_cifar_composer_resnet.ipynb:cell-16`).

    Duck-typed to the Trainer's logger contract: ``log_params(dict)``,
    ``log_metrics(dict, step=)``, ``flush()``.  Creates the experiment/run
    lazily on first write; only the main process ever writes (non-main
    processes can still learn the run id via :func:`broadcast_run_id`).
    """

    def __init__(
        self,
        experiment_name: str = "tpuframe",
        tracking_uri: str = "./mlruns",
        run_name: str | None = None,
        system_metrics: bool = False,
    ):
        self.experiment_name = experiment_name
        self.tracking_uri = tracking_uri
        self.run_name = run_name
        self.system_metrics = system_metrics
        self._tracker: ExperimentTracker | None = None
        self._run: Run | None = None
        self._monitor = None

    @property
    def run(self) -> Run:
        if self._run is None:
            from tpuframe.track.http_store import make_tracker

            self._tracker = make_tracker(self.tracking_uri)
            self._tracker.set_experiment(self.experiment_name)
            self._run = self._tracker.start_run(run_name=self.run_name)
            if self.system_metrics:
                from tpuframe.track.system_metrics import SystemMetricsMonitor

                self._monitor = SystemMetricsMonitor(self._run)
                self._monitor.start()
        return self._run

    def log_params(self, params: Mapping[str, Any]) -> None:
        self.run.log_params(params)

    def log_metrics(self, metrics: Mapping[str, float], step: int = 0) -> None:
        self.run.log_metrics(metrics, step)

    def log_model(self, state: Any, artifact_path: str = "model") -> str:
        return self.run.log_model(state, artifact_path)

    def flush(self, status: str = "FINISHED") -> None:
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None
        if self._run is not None:
            self._run.end(status)
            self._run = None

    def finish(self, error: BaseException | None = None) -> None:
        """End the run with a truthful status — a crashed fit records FAILED
        (the Trainer calls this from its finally block)."""
        self.flush("FAILED" if error is not None else "FINISHED")


# -- module-level convenience (the mlflow-style imperative API) --------------

_DEFAULT_TRACKER: ExperimentTracker | None = None


def set_experiment(name: str, tracking_uri: str = "./mlruns"):
    """File store for local paths, REST client for http(s) tracking URIs
    (the reference's remote-server path, `setup/00_setup.py:86-101`)."""
    from tpuframe.track.http_store import make_tracker

    global _DEFAULT_TRACKER
    _DEFAULT_TRACKER = make_tracker(tracking_uri)
    _DEFAULT_TRACKER.set_experiment(name)
    return _DEFAULT_TRACKER


def start_run(run_name: str | None = None) -> Run:
    if _DEFAULT_TRACKER is None:
        set_experiment("Default")
    return _DEFAULT_TRACKER.start_run(run_name=run_name)


def broadcast_run_id(run_id: str | None, max_len: int = 64) -> str:
    """Propagate rank 0's run id to every process.

    Replaces the reference's char-tensor NCCL broadcast
    (`/root/reference/04_accelerate/01_cifar_accelerate.ipynb:cell-18`).
    Primary path: the C++ host control plane (tpuframe.core.native) — a
    tiny control string should not require compiling an XLA program, and
    it works before/without jax.distributed.  Falls back to jax's
    ``broadcast_one_to_all`` when the native plane is unavailable.
    Call on ALL processes; pass the real id on process 0 and anything
    (e.g. None) elsewhere.
    """
    if rt.process_count() == 1:
        return run_id or ""

    import os

    if int(os.environ.get("WORLD_SIZE", "1")) == rt.process_count():
        try:
            from tpuframe.core.native import control_plane

            return control_plane().broadcast_str(
                run_id if rt.is_main_process() else None
            )
        except Exception:
            pass  # no toolchain / env contract mismatch: use the jax path

    import numpy as np
    from jax.experimental import multihost_utils

    buf = np.zeros(max_len, np.uint8)
    if rt.is_main_process() and run_id:
        raw = run_id.encode()[:max_len]
        buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    return bytes(out[out != 0]).decode()
