"""Model registry: named models, versions, aliases, latest-lookup, reload.

The reference registers Composer-trained models into Unity Catalog via
``MLFlowLogger(model_registry_uri='databricks-uc')`` and reloads them by
name (`/root/reference/03_composer/01_cifar_composer_resnet.ipynb:cell-16`).
This is the tpuframe equivalent over the existing stores:

- **File registry** (:class:`ModelRegistry`): lives under
  ``<tracking_uri>/models/<name>/version-<n>/`` next to the mlruns file
  store.  ``register_model(run, "cifar-resnet")`` snapshots the run's
  logged model artifact (``Run.log_model``) into a new version — the
  registry is self-contained and survives run garbage-collection, like
  MLflow's registry store.  Aliases (``@champion``) and ``latest``
  resolve to versions; ``load()`` returns the model pytree.
- **HTTP mirror** (:class:`HttpModelRegistry`): the same surface against
  a stock MLflow server's registry REST endpoints
  (``registered-models/create``, ``model-versions/create``,
  ``registered-models/alias`` — MLflow REST 2.0), for remote registries.

``models:/name/3`` and ``models:/name@alias`` URIs resolve via
:func:`load_model`, mirroring mlflow's URI convention.
"""

from __future__ import annotations

import os
import re
import shutil
import time
from dataclasses import dataclass
from typing import Any, Mapping

import yaml

from tpuframe.track.mlflow_store import Run, _now_ms, _write_yaml

_MODELS_DIR = "models"
_VERSION_PREFIX = "version-"
_NAME_RE = re.compile(r"^[A-Za-z0-9][\w.\- ]*$")


@dataclass(frozen=True)
class ModelVersion:
    """One registered version: where it came from and where it lives."""

    name: str
    version: int
    run_id: str | None
    source: str  # artifact dir the version was registered from
    path: str  # registry-owned snapshot dir (file registry) or source URI
    created_ms: int
    aliases: tuple[str, ...] = ()


class ModelRegistry:
    """Named-model registry over the mlruns file store.

    >>> reg = ModelRegistry("./mlruns")
    >>> v1 = reg.register_model(run, "cifar-resnet")       # after log_model
    >>> reg.set_alias("cifar-resnet", "champion", v1.version)
    >>> tree = reg.load("cifar-resnet", "@champion", template=state)
    """

    def __init__(self, tracking_uri: str = "./mlruns"):
        self.root = os.path.abspath(str(tracking_uri).removeprefix("file://"))
        self.models_root = os.path.join(self.root, _MODELS_DIR)

    # -- write ---------------------------------------------------------------
    def register_model(
        self,
        run: Run | str,
        name: str,
        artifact_path: str = "model",
        *,
        tags: Mapping[str, Any] | None = None,
    ) -> ModelVersion:
        """Snapshot ``run``'s logged model artifact as the next version of
        ``name`` (creating the registered model on first use, like
        ``mlflow.register_model``).  ``run`` is a file-store :class:`Run`
        (post ``log_model``) or a model artifact directory path."""
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid model name {name!r}")
        if isinstance(run, str):
            source, run_id = run, None
        else:
            source, run_id = run.artifact_path(artifact_path), run.run_id
        if not os.path.isdir(source):
            raise FileNotFoundError(
                f"no model artifact at {source}; call run.log_model() first"
            )

        model_dir = os.path.join(self.models_root, name)
        os.makedirs(model_dir, exist_ok=True)
        meta = os.path.join(model_dir, "meta.yaml")
        if not os.path.exists(meta):
            _write_yaml(meta, {"name": name, "creation_time": _now_ms()})

        # claim the next free version atomically (mkdir is the lock)
        for _ in range(1000):
            version = self._max_version(name) + 1
            vdir = os.path.join(model_dir, f"{_VERSION_PREFIX}{version}")
            try:
                os.makedirs(vdir)
                break
            except FileExistsError:
                continue  # concurrent registrar claimed it; try the next
        else:  # pragma: no cover
            raise RuntimeError(f"could not claim a version slot for {name!r}")

        snapshot = os.path.join(vdir, "artifacts")
        shutil.copytree(source, snapshot)
        _write_yaml(
            os.path.join(vdir, "meta.yaml"),
            {
                "name": name,
                "version": version,
                "run_id": run_id,
                "source": source,
                "creation_time": _now_ms(),
                "utc_time_created": time.strftime(
                    "%Y-%m-%d %H:%M:%S", time.gmtime()
                ),
                **({"tags": dict(tags)} if tags else {}),
            },
        )
        return self.get(name, version)

    def set_alias(self, name: str, alias: str, version: int) -> None:
        """Point ``alias`` at ``version`` (reassigning steals it, like
        mlflow's set-registered-model-alias)."""
        self._require_version(name, version)
        aliases = self._aliases(name)
        aliases[str(alias)] = int(version)
        _write_yaml(os.path.join(self.models_root, name, "aliases.yaml"), aliases)

    def delete_alias(self, name: str, alias: str) -> None:
        aliases = self._aliases(name)
        aliases.pop(str(alias), None)
        _write_yaml(os.path.join(self.models_root, name, "aliases.yaml"), aliases)

    # -- read ----------------------------------------------------------------
    def list_models(self) -> list[str]:
        if not os.path.isdir(self.models_root):
            return []
        return sorted(
            e
            for e in os.listdir(self.models_root)
            if os.path.exists(os.path.join(self.models_root, e, "meta.yaml"))
        )

    def versions(self, name: str) -> list[int]:
        model_dir = os.path.join(self.models_root, name)
        if not os.path.isdir(model_dir):
            return []
        out = []
        for e in os.listdir(model_dir):
            if e.startswith(_VERSION_PREFIX) and os.path.exists(
                os.path.join(model_dir, e, "meta.yaml")
            ):
                out.append(int(e[len(_VERSION_PREFIX):]))
        return sorted(out)

    def get(self, name: str, ref: int | str = "latest") -> ModelVersion:
        """Resolve a version reference: an int, a numeric string,
        ``"latest"``, or ``"@alias"``."""
        version = self._resolve(name, ref)
        vdir = os.path.join(self.models_root, name, f"{_VERSION_PREFIX}{version}")
        with open(os.path.join(vdir, "meta.yaml")) as f:
            meta = yaml.safe_load(f)
        aliases = tuple(
            a for a, v in self._aliases(name).items() if v == version
        )
        return ModelVersion(
            name=name,
            version=version,
            run_id=meta.get("run_id"),
            source=meta.get("source", ""),
            path=os.path.join(vdir, "artifacts"),
            created_ms=int(meta.get("creation_time", 0)),
            aliases=aliases,
        )

    def latest(self, name: str) -> ModelVersion:
        return self.get(name, "latest")

    def load(self, name: str, ref: int | str = "latest", *, template: Any) -> Any:
        """Reload the registered model pytree (``{"params", "batch_stats"}``
        shape written by ``Run.log_model``); ``template`` supplies the tree
        structure — a TrainState or a matching dict both work."""
        from tpuframe.ckpt import load_pytree

        mv = self.get(name, ref)
        if hasattr(template, "params"):  # TrainState-like
            tmpl = {
                "params": template.params,
                "batch_stats": getattr(template, "batch_stats", {}) or {},
            }
        elif isinstance(template, Mapping) and "params" in template:
            tmpl = {
                "params": template["params"],
                "batch_stats": template.get("batch_stats", {}) or {},
            }
        else:  # bare params tree
            tmpl = {"params": template, "batch_stats": {}}
        return load_pytree(os.path.join(mv.path, "model.msgpack"), tmpl)

    # -- internals -----------------------------------------------------------
    def _max_version(self, name: str) -> int:
        vs = self.versions(name)
        return vs[-1] if vs else 0

    def _require_version(self, name: str, version: int) -> None:
        if version not in self.versions(name):
            raise KeyError(
                f"model {name!r} has no version {version}; have {self.versions(name)}"
            )

    def _aliases(self, name: str) -> dict[str, int]:
        path = os.path.join(self.models_root, name, "aliases.yaml")
        try:
            with open(path) as f:
                return {str(k): int(v) for k, v in (yaml.safe_load(f) or {}).items()}
        except FileNotFoundError:
            return {}

    def _resolve(self, name: str, ref: int | str) -> int:
        versions = self.versions(name)
        if not versions:
            raise KeyError(
                f"no registered model {name!r}; have {self.list_models()}"
            )
        if isinstance(ref, int):
            self._require_version(name, ref)
            return ref
        ref = str(ref)
        if ref == "latest":
            return versions[-1]
        if ref.startswith("@"):
            aliases = self._aliases(name)
            if ref[1:] not in aliases:
                raise KeyError(
                    f"model {name!r} has no alias {ref[1:]!r}; "
                    f"have {sorted(aliases)}"
                )
            return aliases[ref[1:]]
        if ref.isdigit():
            self._require_version(name, int(ref))
            return int(ref)
        raise ValueError(f"unresolvable version reference {ref!r}")


def parse_models_uri(uri: str) -> tuple[str, int | str]:
    """``models:/name/3`` -> ("name", 3); ``models:/name@alias`` ->
    ("name", "@alias"); ``models:/name`` -> ("name", "latest")."""
    if not uri.startswith("models:/"):
        raise ValueError(f"not a models:/ URI: {uri!r}")
    rest = uri[len("models:/"):]
    if "@" in rest:
        name, alias = rest.rsplit("@", 1)
        return name, f"@{alias}"
    if "/" in rest:
        name, version = rest.rsplit("/", 1)
        return name, int(version)
    return rest, "latest"


def load_model(uri: str, *, template: Any, tracking_uri: str = "./mlruns") -> Any:
    """Reload by registry URI — the mlflow ``models:/`` convention
    (`03_composer/01_cifar_composer_resnet.ipynb:cell-17`)."""
    name, ref = parse_models_uri(uri)
    return ModelRegistry(tracking_uri).load(name, ref, template=template)


class HttpModelRegistry:
    """The same registry surface against a stock MLflow server (REST 2.0
    registered-models / model-versions / alias endpoints).

    The server owns version numbering and artifact storage; versions
    reference the run's artifact (``runs:/<run_id>/<path>``) rather than
    snapshotting, which is MLflow's own server-side behavior.
    """

    def __init__(self, base_url: str, timeout_s: float = 30.0):
        from tpuframe.track.http_store import _Client

        self._client = _Client(base_url, timeout_s=timeout_s)

    def register_model(
        self, run: Any, name: str, artifact_path: str = "model"
    ) -> ModelVersion:
        from tpuframe.track.http_store import HttpError

        try:
            self._client.call(
                "POST", "/api/2.0/mlflow/registered-models/create", {"name": name}
            )
        except HttpError as e:
            if e.status != 400:  # RESOURCE_ALREADY_EXISTS comes back as 400
                raise
        run_id = getattr(run, "run_id", str(run))
        source = f"runs:/{run_id}/{artifact_path}"
        out = self._client.call(
            "POST",
            "/api/2.0/mlflow/model-versions/create",
            {"name": name, "source": source, "run_id": run_id},
        )["model_version"]
        return ModelVersion(
            name=name,
            version=int(out["version"]),
            run_id=run_id,
            source=source,
            path=source,
            created_ms=int(out.get("creation_timestamp", 0)),
        )

    def set_alias(self, name: str, alias: str, version: int) -> None:
        self._client.call(
            "POST",
            "/api/2.0/mlflow/registered-models/alias",
            {"name": name, "alias": alias, "version": str(version)},
        )

    def get(self, name: str, ref: int | str = "latest") -> ModelVersion:
        ref = str(ref)
        if ref.startswith("@"):
            out = self._client.call(
                "GET",
                "/api/2.0/mlflow/registered-models/alias"
                f"?name={name}&alias={ref[1:]}",
            )["model_version"]
        elif ref == "latest":
            out = self._client.call(
                "POST",
                "/api/2.0/mlflow/registered-models/get-latest-versions",
                {"name": name},
            )["model_versions"][0]
        else:
            out = self._client.call(
                "GET",
                f"/api/2.0/mlflow/model-versions/get?name={name}&version={ref}",
            )["model_version"]
        return ModelVersion(
            name=name,
            version=int(out["version"]),
            run_id=out.get("run_id"),
            source=out.get("source", ""),
            path=out.get("source", ""),
            created_ms=int(out.get("creation_timestamp", 0)),
            aliases=tuple(out.get("aliases", ())),
        )

    def latest(self, name: str) -> ModelVersion:
        return self.get(name, "latest")
