"""TensorBoard event-file sink — no tensorflow/tensorboard dependency.

The reference's DeepSpeed base config asks for TensorBoard output
(`/root/reference/02_deepspeed/deepspeed_config.py:42-46`:
``{"tensorboard": {"enabled": true, "output_path": ..., "job_name": ...}}``).
This writes the real on-disk format a stock TensorBoard reads:

- **TFRecord framing**: ``[len u64][masked crc32c(len)][payload]
  [masked crc32c(payload)]``
- **Event protobuf**, hand-encoded (the scalar subset is tiny): wall_time
  (field 1, double), step (field 2, varint), file_version (field 3) on
  the header record, summary (field 5) holding ``Summary.Value`` entries
  of tag (field 1) + simple_value (field 2, float).

Duck-types the Trainer's logger contract (``log_metrics(dict, step=)``,
``log_params``, ``flush``), so it drops into ``Trainer(loggers=[...])``
next to the MLflow logger.  :func:`from_deepspeed_config` wires the
reference's config block shape straight through.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Any, Mapping

__all__ = ["TensorBoardLogger", "from_deepspeed_config"]

# -- crc32c (Castagnoli), table-driven — zlib.crc32 is the wrong polynomial --

_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ (poly if c & 1 else 0)
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- minimal protobuf wire encoding ------------------------------------------


def _varint(n: int) -> bytes:
    n &= 0xFFFFFFFFFFFFFFFF  # proto int64 two's complement; also keeps a
    out = bytearray()        # negative input from looping forever
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3) + _varint(value)


def _field_double(num: int, value: float) -> bytes:
    return _varint((num << 3) | 1) + struct.pack("<d", value)


def _field_float(num: int, value: float) -> bytes:
    return _varint((num << 3) | 5) + struct.pack("<f", value)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _event(
    wall_time: float,
    step: int = 0,
    file_version: str | None = None,
    summary: bytes | None = None,
) -> bytes:
    out = _field_double(1, wall_time)
    if step:
        out += _field_varint(2, step)
    if file_version is not None:
        out += _field_bytes(3, file_version.encode())
    if summary is not None:
        out += _field_bytes(5, summary)
    return out


def _scalar_summary(values: Mapping[str, float]) -> bytes:
    out = b""
    for tag, value in values.items():
        entry = _field_bytes(1, str(tag).encode()) + _field_float(2, float(value))
        out += _field_bytes(1, entry)
    return out


class TensorBoardLogger:
    """Scalar event writer; one ``events.out.tfevents.*`` file per run.

    >>> tb = TensorBoardLogger("./runs", job_name="cifar")
    >>> tb.log_metrics({"loss": 0.5, "acc": 0.9}, step=10)
    >>> tb.close()
    """

    def __init__(self, output_path: str, job_name: str = "tpuframe"):
        self.logdir = os.path.join(output_path, job_name)
        os.makedirs(self.logdir, exist_ok=True)
        fname = (
            f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
            f".{os.getpid()}"
        )
        self._path = os.path.join(self.logdir, fname)
        self._f = open(self._path, "ab")
        self._record(_event(time.time(), file_version="brain.Event:2"))

    @property
    def path(self) -> str:
        return self._path

    def _record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    @staticmethod
    def _coerce(metrics: Mapping[str, Any]) -> dict[str, float]:
        """Anything float() accepts (numpy/jax scalars included) is a
        scalar; bools and non-numerics are skipped, like the MLflow
        logger's coercion."""
        out = {}
        for k, v in metrics.items():
            if isinstance(v, (bool, str)):
                continue
            try:
                out[k] = float(v)
            except (TypeError, ValueError):
                continue
        return out

    def log_metrics(self, metrics: Mapping[str, Any], step: int = 0) -> None:
        scalars = self._coerce(metrics)
        if scalars:
            self._record(
                _event(time.time(), step=int(step), summary=_scalar_summary(scalars))
            )
            # flush per call (one syscall per epoch/interval): a live
            # `tensorboard --logdir` must see curves mid-run, not at close
            self._f.flush()

    def log_params(self, params: Mapping[str, Any]) -> None:
        self.log_metrics(
            {f"params/{k}": v for k, v in self._coerce(params).items()}, step=0
        )

    def flush(self, status: str | None = None) -> None:
        self._f.flush()

    def finish(self, error: BaseException | None = None) -> None:
        self.close()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def from_deepspeed_config(cfg: Mapping[str, Any]) -> TensorBoardLogger | None:
    """Build a logger from the reference's DeepSpeed ``tensorboard`` block
    (`deepspeed_config.py:42-46`); None when absent/disabled."""
    tb = dict(cfg.get("tensorboard") or {})
    if not tb.get("enabled"):
        return None
    return TensorBoardLogger(
        tb.get("output_path", "./tensorboard"),
        job_name=tb.get("job_name", "tpuframe"),
    )
