"""Memory plane: live watermarks, compiled truth, and OOM forensics.

Three sources of memory truth, cheapest-first, all landing in one
telemetry namespace so the analyzer / doctor / autotune read a single
vocabulary:

- **estimate** — ``parallel.plan_memory`` (stdlib math off the
  ``ParallelPlan``); the trainer registers it here via ``set_context``
  so a crash can attribute bytes without recomputing anything.
- **compiled** — ``record_executable_memory`` reads an AOT
  executable's ``memory_analysis()`` (argument/output/temp/
  generated-code/alias bytes) under its compile label, emits one
  ``memory/executable`` event, and persists the record next to the
  compile cache (``<cache>/memory/``) so a restarted process knows its
  footprint without recompiling.
- **live** — ``update_watermarks`` folds the ``SystemMetricsMonitor``
  sample into process-wide HBM/host peaks (gauges
  ``memory/hbm_peak_mb`` / ``memory/host_peak_mb``), emitting a
  ratcheted ``memory/watermark`` *event* only when the HBM peak grows
  >5% — bounded spam, but the peak reaches the JSONL the analyzer
  reads (gauges don't).

``maybe_oom_event`` is the forensics seam: the trainer's step loop, the
precompiler, and the serve batcher call it from their except blocks;
a ``RESOURCE_EXHAUSTED``-class error becomes one ``memory/oom`` event
carrying the three-way attribution table (estimate vs compiled vs
live, top-N leaves) plus the ``suggest_fit`` escalation ladder — the
crash arrives with the remedy.  Callers always re-raise; this module
only narrates.

Stdlib-only (KN006): ``launch.remote.all_env_vars()`` imports
``MEMORY_ENV_VARS`` from here, and the doctor must read persisted
records against a wedged backend.  Anything needing jax stays in the
caller (the monitor passes already-sampled device stats in).
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any

from tpuframe.parallel.memory import plan_memory, suggest_fit

__all__ = [
    "MEMORY_ENV_VARS",
    "MEMORY_ENV_DOMAINS",
    "memory_env",
    "record_executable_memory",
    "executable_records",
    "update_watermarks",
    "peaks",
    "reset_peaks",
    "is_oom",
    "set_context",
    "clear_context",
    "maybe_oom_event",
]

#: every env knob the memory plane reads — consumed by
#: ``launch.remote.all_env_vars()`` (shipped to every worker) and the
#: doctor's ``memory`` section.
MEMORY_ENV_VARS = (
    "TPUFRAME_MEMORY_SAMPLE_S",
    "TPUFRAME_MEMORY_TOP_LEAVES",
    "TPUFRAME_MEMORY_LIVE",
    "TPUFRAME_MEMORY_BUDGET_MB",
)

#: machine-readable value domains (KN007 keeps the two in lockstep).
MEMORY_ENV_DOMAINS = {
    # watermark sample cadence: becomes SystemMetricsMonitor's default
    # interval, resolved at construction
    "TPUFRAME_MEMORY_SAMPLE_S": {
        "type": "float", "range": (0.1, None), "apply": "restart"},
    # attribution-table depth in memory/oom events
    "TPUFRAME_MEMORY_TOP_LEAVES": {
        "type": "int", "range": (1, 64), "apply": "live"},
    # falsy = estimator-only: no live sampling fold-in, no OOM
    # forensics emission (the classifying seams stay pass-through)
    "TPUFRAME_MEMORY_LIVE": {"type": "bool", "apply": "restart"},
    # planning budget per device; 0/unset = auto from the live
    # device bytes_limit when the backend reports one
    "TPUFRAME_MEMORY_BUDGET_MB": {
        "type": "float", "range": (0, None), "apply": "live"},
}

_MEMORY_DEFAULTS = {
    "TPUFRAME_MEMORY_SAMPLE_S": 10.0,
    "TPUFRAME_MEMORY_TOP_LEAVES": 8,
    "TPUFRAME_MEMORY_LIVE": True,
    "TPUFRAME_MEMORY_BUDGET_MB": 0.0,
}

_FALSY = ("0", "false", "no", "off", "disabled")


def memory_env(environ: dict | None = None) -> dict:
    """Parsed ``TPUFRAME_MEMORY_*`` knobs + defaults; malformed values
    are *reported* (an ``errors`` dict), never raised — the doctor
    prints this and a typo'd knob must not crash a diagnosis run."""
    env = os.environ if environ is None else environ
    out: dict = dict(_MEMORY_DEFAULTS)
    errors: dict[str, str] = {}
    for knob, lo in (("TPUFRAME_MEMORY_SAMPLE_S", 0.1),
                     ("TPUFRAME_MEMORY_BUDGET_MB", 0.0)):
        raw = env.get(knob, "").strip()
        if not raw:
            continue
        try:
            v = float(raw)
            if v < lo:
                raise ValueError("below minimum")
        except ValueError:
            errors[knob] = f"not a float >= {lo}: {raw!r}"
            continue
        out[knob] = v
    raw = env.get("TPUFRAME_MEMORY_TOP_LEAVES", "").strip()
    if raw:
        try:
            v = int(raw)
            if not 1 <= v <= 64:
                raise ValueError("out of range")
            out["TPUFRAME_MEMORY_TOP_LEAVES"] = v
        except ValueError:
            errors["TPUFRAME_MEMORY_TOP_LEAVES"] = f"not an int in [1, 64]: {raw!r}"
    raw = env.get("TPUFRAME_MEMORY_LIVE", "").strip().lower()
    if raw:
        out["TPUFRAME_MEMORY_LIVE"] = raw not in _FALSY
    out["errors"] = errors
    return out


def _tele():
    from tpuframe.track.telemetry import get_telemetry

    return get_telemetry()


# -- live watermarks ----------------------------------------------------------

_RATCHET = 1.05  # emit memory/watermark only on >5% HBM-peak growth

_PEAK_LOCK = threading.Lock()
_PEAKS = {
    "hbm_peak_mb": 0.0,
    "host_peak_mb": 0.0,
    "hbm_limit_mb": 0.0,
    "_emitted_mb": 0.0,
}


def update_watermarks(device_stats: dict[str, float], rss_mb: float,
                      registry: Any = None) -> dict[str, float]:
    """Fold one monitor sample into the process-wide peaks.

    ``device_stats`` is ``system_metrics.device_memory_stats()`` output
    (already sampled by the caller — no double device poll); ``rss_mb``
    the host RSS.  Sets the ``memory/hbm_peak_mb`` / ``host_peak_mb``
    gauges every call; emits the ``memory/watermark`` *event* only when
    the HBM peak ratchets up >5%, so long fits log O(log) events, not
    one per sample.  Returns the current peaks.
    """
    hbm = 0.0
    limit = 0.0
    for k, v in device_stats.items():
        if k.endswith("_mem_used_mb") and v > hbm:
            hbm = v
            util = device_stats.get(k.replace("_mem_used_mb", "_mem_util"), 0)
            if util:
                limit = v / util
    emit = False
    with _PEAK_LOCK:
        if rss_mb > _PEAKS["host_peak_mb"]:
            _PEAKS["host_peak_mb"] = rss_mb
        if limit > _PEAKS["hbm_limit_mb"]:
            _PEAKS["hbm_limit_mb"] = limit
        if hbm > _PEAKS["hbm_peak_mb"]:
            _PEAKS["hbm_peak_mb"] = hbm
            if hbm > _PEAKS["_emitted_mb"] * _RATCHET:
                _PEAKS["_emitted_mb"] = hbm
                emit = True
        snap = {k: v for k, v in _PEAKS.items() if not k.startswith("_")}
    tele = _tele()
    reg = registry if registry is not None else tele.registry
    reg.gauge("memory/hbm_peak_mb").set(snap["hbm_peak_mb"])
    reg.gauge("memory/host_peak_mb").set(snap["host_peak_mb"])
    if emit:
        tele.event("memory/watermark", **snap)
    return snap


def peaks() -> dict[str, float]:
    """Current process-wide peaks (keys without the ratchet internals)."""
    with _PEAK_LOCK:
        return {k: v for k, v in _PEAKS.items() if not k.startswith("_")}


def reset_peaks() -> None:
    """Zero the watermarks (tests; a fresh fit in a reused process)."""
    with _PEAK_LOCK:
        for k in _PEAKS:
            _PEAKS[k] = 0.0


# -- compiled truth -----------------------------------------------------------

#: stats attribute -> record key (duck-typed off CompiledMemoryStats;
#: absent attributes record as 0 so the schema is stable across
#: backends)
_STAT_FIELDS = {
    "argument_size_in_bytes": "argument_mb",
    "output_size_in_bytes": "output_mb",
    "temp_size_in_bytes": "temp_mb",
    "alias_size_in_bytes": "alias_mb",
    "generated_code_size_in_bytes": "generated_code_mb",
    "host_argument_size_in_bytes": "host_argument_mb",
    "host_output_size_in_bytes": "host_output_mb",
    "host_temp_size_in_bytes": "host_temp_mb",
}

_MB = 1024.0 * 1024.0

#: in-process registry of compiled records, by label — skew_report and
#: the OOM forensics read this without touching the filesystem
_EXECUTABLES: dict[str, dict] = {}


def _memory_dir(cache_dir: str | None = None) -> str | None:
    if cache_dir is None:
        from tpuframe.compile.cache import cache_dir_from_env, enabled_dir

        # an explicitly-set TPUFRAME_COMPILE_CACHE is authoritative (the
        # doctor reads records wherever the env points, possibly from a
        # process that never enabled the cache); otherwise records live
        # next to whatever cache this process actually enabled
        if os.environ.get("TPUFRAME_COMPILE_CACHE", "").strip():
            cache_dir = cache_dir_from_env()
        else:
            cache_dir = enabled_dir() or cache_dir_from_env()
    return os.path.join(cache_dir, "memory") if cache_dir else None


def record_executable_memory(compiled: Any, label: str, *,
                             persist: bool = True) -> dict | None:
    """Record ``compiled.memory_analysis()`` under ``label``.

    Emits one ``memory/executable`` event and (by default) persists the
    record next to the compile cache so a cache-hit restart knows its
    footprint without recompiling.  Returns the record, or None when
    the executable exposes no analysis (interpreters, some backends) —
    never raises: memory accounting must not fail a compile.
    """
    analyze = getattr(compiled, "memory_analysis", None)
    if analyze is None:
        return None
    try:
        stats = analyze()
    except Exception:
        return None
    if stats is None:
        return None
    rec: dict[str, Any] = {"label": label}
    for attr, key in _STAT_FIELDS.items():
        rec[key] = round(float(getattr(stats, attr, 0) or 0) / _MB, 3)
    # peak approximation for a donated-state step: arguments + temps +
    # outputs, minus the buffers aliased back onto the arguments
    rec["peak_mb"] = round(
        rec["argument_mb"] + rec["temp_mb"] + rec["output_mb"]
        - rec["alias_mb"], 3,
    )
    if not rec["alias_mb"]:
        # a persistent-cache HIT deserializes the executable WITHOUT
        # aliasing info (alias = 0), inflating peak_mb by the donated
        # bytes — when a prior record of this label (this process or the
        # persisted one from the real compile) knows the aliasing, keep
        # it instead of clobbering better evidence on every restart
        prior = _EXECUTABLES.get(label) or _read_record(label)
        if prior and prior.get("alias_mb"):
            rec = dict(prior)
    _EXECUTABLES[label] = rec
    _tele().event("memory/executable", **rec)
    if persist:
        path = _record_path(label)
        if path:
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(rec, f, indent=1, sort_keys=True)
                os.replace(tmp, path)
            except OSError:
                pass  # a full disk must not fail the compile either
    return rec


def _record_path(label: str, cache_dir: str | None = None) -> str | None:
    d = _memory_dir(cache_dir)
    if not d:
        return None
    name = hashlib.sha256(label.encode()).hexdigest()[:16]
    return os.path.join(d, f"{name}.json")


def _read_record(label: str) -> dict | None:
    path = _record_path(label)
    if not path:
        return None
    try:
        with open(path) as f:
            rec = json.loads(f.read())
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) and rec.get("label") == label else None


def executable_records(cache_dir: str | None = None) -> dict[str, dict]:
    """All known executable-memory records, by compile label.

    In-process records win; persisted ones (from prior runs sharing the
    compile cache) fill the rest — how a restart knows its footprint
    before compiling anything.
    """
    out: dict[str, dict] = {}
    d = _memory_dir(cache_dir)
    if d and os.path.isdir(d):
        for name in sorted(os.listdir(d)):
            if not name.endswith(".json"):
                continue
            try:
                # json.loads, not json.load: the bare name `load` would
                # alias the checkpoint loader in the lint call graph and
                # drag it into the hot-path set
                with open(os.path.join(d, name)) as f:
                    rec = json.loads(f.read())
            except (OSError, ValueError):
                continue
            if isinstance(rec, dict) and rec.get("label"):
                out[rec["label"]] = rec
    out.update(_EXECUTABLES)
    return out


# -- OOM forensics ------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "OUT OF MEMORY")


def is_oom(exc: BaseException) -> bool:
    """Is this an allocation failure?  Matches XLA's RESOURCE_EXHAUSTED
    status (jax surfaces it as ``XlaRuntimeError`` with the status name
    in the message) and the synthetic ``fault.chaos.OomError``."""
    text = f"{type(exc).__name__}: {exc}".upper()
    return any(m in text for m in _OOM_MARKERS) or "RESOURCEEXHAUSTED" in text


_CTX_LOCK = threading.Lock()
_CONTEXT: dict[str, Any] = {}


def set_context(*, plan: Any = None, model_template: Any = None,
                batch_spec: Any = None, opt_template: Any = None,
                comms_template: Any = None, microbatches: int | None = None,
                estimate: dict | None = None) -> dict | None:
    """Register what's running so an OOM can attribute bytes.

    The trainer calls this once per fit (templates from the state it
    just built — shape/dtype carriers, not live arrays, are fine and
    cheaper).  When ``estimate`` is omitted and a plan + model template
    are given, ``plan_memory`` is computed here, once.  Returns the
    estimate in effect.
    """
    est = estimate
    if est is None and plan is not None and model_template is not None:
        try:
            est = plan_memory(
                plan, model_template, batch_spec,
                opt_template=opt_template, comms_template=comms_template,
                microbatches=microbatches,
                top_leaves=memory_env()["TPUFRAME_MEMORY_TOP_LEAVES"],
            )
        except Exception:
            est = None  # forensics context must never fail the fit
    with _CTX_LOCK:
        _CONTEXT.clear()
        _CONTEXT.update(
            plan=plan, model_template=model_template, batch_spec=batch_spec,
            opt_template=opt_template, comms_template=comms_template,
            microbatches=microbatches, estimate=est,
        )
    return est


def clear_context() -> None:
    with _CTX_LOCK:
        _CONTEXT.clear()


def maybe_oom_event(exc: BaseException, *, where: str,
                    step: int | None = None) -> bool:
    """Classify ``exc``; emit ONE ``memory/oom`` event if it's an OOM.

    The event carries the three-way attribution (estimate vs compiled
    vs live peaks), the top-N leaves, and the ``suggest_fit`` ladder
    against the resolved budget (``TPUFRAME_MEMORY_BUDGET_MB``, else
    the live device limit).  Returns True iff classified — the caller
    ALWAYS re-raises; forensics never swallows.  Never raises itself.
    """
    if not is_oom(exc):
        return False
    env = memory_env()
    if not env["TPUFRAME_MEMORY_LIVE"]:
        return False
    try:
        with _CTX_LOCK:
            ctx = dict(_CONTEXT)
        live = peaks()
        budget = env["TPUFRAME_MEMORY_BUDGET_MB"] or live.get("hbm_limit_mb") or None
        execs = executable_records()
        compiled = sorted(
            ({"label": k, "peak_mb": v.get("peak_mb", 0)} for k, v in execs.items()),
            key=lambda r: -r["peak_mb"],
        )[:4]
        estimate = ctx.get("estimate")
        suggestion = None
        if ctx.get("plan") is not None and ctx.get("model_template") is not None:
            try:
                fit = suggest_fit(
                    ctx["plan"], ctx["model_template"], ctx.get("batch_spec"),
                    budget_mb=budget,
                    opt_template=ctx.get("opt_template"),
                    comms_template=ctx.get("comms_template"),
                    microbatches=ctx.get("microbatches"),
                )
                suggestion = {k: v for k, v in fit.items() if k != "candidates"}
                if suggestion.get("suggestion"):
                    # keep the event bounded: the rung, not its full estimate
                    suggestion["suggestion"] = {
                        k: v for k, v in suggestion["suggestion"].items()
                        if k != "estimate"
                    }
            except Exception:
                suggestion = None
        tele = _tele()
        tele.event(
            "memory/oom",
            where=where,
            step=step,
            error=str(exc)[:500],
            estimate_total_mb=(estimate or {}).get("per_device_mb", {}).get("total"),
            estimate=estimate,
            compiled_peaks=compiled,
            live=live,
            budget_mb=budget,
            fit=suggestion,
        )
        tele.registry.counter("memory/oom_total").inc()
    except Exception:
        pass  # narration must never mask the original error
    return True
