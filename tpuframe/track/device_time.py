"""Device-time attribution: parsed profiler traces -> exposed comms.

The skew report (`track/analyze.py`) sees only *host-side* spans — it can
say a step was slow, but not where the device itself spent the time.
This module is the device half: a stdlib-only parser over the trace
files ``jax.profiler`` writes (Chrome Trace Event JSON, gzipped, under
``<logdir>/plugins/profile/<session>/*.trace.json.gz``) that reduces a
captured window to one ``device_time`` record:

- per-class device wall (**compute** / **collective** / **transfer** /
  **idle**), classified by HLO op-name rules over the device execution
  tracks only (host python threads and runtime infra events are noise);
- **exposed_comms_s** — collective wall NOT overlapped by compute,
  computed as interval math on the device timeline
  (``union(collective) - union(compute)``).  This is THE number ROADMAP
  item 3(a) gates on: overlap scheduling shrinks it while bytes-on-wire
  stays constant;
- **overlap_efficiency** — ``1 - exposed/collective`` (1.0 means every
  collective second hid behind compute);
- a **top-k op table** (base op name, count, total seconds, % of device
  time) — the measured fused-kernel target list ROADMAP item 3(b) names.

Never imports jax: the doctor and analyzer must read traces against a
wedged backend.  The capture side lives in `track/profiler.py`
(``ProfilerCallback`` cadence mode writes the captures this parses);
``TPUFRAME_PROFILE_*`` knobs are declared here so the parser, the
capture callback, the doctor, and the launch env-shipping registry all
read one list.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Iterable, Sequence

__all__ = [
    "PROFILE_ENV_VARS",
    "PROFILE_ENV_DOMAINS",
    "DEVICE_TIME_VERSION",
    "classify_op",
    "device_time_report",
    "device_trace_events",
    "find_trace_files",
    "interval_subtract",
    "interval_union",
    "list_captures",
    "load_trace",
    "profile_env",
]

#: every env knob the profile capture path reads — consumed by
#: ``launch.remote.all_env_vars()`` (shipped to every worker) and the
#: doctor's ``profile`` section.  Declared HERE (stdlib-only module),
#: not in profiler.py, so the doctor resolves them against a wedged
#: backend.
PROFILE_ENV_VARS = (
    "TPUFRAME_PROFILE_STEPS",
    "TPUFRAME_PROFILE_EVERY",
    "TPUFRAME_PROFILE_KEEP",
    "TPUFRAME_PROFILE_DIR",
)

#: machine-readable value domains (KN007 keeps the two in lockstep).
#: All "restart": the callback resolves its cadence at construction —
#: rewriting the env under a live fit would silently do nothing.
PROFILE_ENV_DOMAINS = {
    "TPUFRAME_PROFILE_STEPS": {
        "type": "int", "range": (1, None), "apply": "restart"},
    "TPUFRAME_PROFILE_EVERY": {
        "type": "int", "range": (0, None), "apply": "restart"},
    "TPUFRAME_PROFILE_KEEP": {
        "type": "int", "range": (1, None), "apply": "restart"},
    "TPUFRAME_PROFILE_DIR": {"type": "path", "apply": "restart"},
}

_PROFILE_DEFAULTS = {
    "TPUFRAME_PROFILE_STEPS": 0,   # 0 = capture disarmed
    "TPUFRAME_PROFILE_EVERY": 0,   # 0 = one capture, no cadence
    "TPUFRAME_PROFILE_KEEP": 3,    # capture dirs retained per rank
    "TPUFRAME_PROFILE_DIR": "",
}


def profile_env(environ: dict | None = None) -> dict:
    """Parsed ``TPUFRAME_PROFILE_*`` knobs + defaults, with malformed
    values *reported* (an ``errors`` dict), never raised — the doctor
    prints this and a typo'd knob must not crash a diagnosis run."""
    env = os.environ if environ is None else environ
    out: dict = dict(_PROFILE_DEFAULTS)
    errors: dict[str, str] = {}
    for knob in ("TPUFRAME_PROFILE_STEPS", "TPUFRAME_PROFILE_EVERY",
                 "TPUFRAME_PROFILE_KEEP"):
        raw = env.get(knob, "").strip()
        if not raw:
            continue
        try:
            v = int(raw)
            if v < 0:
                raise ValueError("negative")
        except ValueError:
            errors[knob] = f"not a non-negative int: {raw!r}"
            continue
        out[knob] = v
    if env.get("TPUFRAME_PROFILE_DIR", "").strip():
        out["TPUFRAME_PROFILE_DIR"] = env["TPUFRAME_PROFILE_DIR"].strip()
    out["errors"] = errors
    return out


# -- trace file discovery -----------------------------------------------------

#: jax.profiler writes TensorBoard layout: one session dir per capture
_SESSION_GLOB = os.path.join("plugins", "profile", "*")


def find_trace_files(logdir: str) -> list[str]:
    """The ``*.trace.json.gz`` files of the **newest** profiler session
    under ``logdir`` (one per host that captured).  Accepts either the
    capture root (what ``start_trace`` was given) or a session dir
    itself.  Empty list when nothing parseable exists."""
    candidates = [logdir] + sorted(
        glob.glob(os.path.join(logdir, _SESSION_GLOB)), reverse=True
    )
    for d in candidates:
        files = sorted(glob.glob(os.path.join(d, "*.trace.json.gz")))
        files += sorted(glob.glob(os.path.join(d, "*.trace.json")))
        if files:
            return files
    return []


def list_captures(profile_dir: str) -> list[str]:
    """Capture dirs under a ``TPUFRAME_PROFILE_DIR``, oldest-first —
    the rotation order the cadence callback maintains (newest last)."""
    out = []
    try:
        names = sorted(os.listdir(profile_dir))
    except OSError:
        return []
    for name in names:
        p = os.path.join(profile_dir, name)
        if os.path.isdir(p) and name.startswith("capture-"):
            out.append(p)
    return out


def load_trace(path: str) -> dict:
    """One Chrome Trace Event JSON file (gzipped or plain)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        return json.load(f)


# -- op classification --------------------------------------------------------

#: HLO base-name prefixes that put an op on the wire.  Matched against
#: the op name lowercased with the trailing ``.<id>`` stripped.
_COLLECTIVE_PREFIXES = (
    "all-reduce", "allreduce", "all-gather", "allgather",
    "reduce-scatter", "reducescatter", "all-to-all", "alltoall",
    "collective", "partial-reduce", "ncclallreduce", "send", "recv",
)

#: host<->device transfer ops (infeed/outfeed, explicit copies).
_TRANSFER_PREFIXES = (
    "infeed", "outfeed", "copy", "memcpy", "h2d", "d2h",
    "transfer", "device-to-host", "host-to-device",
)

_TRAILING_ID = re.compile(r"\.\d+$")


def _base_name(name: str) -> str:
    """``dot.42`` -> ``dot``: aggregate the top-op table by HLO op, not
    by per-instruction id."""
    return _TRAILING_ID.sub("", name)


def classify_op(name: str) -> str | None:
    """``"collective"`` / ``"transfer"`` / ``"compute"``, or None for
    runtime infra that is not device work (thread-pool bookkeeping etc.
    — CPU traces interleave ``ThunkExecutor::Execute`` style events with
    the real ops, and their inflated nested durations would swamp every
    class)."""
    if not name or "::" in name or name.startswith("$"):
        return None
    base = _base_name(name).lower()
    for p in _COLLECTIVE_PREFIXES:
        if base.startswith(p):
            return "collective"
    for p in _TRANSFER_PREFIXES:
        if base.startswith(p):
            return "transfer"
    return "compute"


# -- device-track selection ---------------------------------------------------


def _is_exec_track(pname: str, tname: str) -> bool:
    """Is (process, thread) a device *execution* timeline?

    TPU/GPU traces put each chip in a ``/device:...`` process whose
    "XLA Ops" threads carry per-op events; the "Steps" / "XLA Modules"
    threads frame the same time at coarser granularity and would double
    count.  CPU traces have no device process — XLA:CPU op execution
    lands on ``tf_XLATfrtCpuClient/<tid>`` threads of the host process
    (the ``python`` thread's nested durations are host bookkeeping, not
    device time) AND on the ``tf_XLAEigen/<tid>`` intra-op pool, which
    is where the thunk runtime actually runs the named HLO ops —
    including every collective (an all-reduce under simulated multi-CPU
    appears ONLY there).  Both pools belong to one host process, so
    their events merge into one device timeline; ``classify_op`` drops
    the pools' ``::`` bookkeeping spans, leaving the real ops.
    """
    t = tname.lower()
    if pname.startswith("/device:"):
        return "step" not in t and "module" not in t
    return "xlatfrtcpuclient" in t or "xlaeigen" in t


def _tracks(trace: dict) -> dict[tuple[Any, Any], dict]:
    """(pid, tid) -> {"process", "thread", "events": [(name, ts, dur)]}
    for the execution tracks of one trace file (ts/dur in µs, offsets
    from trace start)."""
    events = trace.get("traceEvents") or []
    pnames: dict[Any, str] = {}
    tnames: dict[tuple[Any, Any], str] = {}
    for ev in events:
        if ev.get("ph") == "M":
            if ev.get("name") == "process_name":
                pnames[ev.get("pid")] = str((ev.get("args") or {}).get("name", ""))
            elif ev.get("name") == "thread_name":
                tnames[(ev.get("pid"), ev.get("tid"))] = str(
                    (ev.get("args") or {}).get("name", "")
                )
    tracks: dict[tuple[Any, Any], dict] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        key = (ev.get("pid"), ev.get("tid"))
        pname = pnames.get(key[0], "")
        tname = tnames.get(key, "")
        if not _is_exec_track(pname, tname):
            continue
        try:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if dur <= 0:
            continue
        tr = tracks.setdefault(key, {"process": pname, "thread": tname,
                                     "events": []})
        tr["events"].append((str(ev.get("name", "")), ts, dur))
    return tracks


# -- interval math ------------------------------------------------------------


def interval_union(intervals: Iterable[tuple[float, float]]) -> list[tuple[float, float]]:
    """Merged, sorted, non-overlapping union of ``(start, end)`` pairs."""
    ivs = sorted((a, b) for a, b in intervals if b > a)
    out: list[tuple[float, float]] = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def interval_subtract(a: Sequence[tuple[float, float]],
                      b: Sequence[tuple[float, float]]) -> list[tuple[float, float]]:
    """``a - b`` where both are merged unions: the parts of ``a`` not
    covered by ``b`` (the exposed-comms primitive: collective time with
    the compute union carved out)."""
    out: list[tuple[float, float]] = []
    j = 0
    for a0, a1 in a:
        lo = a0
        while j < len(b) and b[j][1] <= lo:
            j += 1
        k = j
        while k < len(b) and b[k][0] < a1:
            b0, b1 = b[k]
            if b0 > lo:
                out.append((lo, min(b0, a1)))
            lo = max(lo, b1)
            if lo >= a1:
                break
            k += 1
        if lo < a1:
            out.append((lo, a1))
    return out


def _union_len(union: Sequence[tuple[float, float]]) -> float:
    return sum(b - a for a, b in union)


# -- the device_time record ---------------------------------------------------

#: bump when the record shape changes (the skew report embeds it; the
#: golden fixture test pins the keys).  1.1: CPU exec-track selection
#: widened to the ``tf_XLAEigen`` intra-op pool — CPU captures now see
#: their collectives, so ``overlap_efficiency`` is measurable off-chip.
DEVICE_TIME_VERSION = "1.1"

_CLASSES = ("compute", "collective", "transfer")


def device_time_report(source: str | dict, *, steps: int | None = None,
                       top_k: int = 10) -> dict | None:
    """Reduce a capture to the ``device_time`` record, or None when the
    source holds no parseable device events.

    ``source`` is a capture dir (session discovery via
    :func:`find_trace_files`), a single trace file path, or an
    already-loaded trace dict.  ``steps`` (when the capture side knows
    how many train steps the window covered) adds the per-step
    divisions ``device_step_s`` / ``exposed_comms_per_step_s``.

    All aggregate seconds are **per device track** means (a 4-chip
    capture reports one device's window, not 4x), so ``window_s`` stays
    comparable across topologies; ``device_tracks`` records the fan-in.
    The identity ``busy_s + idle_s == window_s`` holds exactly per
    track; per-class walls are interval unions, so they only sum above
    ``busy_s`` where classes genuinely overlapped (that excess IS the
    overlap being measured).
    """
    if isinstance(source, dict):
        traces = [source]
        trace_dir = None
    elif os.path.isfile(source):
        traces, trace_dir = [load_trace(source)], os.path.dirname(source)
    else:
        files = find_trace_files(source)
        if not files:
            return None
        traces, trace_dir = [], os.path.dirname(files[0])
        for p in files:
            try:
                traces.append(load_trace(p))
            except (OSError, ValueError):
                continue  # torn/partial capture file: parse what exists

    # one timeline per device: merge a device's exec *threads* (a CPU
    # thread pool runs ops concurrently) into per-class interval unions
    per_device: dict[tuple[int, Any], dict] = {}
    op_totals: dict[str, dict] = {}
    for i, trace in enumerate(traces):
        for (pid, _tid), tr in _tracks(trace).items():
            dev = per_device.setdefault(
                (i, pid),
                {cls: [] for cls in _CLASSES} | {"events": 0},
            )
            for name, ts, dur in tr["events"]:
                cls = classify_op(name)
                if cls is None:
                    continue
                dev[cls].append((ts, ts + dur))
                dev["events"] += 1
                agg = op_totals.setdefault(
                    _base_name(name), {"count": 0, "total_us": 0.0, "class": cls}
                )
                agg["count"] += 1
                agg["total_us"] += dur

    per_device = {k: d for k, d in per_device.items() if d["events"]}
    if not per_device:
        return None

    n_dev = len(per_device)
    window_s = busy_s = idle_s = exposed_s = 0.0
    classes = {cls: {"wall_s": 0.0, "events": 0} for cls in _CLASSES}
    for dev in per_device.values():
        unions = {cls: interval_union(dev[cls]) for cls in _CLASSES}
        all_union = interval_union(
            iv for cls in _CLASSES for iv in unions[cls]
        )
        if not all_union:
            continue
        span = all_union[-1][1] - all_union[0][0]
        busy = _union_len(all_union)
        window_s += span / 1e6
        busy_s += busy / 1e6
        idle_s += (span - busy) / 1e6
        exposed_s += _union_len(
            interval_subtract(unions["collective"], unions["compute"])
        ) / 1e6
        for cls in _CLASSES:
            classes[cls]["wall_s"] += _union_len(unions[cls]) / 1e6
            classes[cls]["events"] += len(dev[cls])

    window_s /= n_dev
    busy_s /= n_dev
    idle_s /= n_dev
    exposed_s /= n_dev
    for cls in _CLASSES:
        classes[cls]["wall_s"] = round(classes[cls]["wall_s"] / n_dev, 6)

    collective_wall = classes["collective"]["wall_s"]
    total_device_us = sum(a["total_us"] for a in op_totals.values())
    top = sorted(op_totals.items(), key=lambda kv: -kv[1]["total_us"])[:top_k]
    top_ops = [
        {
            "name": name,
            "class": agg["class"],
            "count": agg["count"],
            "total_s": round(agg["total_us"] / 1e6, 6),
            "pct": round(100.0 * agg["total_us"] / total_device_us, 2)
            if total_device_us > 0 else 0.0,
        }
        for name, agg in top
    ]
    out: dict = {
        "schema_version": DEVICE_TIME_VERSION,
        "trace_dir": trace_dir,
        "device_tracks": n_dev,
        "steps": steps,
        "window_s": round(window_s, 6),
        "busy_s": round(busy_s, 6),
        "idle_s": round(idle_s, 6),
        "classes": classes,
        "exposed_comms_s": round(exposed_s, 6),
        "overlap_efficiency": (
            round(1.0 - exposed_s / collective_wall, 4)
            if collective_wall > 0 else None
        ),
        "device_step_s": (
            round(window_s / steps, 6) if steps else None
        ),
        "exposed_comms_per_step_s": (
            round(exposed_s / steps, 6) if steps else None
        ),
        "top_ops": top_ops,
    }
    return out


def device_trace_events(source: str, *, limit: int = 200_000) -> list[dict]:
    """Flat device op events for Perfetto merging: ``{device, thread,
    name, class, ts_us, dur_us}`` — ts is the trace-local µs offset; the
    analyzer anchors it on the capture's recorded wall start so host
    spans and device ops share one timeline.  Bounded by ``limit`` (a
    long capture must not balloon the merged trace file)."""
    out: list[dict] = []
    if os.path.isfile(source):
        files = [source]
    else:
        files = find_trace_files(source)
    for p in files:
        try:
            trace = load_trace(p)
        except (OSError, ValueError):
            continue
        for (pid, tid), tr in sorted(_tracks(trace).items(),
                                     key=lambda kv: str(kv[0])):
            dev = tr["process"] or "device"
            for name, ts, dur in tr["events"]:
                cls = classify_op(name)
                if cls is None:
                    continue
                out.append({
                    "device": dev,
                    "thread": tr["thread"] or str(tid),
                    "name": name,
                    "class": cls,
                    "ts_us": ts,
                    "dur_us": dur,
                })
                if len(out) >= limit:
                    return out
    return out
