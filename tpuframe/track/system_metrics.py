"""System-metrics monitor: host + device utilization sampled in background.

Replaces the reference's ``MLFLOW_ENABLE_SYSTEM_METRICS_LOGGING=true`` env
(`/root/reference/01_torch_distributor/02_cifar_torch_distributor_resnet.py:186`)
and its ``nvidia-smi`` notebook cells (SURVEY.md §5 "Tracing / profiling"):
a daemon thread samples /proc (CPU, RSS) and jax device memory stats (TPU HBM
in-use) and appends them to the run's metrics with a monotonically increasing
step, no external agents.

Every sample is also mirrored into the telemetry registry as gauges
(``system/cpu_util``, ``system/rss_mb``, ``system/device<i>_mem_used_mb``,
``system/device<i>_mem_util``), so the Prometheus ``/metrics`` endpoint
(``telemetry.start_metrics_server``) exposes host and HBM utilization —
not just the Run logger path.  ``run=None`` runs the monitor registry-only.
"""

from __future__ import annotations

import os
import threading
import time


def _cpu_times() -> tuple[float, float]:
    """(process_cpu_seconds, wall_seconds)."""
    t = os.times()
    return (t.user + t.system), time.monotonic()


def _rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def device_memory_stats() -> dict[str, float]:
    """Per-device HBM usage in MB (empty on backends without stats, e.g. CPU)."""
    import jax

    out: dict[str, float] = {}
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            used = stats.get("bytes_in_use", 0) / 2**20
            limit = stats.get("bytes_limit", 0) / 2**20
            out[f"device{d.id}_mem_used_mb"] = used
            if limit:
                out[f"device{d.id}_mem_util"] = used / limit
    return out


class SystemMetricsMonitor:
    """Daemon thread logging system metrics every ``interval_s``.

    Args:
      run: a tracker Run with ``log_metrics(dict, step=)``; None samples
        into the telemetry registry only (the Prometheus path).
      registry: MetricsRegistry to mirror gauges into (default: the
        process-wide telemetry's).
    """

    def __init__(self, run=None, interval_s: float | None = None,
                 prefix: str = "system/", registry=None):
        self.run = run
        if interval_s is None:
            # TPUFRAME_MEMORY_SAMPLE_S: the memory plane's watermark
            # cadence doubles as the monitor default (one sampler)
            from tpuframe.track.memory import memory_env

            interval_s = memory_env()["TPUFRAME_MEMORY_SAMPLE_S"]
        self.interval_s = interval_s
        self.prefix = prefix
        self.registry = registry
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._step = 0
        self._lock = threading.Lock()  # serializes thread vs stop() final sample

    def _registry(self):
        if self.registry is not None:
            return self.registry
        from tpuframe.track.telemetry import get_telemetry

        return get_telemetry().registry

    def sample(self) -> dict[str, float]:
        cpu, wall = _cpu_times()
        if not hasattr(self, "_last"):
            self._last = (cpu, wall)
        dcpu = cpu - self._last[0]
        dwall = max(wall - self._last[1], 1e-9)
        self._last = (cpu, wall)
        cpu_util = min(dcpu / dwall, float(os.cpu_count() or 1))
        rss = _rss_mb()
        metrics = {
            f"{self.prefix}cpu_utilization": cpu_util,
            f"{self.prefix}memory_rss_mb": rss,
        }
        devices = device_memory_stats()
        for k, v in devices.items():
            metrics[f"{self.prefix}{k}"] = v
        # registry mirror: the gauge names are fixed (OBSERVABILITY.md),
        # independent of the Run-path prefix, so dashboards scraping
        # /metrics see the same series whatever the run is called
        reg = self._registry()
        reg.gauge("system/cpu_util").set(cpu_util)
        reg.gauge("system/rss_mb").set(rss)
        for k, v in devices.items():
            reg.gauge(f"system/{k}").set(v)
        # memory plane: fold this sample into the process-wide HBM/host
        # watermarks (memory/hbm_peak_mb, memory/host_peak_mb + the
        # ratcheted memory/watermark event) — same sample, no second
        # device poll
        from tpuframe.track.memory import memory_env, update_watermarks

        if memory_env()["TPUFRAME_MEMORY_LIVE"]:
            update_watermarks(devices, rss, registry=reg)
        return metrics

    def _publish(self) -> None:
        with self._lock:
            metrics = self.sample()
            if self.run is not None:
                self.run.log_metrics(metrics, step=self._step)
            self._step += 1

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._publish()

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        # final sample so short runs record at least one point
        self._publish()
