"""Process-wide telemetry spine: spans, metrics registry, JSONL event log.

tpuframe's observability was point solutions — an XLA trace callback
(`track/profiler.py`), epoch-total wall-clock buckets buried in
``Trainer._run_epoch``, a background ``/proc`` sampler — while the repo's
own benchmark history (BENCH_r01–r05) shows the dominant failure mode is
*silent wedging*: ``jax.devices()`` and preflight compiles hanging >90 s
with zero diagnostics.  Production pre-training frameworks (TorchTitan,
PAPERS.md) treat metrics/profiling as a first-class subsystem; this module
is that subsystem for tpuframe.

Three pieces, all stdlib-only (telemetry must keep working precisely when
jax is wedged, so this module NEVER imports jax):

- :meth:`Telemetry.span` — nestable, thread-safe ``with`` regions timed on
  the monotonic clock.  Every span feeds a per-name duration histogram in
  the registry (p50/p95/p99 for free) and, when a sink is configured, one
  rank-tagged JSONL event.  The live span stack per thread is readable by
  the watchdog (`track/watchdog.py`), so a stall report says *where* each
  thread was, in tpuframe terms, not just python frames.
- :class:`MetricsRegistry` — counters, gauges, histograms (bounded
  reservoir: long runs keep *recent* distribution data).  Exports as a
  flat dict for the existing ``TensorBoardLogger``/``MLflowLogger``
  (:func:`publish_to_loggers`, :class:`MetricsExportCallback`) and as a
  Prometheus text page (:meth:`MetricsRegistry.prometheus_text`, served by
  :func:`start_metrics_server` / ``track.http_store.MetricsServer``).
- The **JSONL event log** — one file per rank
  (``events-rank<N>.jsonl``), schema documented in ``OBSERVABILITY.md``.
  Enabled by ``TPUFRAME_TELEMETRY_DIR`` (inherited by launch workers and
  bench children) or :func:`configure`.

The process-wide instance comes from :func:`get_telemetry`; with no
configuration it is memory-only (ring buffer + registry, no file I/O), so
instrumented hot paths cost two ``perf_counter`` calls and a dict update.

Env knobs::

    TPUFRAME_TELEMETRY_DIR       write events-rank<N>.jsonl under this dir
    TPUFRAME_TELEMETRY_MAX_MB    rotate the event log at this size (MB);
                                 segments shift to .1 .. .K, oldest dropped
    TPUFRAME_TELEMETRY_KEEP      rotated segments to keep (default 3;
                                 0 = rotation keeps no history)
    TPUFRAME_WATCHDOG_S          attach a stall watchdog; default deadline
                                 (seconds) for every guarded activity
    TPUFRAME_WATCHDOG_DEADLINES  per-activity overrides, e.g.
                                 "train/step=120,ckpt/save=600"

Every sink-backed log opens with a ``meta`` record (schema version, rank,
hostname, pid, and a wall-clock/monotonic **anchor pair**) and every record
carries both ``ts`` (wall) and ``mono`` (monotonic) timestamps — the fleet
analyzer (``tpuframe.track.analyze``) uses the anchors to place every
rank's events on one timeline even when a rank's wall clock steps mid-run.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Iterator, Mapping, Sequence

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsExportCallback",
    "MetricsRegistry",
    "Span",
    "Telemetry",
    "configure",
    "get_telemetry",
    "publish_to_loggers",
    "reset",
    "start_metrics_server",
]

#: bump when the JSONL record shape changes (OBSERVABILITY.md documents it)
SCHEMA_VERSION = 1

#: every env knob the observability/fault stack reads — THE list, consumed
#: by ``launch.remote`` (shipped to every host: a fleet whose ranks ran
#: without telemetry cannot be skew-analyzed after the fact) and by the
#: doctor's telemetry section.  Add new knobs here, not in the consumers.
OBSERVABILITY_ENV_VARS = (
    "TPUFRAME_TELEMETRY_DIR",
    "TPUFRAME_TELEMETRY_MAX_MB",
    "TPUFRAME_TELEMETRY_KEEP",
    "TPUFRAME_WATCHDOG_S",
    "TPUFRAME_WATCHDOG_DEADLINES",
    "TPUFRAME_STRAGGLER_STEPS",
    "TPUFRAME_STRAGGLER_FACTOR",
    "TPUFRAME_PREEMPT_SIGNALS",
    "TPUFRAME_FLEET_TIMEOUT_S",
)

#: machine-readable value domains for the knobs above (KN007 keeps the
#: two in lockstep).  ``apply`` says whether a new value takes effect on
#: a running process ("live": re-read at every use) or only on a
#: supervised restart ("restart": read once at configure/construction) —
#: the autotuner's legal search space and re-application contract.
OBSERVABILITY_ENV_DOMAINS = {
    "TPUFRAME_TELEMETRY_DIR": {"type": "path", "apply": "restart"},
    "TPUFRAME_TELEMETRY_MAX_MB": {
        "type": "float", "range": (0, None), "apply": "restart"},
    "TPUFRAME_TELEMETRY_KEEP": {
        "type": "int", "range": (0, None), "apply": "restart"},
    "TPUFRAME_WATCHDOG_S": {
        "type": "float", "range": (0, None), "apply": "restart"},
    "TPUFRAME_WATCHDOG_DEADLINES": {
        "type": "int", "range": (1, None), "apply": "restart"},
    "TPUFRAME_STRAGGLER_STEPS": {
        "type": "int", "range": (1, None), "apply": "live"},
    "TPUFRAME_STRAGGLER_FACTOR": {
        "type": "float", "range": (1.0, None), "apply": "live"},
    "TPUFRAME_PREEMPT_SIGNALS": {"type": "bool", "apply": "restart"},
    "TPUFRAME_FLEET_TIMEOUT_S": {
        "type": "float", "range": (0, None), "apply": "live"},
}


def _env_rank() -> int:
    """Process rank from the launch env (never imports jax: telemetry must
    initialize even while the backend is wedged)."""
    for var in ("TPUFRAME_PROCESS_ID", "RANK"):
        v = os.environ.get(var, "")
        if v.isdigit():
            return int(v)
    return 0


def _env_max_bytes() -> int:
    """Rotation threshold from TPUFRAME_TELEMETRY_MAX_MB (0 = unbounded).
    Lenient like every observability knob: garbage (including ``inf``,
    which would overflow int()) reads as "no cap", never as a crash."""
    v = os.environ.get("TPUFRAME_TELEMETRY_MAX_MB", "")
    try:
        mb = float(v)
    except ValueError:
        return 0
    return int(mb * 2**20) if 0 < mb < 2**40 else 0


def _env_keep_segments() -> int:
    """Rotated segments to retain; 0 is honored as "keep none" (rotation
    just truncates) — silently coercing it up would surprise exactly the
    disk-constrained operator who set it."""
    v = os.environ.get("TPUFRAME_TELEMETRY_KEEP", "")
    return int(v) if v.isdigit() else 3


# -- metrics registry ---------------------------------------------------------


class Counter:
    """Monotonic counter (events seen, batches prefetched, retries)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins scalar (current epoch, queue depth, HBM in use)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Bounded-reservoir histogram: lifetime count/sum + a ring of the most
    recent ``max_samples`` observations for percentiles.

    A ring, not a capped list (the old ``StepTimer`` bug,
    `track/profiler.py`): a capped list stops sampling after the first
    ``max_samples`` steps, so a 10-hour run reports the distribution of its
    first minutes.  The ring keeps the *recent* window, which is what a
    stall investigation needs.
    """

    __slots__ = ("name", "max_samples", "count", "total", "_ring", "_lock")

    def __init__(self, name: str, max_samples: int = 2048):
        if max_samples < 1:
            raise ValueError(f"max_samples must be >= 1, got {max_samples}")
        self.name = name
        self.max_samples = max_samples
        self.count = 0
        self.total = 0.0
        self._ring: list[float] = []
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = self.count % self.max_samples
            self.count += 1
            self.total += v
            if len(self._ring) < self.max_samples:
                self._ring.append(v)
            else:
                self._ring[i] = v  # overwrite oldest: insertion-order ring

    def window(self) -> list[float]:
        """The retained (most recent) observations, unordered."""
        with self._lock:
            return list(self._ring)

    @staticmethod
    def _quantile(sorted_vals: Sequence[float], q: float) -> float:
        return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]

    def summary(self) -> dict[str, float]:
        """count/mean over the lifetime, p50/p95/p99 over the recent window."""
        with self._lock:
            vals, count, total = sorted(self._ring), self.count, self.total
        if not vals:
            return {}
        return {
            "count": float(count),
            "mean": total / count,
            "p50": self._quantile(vals, 0.50),
            "p95": self._quantile(vals, 0.95),
            "p99": self._quantile(vals, 0.99),
        }


class MetricsRegistry:
    """Name -> instrument table; get-or-create, thread-safe.

    Names are slash-namespaced (``span/train/step``, ``data/batches_prefetched``
    — conventions in OBSERVABILITY.md).  Exports: :meth:`snapshot` (flat
    dict for the Trainer's logger contract) and :meth:`prometheus_text`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str, max_samples: int = 2048) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, max_samples)
            return h

    def snapshot(self, prefix: str = "") -> dict[str, float]:
        """Flat ``{name: value}`` dict — the shape ``log_metrics`` takes.

        Histograms expand to ``<name>_count/_mean/_p50/_p95/_p99``.
        """
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
        out: dict[str, float] = {}
        for c in counters:
            out[f"{prefix}{c.name}"] = c.value
        for g in gauges:
            out[f"{prefix}{g.name}"] = g.value
        for h in hists:
            for k, v in h.summary().items():
                out[f"{prefix}{h.name}_{k}"] = v
        return out

    @staticmethod
    def _prom_name(name: str) -> str:
        sane = "".join(ch if ch.isalnum() else "_" for ch in name)
        return f"tpuframe_{sane}"

    def prometheus_text(self) -> str:
        """Prometheus exposition format (text/plain; version=0.0.4).

        Histograms export as summaries: ``_count``, ``_sum``, and
        ``{quantile=...}`` sample lines over the recent window.
        """
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
        lines: list[str] = []
        for c in counters:
            n = self._prom_name(c.name)
            lines += [f"# TYPE {n} counter", f"{n} {c.value}"]
        for g in gauges:
            n = self._prom_name(g.name)
            lines += [f"# TYPE {n} gauge", f"{n} {g.value}"]
        for h in hists:
            n = self._prom_name(h.name)
            s = h.summary()
            if not s:
                continue
            lines.append(f"# TYPE {n} summary")
            for q in ("p50", "p95", "p99"):
                lines.append(f'{n}{{quantile="0.{q[1:]}"}} {s[q]}')
            lines += [f"{n}_sum {h.total}", f"{n}_count {int(s['count'])}"]
        return "\n".join(lines) + "\n"


# -- spans --------------------------------------------------------------------


class Span:
    """Handle yielded by :meth:`Telemetry.span`; ``elapsed`` is valid after
    the ``with`` block exits (the Trainer reads it to keep its legacy
    ``data_wait_s``/``dispatch_s``/``host_block_s`` epoch totals)."""

    __slots__ = ("name", "attrs", "stack", "elapsed", "ok", "error", "_t0")

    def __init__(self, name: str, attrs: Mapping[str, Any]):
        self.name = name
        self.attrs = dict(attrs)
        self.stack: list[str] = []
        self.elapsed = 0.0
        self.ok = True
        self.error: str | None = None
        self._t0 = 0.0

    def __repr__(self):
        return f"Span({self.name!r}, elapsed={self.elapsed:.6f}, ok={self.ok})"


class Telemetry:
    """One process-wide spine: span stacks, registry, ring buffer, JSONL sink.

    Args:
      jsonl_path: event-log file (appended, one JSON object per line).
        None = memory-only (ring buffer + registry, no file I/O).
      rank: tag on every record; defaults to the launch env's rank.
      max_events: ring-buffer length (the watchdog dumps the tail of this).
      registry: share an existing :class:`MetricsRegistry` (default: new).
      watchdog: a ``track.watchdog.Watchdog`` to attach (wires both ways).
      span_histograms: auto-observe every span duration into
        ``span/<name>`` in the registry.
      max_bytes: rotate the JSONL file once it reaches this size
        (default: TPUFRAME_TELEMETRY_MAX_MB; 0 = never rotate).
      keep_segments: rotated segments retained as ``<path>.1`` (newest)
        .. ``<path>.K`` (oldest); the analyzer reads them back in order.
    """

    def __init__(
        self,
        jsonl_path: str | None = None,
        *,
        rank: int | None = None,
        max_events: int = 512,
        registry: MetricsRegistry | None = None,
        watchdog: Any = None,
        span_histograms: bool = True,
        max_bytes: int | None = None,
        keep_segments: int | None = None,
    ):
        self.jsonl_path = jsonl_path
        self.rank = _env_rank() if rank is None else int(rank)
        self.registry = registry or MetricsRegistry()
        self.span_histograms = span_histograms
        self.max_bytes = _env_max_bytes() if max_bytes is None else int(max_bytes)
        self.keep_segments = (
            _env_keep_segments() if keep_segments is None
            else max(0, int(keep_segments))
        )
        # clock anchor pair: every record carries a wall ts AND a monotonic
        # ts; the pair below (also published in the meta record) lets the
        # fleet analyzer map this rank's monotonic clock onto the wall
        # timeline fixed at configure time — immune to mid-run NTP steps
        self.anchor_wall = time.time()
        self.anchor_mono = time.monotonic()
        self._recent: deque[dict] = deque(maxlen=max_events)
        self._bytes = 0  # current JSONL segment size (approx, for rotation)
        # _lock guards only in-memory state (span stacks, ring buffer) and
        # is never held across file I/O: the watchdog reads active_spans/
        # recent_events under it WHILE a JSONL write may be hung on a dead
        # filesystem — the stall report must not deadlock on the sink it
        # is reporting about.  _io_lock serializes the file writes alone.
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._file: Any = None
        # live span stacks by thread ident — shared (not thread-local) so the
        # watchdog thread can read every thread's position at dump time
        self._active: dict[int, list[Span]] = {}
        self.watchdog = None
        if watchdog is not None:
            self.attach_watchdog(watchdog)
        if self.jsonl_path is not None:
            # a sink-backed log's FIRST line is the meta record: rank
            # identity + the clock anchor pair must precede any event the
            # fleet analyzer would need to place on the shared timeline
            self._write(self._meta_fields())

    def _meta_fields(self) -> dict:
        try:
            hostname = socket.gethostname()
        except OSError:
            hostname = ""
        return {
            "kind": "meta",
            "name": "telemetry/meta",
            "schema": SCHEMA_VERSION,
            "hostname": hostname,
            "anchor_wall": round(self.anchor_wall, 6),
            "anchor_mono": round(self.anchor_mono, 6),
        }

    # -- wiring --------------------------------------------------------------
    def attach_watchdog(self, watchdog: Any) -> Any:
        """Adopt ``watchdog``: it reads this telemetry's spans/events for its
        reports, and :meth:`guard` routes through it."""
        self.watchdog = watchdog
        watchdog.telemetry = self
        return watchdog

    # -- spans ---------------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, *, emit: bool = True, **attrs: Any) -> Iterator[Span]:
        """Time a region; nestable, exception-transparent.

        ``emit=False`` records the histogram + live-stack visibility but
        skips the JSONL event — for per-batch inner regions where one event
        per occurrence would dominate the log.
        """
        sp = Span(name, attrs)
        ident = threading.get_ident()
        with self._lock:
            stack = self._active.setdefault(ident, [])
            stack.append(sp)
            sp.stack = [s.name for s in stack]
        sp._t0 = time.perf_counter()
        try:
            yield sp
        except BaseException as e:
            sp.ok = False
            sp.error = f"{type(e).__name__}: {e}"[:300]
            raise
        finally:
            sp.elapsed = time.perf_counter() - sp._t0
            with self._lock:
                stack = self._active.get(ident)
                if stack:
                    if stack[-1] is sp:
                        stack.pop()
                    elif sp in stack:  # mis-nested exit: drop just this span
                        stack.remove(sp)
                    if not stack:
                        del self._active[ident]
            if self.span_histograms:
                self.registry.histogram(f"span/{name}").observe(sp.elapsed)
            if emit:
                rec = {
                    "kind": "span",
                    "name": name,
                    "stack": sp.stack,
                    "dur_s": round(sp.elapsed, 6),
                    "ok": sp.ok,
                }
                if sp.error:
                    rec["error"] = sp.error
                if attrs:
                    rec["attrs"] = attrs
                self._write(rec)

    def active_spans(self) -> dict[str, list[str]]:
        """``{thread_name (ident): [span names, outermost first]}`` — the
        watchdog's "where is everyone" view."""
        names = {t.ident: t.name for t in threading.enumerate()}
        with self._lock:
            return {
                f"{names.get(ident, '?')} ({ident})": [s.name for s in stack]
                for ident, stack in self._active.items()
                if stack
            }

    def guard(self, name: str, deadline_s: float | None = None):
        """Watchdog lease for a bounded activity (no-op without a watchdog
        or a resolvable deadline).  Compose with a span::

            with tele.span("ckpt/save"), tele.guard("ckpt/save"):
                ...
        """
        if self.watchdog is None:
            return contextlib.nullcontext()
        return self.watchdog.guard(name, deadline_s)

    # -- events --------------------------------------------------------------
    def event(self, name: str, *, kind: str = "event", **fields: Any) -> None:
        """Append a free-form record (bench preflight attempts, watchdog
        stall reports, worker lifecycle marks)."""
        self._write({"kind": kind, "name": name, **fields})

    def recent_events(self, n: int = 50) -> list[dict]:
        with self._lock:
            return list(self._recent)[-n:]

    def _envelope(self, rec: dict) -> dict:
        return {
            "v": SCHEMA_VERSION,
            "ts": round(time.time(), 6),
            "mono": round(time.monotonic(), 6),
            "rank": self.rank,
            "pid": os.getpid(),
            "thread": threading.current_thread().name,
            **rec,
        }

    def _write(self, rec: dict) -> None:
        rec = self._envelope(rec)
        with self._lock:
            self._recent.append(rec)
        if self.jsonl_path is None:
            return
        line = json.dumps(rec, default=str) + "\n"
        with self._io_lock:
            if self.jsonl_path is None:  # closed/poisoned while we waited
                return
            try:
                if self._file is None:
                    d = os.path.dirname(self.jsonl_path)
                    if d:
                        os.makedirs(d, exist_ok=True)
                    self._file = open(self.jsonl_path, "a")
                    self._bytes = self._file.tell()  # append mode: file size
                self._file.write(line)
                # trace-tagged span records ride the stdio buffer: the
                # traced serve request path emits several per request,
                # and a flush syscall each would serialize every serving
                # thread on this lock (measured ~10% on served p50).
                # Everything else still flushes per line for crash
                # durability — and each such flush carries any buffered
                # trace spans with it; the reader already tolerates a
                # torn buffered tail.
                a = rec.get("attrs")
                if not (rec.get("kind") == "span"
                        and ("trace" in rec or "traces" in rec
                             or (isinstance(a, dict)
                                 and ("trace" in a or "traces" in a)))):
                    self._file.flush()
                # encoded size, not len(line): non-ASCII payloads (error
                # strings, hostnames) are 2-4 UTF-8 bytes per char, and
                # undercounting would let the segment overshoot the cap
                # the disk-constrained operator set
                self._bytes += len(line.encode("utf-8", "replace"))
                if self.max_bytes and self._bytes >= self.max_bytes:
                    self._rotate_locked()
            except OSError:
                # a full/readonly disk must never take the training loop
                # down with it; drop to memory-only
                self._file, self.jsonl_path = None, None

    def _rotate_locked(self) -> None:
        """Shift ``path -> path.1 -> ... -> path.K`` (oldest dropped) and
        reopen a fresh segment headed by its own meta record, so each
        segment is independently alignable.  ``keep_segments=0`` keeps no
        history: the full file is simply dropped.  Caller holds
        ``_io_lock``."""
        base = self.jsonl_path
        self._file.close()
        self._file = None
        if self.keep_segments == 0:
            os.remove(base)
        else:
            oldest = f"{base}.{self.keep_segments}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for k in range(self.keep_segments - 1, 0, -1):
                src = f"{base}.{k}"
                if os.path.exists(src):
                    os.replace(src, f"{base}.{k + 1}")
            os.replace(base, f"{base}.1")
        self._file = open(base, "a")
        # direct write, not _write: we already hold _io_lock, and the
        # rotation meta is a file header, not a ring-buffer event
        head = json.dumps(self._envelope(self._meta_fields()), default=str) + "\n"
        self._file.write(head)
        self._file.flush()
        self._bytes = len(head.encode("utf-8", "replace"))

    def close(self) -> None:
        """Terminal: later writes stay memory-only (a prefetcher thread
        that captured this instance must not reopen the closed file)."""
        if self.watchdog is not None:
            self.watchdog.stop()
        with self._io_lock:
            self.jsonl_path = None
            if self._file is not None:
                self._file.close()
                self._file = None


# -- the process-wide instance ------------------------------------------------

_GLOBAL: Telemetry | None = None
_GLOBAL_LOCK = threading.Lock()


def _default_jsonl_path() -> str | None:
    d = os.environ.get("TPUFRAME_TELEMETRY_DIR")
    if not d:
        return None
    return os.path.join(d, f"events-rank{_env_rank()}.jsonl")


def _parse_deadlines(spec: str) -> dict[str, float]:
    """``"train/step=120,ckpt/save=600"`` -> dict (bad entries skipped)."""
    out: dict[str, float] = {}
    for part in spec.split(","):
        name, sep, val = part.strip().partition("=")
        if not sep or not name:
            continue
        try:
            out[name] = float(val)
        except ValueError:
            continue
    return out


def _watchdog_from_env():
    default_s = os.environ.get("TPUFRAME_WATCHDOG_S")
    per_name = os.environ.get("TPUFRAME_WATCHDOG_DEADLINES")
    if not default_s and not per_name:
        return None
    from tpuframe.track.watchdog import Watchdog

    try:
        default = float(default_s) if default_s else None
    except ValueError:
        default = None
    return Watchdog(
        default_deadline_s=default,
        deadlines=_parse_deadlines(per_name) if per_name else None,
    )


def get_telemetry() -> Telemetry:
    """The process-wide telemetry (lazily created from env knobs)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = Telemetry(
                    _default_jsonl_path(), watchdog=_watchdog_from_env()
                )
    return _GLOBAL


def configure(
    jsonl_path: str | None = None,
    *,
    jsonl_dir: str | None = None,
    watchdog: Any = None,
    rank: int | None = None,
    max_events: int = 512,
    registry: MetricsRegistry | None = None,
) -> Telemetry:
    """Replace the process-wide telemetry (programmatic alternative to the
    env knobs).  ``jsonl_dir`` gives the conventional per-rank filename."""
    global _GLOBAL
    if jsonl_path is None and jsonl_dir is not None:
        r = _env_rank() if rank is None else rank
        jsonl_path = os.path.join(jsonl_dir, f"events-rank{r}.jsonl")
    tele = Telemetry(
        jsonl_path,
        rank=rank,
        max_events=max_events,
        registry=registry,
        watchdog=watchdog,
    )
    with _GLOBAL_LOCK:
        old, _GLOBAL = _GLOBAL, tele
    if old is not None:
        old.close()
    return tele


def reset() -> None:
    """Drop the process-wide instance (tests)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        old, _GLOBAL = _GLOBAL, None
    if old is not None:
        old.close()


# -- exporters ----------------------------------------------------------------


def publish_to_loggers(
    loggers: Sequence[Any],
    step: int,
    *,
    prefix: str = "telemetry/",
    registry: MetricsRegistry | None = None,
) -> dict[str, float]:
    """Bridge the registry into the existing logger contract
    (``log_metrics(dict, step=)`` — TensorBoardLogger, MLflowLogger, any
    duck-typed tracker).  Returns the published snapshot."""
    snap = (registry or get_telemetry().registry).snapshot(prefix=prefix)
    if snap:
        for lg in loggers:
            lg.log_metrics(dict(snap), step=step)
    return snap


class MetricsExportCallback:
    """Trainer callback publishing the registry to the run's loggers at
    every epoch end (rank-0, via the Trainer's own logging discipline).

    Duck-typed against ``tpuframe.train.callbacks.Callback`` rather than
    subclassing it — importing the train package would pull jax into every
    telemetry consumer (bench.py's parent must stay jax-free).
    """

    def __init__(self, prefix: str = "telemetry/"):
        self.prefix = prefix

    # the Trainer drives these via getattr(cb, hook) — all hooks must exist
    def on_fit_start(self, trainer) -> None: ...
    def on_epoch_start(self, trainer, epoch) -> None: ...
    def on_step_start(self, trainer) -> None: ...
    def on_step_end(self, trainer) -> None: ...
    def on_batch_end(self, trainer, metrics) -> None: ...
    def on_eval_end(self, trainer, epoch, metrics) -> None: ...
    def on_fit_end(self, trainer) -> None: ...

    def on_epoch_end(self, trainer, epoch, metrics) -> None:
        snap = get_telemetry().registry.snapshot(prefix=self.prefix)
        if snap:
            trainer._log_metrics(snap, step=epoch)


def start_metrics_server(port: int = 0, registry: MetricsRegistry | None = None):
    """Serve ``/metrics`` (Prometheus text) from a daemon thread; returns
    the ``track.http_store.MetricsServer`` (``.port``, ``.url``, ``.close()``)."""
    from tpuframe.track.http_store import MetricsServer

    return MetricsServer(registry=registry, port=port)
