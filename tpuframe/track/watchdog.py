"""Stall watchdog: deadline-monitored activities + all-thread stack dumps.

The failure mode this exists for is documented in this repo's own history
(BENCH_r01–r05, ``benchmarks/results/tunnel_probes.jsonl``): a wedged
backend makes ``jax.devices()``, preflight compiles, or a dispatched train
step hang *forever* — no exception, no log line, nothing for a driver to
attribute.  The watchdog turns every such hang into an attributed report
while the process is still wedged:

- Instrumented code opens a **lease** around each bounded activity
  (``watchdog.guard("train/step")`` — or ``Telemetry.guard``, which
  composes with the matching span).  Long loops can ``beat()`` the lease
  to push its deadline forward.
- A daemon thread (started lazily with the first lease) checks deadlines
  and, when one expires, dumps to stderr + the telemetry JSONL log:
  the overdue activity, every thread's **live span stack** (tpuframe-level
  "where"), every thread's **python stack** (``sys._current_frames``,
  ``faulthandler``-style), and the last-N telemetry events (what led up
  to the stall).
- If the activity later completes, a ``stall_recovered`` event records
  the real duration — distinguishing "wedged forever" from "slow".

Deadlines resolve per activity name: explicit argument > the ``deadlines``
table > ``default_deadline_s``; unresolved means unmonitored (guards are
free to place unconditionally).  Stdlib-only, never imports jax.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import contextlib
import io
import itertools
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Iterator, Mapping

__all__ = ["Watchdog", "WatchdogGuard", "format_all_stacks"]

#: cap on the stack text embedded in a JSONL stall record (stderr gets it all)
_JSONL_STACK_CAP = 20_000


def format_all_stacks() -> str:
    """Every thread's python stack, named — ``faulthandler.dump_traceback``
    with thread names and no fileno requirement."""
    try:
        id2name = {t.ident: t.name for t in threading.enumerate()}
        buf = io.StringIO()
        for ident, frame in sorted(sys._current_frames().items()):
            print(f"--- thread {id2name.get(ident, '?')} ({ident}) ---", file=buf)
            traceback.print_stack(frame, file=buf)
        return buf.getvalue()
    except Exception as e:  # a dump helper must never raise into the loop
        return f"<stack dump failed: {type(e).__name__}: {e}>"


class _Lease:
    __slots__ = ("token", "name", "deadline_s", "expires_at", "started",
                 "dumped", "ever_dumped")

    def __init__(self, token: int, name: str, deadline_s: float):
        self.token = token
        self.name = name
        self.deadline_s = deadline_s
        self.started = time.monotonic()
        self.expires_at = self.started + deadline_s
        # ``dumped`` is the re-report arm (beat() resets it); ``ever_dumped``
        # is sticky so end() knows a stall_recovered record is owed even
        # after an intervening heartbeat
        self.dumped = False
        self.ever_dumped = False


class WatchdogGuard:
    """Context-manager handle from :meth:`Watchdog.guard`; ``beat()`` pushes
    the deadline forward from *now* (heartbeat for long loops)."""

    __slots__ = ("_wd", "_token")

    def __init__(self, wd: "Watchdog", token: int | None):
        self._wd = wd
        self._token = token

    @property
    def monitored(self) -> bool:
        return self._token is not None

    def beat(self) -> None:
        if self._token is not None:
            self._wd.beat(self._token)


class Watchdog:
    """Daemon-thread deadline monitor over named activity leases.

    Args:
      default_deadline_s: deadline for activities with no per-name entry
        (None = such activities are unmonitored).
      deadlines: per-activity-name deadline table (seconds).
      poll_interval_s: max sleep between checks; the loop wakes earlier
        when a lease expires sooner, so sub-second deadlines are detected
        promptly (the test contract: report within 2x the deadline).
      sink: where stderr-style reports go (default ``sys.stderr`` read at
        dump time, so pytest's capture and redirects work).
      telemetry: the spine whose span stacks / recent events enrich
        reports and whose JSONL log records them (set automatically by
        ``Telemetry.attach_watchdog``).
      max_report_events: how many trailing telemetry events a report embeds.
    """

    def __init__(
        self,
        *,
        default_deadline_s: float | None = None,
        deadlines: Mapping[str, float] | None = None,
        poll_interval_s: float = 0.25,
        sink: Any = None,
        telemetry: Any = None,
        max_report_events: int = 20,
    ):
        self.default_deadline_s = default_deadline_s
        self.deadlines = dict(deadlines or {})
        self.poll_interval_s = poll_interval_s
        self.sink = sink
        self.telemetry = telemetry
        self.max_report_events = max_report_events
        #: recent stall reports (dicts), for tests and the doctor
        self.reports: deque[dict] = deque(maxlen=16)
        self._leases: dict[int, _Lease] = {}
        self._tokens = itertools.count(1)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._closed = False

    # -- lease lifecycle -----------------------------------------------------
    def resolve_deadline(self, name: str, deadline_s: float | None) -> float | None:
        if deadline_s is not None:
            return float(deadline_s)
        if name in self.deadlines:
            return float(self.deadlines[name])
        return self.default_deadline_s

    def begin(self, name: str, deadline_s: float | None = None) -> int | None:
        """Open a lease; returns a token, or None when unmonitored."""
        d = self.resolve_deadline(name, deadline_s)
        if d is None or d <= 0:
            return None
        lease = _Lease(next(self._tokens), name, d)
        with self._lock:
            if self._closed:  # stopped watchdogs stay stopped
                return None
            self._leases[lease.token] = lease
            self._ensure_thread()
        return lease.token

    def beat(self, token: int) -> None:
        """Heartbeat: the activity is alive; re-arm its deadline from now."""
        now = time.monotonic()
        with self._lock:
            lease = self._leases.get(token)
            if lease is not None:
                lease.expires_at = now + lease.deadline_s
                lease.dumped = False  # a recovered-then-stalled lease re-reports

    def end(self, token: int) -> None:
        with self._lock:
            lease = self._leases.pop(token, None)
        if lease is not None and lease.ever_dumped and self.telemetry is not None:
            self.telemetry.event(
                lease.name,
                kind="stall_recovered",
                total_s=round(time.monotonic() - lease.started, 3),
                deadline_s=lease.deadline_s,
            )

    def guard(self, name: str, deadline_s: float | None = None):
        """``with``-scoped lease (the instrumentation entry point)."""

        @contextlib.contextmanager
        def cm() -> Iterator[WatchdogGuard]:
            token = self.begin(name, deadline_s)
            try:
                yield WatchdogGuard(self, token)
            finally:
                if token is not None:
                    self.end(token)

        return cm()

    # -- monitor loop --------------------------------------------------------
    def _ensure_thread(self) -> None:
        # caller holds self._lock
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="tpuframe-watchdog", daemon=True
            )
            self._thread.start()

    def _sleep_s(self) -> float:
        """Sleep until the nearest live deadline (clamped), so short test
        deadlines are caught well inside their 2x budget."""
        now = time.monotonic()
        with self._lock:
            pending = [
                lease.expires_at - now
                for lease in self._leases.values()
                if not lease.dumped
            ]
        if not pending:
            return self.poll_interval_s
        return max(0.02, min(min(pending), self.poll_interval_s))

    def _loop(self) -> None:
        while not self._stop.wait(self._sleep_s()):
            now = time.monotonic()
            expired: list[_Lease] = []
            with self._lock:
                for lease in self._leases.values():
                    if not lease.dumped and now >= lease.expires_at:
                        lease.dumped = lease.ever_dumped = True
                        expired.append(lease)
            for lease in expired:
                try:
                    self._dump(lease, now)
                except Exception:
                    pass  # the monitor must survive its own report failing

    # -- reporting -----------------------------------------------------------
    def _dump(self, lease: _Lease, now: float) -> None:
        overdue = now - lease.started - lease.deadline_s
        spans: dict[str, list[str]] = {}
        recent: list[dict] = []
        if self.telemetry is not None:
            spans = self.telemetry.active_spans()
            recent = self.telemetry.recent_events(self.max_report_events)
        stacks = format_all_stacks()

        header = (
            f"tpuframe watchdog: STALL {lease.name!r} exceeded its "
            f"{lease.deadline_s:.2f}s deadline ({overdue:.2f}s overdue)"
        )
        lines = [f"==== {header} ====", "-- active telemetry spans --"]
        if spans:
            lines += [f"  {t}: {' > '.join(names)}" for t, names in spans.items()]
        else:
            lines.append("  (none)")
        lines.append("-- all-thread python stacks --")
        lines.append(stacks.rstrip())
        lines.append(f"-- last {len(recent)} telemetry events --")
        for ev in recent:
            lines.append(
                "  " + " ".join(
                    f"{k}={ev[k]}" for k in ("ts", "kind", "name", "dur_s")
                    if k in ev
                )
            )
        lines.append("==== end tpuframe watchdog report ====")
        text = "\n".join(lines) + "\n"

        sink = self.sink if self.sink is not None else sys.stderr
        try:
            sink.write(text)
            sink.flush()
        except Exception:
            pass

        report = {
            "name": lease.name,
            "deadline_s": lease.deadline_s,
            "overdue_s": round(overdue, 3),
            "spans": spans,
            "stacks": stacks[:_JSONL_STACK_CAP],
            "recent": [
                {k: ev[k] for k in ("kind", "name") if k in ev} for ev in recent
            ],
        }
        self.reports.append(report)
        if self.telemetry is not None:
            self.telemetry.event(lease.name, kind="stall", **{
                k: v for k, v in report.items() if k != "name"
            })

    def stop(self) -> None:
        """Terminal: the monitor thread exits and later begin() calls are
        refused (a swapped-out telemetry instance must not resurrect its
        old watchdog through a lingering guard site)."""
        with self._lock:
            self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
