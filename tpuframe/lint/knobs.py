"""KN rules — TPUFRAME_* knob accounting across lists, reads, and docs.

The spines ship their env knobs to every worker through
``launch.remote.all_env_vars()``, which aggregates the per-spine
``*_ENV_VARS`` lists; the doctor prints the same registry.  A knob read
in code but absent from every list silently never reaches the fleet — a
worker tuned locally behaves untuned remotely, the exact class of bug
this family exists to kill.  Rules:

- **KN001** — a literal ``TPUFRAME_*`` env read with no declaring list.
- **KN002** — a knob declared in more than one list (ambiguous owner).
- **KN003** — a declared knob that no code reads (dead registry row —
  usually a renamed knob whose list entry was forgotten).
- **KN004** — a shipped list (no ``# tpuframe-lint: not-shipped`` marker
  on its assignment line) that ``all_env_vars()`` does not aggregate.
- **KN005** — a declared knob documented in none of OBSERVABILITY.md /
  FAULT.md / SERVE.md / PERF.md.
- **KN007** — a declared knob with no (or an invalid) value domain in
  the sibling ``*_ENV_DOMAINS`` dict, or a domain entry for a knob the
  list no longer declares.  The domains are the autotuner's legal
  search space (``type``/``range``/``choices``) and its re-application
  contract (``apply``: "live" | "restart") — an undomained knob is a
  knob the autotuner must not touch, so the gap fails loud.

Read detection covers ``os.environ.get/[]``, ``os.getenv``,
``"X" in os.environ``, and one level of indirection: any function whose
body reads the environment through one of its parameters (``_env_int``,
``_env_truthy``, ...) turns its literal-name call sites into reads, with
the call's constant companion argument recorded as the default — which
is how ``--knobs`` reconstructs the inventory the future ``core/config``
typed registry will consume.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import ast
import dataclasses
from typing import Any

from tpuframe.lint.driver import DOC_FILES, Repo
from tpuframe.lint.report import Finding

RULES = {
    "KN001": "TPUFRAME_* env read not declared in any *_ENV_VARS list",
    "KN002": "knob declared in more than one *_ENV_VARS list",
    "KN003": "declared knob never read anywhere in code",
    "KN004": "shipped *_ENV_VARS list not aggregated by all_env_vars()",
    "KN005": "declared knob documented in no schema doc",
    "KN006": "all_env_vars() imports a knob list from a non-stdlib-only module",
    "KN007": "declared knob missing (or carrying an invalid/stale) value domain",
}

_PREFIX = "TPUFRAME_"


@dataclasses.dataclass
class KnobList:
    name: str
    module: str
    rel: str
    line: int
    entries: tuple[str, ...]
    shipped: bool


@dataclasses.dataclass
class KnobRead:
    name: str
    rel: str
    line: int
    default: Any = None
    has_default: bool = False


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def collect_lists(repo: Repo) -> list[KnobList]:
    out = []
    for src in repo.files.values():
        for node in src.nodes:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not (isinstance(target, ast.Name)
                    and target.id.endswith("_ENV_VARS")):
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                continue
            entries = tuple(
                v for v in (_const_str(e) for e in node.value.elts)
                if v is not None
            )
            shipped = not any(
                d == "not-shipped"
                for line, d in src.directive_lines.items()
                if node.lineno <= line <= (node.end_lineno or node.lineno)
            )
            out.append(KnobList(
                name=target.id, module=src.module, rel=src.rel,
                line=node.lineno, entries=entries, shipped=shipped,
            ))
    return out


@dataclasses.dataclass
class KnobDomains:
    name: str          # the *_ENV_DOMAINS symbol
    module: str
    rel: str
    line: int
    entries: dict[str, dict]


#: legal values for the domain entry fields KN007 validates
_DOMAIN_TYPES = ("int", "float", "bool", "enum", "str", "path")
_DOMAIN_APPLY = ("live", "restart")


def collect_domains(repo: Repo) -> list[KnobDomains]:
    """Every ``*_ENV_DOMAINS`` dict-literal assignment, evaluated.  A
    non-literal dict (computed keys, comprehension) collects as empty —
    which KN007 then reports as every knob missing its domain, the
    correct failure for a registry that must be statically readable."""
    out = []
    for src in repo.files.values():
        for node in src.nodes:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not (isinstance(target, ast.Name)
                    and target.id.endswith("_ENV_DOMAINS")):
                continue
            entries: dict[str, dict] = {}
            if isinstance(node.value, ast.Dict):
                try:
                    raw = ast.literal_eval(node.value)
                except ValueError:
                    raw = {}
                entries = {
                    k: v for k, v in raw.items()
                    if isinstance(k, str) and isinstance(v, dict)
                }
            out.append(KnobDomains(
                name=target.id, module=src.module, rel=src.rel,
                line=node.lineno, entries=entries,
            ))
    return out


def _domain_error(entry: dict) -> str | None:
    """Why ``entry`` is not a usable domain, or None when it is."""
    t = entry.get("type")
    if t not in _DOMAIN_TYPES:
        return f"'type' must be one of {_DOMAIN_TYPES}, got {t!r}"
    if entry.get("apply") not in _DOMAIN_APPLY:
        return f"'apply' must be one of {_DOMAIN_APPLY}"
    if t == "enum":
        choices = entry.get("choices")
        if not (isinstance(choices, (tuple, list)) and choices
                and all(isinstance(c, str) for c in choices)):
            return "enum domain needs a non-empty 'choices' tuple of strings"
    if t in ("int", "float"):
        rng = entry.get("range")
        if not (isinstance(rng, (tuple, list)) and len(rng) == 2):
            return "numeric domain needs a 'range' pair (lo, hi); " \
                   "either bound may be None"
        lo, hi = rng
        ok = all(b is None or isinstance(b, (int, float)) for b in (lo, hi))
        if not ok or (lo is not None and hi is not None and lo > hi):
            return f"'range' bounds must be numbers-or-None with lo <= hi, " \
                   f"got {rng!r}"
    return None


def _domains_for(kl: KnobList,
                 domains: list[KnobDomains]) -> KnobDomains | None:
    """The sibling domains dict for a knob list: same module, same
    prefix (``X_ENV_VARS`` <-> ``X_ENV_DOMAINS``)."""
    want = kl.name[: -len("_ENV_VARS")] + "_ENV_DOMAINS"
    for kd in domains:
        if kd.module == kl.module and kd.name == want:
            return kd
    return None


def _env_param_readers(repo: Repo) -> dict[str, int]:
    """Function name -> positional index of its env-name parameter, for
    functions that read the environment through a parameter — iterated to
    a fixpoint so wrappers of wrappers count (``_env_int`` delegating to
    ``_env_float`` which does the ``os.environ.get``)."""
    # one AST pass per def extracts the two candidate shapes (direct env
    # reads of a param; delegations to another function); the fixpoint
    # then iterates over that compact summary, not the trees
    summaries = []  # (name, params, direct_param_names, [(callee, args)])
    for src in repo.files.values():
        for node in src.nodes:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in node.args.args]
            direct: set[str] = set()
            calls: list[tuple[str, list]] = []
            for inner in ast.walk(node):
                name_arg = _direct_env_name_expr(inner)
                if isinstance(name_arg, ast.Name) and name_arg.id in params:
                    direct.add(name_arg.id)
                elif isinstance(inner, ast.Call):
                    func = inner.func
                    callee = func.attr if isinstance(func, ast.Attribute) \
                        else (func.id if isinstance(func, ast.Name) else None)
                    if callee is not None and any(
                        isinstance(a, ast.Name) and a.id in params
                        for a in inner.args
                    ):
                        calls.append((callee, inner.args))
            summaries.append((node.name, params, direct, calls))

    readers: dict[str, int] = {}
    changed = True
    while changed:
        changed = False
        for name, params, direct, calls in summaries:
            if name in readers:
                continue
            hit = next(iter(direct), None)
            if hit is None:
                for callee, args in calls:
                    idx = readers.get(callee)
                    if (idx is not None and idx < len(args)
                            and isinstance(args[idx], ast.Name)
                            and args[idx].id in params):
                        hit = args[idx].id
                        break
            if hit is not None:
                readers[name] = params.index(hit)
                changed = True
    return readers


def _name_constants(src) -> dict[str, str]:
    """name -> TPUFRAME_* string for ``FOO_ENV = "TPUFRAME_X"``-style
    bindings anywhere in the module, so reads through the symbol count."""
    out: dict[str, str] = {}
    for node in src.nodes:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            v = _const_str(node.value)
            if v is not None and v.startswith(_PREFIX):
                out[node.targets[0].id] = v
    return out


def _direct_env_name_expr(node: ast.AST) -> ast.AST | None:
    """The name-expression of a direct environment read at ``node``
    (``environ.get(X)``, ``getenv(X)``, ``environ[X]``, ``X in environ``),
    or None."""
    if isinstance(node, ast.Call):
        func = node.func
        attr = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if attr == "getenv" and node.args:
            return node.args[0]
        if attr in ("get", "pop", "setdefault") and node.args:
            recv = func.value if isinstance(func, ast.Attribute) else None
            if isinstance(recv, ast.Attribute) and recv.attr == "environ":
                return node.args[0]
            if isinstance(recv, ast.Name) and recv.id in ("environ", "env"):
                return node.args[0]
    elif isinstance(node, ast.Subscript):
        v = node.value
        if (isinstance(v, ast.Attribute) and v.attr == "environ") or (
            isinstance(v, ast.Name) and v.id == "environ"
        ):
            return node.slice
    elif isinstance(node, ast.Compare) and len(node.ops) == 1:
        if isinstance(node.ops[0], (ast.In, ast.NotIn)):
            c = node.comparators[0]
            if (isinstance(c, ast.Attribute) and c.attr == "environ") or (
                isinstance(c, ast.Name) and c.id == "environ"
            ):
                return node.left
    return None


def collect_reads(repo: Repo) -> list[KnobRead]:
    """Every literal TPUFRAME_* environment read (direct or through a
    reader helper), plus literal ``.get``/``[]``/``in`` accesses on
    constructed env mappings (worker-env plumbing reads count too)."""
    readers = _env_param_readers(repo)
    reads: list[KnobRead] = []
    consts: dict[str, str] = {}

    def add(src, node, name_node, default=None, has_default=False):
        name = _const_str(name_node)
        if name is None and isinstance(name_node, ast.Name):
            name = consts.get(name_node.id)
        if name is None or not name.startswith(_PREFIX):
            return
        reads.append(KnobRead(
            name=name, rel=src.rel, line=node.lineno,
            default=default, has_default=has_default,
        ))

    for src in repo.files.values():
        consts = _name_constants(src)
        for node in src.nodes:
            direct = _direct_env_name_expr(node)
            if direct is not None:
                default, has_default = None, False
                if isinstance(node, ast.Call) and len(node.args) > 1:
                    d = node.args[1]
                    if isinstance(d, ast.Constant):
                        default, has_default = d.value, True
                add(src, node, direct, default, has_default)
                continue
            # generic mapping access with a TPUFRAME_ literal key
            if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                add(src, node, node.slice)
            elif isinstance(node, ast.Call):
                func = node.func
                attr = func.attr if isinstance(func, ast.Attribute) else (
                    func.id if isinstance(func, ast.Name) else None
                )
                if attr == "get" and node.args:
                    add(src, node, node.args[0])
                elif attr in readers and node.args:
                    idx = readers[attr]
                    if idx < len(node.args):
                        default, has_default = None, False
                        for other in node.args[idx + 1:]:
                            if isinstance(other, ast.Constant):
                                default, has_default = other.value, True
                                break
                        add(src, node, node.args[idx], default, has_default)
    return reads


def _aggregated_list_names(repo: Repo) -> set[str]:
    """List names reachable from ``all_env_vars()``: every ``*_ENV_VARS``
    name loaded or imported inside that function's body."""
    out: set[str] = set()
    for src in repo.files.values():
        for node in src.nodes:
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == "all_env_vars"):
                for inner in ast.walk(node):
                    if isinstance(inner, ast.Name) and inner.id.endswith(
                        "_ENV_VARS"
                    ):
                        out.add(inner.id)
                    elif isinstance(inner, ast.ImportFrom):
                        out.update(
                            a.name for a in inner.names
                            if a.name.endswith("_ENV_VARS")
                        )
    return out


def knob_inventory(repo: Repo) -> list[dict]:
    """The reconciled inventory ``--knobs`` emits: one row per knob with
    its declaring list(s), parseable default(s), read sites, and doc
    locations — the machine-readable input for the future ``core/config``
    typed knob registry (ROADMAP item 5)."""
    lists = collect_lists(repo)
    reads = collect_reads(repo)
    domains = collect_domains(repo)
    by_name: dict[str, dict] = {}

    def row(name: str) -> dict:
        return by_name.setdefault(name, {
            "name": name, "lists": [], "defaults": [], "reads": [],
            "docs": [], "shipped": False, "domain": None,
        })

    for kl in lists:
        kd = _domains_for(kl, domains)
        for name in kl.entries:
            r = row(name)
            r["lists"].append(f"{kl.module}.{kl.name}")
            r["shipped"] = r["shipped"] or kl.shipped
            if kd is not None and r["domain"] is None:
                d = kd.entries.get(name)
                if d is not None and _domain_error(d) is None:
                    r["domain"] = d
    for rd in reads:
        r = row(rd.name)
        r["reads"].append(f"{rd.rel}:{rd.line}")
        if rd.has_default and rd.default is not None \
                and rd.default not in r["defaults"]:
            r["defaults"].append(rd.default)
    for name, r in by_name.items():
        r["docs"] = [d for d in DOC_FILES if name in repo.docs.get(d, "")]
    return [by_name[k] for k in sorted(by_name)]


def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    lists = collect_lists(repo)
    reads = collect_reads(repo)
    aggregated = _aggregated_list_names(repo)

    declared: dict[str, list[KnobList]] = {}
    for kl in lists:
        for name in kl.entries:
            declared.setdefault(name, []).append(kl)
    read_names = {r.name for r in reads}

    seen_undeclared: set[str] = set()
    for rd in reads:
        if rd.name in declared or rd.name in seen_undeclared:
            continue
        seen_undeclared.add(rd.name)
        findings.append(Finding(
            rule="KN001", file=rd.rel, line=rd.line,
            message=(
                f"env knob {rd.name!r} is read here but declared in no "
                "*_ENV_VARS list — workers launched remotely will never "
                "receive it"
            ),
            hint=(
                "add it to the owning spine's *_ENV_VARS list (or to "
                "LAUNCH_CONTRACT_ENV_VARS in launch/remote.py if the "
                "launcher computes it per rank)"
            ),
        ))

    for name, owners in declared.items():
        if len(owners) > 1:
            findings.append(Finding(
                rule="KN002", file=owners[1].rel, line=owners[1].line,
                message=(
                    f"knob {name!r} is declared in "
                    f"{len(owners)} lists: "
                    + ", ".join(f"{o.module}.{o.name}" for o in owners)
                ),
                hint="keep exactly one declaring list per knob",
            ))
        if name not in read_names:
            findings.append(Finding(
                rule="KN003", file=owners[0].rel, line=owners[0].line,
                message=(
                    f"knob {name!r} is declared in {owners[0].name} but "
                    "never read anywhere in the tree"
                ),
                hint=(
                    "delete the stale entry, or wire the knob up — a "
                    "declared-but-unread knob is a silent no-op for users "
                    "who set it"
                ),
            ))

    # KN006: the aggregate must resolve on a wedged/jax-less process —
    # every module all_env_vars() imports a list from (and every package
    # __init__ executed on the way) must carry the stdlib-only contract.
    # JF can't see this (function-level imports are its sanctioned lazy
    # escape hatch); the knob registry is the one place laziness is not
    # enough, because the doctor calls this function on broken installs.
    from tpuframe.lint.imports import resolve_import

    for src in repo.files.values():
        for node in src.nodes:
            if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == "all_env_vars"):
                continue
            for inner in ast.walk(node):
                if not isinstance(inner, (ast.Import, ast.ImportFrom)):
                    continue
                internal, _ = resolve_import(repo, src, inner)
                for dep in dict.fromkeys(internal):
                    if repo.files[dep].stdlib_only:
                        continue
                    findings.append(Finding(
                        rule="KN006", file=src.rel, line=inner.lineno,
                        message=(
                            f"all_env_vars() imports through {dep!r}, "
                            "which is not marked stdlib-only — the doctor "
                            "reads the knob registry on wedged/jax-less "
                            "processes, so this import chain must never "
                            "drag in a heavy dependency"
                        ),
                        hint=(
                            f"make {dep} stdlib-only (lazy package "
                            "__init__, marker comment) or declare the "
                            "list in a module that already is"
                        ),
                    ))

    for kl in lists:
        if kl.shipped and kl.name not in aggregated:
            findings.append(Finding(
                rule="KN004", file=kl.rel, line=kl.line,
                message=(
                    f"{kl.name} is not aggregated by "
                    "launch.remote.all_env_vars() — its knobs never ship "
                    "to remote workers"
                ),
                hint=(
                    "import and add it inside all_env_vars(), or mark the "
                    "assignment '# tpuframe-lint: not-shipped' if the "
                    "launcher computes these per rank"
                ),
            ))

    if repo.docs:
        for name, owners in sorted(declared.items()):
            if any(name in text for text in repo.docs.values()):
                continue
            findings.append(Finding(
                rule="KN005", file=owners[0].rel, line=owners[0].line,
                message=(
                    f"knob {name!r} is documented in none of "
                    + "/".join(DOC_FILES)
                ),
                hint="add a row to the owning spine's knob table",
            ))

    # KN007: every declared knob needs a valid entry in the sibling
    # *_ENV_DOMAINS dict, and every domain entry needs a declaring knob
    # — the autotuner trusts this registry as its legal search space.
    domains = collect_domains(repo)
    for kl in lists:
        kd = _domains_for(kl, domains)
        if kd is None:
            findings.append(Finding(
                rule="KN007", file=kl.rel, line=kl.line,
                message=(
                    f"{kl.name} has no sibling "
                    f"{kl.name[:-len('_ENV_VARS')]}_ENV_DOMAINS dict — "
                    f"{len(kl.entries)} knob(s) have no value domain"
                ),
                hint=(
                    "declare a literal *_ENV_DOMAINS dict beside the list: "
                    "{'KNOB': {'type': ..., 'range'/'choices': ..., "
                    "'apply': 'live'|'restart'}}"
                ),
            ))
            continue
        for name in kl.entries:
            entry = kd.entries.get(name)
            if entry is None:
                findings.append(Finding(
                    rule="KN007", file=kd.rel, line=kd.line,
                    message=(
                        f"knob {name!r} is declared in {kl.name} but has "
                        f"no entry in {kd.name} — the autotuner has no "
                        "legal search space for it"
                    ),
                    hint=(
                        "add {'type': ..., 'range'/'choices': ..., "
                        "'apply': 'live'|'restart'} for it"
                    ),
                ))
                continue
            err = _domain_error(entry)
            if err is not None:
                findings.append(Finding(
                    rule="KN007", file=kd.rel, line=kd.line,
                    message=f"domain entry for {name!r} is invalid: {err}",
                    hint="fix the entry so the inventory can expose it",
                ))
        for name in kd.entries:
            if name not in kl.entries:
                findings.append(Finding(
                    rule="KN007", file=kd.rel, line=kd.line,
                    message=(
                        f"{kd.name} carries an entry for {name!r}, which "
                        f"{kl.name} does not declare — a stale domain row"
                    ),
                    hint="drop the entry or re-declare the knob",
                ))
    return findings
