"""CS rules — the chaos-site registry vs the instrumented call sites.

Chaos coverage is a closed loop: a site string fired through
``fault.chaos.maybe_fire``/``site`` must be declared in
``fault.chaos.CHAOS_SITES`` (so seeded plans and drills can target it by
name) and documented in FAULT.md (so an operator reading a
``fault/chaos_injected`` event knows what was hit).  A fired-but-
undeclared site is untargetable chaos; a declared-but-unfired site is a
drill aimed at nothing — both are silent coverage loss.  Rules:

- **CS001** — a site fired in code but missing from ``CHAOS_SITES``.
- **CS002** — a ``CHAOS_SITES`` row whose site is fired nowhere
  (injector *defaults* inside ``fault/chaos.py`` don't count as firings
  — only instrumented call sites in library code do).
- **CS003** — a ``CHAOS_SITES`` row not mentioned in FAULT.md.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import ast

from tpuframe.lint.driver import Repo
from tpuframe.lint.report import Finding

RULES = {
    "CS001": "chaos site fired in code but not declared in CHAOS_SITES",
    "CS002": "CHAOS_SITES entry never fired by any instrumented call site",
    "CS003": "CHAOS_SITES entry not documented in FAULT.md",
}

_FIRERS = ("maybe_fire", "site")


def _chaos_module(repo: Repo) -> str | None:
    for name in repo.files:
        if name.endswith(".fault.chaos"):
            return name
    return None


def declared_sites(repo: Repo) -> dict[str, int]:
    """site -> declaration line, from the CHAOS_SITES dict literal."""
    mod = _chaos_module(repo)
    if mod is None:
        return {}
    out: dict[str, int] = {}
    for node in ast.walk(repo.files[mod].tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Name) and t.id == "CHAOS_SITES"):
            continue
        if isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out[k.value] = k.lineno
    return out


def fired_sites(repo: Repo) -> dict[str, list[tuple[str, int]]]:
    """site -> [(file, line)] for literal maybe_fire()/site() call sites
    outside fault/chaos.py itself."""
    chaos_mod = _chaos_module(repo)
    out: dict[str, list[tuple[str, int]]] = {}
    for src in repo.files.values():
        if src.module == chaos_mod:
            continue
        # bare-name firer calls only count when this module actually
        # imported the name from fault.chaos — an unrelated local
        # `site(url)` helper must not register spurious chaos sites
        imported_firers = {
            a.asname or a.name
            for node in src.nodes
            if isinstance(node, ast.ImportFrom)
            and (node.module or "").endswith("fault.chaos")
            for a in node.names
            if a.name in _FIRERS
        }
        for node in src.nodes:
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                # require the chaos-module receiver (chaos.site(...))
                recv = func.value
                if func.attr not in _FIRERS or not (
                    isinstance(recv, ast.Name) and recv.id == "chaos"
                ):
                    continue
            elif isinstance(func, ast.Name):
                if func.id not in imported_firers:
                    continue
            else:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.setdefault(arg.value, []).append((src.rel, node.lineno))
    return out


def check(repo: Repo) -> list[Finding]:
    chaos_mod = _chaos_module(repo)
    if chaos_mod is None:
        return []
    chaos_src = repo.files[chaos_mod]
    declared = declared_sites(repo)
    fired = fired_sites(repo)
    findings: list[Finding] = []

    for site, where in sorted(fired.items()):
        if site in declared:
            continue
        rel, line = where[0]
        findings.append(Finding(
            rule="CS001", file=rel, line=line,
            message=(
                f"chaos site {site!r} is fired here but not declared in "
                "fault.chaos.CHAOS_SITES"
            ),
            hint=(
                "add a CHAOS_SITES row (site -> where it instruments) and "
                "a FAULT.md mention so drills can target it by name"
            ),
        ))

    for site, line in sorted(declared.items()):
        if site not in fired:
            findings.append(Finding(
                rule="CS002", file=chaos_src.rel, line=line,
                message=(
                    f"CHAOS_SITES declares {site!r} but no instrumented "
                    "call site fires it"
                ),
                hint=(
                    "instrument the code path with chaos.maybe_fire("
                    f"{site!r}, ...) or delete the dead registry row"
                ),
            ))
        if "FAULT.md" in repo.docs and site not in repo.docs["FAULT.md"]:
            findings.append(Finding(
                rule="CS003", file=chaos_src.rel, line=line,
                message=f"chaos site {site!r} is not documented in FAULT.md",
                hint="add it to FAULT.md's injector/site reference",
            ))
    return findings
