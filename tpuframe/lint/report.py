"""Findings, suppressions, and output rendering for the invariant linter.

A :class:`Finding` is one violated contract: rule id, ``file:line``
anchor, a one-line message, and a one-line fix hint.  Two suppression
channels exist, both designed to be *visible in review*:

- inline — ``# tpuframe-lint: disable=KN001`` (comma-separated ids, or
  ``disable=all``) as a real comment on the finding's line; parsed with
  ``tokenize``, so the same text inside a docstring does not count;
- a suppressions file (``--suppressions``) with one
  ``RULE:file-glob[:message-substring]`` entry per line — the repo's
  own file must stay empty or justified line-by-line (see LINT.md).
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import dataclasses
import fnmatch
import json
from typing import Any, Iterable


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violated invariant at one source location."""

    rule: str      # e.g. "KN001"
    file: str      # repo-relative path ("tpuframe/track/telemetry.py")
    line: int      # 1-based
    message: str   # what drifted
    hint: str      # how to fix it

    def format(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} {self.message}\n" \
               f"    fix: {self.hint}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Suppressions:
    """Parsed ``--suppressions`` file: ``RULE:file-glob[:substr]`` lines.

    ``#`` comments and blank lines are ignored.  ``RULE`` may be ``*``;
    the optional third field matches as a substring of the message —
    narrow enough that one entry cannot quietly swallow a whole rule's
    future findings unless it explicitly asks to (``RULE:*``).
    """

    def __init__(self, entries: Iterable[tuple[str, str, str]] = ()):
        self.entries = list(entries)

    @classmethod
    def parse(cls, text: str) -> "Suppressions":
        entries = []
        for raw in text.splitlines():
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split(":", 2)
            if len(parts) < 2:
                raise ValueError(
                    f"bad suppression line {raw!r}: want RULE:file-glob[:substr]"
                )
            rule, pattern = parts[0].strip(), parts[1].strip()
            substr = parts[2].strip() if len(parts) > 2 else ""
            entries.append((rule, pattern, substr))
        return cls(entries)

    @classmethod
    def load(cls, path: str) -> "Suppressions":
        with open(path) as f:
            return cls.parse(f.read())

    def matches(self, finding: Finding) -> bool:
        for rule, pattern, substr in self.entries:
            if rule not in ("*", finding.rule):
                continue
            if not fnmatch.fnmatch(finding.file, pattern):
                continue
            if substr and substr not in finding.message:
                continue
            return True
        return False


def split_suppressed(
    findings: Iterable[Finding], suppressions: Suppressions | None
) -> tuple[list[Finding], list[Finding]]:
    """(kept, suppressed) under the suppressions file (inline disables
    are already applied by the driver, per-line, before this)."""
    kept: list[Finding] = []
    dropped: list[Finding] = []
    for f in findings:
        (dropped if suppressions is not None and suppressions.matches(f)
         else kept).append(f)
    return kept, dropped


def render_text(result: Any) -> str:
    """Human-readable report (``result`` is a ``driver.LintResult``)."""
    out = []
    for f in result.findings:
        out.append(f.format())
    out.append(
        f"tpuframe.lint: {len(result.findings)} finding(s) "
        f"({result.suppressed_count} suppressed) over "
        f"{result.files_scanned} file(s), {result.rules_run} rule(s)"
    )
    return "\n".join(out)


def render_json(result: Any) -> str:
    return json.dumps(
        {
            "findings": [f.to_json() for f in result.findings],
            "counts": result.rule_counts(),
            "suppressed": result.suppressed_count,
            "files_scanned": result.files_scanned,
            "rules_run": result.rules_run,
            "clean": not result.findings,
        },
        indent=2,
    )
