"""TS rules — telemetry names vs the schema docs, both directions.

OBSERVABILITY.md (with the serve/fault families detailed in SERVE.md and
FAULT.md) is the schema of record for every span/event/counter/gauge/
histogram name: dashboards, the fleet analyzer, and the runbooks all key
on those names.  An undocumented name is unmonitorable by anyone who
didn't read the diff; a documented-but-gone name is a dashboard
silently flatlining.  Rules:

- **TS001** — a slash-namespaced name literal passed to
  ``span``/``event``/``counter``/``gauge``/``histogram``/``guard``
  appears in none of the schema docs.
- **TS002** — a slash-namespaced name backticked in a schema doc is
  emitted nowhere in code (dynamic families — ``span/*`` auto
  histograms, ``system/device<i>_*`` — and chaos site names are
  excluded; sites are CS territory).

Names built with f-strings are dynamic and skipped — document the
family in prose instead (the ``span/<span name>`` convention).
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import ast
import re

from tpuframe.lint.driver import Repo
from tpuframe.lint.report import Finding
from tpuframe.lint.sites import declared_sites

RULES = {
    "TS001": "telemetry name used in code but absent from the schema docs",
    "TS002": "telemetry name documented but emitted nowhere in code",
}

#: docs that carry schema rows for telemetry names
SCHEMA_DOCS = ("OBSERVABILITY.md", "FAULT.md", "SERVE.md")

_EMITTERS = ("span", "event", "counter", "gauge", "histogram", "guard")

#: backticked `layer/thing` tokens in the docs (letters/digits/underscore
#: segments only — placeholders like `span/<span name>` self-exclude)
_DOC_NAME_RE = re.compile(r"`([a-z][a-z0-9_]*(?:/[a-z0-9_]+)+)`")


def code_names(repo: Repo) -> dict[str, list[tuple[str, int]]]:
    """name -> [(file, line)] for every literal slash-namespaced name
    passed to a telemetry emitter method."""
    out: dict[str, list[tuple[str, int]]] = {}
    for src in repo.files.values():
        for node in src.nodes:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMITTERS
                    and node.args):
                continue
            arg = node.args[0]
            if (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and "/" in arg.value):
                out.setdefault(arg.value, []).append((src.rel, node.lineno))
    return out


def doc_names(repo: Repo) -> dict[str, str]:
    """name -> first doc file that backticks it."""
    out: dict[str, str] = {}
    for doc in SCHEMA_DOCS:
        for m in _DOC_NAME_RE.finditer(repo.docs.get(doc, "")):
            out.setdefault(m.group(1), doc)
    return out


def check(repo: Repo) -> list[Finding]:
    if not any(repo.docs.get(d) for d in SCHEMA_DOCS):
        return []  # installed-package mode: nothing to diff against
    findings: list[Finding] = []
    used = code_names(repo)
    documented = doc_names(repo)
    sites = set(declared_sites(repo))

    for name, where in sorted(used.items()):
        if name in documented:
            continue
        rel, line = where[0]
        findings.append(Finding(
            rule="TS001", file=rel, line=line,
            message=(
                f"telemetry name {name!r} is emitted here but documented "
                f"in none of {'/'.join(SCHEMA_DOCS)}"
            ),
            hint=(
                "add a schema row for it in OBSERVABILITY.md (serve/fault "
                "families may live in SERVE.md/FAULT.md)"
            ),
        ))

    code_prefixes = {n.split("/", 1)[0] for n in used}
    # names reaching an emitter through a variable (supervisor's
    # failure-class counter, the health gauge table) still appear as
    # quoted literals somewhere in the tree — that counts as emitted
    def literal_in_code(name: str) -> bool:
        dq, sq = f'"{name}"', f"'{name}'"
        return any(dq in s.text or sq in s.text for s in repo.files.values())

    for name, doc in sorted(documented.items()):
        prefix = name.split("/", 1)[0]
        if name in used or name in sites:
            continue
        if prefix not in code_prefixes or name.startswith("span/"):
            continue  # dynamic family or a namespace code never emits
        if literal_in_code(name):
            continue
        findings.append(Finding(
            rule="TS002", file=doc, line=repo.doc_line(doc, f"`{name}`"),
            message=(
                f"documented telemetry name {name!r} is emitted nowhere "
                "in code"
            ),
            hint=(
                "drop (or un-backtick) the stale schema row, or restore "
                "the emitter — a dashboard keyed on this name is flat"
            ),
        ))
    return findings
