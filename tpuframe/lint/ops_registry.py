"""OP rules — the kernel dispatch registry vs the ``ops/`` modules.

The kernel ledger dispatches by name: ``ops.ledger.OPS_REGISTRY`` is
the closed list of dispatchable kernels, each with its entry-point
symbol and the parity test that pins kernel == jnp oracle.  A kernel
module absent from the registry is invisible to ``TPUFRAME_KERNELS``
and the pricing bench (it ships un-A/B-able); a registry row whose
parity test doesn't exist is an untested dispatch claim.  Rules:

- **OP001** — an ``ops/`` kernel module missing from ``OPS_REGISTRY``
  (the dispatch plumbing itself — ``dispatch``, ``ledger``, the package
  ``__init__`` — is exempt).
- **OP002** — a registry row whose ``parity_test``
  (``tests/file.py::[Class::]test_name``) points at a missing file or
  a test function that isn't defined there.
- **OP003** — a registry row whose ``module``/``symbol``/``reference``
  doesn't resolve to a definition in the scanned tree.
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import ast
import os

from tpuframe.lint.driver import Repo
from tpuframe.lint.report import Finding

RULES = {
    "OP001": "ops/ kernel module not declared in OPS_REGISTRY",
    "OP002": "OPS_REGISTRY parity test missing or undefined",
    "OP003": "OPS_REGISTRY module/symbol does not resolve",
}

#: dispatch plumbing, not kernels — exempt from OP001
_PLUMBING = ("dispatch", "ledger")


def _ledger_module(repo: Repo) -> str | None:
    for name in repo.files:
        if name.endswith(".ops.ledger"):
            return name
    return None


def _const(node) -> object:
    return node.value if isinstance(node, ast.Constant) else None


def declared_ops(repo: Repo) -> dict[str, dict]:
    """op -> {field: value, "line": decl line}, from the OPS_REGISTRY
    dict literal (string/None fields only — tuples are skipped)."""
    mod = _ledger_module(repo)
    if mod is None:
        return {}
    out: dict[str, dict] = {}
    for node in ast.walk(repo.files[mod].tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Name) and t.id == "OPS_REGISTRY"):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            op = _const(k)
            if not isinstance(op, str) or not isinstance(v, ast.Dict):
                continue
            entry: dict = {"line": k.lineno}
            for fk, fv in zip(v.keys, v.values):
                field = _const(fk)
                if isinstance(field, str):
                    entry[field] = _const(fv)
            out[op] = entry
    return out


def _defined_symbols(repo: Repo, module: str) -> set[str]:
    src = repo.files.get(module)
    if src is None:
        return set()
    out: set[str] = set()
    for node in src.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _parity_test_finding(repo: Repo, op: str, entry: dict,
                         ledger_rel: str) -> Finding | None:
    ref = entry.get("parity_test")
    line = entry["line"]
    if not isinstance(ref, str) or "::" not in ref:
        return Finding(
            rule="OP002", file=ledger_rel, line=line,
            message=(
                f"OPS_REGISTRY[{op!r}] parity_test must be "
                "'tests/file.py::[Class::]test_name', got "
                f"{ref!r}"
            ),
            hint="point it at the kernel-vs-oracle parity test",
        )
    path, _, rest = ref.partition("::")
    test_name = rest.split("::")[-1]
    abspath = os.path.join(repo.docs_root, path)
    if not os.path.exists(abspath):
        return Finding(
            rule="OP002", file=ledger_rel, line=line,
            message=(
                f"OPS_REGISTRY[{op!r}] parity test file {path!r} does "
                "not exist"
            ),
            hint="write the parity test (kernel output == jnp oracle)",
        )
    try:
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        text = ""
    if f"def {test_name}" not in text:
        return Finding(
            rule="OP002", file=ledger_rel, line=line,
            message=(
                f"OPS_REGISTRY[{op!r}] names parity test "
                f"{test_name!r} but {path} defines no such test"
            ),
            hint=f"define `def {test_name}` in {path} (or fix the row)",
        )
    return None


def check(repo: Repo) -> list[Finding]:
    ledger_mod = _ledger_module(repo)
    if ledger_mod is None:
        return []
    ledger_src = repo.files[ledger_mod]
    declared = declared_ops(repo)
    registered_modules = {
        e.get("module") for e in declared.values()
    }
    findings: list[Finding] = []

    # OP001: every ops/ kernel module is in the registry
    ops_pkg = ledger_mod.rsplit(".", 1)[0]  # "<package>.ops"
    for module, src in sorted(repo.files.items()):
        if not module.startswith(ops_pkg + "."):
            continue
        leaf = module.rsplit(".", 1)[-1]
        if leaf in _PLUMBING or leaf.startswith("_"):
            continue
        if module not in registered_modules:
            findings.append(Finding(
                rule="OP001", file=src.rel, line=1,
                message=(
                    f"ops kernel module {module!r} is not declared in "
                    "ops.ledger.OPS_REGISTRY"
                ),
                hint=(
                    "add a registry row (module, symbol, reference, "
                    "parity_test) so the op is dispatchable and priced"
                ),
            ))

    for op, entry in sorted(declared.items()):
        line = entry["line"]
        module = entry.get("module")
        if not isinstance(module, str) or module not in repo.files:
            findings.append(Finding(
                rule="OP003", file=ledger_src.rel, line=line,
                message=(
                    f"OPS_REGISTRY[{op!r}] module {module!r} is not in "
                    "the scanned tree"
                ),
                hint="fix the module path (stale registry row?)",
            ))
        else:
            symbols = _defined_symbols(repo, module)
            for field in ("symbol", "reference"):
                sym = entry.get(field)
                if sym is None:
                    continue  # reference=None: kernel is its own oracle
                if sym not in symbols:
                    findings.append(Finding(
                        rule="OP003", file=ledger_src.rel, line=line,
                        message=(
                            f"OPS_REGISTRY[{op!r}] {field} {sym!r} is "
                            f"not defined in {module}"
                        ),
                        hint="fix the registry row or define the symbol",
                    ))
        f = _parity_test_finding(repo, op, entry, ledger_src.rel)
        if f is not None:
            findings.append(f)
    return findings
