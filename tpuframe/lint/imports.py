"""JF rules — the jax-free contract, verified over the real import graph.

A module marked ``# tpuframe-lint: stdlib-only`` promises it is
importable with nothing but the standard library installed — the
telemetry/fault/doctor stack's "works while jax is wedged (or absent)"
story rests on it.  Prose can't keep that promise; imports can break it
three ways, and each is a rule:

- **JF001** — the marked module itself imports a non-stdlib package at
  module level (``import jax``, ``import numpy``, ...).  Lazy
  function-level imports are the sanctioned escape hatch and are not
  findings.
- **JF002** — the marked module imports, at module level, a tpuframe
  module that is *not* marked: the contract must hold transitively, and
  an unmarked dependency is unchecked territory.  Package ``__init__``
  execution counts — importing ``tpuframe.a.b`` runs ``tpuframe/
  __init__.py`` and ``tpuframe/a/__init__.py``, so those must be marked
  (i.e. lazy / stdlib-clean) too.  This is exactly the drift that broke
  nothing until a doctor ran against a wedged backend.

``from __future__``, ``typing``-only blocks guarded by
``if TYPE_CHECKING:``, and imports inside functions are exempt (they
don't execute at import time).
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import ast
import sys
from typing import Iterator

from tpuframe.lint.driver import Repo, SourceFile
from tpuframe.lint.report import Finding

RULES = {
    "JF001": "stdlib-only module imports a non-stdlib package at module level",
    "JF002": "stdlib-only module imports an unmarked tpuframe module at module level",
}

_STDLIB = frozenset(sys.stdlib_module_names) | {"__future__"}


def _is_type_checking_if(node: ast.If) -> bool:
    t = node.test
    return (isinstance(t, ast.Name) and t.id == "TYPE_CHECKING") or (
        isinstance(t, ast.Attribute) and t.attr == "TYPE_CHECKING"
    )


def module_level_imports(
    tree: ast.Module,
) -> Iterator[ast.Import | ast.ImportFrom]:
    """Imports that execute when the module does: top-level statements,
    descending through module-level ``if``/``try`` bodies (an import
    under ``try: ... except ImportError`` still runs), skipping
    ``if TYPE_CHECKING:`` blocks and function/class bodies."""
    stack: list[ast.stmt] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, ast.If):
            if not _is_type_checking_if(node):
                stack.extend(node.body)
            stack.extend(node.orelse)
        elif isinstance(node, ast.Try):
            stack.extend(node.body)
            stack.extend(node.orelse)
            stack.extend(node.finalbody)
            for h in node.handlers:
                stack.extend(h.body)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            # `with contextlib.suppress(ImportError): import numpy` still
            # executes the import at module level
            stack.extend(node.body)


def _internal_closure(repo: Repo, dotted: str) -> list[str]:
    """The repo modules executed by importing ``dotted``: every package
    ``__init__`` on the path, plus the module itself when it exists."""
    parts = dotted.split(".")
    out = []
    for i in range(1, len(parts) + 1):
        name = ".".join(parts[:i])
        if name in repo.files:
            out.append(name)
    return out


def resolve_import(
    repo: Repo, src: SourceFile, node: ast.Import | ast.ImportFrom
) -> tuple[list[str], list[str]]:
    """(internal module names executed, external top-level names imported)."""
    internal: list[str] = []
    external: list[str] = []

    def add(dotted: str) -> None:
        if dotted.split(".")[0] == repo.package:
            internal.extend(_internal_closure(repo, dotted))
        else:
            external.append(dotted.split(".")[0])

    if isinstance(node, ast.Import):
        for alias in node.names:
            add(alias.name)
        return internal, external

    base = node.module or ""
    if node.level:  # relative: resolve against this module's package
        pkg_parts = src.module.split(".")
        if not src.path.endswith("__init__.py"):
            pkg_parts = pkg_parts[:-1]
        if node.level > 1:
            pkg_parts = pkg_parts[: -(node.level - 1)] or pkg_parts[:1]
        base = ".".join(pkg_parts + ([node.module] if node.module else []))
    add(base)
    # `from pkg.mod import name` may bind submodules too
    if base.split(".")[0] == repo.package:
        for alias in node.names:
            sub = f"{base}.{alias.name}"
            if sub in repo.files:
                internal.extend(_internal_closure(repo, sub))
    return internal, external


def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for src in repo.files.values():
        if not src.stdlib_only:
            continue
        for node in module_level_imports(src.tree):
            internal, external = resolve_import(repo, src, node)
            for top in external:
                if top in _STDLIB:
                    continue
                findings.append(Finding(
                    rule="JF001",
                    file=src.rel,
                    line=node.lineno,
                    message=(
                        f"module is marked stdlib-only but imports "
                        f"{top!r} at module level"
                    ),
                    hint=(
                        "import it lazily inside the function that needs "
                        "it, or remove the '# tpuframe-lint: stdlib-only' "
                        "marker and every contract that relies on it"
                    ),
                ))
            for dep in dict.fromkeys(internal):
                if dep == src.module or repo.files[dep].stdlib_only:
                    continue
                findings.append(Finding(
                    rule="JF002",
                    file=src.rel,
                    line=node.lineno,
                    message=(
                        f"module is marked stdlib-only but imports "
                        f"unmarked module {dep!r} at module level (package "
                        "__init__ execution counts)"
                    ),
                    hint=(
                        f"mark {dep} '# tpuframe-lint: stdlib-only' if it "
                        "qualifies (the linter will then hold it to the "
                        "same contract), or make this import lazy"
                    ),
                ))
    return findings
