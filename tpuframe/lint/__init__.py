"""Invariant linter: AST-enforced contracts the test suite can't see.

Eight PRs of spine-building accumulated load-bearing invariants that
existed only as docstring prose: which modules are contractually
stdlib-only (the doctor/telemetry/fault stack must import while jax is
wedged), which ``TPUFRAME_*`` knobs ship to workers through which
``*_ENV_VARS`` list, which telemetry names have schema rows in
OBSERVABILITY.md, which chaos sites are declared in
``fault.chaos.CHAOS_SITES``, and which hot-path functions must not
silently sync device→host.  This package machine-checks all of it by
parsing the tree (``ast`` + ``tokenize`` — the pass itself is
stdlib-only and never imports jax, numpy, or any tpuframe module that
does), so every one of those invariants is a failing tier-1 test the
moment a future PR drifts.

Run it::

    python -m tpuframe.lint              # human-readable, exit 0 clean / 3 findings
    python -m tpuframe.lint --json       # machine-readable findings
    python -m tpuframe.lint --knobs --json   # reconciled knob inventory
                                             # (the core/config registry seam)

Rule families (catalog with fix hints in LINT.md):

- **JF** (``lint.imports``) — jax-free contract: a module marked
  ``# tpuframe-lint: stdlib-only`` may import, at module level, only the
  stdlib and other marked modules — verified over the real import graph
  including package ``__init__`` execution, not just the file.
- **KN** (``lint.knobs``) — knob accounting: every literal
  ``TPUFRAME_*`` env read is declared in exactly one ``*_ENV_VARS``
  list, every entry is read somewhere, every shipped list is aggregated
  by ``launch.remote.all_env_vars()``, and every knob is documented.
- **TS** (``lint.schema``) — telemetry schema drift: span/event/counter/
  gauge/histogram name literals exist in the OBSERVABILITY/FAULT/SERVE
  schema docs, and documented names still exist in code.
- **HP** (``lint.hazards``) — hot-path hazards: un-spanned device→host
  syncs, Python branching on traced values, and donation of
  possibly-aliased buffers, in functions reachable from the jitted
  step/serve paths.
- **CS** (``lint.sites``) — chaos-site registry: every fired injection
  site is declared in ``fault.chaos.CHAOS_SITES`` and documented in
  FAULT.md, and every declared site is actually instrumented.
- **OP** (``lint.ops_registry``) — kernel dispatch registry: every
  ``ops/`` kernel module is declared in ``ops.ledger.OPS_REGISTRY``
  with a resolvable entry point and an existing parity test, so a
  kernel can't ship undispatched or untested.

Suppression: inline ``# tpuframe-lint: disable=RULE`` on the finding's
line, or a ``--suppressions`` file (``RULE:file-glob[:substr]`` per
line).  The repo's own acceptance test (``tests/test_lint.py``) runs the
full pass over ``tpuframe/`` and asserts zero unsuppressed findings.
"""

# tpuframe-lint: stdlib-only

from tpuframe.lint.driver import LintResult, Repo, load_repo, run_lint
from tpuframe.lint.report import Finding, Suppressions, render_json, render_text

__all__ = [
    "Finding",
    "LintResult",
    "Repo",
    "Suppressions",
    "load_repo",
    "render_json",
    "render_text",
    "run_lint",
]
