"""Repo model + orchestration for the invariant linter.

One parse per file: :func:`load_repo` walks a package tree, parses every
``.py`` into an AST, extracts the ``# tpuframe-lint:`` directives with
``tokenize`` (real comments only — the same text inside a docstring is
prose, not policy), and loads the schema docs from the repo root.  The
rule families (``lint.imports`` / ``knobs`` / ``schema`` / ``hazards`` /
``sites`` / ``ops_registry``) are pure functions over that model, so
the whole pass costs one tree walk + six AST passes — cheap enough for
tier-1 and the doctor (``benchmarks/bench_lint.py`` prices it).
"""

# tpuframe-lint: stdlib-only

from __future__ import annotations

import ast
import dataclasses
import io
import os
import tokenize
from typing import Iterable

from tpuframe.lint.report import Finding, Suppressions, split_suppressed

#: docs the schema/knob/site rules cross-check, looked up in the repo root
#: (the package dir's parent); a missing doc skips the rules that need it
#: (an installed wheel has no OBSERVABILITY.md — the pass still runs the
#: pure-code rules there)
DOC_FILES = ("OBSERVABILITY.md", "FAULT.md", "SERVE.md", "PERF.md")

#: hot-path seed modules (suffix match under the scanned package): every
#: function defined here, plus everything reachable from them, is "hot"
HOT_PATH_SEEDS = ("train.step", "serve.engine")


@dataclasses.dataclass
class SourceFile:
    """One parsed module + its lint directives."""

    rel: str                       # path relative to the repo root
    path: str                      # absolute path
    module: str                    # dotted module name ("tpuframe.track.telemetry")
    text: str
    tree: ast.Module
    stdlib_only: bool              # carries "# tpuframe-lint: stdlib-only"
    disabled: dict[int, set[str]]  # line -> disabled rule ids ({"all"} = any)
    directive_lines: dict[int, str]  # line -> raw directive (e.g. "not-shipped")
    _nodes: list | None = None

    @property
    def nodes(self) -> list[ast.AST]:
        """Flattened AST, walked once and shared by every rule family
        (the pass's dominant cost is repeated ast.walk otherwise)."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def rule_disabled(self, rule: str, line: int) -> bool:
        d = self.disabled.get(line, ())
        return rule in d or "all" in d


@dataclasses.dataclass
class Repo:
    """Everything the rule families look at."""

    package_root: str            # absolute dir of the scanned package
    package: str                 # its import name ("tpuframe")
    docs_root: str               # where the schema docs live
    files: dict[str, SourceFile]          # keyed by module name
    docs: dict[str, str]                  # doc filename -> text

    def doc_line(self, doc: str, needle: str) -> int:
        """1-based line of the first occurrence of ``needle`` in ``doc``
        (0 when absent) — so doc-side findings anchor to a real line."""
        text = self.docs.get(doc, "")
        pos = text.find(needle)
        return text.count("\n", 0, pos) + 1 if pos >= 0 else 0


def _parse_directives(text: str) -> tuple[bool, dict, dict]:
    """Extract ``# tpuframe-lint:`` directives from real COMMENT tokens."""
    stdlib_only = False
    disabled: dict[int, set[str]] = {}
    directive_lines: dict[int, str] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            body = tok.string.lstrip("#").strip()
            if not body.startswith("tpuframe-lint:"):
                continue
            directive = body[len("tpuframe-lint:"):].strip()
            line = tok.start[0]
            directive_lines[line] = directive
            if directive == "stdlib-only":
                stdlib_only = True
            elif directive.startswith("disable="):
                rules = {r.strip() for r in
                         directive[len("disable="):].split(",") if r.strip()}
                disabled.setdefault(line, set()).update(rules)
            # other directives (e.g. "not-shipped") are consumed by the
            # rule that defines them, via directive_lines
    except tokenize.TokenError:
        pass  # a syntactically broken file already fails ast.parse loudly
    return stdlib_only, disabled, directive_lines


def _module_name(package: str, rel_to_pkg: str) -> str:
    parts = rel_to_pkg.split(os.sep)
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][: -len(".py")]
    return ".".join([package] + [p for p in parts if p])


def load_repo(package_dir: str | None = None,
              docs_dir: str | None = None) -> Repo:
    """Parse a package tree into a :class:`Repo`.

    Defaults scan the installed ``tpuframe`` package with docs from its
    parent directory (= the repo root in a source checkout).  Tests point
    this at fixture trees — any directory whose basename is the package
    name works.
    """
    if package_dir is None:
        import tpuframe

        package_dir = os.path.dirname(os.path.abspath(tpuframe.__file__))
    package_dir = os.path.abspath(package_dir)
    package = os.path.basename(package_dir)
    docs_root = os.path.abspath(docs_dir) if docs_dir else os.path.dirname(package_dir)

    files: dict[str, SourceFile] = {}
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = sorted(
            d for d in dirnames
            if not d.startswith(".") and d != "__pycache__"
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel_to_pkg = os.path.relpath(path, package_dir)
            rel = os.path.join(package, rel_to_pkg)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            tree = ast.parse(text, filename=rel)
            stdlib_only, disabled, directive_lines = _parse_directives(text)
            module = _module_name(package, rel_to_pkg)
            files[module] = SourceFile(
                rel=rel, path=path, module=module, text=text, tree=tree,
                stdlib_only=stdlib_only, disabled=disabled,
                directive_lines=directive_lines,
            )

    docs = {}
    for doc in DOC_FILES:
        p = os.path.join(docs_root, doc)
        if os.path.exists(p):
            with open(p, encoding="utf-8") as f:
                docs[doc] = f.read()
    return Repo(package_root=package_dir, package=package,
                docs_root=docs_root, files=files, docs=docs)


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    suppressed_count: int
    files_scanned: int
    rules_run: int

    def rule_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return dict(sorted(counts.items()))


def _apply_inline_disables(repo: Repo, findings: Iterable[Finding]) -> tuple[list, int]:
    by_rel = {f.rel: f for f in repo.files.values()}
    kept, dropped = [], 0
    for f in findings:
        src = by_rel.get(f.file)
        if src is not None and src.rule_disabled(f.rule, f.line):
            dropped += 1
        else:
            kept.append(f)
    return kept, dropped


def run_lint(
    package_dir: str | None = None,
    docs_dir: str | None = None,
    suppressions: Suppressions | str | None = None,
) -> LintResult:
    """The full pass: load, run every rule family, apply suppressions."""
    from tpuframe.lint import (
        hazards, imports, knobs, ops_registry, schema, sites,
    )

    repo = load_repo(package_dir, docs_dir)
    families = (imports, knobs, schema, sites, hazards, ops_registry)
    findings: list[Finding] = []
    rules_run = 0
    for family in families:
        rules_run += len(family.RULES)
        findings.extend(family.check(repo))
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    findings, inline_dropped = _apply_inline_disables(repo, findings)
    if isinstance(suppressions, str):
        suppressions = Suppressions.load(suppressions)
    findings, file_dropped = split_suppressed(findings, suppressions)
    return LintResult(
        findings=findings,
        suppressed_count=inline_dropped + len(file_dropped),
        files_scanned=len(repo.files),
        rules_run=rules_run,
    )
