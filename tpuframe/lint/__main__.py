"""CLI: ``python -m tpuframe.lint [--json] [--suppressions FILE] [--knobs]``.

Exit codes mirror the fleet analyzer's regression-gate convention:
0 = clean, 3 = unsuppressed findings (CI-gateable), 2 = usage error.
"""

# tpuframe-lint: stdlib-only

import argparse
import json
import sys

from tpuframe.lint.driver import load_repo, run_lint
from tpuframe.lint.report import Suppressions, render_json, render_text


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuframe.lint",
        description=(
            "tpuframe invariant linter: jax-free contracts, knob "
            "accounting, telemetry schema drift, hot-path hazards, "
            "chaos-site registry (rule catalog in LINT.md)"
        ),
    )
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--suppressions", default=None, metavar="FILE",
                    help="suppressions file (RULE:file-glob[:substr] lines)")
    ap.add_argument("--root", default=None, metavar="DIR",
                    help="package dir to scan (default: the installed "
                         "tpuframe package)")
    ap.add_argument("--docs", default=None, metavar="DIR",
                    help="dir holding the schema docs (default: the "
                         "package dir's parent)")
    ap.add_argument("--knobs", action="store_true",
                    help="emit the reconciled TPUFRAME_* knob inventory "
                         "instead of findings (the core/config registry "
                         "seam; pairs with --json)")
    args = ap.parse_args(argv)

    try:
        suppressions = (Suppressions.load(args.suppressions)
                        if args.suppressions else None)
    except (OSError, ValueError) as e:
        print(f"tpuframe.lint: bad suppressions file: {e}", file=sys.stderr)
        return 2

    if args.knobs:
        from tpuframe.lint.knobs import knob_inventory

        inventory = knob_inventory(load_repo(args.root, args.docs))
        if args.as_json:
            print(json.dumps({"knobs": inventory, "count": len(inventory)},
                             indent=2))
        else:
            for row in inventory:
                lists = ", ".join(row["lists"]) or "UNDECLARED"
                docs = ", ".join(row["docs"]) or "undocumented"
                default = (f" default={row['defaults'][0]!r}"
                           if row["defaults"] else "")
                d = row.get("domain")
                if d:
                    constraint = d.get("choices") or d.get("range")
                    domain = (f" <{d['type']}"
                              + (f" {constraint}" if constraint else "")
                              + f" apply={d['apply']}>")
                else:
                    domain = " <no domain>"
                print(f"{row['name']}: {lists}{default}{domain} [{docs}] "
                      f"({len(row['reads'])} read site(s))")
            print(f"{len(inventory)} knob(s)")
        return 0

    result = run_lint(args.root, args.docs, suppressions)
    print(render_json(result) if args.as_json else render_text(result))
    return 3 if result.findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
